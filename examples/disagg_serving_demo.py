"""Disaggregated prefill/decode serving — KV pages streamed live
between a compute-dense prefill pool and a bandwidth-dense decode pool
(pipegoose_tpu/serving/disagg/, docs/serving.md "Disaggregated
prefill/decode").

Watch the whole contract in one run:

1. a tp=2 prefill pool chunks through the prompts and STREAMS each
   finished page across the mesh boundary (int8 wire: q + scale
   planes, never fp);
2. the tp=1 decode pool stages the transfers against its admission
   ledger, admits each request the moment its page table materializes
   (no prefill runs there), and decodes;
3. the greedy output is TOKEN-IDENTICAL to one monolithic engine;
4. the request tracer's new ``transfer`` phase makes
   queue + prefill + transfer + decode + stall == e2e exactly;
5. an injected transfer fault falls back to a local re-prefill —
   same tokens.

    python examples/disagg_serving_demo.py --fake-devices 8
    python examples/disagg_serving_demo.py --fake-devices 8 --tp-prefill 2
"""
from __future__ import annotations

import argparse

import jax
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp-prefill", type=int, default=2,
                    help="tensor-parallel width of the PREFILL pool "
                         "(decode stays tp=1: the 2->1 reshard demo)")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--max-new", type=int, default=5)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steps", type=int, default=None,
                    help="cap max_new_tokens per request (smoke runs)")
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices")
    args = ap.parse_args()
    if args.steps:
        args.max_new = min(args.max_new, args.steps)
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    from pipegoose_tpu.distributed import ParallelContext
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.serving import DisaggEngine, Request, ServingEngine
    from pipegoose_tpu.serving.disagg import TransferError, set_transfer_fault
    from pipegoose_tpu.telemetry import MetricsRegistry
    from pipegoose_tpu.telemetry.reqtrace import RequestTracer

    cfg = bloom.BloomConfig(vocab_size=64, hidden_size=64, n_layer=2,
                            n_head=4)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(args.seed)
    shared = rng.randint(1, 64, (13,))
    prompts = [np.concatenate([shared, rng.randint(1, 64, (2 + i % 4,))])
               for i in range(args.requests)]

    def requests():
        return [Request(prompt=p, max_new_tokens=args.max_new)
                for p in prompts]

    print("== monolithic reference (one engine, int8 KV) ==")
    single = ServingEngine(params, cfg, num_slots=2, num_pages=32,
                           page_size=4, max_context=32, prefix_cache=True,
                           prefill_chunk=8, kv_dtype="int8",
                           registry=MetricsRegistry())
    ref_outs, _ = single.run(requests())

    print(f"== disagg: tp={args.tp_prefill} prefill pool -> tp=1 decode "
          f"pool, int8 wire ==")
    mesh = specs = None
    if args.tp_prefill > 1:
        ctx = ParallelContext(tensor_parallel_size=args.tp_prefill,
                              data_parallel_size=max(
                                  1, (args.fake_devices or args.tp_prefill)
                                  // args.tp_prefill))
        mesh, specs = ctx.mesh, bloom.tp_specs(params)
    reg = MetricsRegistry(enabled=True)
    tracer = RequestTracer(registry=reg, keep_completed=args.requests)
    pe = ServingEngine(params, cfg, num_slots=2, num_pages=32, page_size=4,
                       max_context=32, prefix_cache=True, prefill_chunk=8,
                       prefill_only=True, kv_dtype="int8", mesh=mesh,
                       param_specs=specs, registry=MetricsRegistry())
    de = ServingEngine(params, cfg, num_slots=2, num_pages=32, page_size=4,
                       max_context=32, prefix_cache=True, prefill_chunk=8,
                       kv_dtype="int8", registry=MetricsRegistry(),
                       stall_patience=10_000)
    disagg = DisaggEngine(pe, de, max_inflight=4, registry=reg,
                          tracer=tracer)
    outs, metrics = disagg.run(requests())
    for a, b in zip(ref_outs, outs):
        assert np.array_equal(a.generated, b.generated), (
            f"request {b.uid} diverged from the monolithic reference"
        )
    print(f"token-identical: {len(outs)}/{len(ref_outs)} requests match "
          f"the monolithic engine exactly")
    xfer = metrics["transfer"]
    print(f"transfer: {xfer['handoffs']} handoffs, {xfer['pages']} pages, "
          f"{xfer['wire_bytes']} wire bytes "
          f"({xfer['wire_savings_ratio']:.0%} below the fp equivalent "
          f"{xfer['fp_equiv_bytes']} — q+scale, never dequantized)")
    print(f"decode-pool rate: {metrics['decode_pool_tokens_per_s']} tok/s "
          f"(e2e {metrics['decode_tokens_per_s']} tok/s)")

    print("== attribution: queue + prefill + transfer + decode + stall "
          "== e2e ==")
    print(f"{'uid':>4} {'queue':>8} {'prefill':>8} {'transfer':>9} "
          f"{'decode':>8} {'stall':>8} {'sum':>8} {'e2e':>8}")
    for tl in sorted(tracer.completed, key=lambda tl: tl.uid):
        c = tl.components
        total = sum(c.values())
        assert abs(total - tl.e2e_s) < 1e-6, (tl.uid, total, tl.e2e_s)
        assert c["transfer_s"] > 0, "transfer phase must be first-class"
        print(f"{tl.uid:>4} {c['queue_s']:>8.4f} {c['prefill_s']:>8.4f} "
              f"{c['transfer_s']:>9.4f} {c['decode_s']:>8.4f} "
              f"{c['stall_s']:>8.4f} {total:>8.4f} {tl.e2e_s:>8.4f}")
    print("attribution exact for every request")

    print("== transfer fault -> local re-prefill fallback ==")
    hits = [0]

    def fault(kind, uid, n_pages):
        hits[0] += 1
        if hits[0] == 2:
            raise TransferError("injected link fault")

    prev = set_transfer_fault(fault)
    try:
        outs_f, metrics_f = disagg.run(requests())
    finally:
        set_transfer_fault(prev)
    for a, b in zip(ref_outs, outs_f):
        assert np.array_equal(a.generated, b.generated)
    print(f"fallbacks: {metrics_f['transfer']['fallbacks']} "
          f"(failures: {metrics_f['transfer']['failures']}) — "
          f"tokens still identical")
    print(f"done: {len(outs)} requests token-identical across pools, "
          f"{xfer['pages']} pages streamed at wire precision, "
          f"attribution exact, fallback verified")


if __name__ == "__main__":
    main()
