"""Long-context training with ring attention (sequence parallelism) —
the capability the reference advertised but never implemented
(README.md:96; SURVEY.md §5).

    python examples/long_context.py --fake-devices 8 --sp 4 --dp 2 --seq 4096
"""
from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from pipegoose_tpu.distributed import ParallelContext
from pipegoose_tpu.models import bloom
from pipegoose_tpu.optim.zero import DistributedOptimizer
from pipegoose_tpu.trainer import LossLoggerCallback, Trainer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sp", type=int, default=4)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    ctx = ParallelContext(
        sequence_parallel_size=args.sp,
        tensor_parallel_size=args.tp,
        data_parallel_size=args.dp,
    )
    cfg = bloom.BloomConfig(
        vocab_size=2048, hidden_size=256, n_layer=4, n_head=8,
        dtype=jnp.bfloat16, remat=True,
    )
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))

    def loss_fn(p, ids):
        return bloom.loss_fn_sp(
            p, ids, None, ids, cfg,
            tp_axis="tensor" if args.tp > 1 else None, sp_axis="seq",
        )

    trainer = Trainer(
        loss_fn,
        params,
        bloom.tp_specs(params),
        DistributedOptimizer(optax.adam(1e-4), axis_name="data"),
        ctx,
        batch_spec=P("data", "seq"),
        grad_sync_axes=(("seq", "sum"),),
        callbacks=[LossLoggerCallback(every=2)],
    )
    rng = np.random.RandomState(0)
    batches = (
        jnp.asarray(rng.randint(0, cfg.vocab_size, (args.batch, args.seq)))
        for _ in range(args.steps)
    )
    state = trainer.fit(batches, max_steps=args.steps)
    last = f"{float(state.last_loss):.4f}" if state.last_loss is not None else "n/a (no new steps)"
    print(f"done: {state.step} steps, final loss {last}")


if __name__ == "__main__":
    main()
