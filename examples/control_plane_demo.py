"""Control-plane demo: N serving replicas behind one front door —
cache-aware routing, per-tenant fair-share dispatch, and a scale-down
drain that drops zero admitted work.

The run walks the multi-replica control plane (ISSUE 12,
docs/serving.md "Control plane"):

- two ``ServingEngine`` replicas (own scheduler, page pool, radix
  prefix cache each) driven tick-by-tick by a ``ControlPlane``;
- the SAME multi-tenant Zipf-skewed replay routed ``round_robin`` vs
  ``cache_aware`` — the cache-aware arm forwards measurably fewer
  prefill tokens because requests land on the replica already holding
  their longest cached prefix (asserted);
- per-tenant deficit-round-robin dispatch: the hot tenant's flood
  cannot monopolize the early dispatch slots (asserted on the router's
  decision log);
- a forced drain mid-run: in-flight requests preempt, migrate, and
  re-prefill on the surviving replica — outputs token-identical to the
  no-drain run (asserted);
- the fleet surface: merged per-replica metrics (``FleetRegistry``),
  ``/debug/fleet`` on a live ``OpsServer``, and the router's Perfetto
  decision track next to the usual host spans.

    python examples/control_plane_demo.py --fake-devices 8
    JAX_PLATFORMS=cpu python examples/control_plane_demo.py --requests 16
"""
from __future__ import annotations

import argparse
import json
import os
import shutil


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--prefix-len", type=int, default=48)
    ap.add_argument("--steps", type=int, default=2,
                    help="accepted for the shared example-runner CLI; "
                         "serving runs are request-driven")
    ap.add_argument("--out-dir", default="control_plane_out")
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    from urllib.request import urlopen

    import jax
    import numpy as np

    from pipegoose_tpu import telemetry
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.serving import (
        Request,
        ServingEngine,
        make_skewed_replay,
    )
    from pipegoose_tpu.serving.control_plane import ControlPlane

    shutil.rmtree(args.out_dir, ignore_errors=True)
    os.makedirs(args.out_dir, exist_ok=True)

    cfg = bloom.BloomConfig(vocab_size=64, hidden_size=32, n_layer=2,
                            n_head=2)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    replay = make_skewed_replay(
        n_requests=args.requests, n_prefixes=3,
        prefix_len=args.prefix_len, suffix_lens=(2, 4), max_new=2,
        vocab=64, seed=0, n_tenants=3,
    )

    def factory(name, registry):
        return ServingEngine(params, cfg, num_slots=1, num_pages=33,
                             page_size=8, max_context=96,
                             prefix_cache=True, registry=registry)

    def reqs():
        return [Request(prompt=p, max_new_tokens=n, tenant=t)
                for p, n, t in replay]

    # -- routing arms: the same trace, two placement policies ---------------
    forwarded = {}
    planes = {}
    # pull_hints off: the A/B isolates ROUTING — with fleet prefix
    # sharing on, a round-robin miss pulls the warm peer's KV pages
    # instead of recomputing and both arms forward the same count
    # (that arm is examples/kv_tier_demo.py's story)
    for policy in ("round_robin", "cache_aware"):
        plane = ControlPlane(factory, n_replicas=args.replicas,
                             policy=policy, pull_hints=False)
        plane.run(reqs())                    # compile + seed caches
        plane.clear_prefix_caches()          # cold caches, warm programs
        outs, metrics = plane.run(reqs())
        forwarded[policy] = metrics["prefill_tokens"]
        planes[policy] = plane
        print(f"{policy:>12}: forwarded {metrics['prefill_tokens']:4d} "
              f"prefill tokens, {metrics['decode_tokens_per_s']:.0f} "
              f"tok/s, shed {metrics['shed_requests']}")
    assert forwarded["cache_aware"] < forwarded["round_robin"], forwarded

    # -- fairness: DRR interleaves tenants in the dispatch order ------------
    plane = planes["cache_aware"]
    order = [d["tenant"] for d in plane.router.decisions][:6]
    print(f"first dispatch wave interleaves tenants: {order}")
    assert len(set(order)) >= 2, order

    # -- drain: scale-down drops zero admitted work -------------------------
    clean, _ = plane.run(reqs())

    def force_drain(p, tick):
        if tick == 3 and len(p.serving_replicas()) > 1:
            def owed(rep):
                s = rep.engine.sched.capacity_snapshot()
                return s["queued_tokens"] + s["active_tokens_remaining"]
            victim = max(p.serving_replicas(), key=owed)
            print(f"tick {tick}: draining {victim.name} "
                  f"({len(victim.engine.sched.active())} in flight)")
            p.start_drain(victim.name)

    drained, metrics = plane.run(reqs(), tick_hook=force_drain)
    assert len(drained) == len(clean)
    for a, b in zip(clean, drained):
        np.testing.assert_array_equal(a.generated, b.generated)
    migrated = int(plane._m_migrated.value)
    print(f"drain migrated {migrated} in-flight request(s); all "
          f"{len(drained)} outputs token-identical to the no-drain run")

    # -- the fleet surface: /debug/fleet + Perfetto router track ------------
    status = plane.fleet_status()
    with telemetry.OpsServer(registry=plane.fleet, port=0,
                             fleet=plane.fleet_status) as srv:
        body = json.loads(
            urlopen(srv.url + "/debug/fleet", timeout=5).read())
        assert body["router"]["decisions_total"] > 0
        n_metrics = len(telemetry.parse_prometheus_text(
            urlopen(srv.url + "/metrics", timeout=5).read().decode()))
    trace_path = os.path.join(args.out_dir, "trace.json")
    with telemetry.ChromeTraceExporter(trace_path,
                                       registry=plane.registry) as exp:
        exp.add_router_decisions(plane.router.decisions)
    print(json.dumps({
        "prefill_tokens": forwarded,
        "replicas": [r["name"] + ":" + r["state"]
                     for r in status["replicas"]],
        "tenants": {t: s["dispatched_token_share"]
                    for t, s in status["tenants"].items()},
        "fleet_metrics_exported": n_metrics,
        "trace": trace_path,
    }, indent=2))
    print(
        f"done: cache-aware routing forwarded "
        f"{forwarded['cache_aware']} vs {forwarded['round_robin']} "
        f"prefill tokens across {args.replicas} replicas; drain dropped "
        f"zero of {len(drained)} requests; open {trace_path} in "
        f"ui.perfetto.dev"
    )


if __name__ == "__main__":
    main()
