"""Flight-recorder demo: an injected mid-run gradient overflow, caught
by the in-graph health stats, black-boxed by the flight recorder,
healed by AutoRecovery, and exported as a Perfetto trace.

The run wires the full health/forensics stack
(docs/observability.md):

- ``Trainer(with_health=True)`` — the compiled step also returns
  global + per-module grad norms, update stats, and nonfinite-leaf
  counts (telemetry/health.py);
- ``FlightRecorder`` — rings the last N step records and, on the
  poisoned step (an ``inf`` gradient bomb localized to the embedding
  group), dumps ``blackbox_stepNNNNNNNN_nonfinite.json`` naming the
  offending module group;
- ``AutoRecovery(recorder=...)`` — consumes the structured trigger,
  restores the last checkpoint, and the run continues to its target
  step count;
- ``ChromeTraceExporter`` — the span stream plus a theoretical
  ``GPipeScheduler`` clock timeline land in ``trace.json``; open it at
  https://ui.perfetto.dev, and the ``pipeline.bubble_fraction`` gauge
  sits next to the MFU gauge in the snapshot.

    python examples/flight_recorder_demo.py --fake-devices 8 --tp 2 --dp 4
    JAX_PLATFORMS=cpu python examples/flight_recorder_demo.py --steps 4
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import shutil


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--out-dir", default="flightrec_out")
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pipegoose_tpu import telemetry
    from pipegoose_tpu.distributed import ParallelContext
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.nn.pipeline_parallel.scheduler import GPipeScheduler
    from pipegoose_tpu.optim.zero import DistributedOptimizer
    from pipegoose_tpu.telemetry import (
        ChromeTraceExporter,
        FlightRecorder,
        TelemetryCallback,
        register_pipeline_gauges,
    )
    from pipegoose_tpu.trainer import AutoRecovery, CheckpointCallback, Trainer

    os.makedirs(args.out_dir, exist_ok=True)
    ckpt_dir = os.path.join(args.out_dir, "ckpt")
    bb_dir = os.path.join(args.out_dir, "blackbox")
    trace_path = os.path.join(args.out_dir, "trace.json")
    # the demo owns its out-dir: a stale step_N checkpoint from a prior
    # run would make orbax refuse the save (and stale black boxes would
    # confuse the assertions below)
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    shutil.rmtree(bb_dir, ignore_errors=True)

    cfg = bloom.BloomConfig(vocab_size=256, hidden_size=64, n_layer=2,
                            n_head=4)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    ctx = ParallelContext(tensor_parallel_size=args.tp,
                          data_parallel_size=args.dp)

    POISON = 0  # batches whose first token is 0 detonate the bomb

    def loss_fn(p, ids):
        base = bloom.loss_fn(p, ids, None, ids, cfg, tp_axis="tensor")
        # gradient-overflow injector: inf * ||embed||^2 poisons the
        # embedding group's gradients (and only that group) — the
        # stand-in for a real bad-batch / optimizer blow-up
        bomb = jnp.where(ids[0, 0] == POISON, jnp.float32(jnp.inf), 0.0)
        return base + bomb * jnp.sum(
            jnp.square(p["embed"]["weight"].astype(jnp.float32))
        )

    def batches():
        rng = np.random.RandomState(0)
        # one extra batch: the poisoned step is rolled back and its
        # replacement comes from the stream's tail
        for i in range(args.steps + 1):
            ids = rng.randint(1, cfg.vocab_size, (args.batch, args.seq))
            if i == 1:  # mid-run: after the first checkpoint exists
                ids[0, 0] = POISON
            yield jnp.asarray(ids)

    reg = telemetry.get_registry()
    trace = ChromeTraceExporter(trace_path, registry=reg)
    recorder = FlightRecorder(bb_dir, capacity=32)
    recovery = AutoRecovery(ckpt_dir, max_restores=2, recorder=recorder)
    trainer = Trainer(
        loss_fn, params, bloom.tp_specs(params),
        DistributedOptimizer(optax.adam(1e-3), axis_name="data"), ctx,
        with_health=True,
        callbacks=[
            CheckpointCallback(ckpt_dir, every=1),
            recorder,
            recovery,
            TelemetryCallback(fence=True),  # enables the registry too
        ],
    )
    state = trainer.fit(batches(), max_steps=args.steps)

    assert recovery.restores == 1, recovery.restores
    assert state.step == args.steps, state.step
    dumps = sorted(glob.glob(os.path.join(bb_dir, "blackbox_*.json")))
    assert dumps, "gradient overflow produced no black box"
    box = json.load(open(dumps[0]))
    assert box["trigger"]["name"] == "nonfinite"
    assert "'embed'" in box["trigger"]["reason"]

    # Perfetto trace: measured spans + the theoretical pipeline clock
    # timeline of an (M=8, P=4) GPipe schedule next to them
    sched = GPipeScheduler(8, 4)
    step_p50 = reg.histogram("span.train.step.seconds").quantile(0.5)
    bubble = register_pipeline_gauges(sched, registry=reg,
                                      step_seconds=step_p50)
    trace.add_pipeline_timeline(sched, clock_s=max(step_p50, 1e-3) / 8)
    trace.write()
    trace.close()

    final_health = telemetry.host_health(state.last_health)
    summary = {
        "steps": state.step,
        "restores": recovery.restores,
        "trigger": box["trigger"]["name"],
        "trigger_reason": box["trigger"]["reason"],
        "black_box": dumps[0],
        "final_grad_norm": round(final_health["grad_norm"], 4),
        "final_update_ratio": round(final_health["update_ratio"], 6),
        "pipeline_bubble_fraction": round(bubble, 4),
        "trace": trace_path,
    }
    print(json.dumps(summary, indent=2))
    print(
        f"done: {state.step} steps with 1 gradient overflow black-boxed "
        f"({os.path.basename(dumps[0])}) and auto-recovered; open "
        f"{trace_path} in ui.perfetto.dev"
    )


if __name__ == "__main__":
    main()
