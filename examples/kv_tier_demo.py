"""Fleet-wide KV memory hierarchy — host-DRAM prefix-cache tiering and
cross-replica prefix sharing (pipegoose_tpu/serving/kv_tier/,
docs/serving.md "KV memory hierarchy").

Watch the whole contract in one run:

1. an int8 pool whose prefix working set OVERFLOWS its HBM pages
   spills evicted pages into a byte-budgeted host-DRAM tier at wire
   precision (q + scale planes verbatim, never fp) and restores them
   on replay — fewer recomputed prefill tokens than plain
   LRU-evict-and-recompute, TOKEN-IDENTICAL to an all-HBM reference;
2. the request tracer's new ``restore`` phase keeps the attribution
   identity exact: queue + prefill + restore + transfer + decode +
   stall == e2e;
3. a cold replica PULLS a prefix a warm peer holds through the disagg
   transfer machinery instead of recomputing it — same tokens;
4. an injected host-tier I/O fault degrades to recompute — same
   tokens, never a stall or lost request;
5. ``memory_report()`` pins the tier's resident bytes at the exact
   int8 wire census.

    python examples/kv_tier_demo.py --fake-devices 8
"""
from __future__ import annotations

import argparse

import jax
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=6,
                    help="requests per replay phase")
    ap.add_argument("--max-new", type=int, default=4)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--steps", type=int, default=None,
                    help="cap max_new_tokens per request (smoke runs)")
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices")
    args = ap.parse_args()
    if args.steps:
        args.max_new = min(args.max_new, args.steps)
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.serving import Request, ServingEngine
    from pipegoose_tpu.serving.kv_tier import (
        HostTier,
        HostTierError,
        set_host_tier_fault,
    )
    from pipegoose_tpu.serving.kv_tier.restore import wire_page_bytes
    from pipegoose_tpu.telemetry import MetricsRegistry
    from pipegoose_tpu.telemetry.reqtrace import RequestTracer

    cfg = bloom.BloomConfig(vocab_size=64, hidden_size=64, n_layer=2,
                            n_head=4)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(args.seed)
    n_per = max(2, args.requests // 2)
    prefixes = [rng.randint(1, 64, (12,)) for _ in range(2)]
    suffixes = [rng.randint(1, 64, (2,)) for _ in range(n_per)]

    def phase(prefix):
        return [Request(prompt=np.concatenate([prefix, s]),
                        max_new_tokens=args.max_new) for s in suffixes]

    # the replay: prefix A, then B (whose pages evict A's), then A
    # again — plain LRU has forgotten A and recomputes; the tier hasn't
    schedule = (prefixes[0], prefixes[1], prefixes[0])
    kw = dict(num_slots=2, page_size=4, max_context=32, prefill_chunk=4,
              prefix_cache=True, kv_dtype="int8")

    print("== all-HBM reference (ample pages) ==")
    ref = ServingEngine(params, cfg, num_pages=65,
                        registry=MetricsRegistry(), **kw)
    ref_outs = []
    for pfx in schedule:
        outs, _ = ref.run(phase(pfx))
        ref_outs += [o.generated for o in outs]

    print("== plain LRU-evict-and-recompute (overflowing pool) ==")
    lru = ServingEngine(params, cfg, num_pages=9,
                        registry=MetricsRegistry(), **kw)
    lru_prefill = 0
    for pfx in schedule:
        _, m = lru.run(phase(pfx))
        lru_prefill += m["prefill_tokens"]

    print("== host-DRAM tier under the same overflowing pool ==")
    tier = HostTier(1 << 20)
    reg = MetricsRegistry(enabled=True)
    tracer = RequestTracer(registry=reg,
                           keep_completed=3 * len(suffixes))
    eng = ServingEngine(params, cfg, num_pages=9, host_tier=tier,
                        registry=reg, **kw)
    eng.attach_tracer(tracer)
    tier_outs, tier_prefill, restored = [], 0, 0
    for pfx in schedule:
        outs, m = eng.run(phase(pfx))
        tier_outs += [o.generated for o in outs]
        tier_prefill += m["prefill_tokens"]
        restored += m["kv_tier"]["restored_tokens"]
    for a, b in zip(ref_outs, tier_outs):
        assert np.array_equal(a, b), "spill->restore diverged"
    assert restored > 0 and tier_prefill < lru_prefill
    print(f"token-identical to all-HBM; prefill tokens "
          f"{lru_prefill} (LRU recompute) -> {tier_prefill} "
          f"({restored} restored from host DRAM instead)")
    rep = eng.memory_report()["host_tier"]
    wire = wire_page_bytes(eng)
    assert rep["resident_bytes"] == rep["resident_pages"] * wire
    print(f"host tier: {rep['resident_pages']} pages x {wire} B int8 "
          f"wire slabs = {rep['resident_bytes']} B resident "
          f"(exact q+scale census, never fp) of "
          f"{rep['budget_bytes']} B budget")

    print("== attribution: queue + prefill + restore + transfer + "
          "decode + stall == e2e ==")
    saw_restore = False
    for tl in sorted(tracer.completed, key=lambda tl: tl.uid):
        c = tl.components
        total = sum(c.values())
        assert abs(total - tl.e2e_s) < 1e-6, (tl.uid, total, tl.e2e_s)
        saw_restore = saw_restore or c["restore_s"] > 0
    assert saw_restore, "restore phase must be first-class"
    print(f"attribution exact for all {len(tracer.completed)} requests "
          f"(restore_s > 0 on the replayed prefix)")

    print("== cross-replica pull: cold replica <- warm peer ==")
    puller = ServingEngine(params, cfg, num_pages=33,
                           registry=MetricsRegistry(), **kw)
    puller.set_peer_source(eng)
    pull_outs, pm = puller.run(phase(schedule[-1]))
    for a, b in zip(ref_outs[-len(suffixes):],
                    [o.generated for o in pull_outs]):
        assert np.array_equal(a, b), "cross-replica pull diverged"
    print(f"{pm['kv_tier']['pulls']} pull(s), "
          f"{pm['kv_tier']['pulled_tokens']} tokens shipped from the "
          f"peer at wire precision — tokens identical")

    print("== host-tier I/O fault -> recompute fallback ==")
    def fault(op, key, n_pages):
        if op == "restore":
            raise HostTierError("injected host-tier I/O error")

    fresh = ServingEngine(params, cfg, num_pages=9,
                          host_tier=tier, registry=MetricsRegistry(),
                          **kw)
    prev = set_host_tier_fault(fault)
    try:
        fb_outs, fm = fresh.run(phase(schedule[-1]))
    finally:
        set_host_tier_fault(prev)
    for a, b in zip(ref_outs[-len(suffixes):],
                    [o.generated for o in fb_outs]):
        assert np.array_equal(a, b), "fallback recompute diverged"
    assert fm["kv_tier"]["fallbacks"] >= 1
    print(f"{fm['kv_tier']['fallbacks']} fallback(s) degraded to "
          f"recompute — tokens still identical, nothing lost")

    print(f"done: {len(tier_outs)} requests token-identical across the "
          f"hierarchy, {restored} tokens restored, "
          f"{pm['kv_tier']['pulled_tokens']} pulled cross-replica, "
          f"attribution exact, fault fallback verified")


if __name__ == "__main__":
    main()
