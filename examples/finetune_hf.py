"""Fine-tune ANY registered HF checkpoint (bloom / llama / mixtral)
with hybrid parallelism — the reference's core UX ("hand it a mapped HF
model", tensor_parallel.py:27-42) through the policy-table converter.

Run (fake CPU devices for a local smoke run):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/finetune_hf.py --tp 2 --dp 4 --steps 10

With a real checkpoint (needs network/cache):
    python examples/finetune_hf.py --model TinyLlama/TinyLlama-1.1B-Chat-v1.0
"""
from __future__ import annotations

import argparse

import jax
import numpy as np
import optax


def tiny_llama_random():
    """Offline default: a small random HF Llama (no network needed)."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    return LlamaForCausalLM(
        LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=352,
            num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
            tie_word_embeddings=False, use_cache=False,
        )
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None, help="HF checkpoint id (default: tiny random llama)")
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--n-accum", type=int, default=1)
    ap.add_argument("--lr", type=float, default=1e-4)
    args = ap.parse_args()

    if args.model:
        from transformers import AutoModelForCausalLM

        hf_model = AutoModelForCausalLM.from_pretrained(args.model)
    else:
        hf_model = tiny_llama_random()

    from pipegoose_tpu.distributed import ParallelContext
    from pipegoose_tpu.models import from_hf
    from pipegoose_tpu.optim.zero import DistributedOptimizer
    from pipegoose_tpu.trainer import LossLoggerCallback, Trainer

    cfg, params, module = from_hf(hf_model)
    del hf_model  # torch copy no longer needed

    ctx = ParallelContext(tensor_parallel_size=args.tp, data_parallel_size=args.dp)

    def loss_fn(p, ids):
        return module.loss_fn(p, ids, None, ids, cfg, tp_axis="tensor")

    trainer = Trainer(
        loss_fn,
        params,
        module.specs(params) if hasattr(module, "specs") else module.tp_specs(params),
        DistributedOptimizer(optax.adamw(args.lr), axis_name="data"),
        ctx,
        n_accum=args.n_accum,
        callbacks=[LossLoggerCallback(every=1)],
    )

    rng = np.random.RandomState(0)
    batches = (
        jax.numpy.asarray(rng.randint(0, cfg.vocab_size, (args.batch, args.seq)))
        for _ in range(args.steps)
    )
    state = trainer.fit(batches, max_steps=args.steps)
    print(f"done: {state.step} steps, final loss {float(state.last_loss):.4f}")
    ctx.destroy()


if __name__ == "__main__":
    import os

    if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
        jax.config.update("jax_platforms", "cpu")
    main()
