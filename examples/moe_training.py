"""Expert-parallel MoE training (Switch/Mixtral-style) — capability
parity with the reference's MoE convergence script
(tests/convergence/run_ep.py), TPU-first: EP x TP x DP on one mesh with
static-shape all_to_all dispatch.

    python examples/moe_training.py --fake-devices 8 --ep 2 --tp 2 --dp 2 --steps 20
"""
from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from pipegoose_tpu.distributed import ParallelContext
from pipegoose_tpu.models import bloom_moe
from pipegoose_tpu.optim.zero import DistributedOptimizer
from pipegoose_tpu.trainer import LossLoggerCallback, Trainer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ep", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--top-k", type=int, default=2)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    ctx = ParallelContext(
        expert_parallel_size=args.ep,
        tensor_parallel_size=args.tp,
        data_parallel_size=args.dp,
    )
    cfg = bloom_moe.BloomMoEConfig(
        vocab_size=2048, hidden_size=256, n_layer=4, n_head=8,
        num_experts=args.experts, top_k=args.top_k,
    )
    params = bloom_moe.init_params(cfg, jax.random.PRNGKey(0))

    def loss_fn(p, ids, rng):
        rng = jax.random.fold_in(
            rng,
            jax.lax.axis_index("data") * args.ep + jax.lax.axis_index("expert"),
        )
        return bloom_moe.loss_fn(
            p, ids, None, ids, cfg, tp_axis="tensor", ep_axis="expert",
            rng=rng, train=True,
        )

    trainer = Trainer(
        loss_fn,
        params,
        bloom_moe.moe_specs(params),
        DistributedOptimizer(optax.adam(1e-4), axis_name="data"),
        ctx,
        batch_spec=P(("data", "expert")),
        loss_axis=("data", "expert"),
        grad_sync_axes=(("expert", "mean"),),
        with_rng=True,
        callbacks=[LossLoggerCallback(every=5)],
    )

    rng = np.random.RandomState(0)
    batches = (
        jnp.asarray(rng.randint(0, cfg.vocab_size, (args.batch, args.seq)))
        for _ in range(args.steps)
    )
    state = trainer.fit(batches, max_steps=args.steps)
    last = f"{float(state.last_loss):.4f}" if state.last_loss is not None else "n/a (no new steps)"
    print(f"done: {state.step} steps, final loss {last}")


if __name__ == "__main__":
    main()
