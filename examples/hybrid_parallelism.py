"""Hybrid TP x DP training of BLOOM on TPU — the framework's flagship
entrypoint (capability parity with the reference's
examples/hybrid_parallelism.py, redesigned TPU-first: one mesh, one
compiled train step, no torchrun/process groups).

Run (any JAX device set; for a local smoke run on fake CPU devices —
works even where a sitecustomize pins an accelerator platform):
    python examples/hybrid_parallelism.py --fake-devices 8 --tp 2 --dp 4 --steps 20

With a HF checkpoint (needs network/cache):
    python examples/hybrid_parallelism.py --model bigscience/bloom-560m
"""
from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pipegoose_tpu.distributed import ParallelContext
from pipegoose_tpu.models import bloom
from pipegoose_tpu.optim.zero import DistributedOptimizer
from pipegoose_tpu.trainer import LossLoggerCallback, Trainer


def synthetic_batches(vocab, batch, seq, steps, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        yield jnp.asarray(rng.randint(0, vocab, (batch, seq)))


def hf_batches(model_name, batch, seq, steps):
    """Tokenized text batches from HF datasets (reference uses imdb,
    examples/hybrid_parallelism.py)."""
    from datasets import load_dataset
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(model_name)
    tok.pad_token = tok.pad_token or tok.eos_token
    ds = load_dataset("imdb", split="train")
    texts = [r["text"] for r in ds.select(range(batch * steps))]
    for i in range(steps):
        chunk = texts[i * batch : (i + 1) * batch]
        enc = tok(chunk, padding="max_length", truncation=True, max_length=seq,
                  return_tensors="np")
        yield jnp.asarray(enc["input_ids"])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=1e-4)
    ap.add_argument("--model", default=None,
                    help="HF checkpoint (e.g. bigscience/bloom-560m); default: tiny random")
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    ctx = ParallelContext(tensor_parallel_size=args.tp, data_parallel_size=args.dp)
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32

    if args.model:
        from transformers import BloomForCausalLM

        from pipegoose_tpu.models.hf import bloom_params_from_hf

        hf = BloomForCausalLM.from_pretrained(args.model)
        cfg, params = bloom_params_from_hf(hf, dtype=dtype)
        batches = hf_batches(args.model, args.batch, args.seq, args.steps)
    else:
        cfg = bloom.BloomConfig(
            vocab_size=2048, hidden_size=256, n_layer=4, n_head=8, dtype=dtype
        )
        params = bloom.init_params(cfg, jax.random.PRNGKey(0))
        batches = synthetic_batches(cfg.vocab_size, args.batch, args.seq, args.steps)

    def loss_fn(p, ids):
        return bloom.loss_fn(p, ids, None, ids, cfg, tp_axis="tensor")

    callbacks = [LossLoggerCallback(every=5)]
    if args.ckpt_dir:
        from pipegoose_tpu.trainer import CheckpointCallback

        callbacks.append(CheckpointCallback(args.ckpt_dir, every=100))

    trainer = Trainer(
        loss_fn,
        params,
        bloom.tp_specs(params),
        DistributedOptimizer(optax.adam(args.lr), axis_name="data"),
        ctx,
        callbacks=callbacks,
        resume_dir=args.ckpt_dir,
    )
    state = trainer.fit(batches, max_steps=args.steps)
    last = f"{float(state.last_loss):.4f}" if state.last_loss is not None else "n/a (no new steps)"
    print(f"done: {state.step} steps, final loss {last}")


if __name__ == "__main__":
    main()
