"""Mesh-doctor demo: a silently mis-sharded weight, caught at compile
time, then fixed — without running a single training step.

Story (the failure mode ISSUE 4 exists for): a GSPMD/auto-parallel
train step over a Megatron-style MLP (column-sharded w1, row-sharded
w2 — the canonical tensor-parallel layout that needs NO gathers, only
one partial-sum all-reduce per matmul pair) is built with w1's
PartitionSpec accidentally left replicated. Nothing crashes — GSPMD
happily compiles it, the partitioner quietly inserts an all-gather to
re-shard the dataflow, and the only runtime symptom is a slower,
fatter step. The doctor (pipegoose_tpu/telemetry/doctor.py) diffs the
compiled program against the intended specs, names the offending
module path, and shows the inserted gather; the fixed spec then
compiles back to ZERO resharding-gather bytes and passes the same
guards that run in CI (scripts/mesh_doctor.py, scripts/ci_fast.sh).

    python examples/mesh_doctor_demo.py --fake-devices 8 --tp 2 --dp 4
"""
from __future__ import annotations

import argparse
import functools


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--ffn", type=int, default=256)
    ap.add_argument("--steps", type=int, default=2)  # unused; harness arg
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pipegoose_tpu import telemetry
    from pipegoose_tpu.distributed import ParallelContext

    H, F, B = args.hidden, args.ffn, args.batch
    ctx = ParallelContext(tensor_parallel_size=args.tp,
                          data_parallel_size=args.dp)
    mesh = ctx.mesh
    key = jax.random.PRNGKey(0)
    params = {
        "mlp": {
            "w1": jax.random.normal(key, (H, F)) * 0.02,
            "w2": jax.random.normal(key, (F, H)) * 0.02,
        },
        "head": {"w": jax.random.normal(key, (H, 8)) * 0.02},
    }
    # the INTENDED layout: Megatron column/row pair, tiny head replicated
    intended = {
        "mlp": {"w1": P(None, "tensor"), "w2": P("tensor", None)},
        "head": {"w": P()},
    }
    # the DEFECT: w1 left replicated — compiles fine, gathers silently
    broken = {
        "mlp": {"w1": P(), "w2": P("tensor", None)},
        "head": {"w": P()},
    }
    opt = optax.adam(1e-3)

    def loss_fn(p, x):  # single-device code; GSPMD derives collectives
        h = jax.nn.gelu(x @ p["mlp"]["w1"]) @ p["mlp"]["w2"]
        return ((h @ p["head"]["w"]) ** 2).mean()

    def build(spec_tree):
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), spec_tree,
            is_leaf=lambda x: isinstance(x, P))
        p = jax.tree_util.tree_map(jax.device_put, params, shardings)
        o = jax.jit(opt.init)(p)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(p, o, x):
            loss, grads = jax.value_and_grad(loss_fn)(p, x)
            updates, o = opt.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return jax.lax.with_sharding_constraint(p, shardings), o, loss

        return p, o, step

    x = jax.device_put(jnp.ones((B, H)),
                       NamedSharding(mesh, P("data", None)))

    def doctor(spec_tree):
        p, o, step = build(spec_tree)
        return telemetry.diagnose(
            step, p, o, x,
            intended=(intended, None, P("data", None)),
            labels=("params", "opt_state", "batch"),
            mesh=mesh, large_bytes=1 << 12,
        )

    # -- diagnose the broken build ----------------------------------------
    report = doctor(broken)
    offenders = report.sharding.mismatches()
    assert any("w1" in b.path for b in offenders), offenders
    print("DEFECT found by the doctor (no step was run):")
    for b in offenders:
        print(f"  {b.path}: intended {b.intended} -> actual {b.actual} "
              f"({', '.join(b.flags)})")
    gathers = [c for c in report.sharding.resharding_collectives
               if c.op in ("all-gather", "collective-permute", "all-to-all")]
    print(f"  partitioner-inserted gather traffic: "
          f"{sum(c.bytes for c in gathers)}B "
          f"({len(gathers)} collective(s))")
    try:
        telemetry.assert_matches_intended(report)
        raise SystemExit("guard unexpectedly passed")
    except telemetry.ShardingRegressionError as e:
        print(f"  guard fired as designed: {str(e).splitlines()[0]}")

    # -- the fix: build with the intended specs ---------------------------
    fixed = doctor(intended)
    telemetry.assert_matches_intended(fixed)
    # the auto path's partial-sum all-reduces are partitioner-derived by
    # construction; the guard pins that no GATHER resharding sneaks in
    telemetry.assert_no_resharding(fixed, allow=["all-reduce"])
    fixed_gathers = sum(
        c.bytes for c in fixed.sharding.resharding_collectives
        if c.op in ("all-gather", "collective-permute", "all-to-all"))
    assert fixed_gathers == 0, fixed.sharding.collectives
    print(f"\nFIXED plan: mismatches=0, resharding-gather bytes="
          f"{fixed_gathers}, replicated="
          f"{fixed.sharding.replicated_bytes}B/dev")
    print()
    print(fixed.format_table(max_rows=8))
    ctx.destroy()
    print(f"\ndone: doctor caught {len(offenders)} mis-sharded buffer(s); "
          f"fixed plan has zero resharding-gather bytes")


if __name__ == "__main__":
    main()
