"""Elastic-training demo: lose half the mesh mid-run, watch the run
replan, reshard, and keep going — no manual restart.

The run wires the full elasticity stack (docs/robustness.md):

- ``ChaosMonkey`` (testing/chaos.py) — a deterministic schedule
  injects a ``device_loss`` at step 3: half the 8-device fake cluster
  "is preempted", and the structured ``device_loss`` trigger fired
  through the ``FlightRecorder`` names the lost and surviving ids;
- ``ElasticRecovery`` (trainer/elastic.py) — consumes the trigger,
  picks a feasible layout at the surviving count (keep tp, shrink dp),
  rebuilds ``ParallelContext`` + the compiled hybrid step over exactly
  the survivors, cross-mesh-restores the step-2 orbax checkpoint, and
  lets ``fit`` resume — the same Python loop, now driving a 4-device
  program;
- the ``elastic_resume`` black box — ONE JSON artifact naming the lost
  devices, the chosen layout, the rewind step, and the doctor's
  zero-resharding verdict on the rebuilt program.

    python examples/elastic_training_demo.py --fake-devices 8 --tp 2 --dp 4
    JAX_PLATFORMS=cpu python examples/elastic_training_demo.py --steps 2

``--steps`` counts the POST-RESUME steps: the prologue (two clean
steps, a checkpoint at step 2, the loss at step 3) is fixed so the
demo always has a checkpoint to rewind to.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--steps", type=int, default=3,
                    help="steps to run AFTER the reshard-and-resume")
    ap.add_argument("--lose", type=int, default=4,
                    help="devices lost at step 3")
    ap.add_argument("--out-dir", default="elastic_out")
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pipegoose_tpu.distributed import ParallelContext
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.optim.zero import DistributedOptimizer
    from pipegoose_tpu.telemetry import FlightRecorder
    from pipegoose_tpu.testing import ChaosMonkey, ChaosSchedule, Injection
    from pipegoose_tpu.trainer import (
        CheckpointCallback,
        ElasticRecovery,
        Trainer,
    )

    os.makedirs(args.out_dir, exist_ok=True)
    ckpt_dir = os.path.join(args.out_dir, "ckpt")
    bb_dir = os.path.join(args.out_dir, "blackbox")
    # the demo owns its out-dir: a stale step_N checkpoint from a prior
    # run would make orbax refuse the save
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    shutil.rmtree(bb_dir, ignore_errors=True)

    cfg = bloom.BloomConfig(vocab_size=256, hidden_size=64, n_layer=2,
                            n_head=4)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    ctx = ParallelContext(tensor_parallel_size=args.tp,
                          data_parallel_size=args.dp)
    n0 = len(list(ctx.mesh.devices.flat))

    def loss_fn(p, ids):
        return bloom.loss_fn(p, ids, None, ids, cfg, tp_axis="tensor")

    def batches():
        rng = np.random.RandomState(0)
        # prologue (2 clean steps + the doomed step 3) + the resumed
        # tail; one extra batch replaces the rolled-back step's
        for _ in range(3 + args.steps + 1):
            yield jnp.asarray(
                rng.randint(1, cfg.vocab_size, (args.batch, args.seq))
            )

    recorder = FlightRecorder(bb_dir, capacity=32)
    monkey = ChaosMonkey(
        ChaosSchedule([Injection(3, "device_loss",
                                 (("n_lose", args.lose),))]),
        recorder=recorder, checkpoint_dir=ckpt_dir,
    )
    recovery = ElasticRecovery(ckpt_dir, max_restores=2, recorder=recorder)
    trainer = Trainer(
        loss_fn, params, bloom.tp_specs(params),
        DistributedOptimizer(optax.adam(1e-3), axis_name="data"), ctx,
        callbacks=[monkey, CheckpointCallback(ckpt_dir, every=2),
                   recorder, recovery],
    )
    state = trainer.fit(batches(), max_steps=3 + args.steps)

    assert recovery.restores == 1, recovery.restores
    assert all(np.isfinite(float(l)) for l in state.losses)
    (resume,) = recovery.resumes
    n1 = len(list(trainer.parallel_context.mesh.devices.flat))
    assert n1 == n0 - args.lose, (n0, n1)
    box = json.load(open(resume["dump_path"]))
    assert box["trigger"]["name"] == "elastic_resume"

    summary = {
        "devices_before": n0,
        "devices_after": n1,
        "lost_device_ids": resume["lost_device_ids"],
        "layout_after": resume["layout"],
        "restored_step": resume["restored_step"],
        "doctor_zero_resharding": resume["doctor_zero_resharding"],
        "steps": state.step,
        "final_loss": round(float(state.losses[-1]), 4),
        "black_box": resume["dump_path"],
    }
    print(json.dumps(summary, indent=2))
    print(
        f"done: lost {args.lose} of {n0} devices at step 3, replanned to "
        f"dp={resume['layout']['dp']} tp={resume['layout']['tp']} on the "
        f"{n1} survivors, cross-mesh-restored step "
        f"{resume['restored_step']}, and ran to step {state.step} — see "
        f"{os.path.basename(resume['dump_path'])}"
    )


if __name__ == "__main__":
    main()
