"""Communication-engine demo: ring-overlap TP + quantized gradient
all-reduce, verified with the mesh doctor (docs/comm.md, ISSUE 5).

Story: a hybrid TP x DP BLOOM train step spends wire time in two
places — the per-layer TP collectives serialized against the matmuls,
and the fp32 ZeRO gradient reduce-scatter. This demo builds the same
step three ways and shows, without trusting a stopwatch:

1. baseline — monolithic collectives, fp32 gradients;
2. overlap — ``config.overlap_tp=True``: the doctor's compiled
   schedule shows the layer traffic turned into ``ppermute`` ring hops
   (hideable behind the partial matmuls) with ZERO partitioner-inserted
   resharding, and the losses still match the baseline exactly;
3. int8 — ``grad_comm="int8"``: the gradient reduction's estimated
   wire bytes drop ~4x (doctor accounting + the ``comm.bytes_saved``
   gauge), and a short training run stays within tolerance of fp32.

    python examples/comm_overlap_demo.py --fake-devices 8 --tp 2 --dp 4
"""
from __future__ import annotations

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pipegoose_tpu import telemetry
    from pipegoose_tpu.distributed import ParallelContext
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.optim.zero import DistributedOptimizer
    from pipegoose_tpu.parallel import make_hybrid_train_step

    reg = telemetry.get_registry()
    reg.enable()
    ctx = ParallelContext(tensor_parallel_size=args.tp,
                          data_parallel_size=args.dp)
    base_cfg = dict(vocab_size=256, hidden_size=64, n_layer=2, n_head=4)
    rng = np.random.RandomState(0)
    batches = [
        jnp.asarray(rng.randint(0, 256, (args.batch, args.seq)))
        for _ in range(args.steps)
    ]

    def build_and_run(overlap, grad_comm):
        cfg = bloom.BloomConfig(**base_cfg, overlap_tp=overlap)
        params = bloom.init_params(cfg, jax.random.PRNGKey(0))
        specs = bloom.tp_specs(params)
        opt = DistributedOptimizer(
            optax.adam(5e-3), axis_name="data", grad_comm=grad_comm,
            error_feedback=grad_comm != "fp32",
        )

        def loss_fn(p, ids):
            return bloom.loss_fn(p, ids, None, ids, cfg, tp_axis="tensor")

        init_fn, make_step = make_hybrid_train_step(
            loss_fn, specs, opt, ctx, overlap_tp=overlap
        )
        opt_sds = jax.eval_shape(init_fn, params)
        step = make_step(params)
        report = telemetry.diagnose(
            step, params, opt_sds,
            jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int32),
            labels=("params", "opt_state", "batch"), mesh=ctx.mesh,
        )
        opt_state = init_fn(params)
        losses = []
        p = params
        for ids in batches:
            p, opt_state, loss = step(p, opt_state, ids)
            losses.append(float(loss))
        return losses, report

    # -- 1. baseline -------------------------------------------------------
    base_losses, base_rep = build_and_run(False, "fp32")
    print(f"baseline losses: {[round(x, 4) for x in base_losses]}")

    # -- 2. overlap: ppermute ring, zero resharding, exact losses ----------
    ovl_losses, ovl_rep = build_and_run(True, "fp32")
    telemetry.assert_no_resharding(ovl_rep)
    perms = [c for c in ovl_rep.sharding.collectives
             if c.op == "collective-permute" and c.source == "ppermute"]
    assert perms, "overlap step must ring with ppermute"
    assert all(abs(a - b) < 2e-3 for a, b in zip(ovl_losses, base_losses)), (
        ovl_losses, base_losses)
    print(f"overlap: {len(perms)} ppermute ring hops in the compiled "
          f"schedule, zero partitioner resharding, losses match "
          f"{[round(x, 4) for x in ovl_losses]}")

    # -- 3. int8 gradient reduction: ~4x fewer wire bytes ------------------
    int8_losses, int8_rep = build_and_run(False, "int8")

    def reduction_wire(rep):
        by_op = telemetry.wire_bytes_by_op(rep, axes=("data",))
        return by_op.get("reduce-scatter", 0) + by_op.get("all-to-all", 0)

    fp32_wire, int8_wire = reduction_wire(base_rep), reduction_wire(int8_rep)
    ratio = fp32_wire / max(int8_wire, 1)
    assert ratio >= 3.0, (fp32_wire, int8_wire)
    gap = max(abs(a - b) for a, b in zip(int8_losses, base_losses))
    assert gap < 5e-2, (int8_losses, base_losses)
    saved = reg.gauge("comm.bytes_saved").value
    print(f"int8 grad reduction: wire bytes {fp32_wire} -> {int8_wire} "
          f"({ratio:.1f}x less), comm.bytes_saved gauge = {saved:.0f}, "
          f"max loss gap vs fp32 = {gap:.4f}")

    ctx.destroy()
    print(f"\ndone: overlap rings {len(perms)} ppermutes with exact "
          f"losses; int8 cuts gradient wire bytes {ratio:.1f}x "
          f"(loss gap {gap:.4f})")


if __name__ == "__main__":
    main()
