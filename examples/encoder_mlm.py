"""Encoder (ALBERT) masked-LM training + fill-mask inference — the
bidirectional family through the same Trainer/mesh machinery as the
causal examples. The reference demonstrated encoders only via a DP test
on bert-tiny (tests/nn/data_parallel/test_data_parallel.py:18); here the
encoder trains TP x DP with ZeRO-1 and then fills masked tokens.

    python examples/encoder_mlm.py --fake-devices 8 --tp 2 --dp 4 --steps 20
"""
from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from pipegoose_tpu.distributed import ParallelContext
from pipegoose_tpu.models import albert
from pipegoose_tpu.optim.zero import DistributedOptimizer
from pipegoose_tpu.trainer import LossLoggerCallback, Trainer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--mask-rate", type=float, default=0.15)
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    ctx = ParallelContext(
        tensor_parallel_size=args.tp, data_parallel_size=args.dp
    )
    cfg = albert.AlbertConfig(
        vocab_size=2048, embedding_size=64, hidden_size=256, n_layer=4,
        n_head=8, intermediate_size=512, max_position_embeddings=args.seq,
    )
    params = albert.init_params(cfg, jax.random.PRNGKey(0))
    mask_id = cfg.vocab_size - 1  # reserve the last id as [MASK]

    # batch = dict(ids=corrupted inputs, labels=originals, lmask=masked
    # positions) — the BERT objective: predict the original token at
    # every [MASK] slot
    def loss_fn(p, batch):
        return albert.loss_fn(
            p, batch["ids"], None, batch["labels"], cfg, tp_axis="tensor",
            label_mask=batch["lmask"],
        )

    trainer = Trainer(
        loss_fn,
        params,
        albert.tp_specs(params),
        DistributedOptimizer(optax.adam(1e-3), axis_name="data"),
        ctx,
        batch_spec={"ids": P("data"), "labels": P("data"), "lmask": P("data")},
        callbacks=[LossLoggerCallback(every=5)],
    )

    rng = np.random.RandomState(0)

    def make_batch():
        # learnable synthetic language: token = f(position, phase) so
        # the bidirectional context + position embeddings genuinely
        # predict the masked slots (random ids would be unlearnable)
        phase = rng.randint(0, 4, (args.batch, 1))
        pos = np.arange(args.seq)[None, :]
        labels = (pos + phase * args.seq) % (cfg.vocab_size - 1)
        lmask = (rng.rand(args.batch, args.seq) < args.mask_rate)
        ids = np.where(lmask, mask_id, labels)
        return {
            "ids": jnp.asarray(ids),
            "labels": jnp.asarray(labels),
            "lmask": jnp.asarray(lmask.astype(np.int32)),
        }

    state = trainer.fit((make_batch() for _ in range(args.steps)),
                        max_steps=args.steps)
    last = (
        f"{float(state.last_loss):.4f}"
        if state.last_loss is not None else "n/a (no new steps)"
    )
    print(f"done: {state.step} steps, final loss {last}")

    # fill-mask inference on the trained params (single-device path)
    demo = make_batch()
    filled = albert.fill_mask(
        trainer.params, demo["ids"][:1], mask_id, cfg
    )
    n_masked = int(demo["lmask"][:1].sum())
    n_right = int(
        ((filled == demo["labels"][:1]) & (demo["lmask"][:1] > 0)).sum()
    )
    print(f"fill-mask: recovered {n_right}/{n_masked} masked tokens")


if __name__ == "__main__":
    main()
