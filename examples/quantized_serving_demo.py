"""Quantized BLOOM serving (ISSUE 10): int8/int4 weights through the
dequant-fused matmul + int8 paged KV, on the full cached+chunked
engine — watch greedy parity against the fp engine, the measured HBM
drop and page-capacity multiplier, and the planner's feasibility flip
(docs/serving.md "Quantized inference", pipegoose_tpu/quant/).

    python examples/quantized_serving_demo.py --fake-devices 8 --tp 2
    python examples/quantized_serving_demo.py --requests 8
"""
from __future__ import annotations

import argparse

import jax
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--page-size", type=int, default=4)
    ap.add_argument("--max-context", type=int, default=64)
    ap.add_argument("--steps", type=int, default=None,
                    help="cap max_new_tokens per request (smoke runs)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fake-devices", type=int, default=None)
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    from pipegoose_tpu.distributed import ParallelContext
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.planner import plan_serving_decode
    from pipegoose_tpu.planner.cost import CostModel
    from pipegoose_tpu.serving import Request, ServingEngine

    cfg = bloom.BloomConfig(vocab_size=64, hidden_size=64, n_layer=2,
                            n_head=4)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))

    # a Zipf-ish workload: most prompts share one hot prefix, so the
    # prefix cache and the quantized pages exercise the same pages
    rng = np.random.RandomState(args.seed)
    shared = rng.randint(1, 64, (13,))
    reqs = []
    for _ in range(args.requests):
        tail = rng.randint(1, 64, (int(rng.randint(2, 6)),))
        prompt = np.concatenate([shared, tail]) if rng.rand() < 0.7 else tail
        max_new = int(rng.randint(3, 8))
        if args.steps:
            max_new = min(max_new, args.steps)
        reqs.append((prompt, max_new))

    ctx = mesh = param_specs = None
    if args.tp > 1:
        dp = max(len(jax.devices()) // args.tp, 1)
        ctx = ParallelContext(tensor_parallel_size=args.tp,
                              data_parallel_size=dp)
        mesh, param_specs = ctx.mesh, bloom.tp_specs(params)

    try:
        def build(**quant):
            return ServingEngine(
                params, cfg, num_slots=args.slots, num_pages=32,
                page_size=args.page_size, max_context=args.max_context,
                mesh=mesh, param_specs=param_specs, prefix_cache=True,
                prefill_chunk=8, **quant,
            )

        def serve(eng):
            outs, metrics = eng.run(
                [Request(prompt=p, max_new_tokens=n) for p, n in reqs]
            )
            return [np.asarray(o.generated) for o in outs], metrics

        fp_eng = build()
        fp_tokens, _ = serve(fp_eng)
        fp_mem = fp_eng.memory_report()

        print("arm            parity  weights_B  kv_B     pages_vs_fp")
        rows = [("fp", {}), ("int8w", dict(weight_dtype="int8")),
                ("int4w", dict(weight_dtype="int4", weight_group_size=16)),
                ("int8w+int8kv", dict(weight_dtype="int8", kv_dtype="int8"))]
        capacity = 1.0
        for label, quant in rows:
            eng = build(**quant)
            tokens, _ = serve(eng)
            mem = eng.memory_report()
            identical = all(np.array_equal(a, b)
                            for a, b in zip(fp_tokens, tokens))
            assert identical, f"{label} diverged from the fp engine"
            if label == "int8w+int8kv":
                capacity = mem["kv"]["page_capacity_ratio"]
            print(f"{label:<14} {'exact':<7} "
                  f"{mem['weights']['total_bytes']:<10} "
                  f"{mem['kv']['total_bytes']:<8} "
                  f"{mem['kv']['page_capacity_ratio']:.2f}x")
        assert capacity >= 1.8, f"page capacity {capacity} < 1.8x"

        # the planner's view: a budget only the quantized layouts fit
        from pipegoose_tpu.planner.serving import (
            ServingCandidate,
            serving_kv_bytes,
            serving_weight_bytes,
        )
        fp_cand = ServingCandidate(1, "fp", "fp")
        q_cand = ServingCandidate(1, "int8", "int8")
        pages, ps = 256, 16
        budget = (serving_weight_bytes(cfg, fp_cand)
                  + serving_kv_bytes(cfg, fp_cand, pages, ps)
                  + serving_weight_bytes(cfg, q_cand)
                  + serving_kv_bytes(cfg, q_cand, pages, ps)) / 2
        plan = plan_serving_decode(
            cfg, 1, num_pages=pages, page_size=ps,
            cost_model=CostModel.for_device("cpu", hbm_bytes=budget),
        )
        by_name = {r["name"]: r for r in plan["rows"]}
        assert not by_name[fp_cand.name]["feasible"]
        assert by_name[q_cand.name]["feasible"]
        print(f"planner @ {budget / 1024:.0f}KiB budget: "
              f"[PRUNE] {by_name[fp_cand.name]['reason']}")
        print(f"planner @ {budget / 1024:.0f}KiB budget: "
              f"[ok]    {by_name[q_cand.name]['reason']}")

        print(
            f"done: {args.requests} quantized requests greedy-exact vs fp "
            f"(tp={args.tp}), {fp_mem['weights']['total_bytes']} -> "
            f"int8 weights, {capacity:.2f}x page capacity"
        )
    finally:
        if ctx is not None:
            ctx.destroy()


if __name__ == "__main__":
    main()
