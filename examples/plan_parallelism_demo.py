"""Parallelism-planner demo: "how do I run this model on 8 chips" as
one static search (pipegoose_tpu/planner/, docs/planner.md, ISSUE 7).

Story: choosing (dp, tp) x overlap x grad_comm by hand means compiling
and timing every combination on hardware. The planner does the search
with ZERO device time — every candidate is one shape-only lower+compile
through the mesh doctor, scored by wire bytes over the chip's
interconnect bandwidths, compiled FLOPs over its peak, and HBM peak
against its budget. The demo:

1. ranks the full layout space for a bloom-tiny model on a faked
   8-device mesh (infeasible layouts pruned with stated reasons);
2. shows the top-1 is a zero-resharding hybrid config — its embedded
   doctor report contains NO partitioner-inserted collectives (the
   compiled plan is exactly the intended plan);
3. shows the planner's reasoning: the ring-overlap + int8-wire
   candidates win because the cost model sees their tensor-axis time
   hidden and their gradient bytes cut — the same effects docs/comm.md
   measured on hardware.

    python examples/plan_parallelism_demo.py --fake-devices 8
"""
from __future__ import annotations

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--top-k", type=int, default=5)
    ap.add_argument("--steps", type=int, default=0,
                    help="unused (uniform example CLI; the planner "
                         "executes nothing)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import fake_cluster
        fake_cluster(args.fake_devices)

    import jax

    from pipegoose_tpu import telemetry
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.planner import (
        BloomPlanModel,
        CostModel,
        enumerate_candidates,
        run_plan,
    )

    reg = telemetry.get_registry()
    reg.enable()
    n = len(jax.devices())
    cfg = bloom.BloomConfig(
        vocab_size=128, hidden_size=64, n_layer=2, n_head=4
    )
    model = BloomPlanModel(cfg, batch=args.batch, seq=args.seq)
    # fp32-vs-int8 and overlap on/off is where the comm engine's wins
    # live; remat stays on (one knob fewer keeps the demo under a
    # minute — the CLI sweeps the full space)
    candidates = enumerate_candidates(
        n, grad_comms=("fp32", "int8"), remat=(True,)
    )
    print(f"enumerated {len(candidates)} candidate layout(s) for "
          f"{n} devices\n")
    report = run_plan(model, candidates, CostModel.for_device("cpu"))
    print(report.format_table(top_k=args.top_k))

    top = report.top
    assert top is not None, "no feasible candidate"
    b = top.breakdown

    # 2. the top-1 is a ZERO-RESHARDING config: its compiled schedule
    # contains only collectives the model wrote (ppermute ring hops,
    # the ZeRO reduce-scatter), nothing partitioner-inserted
    telemetry.assert_no_resharding(top.doctor)
    resharding = top.doctor.sharding.resharding_bytes
    print(f"\ntop-1 {top.name}: partitioner-inserted resharding bytes = "
          f"{resharding} (doctor-pinned zero)")

    # 3. the cost model's reasoning, in numbers
    print(f"top-1 anatomy: compute {b['compute_seconds'] * 1e3:.3f}ms + "
          f"comm {b['comm_seconds'] * 1e3:.3f}ms "
          f"({b['comm_seconds_by_axes']})")
    assert top.candidate.grad_comm == "int8" and top.candidate.overlap_tp, (
        "expected the ring-overlap + int8-wire candidate to rank first",
        top.name,
    )
    gauges = {k: reg.gauge(k).value for k in (
        "planner.candidates_evaluated", "planner.pruned_infeasible",
        "planner.top1_score",
    )}
    print(f"planner gauges: {gauges}")
    print(f"\ndone: ranked {len(report.ranked)} layouts "
          f"({len(report.pruned)} pruned with reasons); top-1 {top.name} "
          f"is a zero-resharding hybrid config")


if __name__ == "__main__":
    main()
