"""Continuous-batching BLOOM serving over the paged KV pool — mixed-
length requests multiplexed through a fixed slot set, A/B'd against
naive drain-then-refill padded batching (pipegoose_tpu/serving/,
docs/serving.md).

    python examples/serve_bloom.py --fake-devices 8 --tp 2
    python examples/serve_bloom.py --requests 12 --slots 4
"""
from __future__ import annotations

import argparse
import json

import jax
import numpy as np

from pipegoose_tpu.models import bloom


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--max-context", type=int, default=64)
    ap.add_argument("--steps", type=int, default=None,
                    help="cap max_new_tokens per request (smoke runs)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    from pipegoose_tpu.distributed import ParallelContext
    from pipegoose_tpu.serving import serving_ab_benchmark

    cfg = bloom.BloomConfig(vocab_size=256, hidden_size=128, n_layer=2,
                            n_head=4)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))

    # a mixed-length workload: short chats next to long completions —
    # exactly where padded batching wastes decode steps
    rng = np.random.RandomState(args.seed)
    specs = []
    for _ in range(args.requests):
        prompt_len = int(rng.randint(2, args.max_context // 2))
        max_new = int(rng.randint(2, args.max_context - prompt_len))
        if args.steps:
            max_new = min(max_new, args.steps)
        specs.append((prompt_len, max_new))

    ctx = mesh = param_specs = None
    if args.tp > 1:
        dp = max(len(jax.devices()) // args.tp, 1)
        ctx = ParallelContext(tensor_parallel_size=args.tp,
                              data_parallel_size=dp)
        mesh, param_specs = ctx.mesh, bloom.tp_specs(params)
    try:
        pool_pages = 1 + args.slots * (args.max_context // args.page_size)
        res = serving_ab_benchmark(
            params, cfg, specs, num_slots=args.slots, num_pages=pool_pages,
            page_size=args.page_size, max_context=args.max_context,
            mesh=mesh, param_specs=param_specs,
        )
    finally:
        if ctx is not None:
            ctx.destroy()

    print(json.dumps(res, indent=2))
    print(
        f"done: {args.requests} requests through {args.slots} slots "
        f"(tp={args.tp}), continuous/static decode-step ratio "
        f"{res['continuous']['decode_steps']}/{res['static']['decode_steps']}"
        f", throughput speedup {res['speedup']}x"
    )


if __name__ == "__main__":
    main()
