"""Request-tracing demo: a skewed-prefix serving replay with per-request
latency attribution, a live ops endpoint, and a /healthz probe that
flips to 503 under an injected decode stall.

The run wires the full request-observability stack (ISSUE 8,
docs/observability.md):

- ``ServingEngine(tracer=RequestTracer(...))`` — every request's
  lifecycle (admit, prefill chunks with cache-hit counts, first token,
  decode ticks, preemptions) is recorded and its TTFT/e2e decomposed
  into additive queue/prefill/decode/stall components;
- ``SLOMonitor`` — a TTFT SLO evaluated over fast+slow burn-rate
  windows, feeding /healthz;
- ``FlightRecorder`` — a (demo-injected) ``decode_stall`` trigger whose
  black box embeds the request timelines;
- ``OpsServer`` — /metrics (Prometheus text), /healthz (200 -> 503 on
  the stall), /debug/requests (the timelines as JSON), all on an
  ephemeral port;
- ``ChromeTraceExporter.add_request_timelines`` — one Perfetto track
  per decode slot, markers for preempt/COW, next to the host spans.

    python examples/request_trace_demo.py --fake-devices 8
    JAX_PLATFORMS=cpu python examples/request_trace_demo.py --requests 8
"""
from __future__ import annotations

import argparse
import json
import os
import shutil


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=10)
    ap.add_argument("--prefix-len", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=4)
    ap.add_argument("--steps", type=int, default=2,
                    help="accepted for the shared example-runner CLI; "
                         "serving runs are request-driven")
    ap.add_argument("--out-dir", default="reqtrace_out")
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    import jax
    from urllib.request import urlopen
    from urllib.error import HTTPError

    from pipegoose_tpu import telemetry
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.serving import Request, ServingEngine, make_skewed_replay

    shutil.rmtree(args.out_dir, ignore_errors=True)
    os.makedirs(args.out_dir, exist_ok=True)

    reg = telemetry.get_registry()
    reg.enable()

    cfg = bloom.BloomConfig(vocab_size=128, hidden_size=64, n_layer=2,
                            n_head=4)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))

    recorder = telemetry.FlightRecorder(args.out_dir, capacity=32,
                                        registry=reg)
    tracer = telemetry.RequestTracer(registry=reg,
                                     keep_completed=2 * args.requests)
    engine = ServingEngine(
        params, cfg, num_slots=2, num_pages=33, page_size=8,
        max_context=64, prefix_cache=True, prefill_chunk=16,
        recorder=recorder, registry=reg,
    )

    replay = make_skewed_replay(
        n_requests=args.requests, n_prefixes=2, prefix_len=args.prefix_len,
        suffix_lens=(2, 4, 6), max_new=args.max_new, vocab=128, seed=0,
    )

    def requests():
        return [Request(prompt=p, max_new_tokens=n) for p, n in replay]

    engine.run(requests())       # cold: compiles + seeds the prefix cache
    engine.attach_tracer(tracer)  # trace the WARM replay only, so the
    outs, metrics = engine.run(requests())   # attribution has no compiles

    # -- attribution table -------------------------------------------------
    summary = tracer.attribution_summary()
    rows = {r["uid"]: r for r in summary["requests"]}
    print("per-request latency attribution (seconds):")
    print(f"{'uid':>4} {'queue':>8} {'prefill':>8} {'decode':>8} "
          f"{'stall':>8} {'e2e':>8} {'ttft':>8} {'hit_tok':>7}")
    for o in outs:
        r = rows[o.uid]
        c = r["components"]
        print(f"{o.uid:>4} {c['queue_s']:>8.4f} {c['prefill_s']:>8.4f} "
              f"{c['decode_s']:>8.4f} {c['stall_s']:>8.4f} "
              f"{r['e2e_s']:>8.4f} {r['ttft_s']:>8.4f} "
              f"{r['hit_tokens']:>7}")
        assert abs(sum(c.values()) - r["e2e_s"]) <= 0.01 * r["e2e_s"]
    print(f"mean components: {summary['mean_components']}")
    print(f"cache hit share: {summary['cache_hit_share']:.2%}")

    # -- Perfetto export ---------------------------------------------------
    trace_path = os.path.join(args.out_dir, "request_trace.json")
    exporter = telemetry.ChromeTraceExporter(trace_path, registry=reg)
    exporter.add_request_timelines(tracer)
    exporter.write()
    exporter.close()

    # -- ops endpoint + injected stall -------------------------------------
    slo = telemetry.SLOMonitor(
        telemetry.default_serving_slos(ttft_objective_s=5.0),
        registry=reg, recorder=recorder,
    )
    ops = telemetry.OpsServer(registry=reg, port=0, slo=slo,
                              recorder=recorder, tracer=tracer)
    url = ops.start()
    assert url is not None
    metrics_text = urlopen(url + "/metrics", timeout=5).read().decode()
    n_samples = len(telemetry.parse_prometheus_text(metrics_text))
    hz = urlopen(url + "/healthz", timeout=5)
    assert hz.status == 200 and json.loads(hz.read())["ok"] is True
    print(f"/metrics: {n_samples} samples; /healthz: 200 ok")

    dbg = json.loads(urlopen(url + "/debug/requests", timeout=5).read())
    assert len(dbg["completed"]) >= args.requests

    # inject a decode stall: the watchdog path fires the same trigger a
    # real livelock would, black-boxing the request timelines
    trig = recorder.trigger_decode_stall(
        0, "demo-injected stall: queue head can never be admitted",
        context={"injected": True},
    )
    try:
        urlopen(url + "/healthz", timeout=5)
        raise AssertionError("/healthz stayed 200 under a stall trigger")
    except HTTPError as e:
        body = json.loads(e.read())
        assert e.code == 503 and body["problems"][0]["name"] == "decode_stall"
        print(f"/healthz after injected stall: 503 "
              f"({body['problems'][0]['reason']})")
    box = json.load(open(trig.dump_path))
    assert "request_timelines" in box
    ops.stop()

    print(json.dumps({
        "requests": len(outs),
        "decode_tokens_per_s": metrics["decode_tokens_per_s"],
        "cache_hit_share": round(summary["cache_hit_share"], 4),
        "mean_ttft_s": round(summary["mean_ttft_s"], 6),
        "ops_metrics_samples": n_samples,
        "black_box": trig.dump_path,
        "trace": trace_path,
    }, indent=2))
    print(
        f"done: {len(outs)} requests attributed "
        f"(hit share {summary['cache_hit_share']:.0%}), /healthz flipped "
        f"200->503 on the injected stall; open {trace_path} in "
        f"ui.perfetto.dev"
    )


if __name__ == "__main__":
    main()
