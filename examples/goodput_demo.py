"""Goodput & incident-ledger demo: where did the fleet's wall clock go,
and what did that crash actually cost?

The run walks the fleet goodput ledger (ISSUE 19,
docs/observability.md "Goodput & incidents"):

- a two-replica ``ControlPlane`` with ``goodput=True``: every
  replica-second of the run is attributed to exactly one class
  (productive / compile_warmup / idle / stall / suspect_probing /
  failed_quarantine / ...) under the conservation contract — per
  replica, class-seconds sum to alive wall within 1e-6 (asserted);
- a seeded ``replica_crash`` (the chaos harness) mid-run: the ledger
  mints ONE ``Incident`` joined to the ``chaos.injection``
  flight-recorder record (detection-latency ticks), accruing a
  capacity-gap integral in replica-seconds while the fleet runs
  degraded;
- ``rejoin`` closes the incident: MTTR (detection -> accepting again)
  and the SLO burn over the incident window land on the incident row
  (asserted > 0);
- the surfaces: the incident table on stdout, ``/debug/goodput`` on a
  live ``OpsServer``, and the per-replica STATE BAND track — one
  colored slice per class episode + incident instant markers — in a
  Perfetto trace next to the router's decision track.

    python examples/goodput_demo.py --fake-devices 8
    JAX_PLATFORMS=cpu python examples/goodput_demo.py --requests 12
"""
from __future__ import annotations

import argparse
import json
import os
import shutil


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=10)
    ap.add_argument("--crash-tick", type=int, default=4)
    ap.add_argument("--steps", type=int, default=2,
                    help="accepted for the shared example-runner CLI; "
                         "serving runs are request-driven")
    ap.add_argument("--out-dir", default="goodput_out")
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    from urllib.request import urlopen

    import jax

    from pipegoose_tpu import telemetry
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.serving import (
        Request,
        ServingEngine,
        make_skewed_replay,
    )
    from pipegoose_tpu.serving.control_plane import ControlPlane
    from pipegoose_tpu.testing.chaos import (
        ChaosMonkey,
        ChaosSchedule,
        Injection,
    )

    shutil.rmtree(args.out_dir, ignore_errors=True)
    os.makedirs(args.out_dir, exist_ok=True)

    cfg = bloom.BloomConfig(vocab_size=64, hidden_size=32, n_layer=2,
                            n_head=2)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    replay = make_skewed_replay(
        n_requests=args.requests, n_prefixes=3, prefix_len=32,
        suffix_lens=(2, 4), max_new=3, vocab=64, seed=0, n_tenants=2,
    )

    def factory(name, registry):
        return ServingEngine(params, cfg, num_slots=1, num_pages=33,
                             page_size=8, max_context=96,
                             prefix_cache=True, registry=registry)

    def reqs(seed=0):
        return [Request(prompt=p, max_new_tokens=n, tenant=t)
                for p, n, t in replay]

    # -- a crash mid-run: the ledger watches the whole arc ------------------
    recorder = telemetry.FlightRecorder(args.out_dir, capacity=256)
    plane = ControlPlane(factory, n_replicas=2, policy="cache_aware",
                         recorder=recorder, goodput=True)
    monkey = ChaosMonkey(
        ChaosSchedule([Injection(args.crash_tick, "replica_crash",
                                 (("replica", 1),))]),
        recorder=recorder,
    )
    outs, metrics = plane.run(reqs(), tick_hook=monkey.fleet_hook)
    print(f"crash run: {len(outs)}/{args.requests} requests finished "
          f"(salvage re-dispatched the victim's work)")

    # -- rejoin closes the incident: MTTR + capacity gap stop accruing ------
    plane.rejoin("replica1")
    outs2, _ = plane.run(reqs(seed=1))

    ledger = plane.goodput
    cons = ledger.conservation()
    assert cons["ok"], cons  # class-seconds == alive wall, per replica
    print(f"conservation: max error "
          f"{cons['max_error_s']:.2e}s across "
          f"{len(cons['replicas'])} replicas (contract: <= 1e-6)")

    summary = ledger.summary()
    print(f"goodput fraction {summary['goodput_fraction']:.2%} over "
          f"{summary['wall_seconds']:.2f}s fleet wall:")
    for klass, secs in sorted(summary["classes"].items(),
                              key=lambda kv: -kv[1]):
        print(f"  {klass:>18}: {secs:8.3f}s")

    # -- the incident table -------------------------------------------------
    incidents = ledger.report()["incident_log"]
    assert len(incidents) == 1, incidents
    inc = incidents[0]
    assert not inc["open"] and inc["resolved_by"] == "rejoin"
    assert inc["mttr_s"] > 0 and inc["capacity_gap_integral_s"] > 0
    print("incident ledger:")
    print(f"  #{inc['id']} {inc['kind']} on {inc['replica']} "
          f"(detected tick {inc['tick_detected']}, "
          f"injection join latency "
          f"{inc['detection_latency_ticks']} tick(s))")
    print(f"    MTTR {inc['mttr_s'] * 1e3:.1f}ms "
          f"({inc['mttr_ticks']} ticks, resolved by "
          f"{inc['resolved_by']}); capacity gap integral "
          f"{inc['capacity_gap_integral_s'] * 1e3:.1f} replica-ms")
    print(f"    salvaged uids {inc['salvaged_uids']}, lost "
          f"{inc['lost_uids']}; availability over window "
          f"{inc['slo_burn']['availability']:.2%}")

    # -- the surfaces: /debug/goodput + Perfetto state bands ----------------
    with telemetry.OpsServer(registry=plane.fleet, port=0,
                             fleet=plane.fleet_status,
                             goodput=ledger.report) as srv:
        body = json.loads(
            urlopen(srv.url + "/debug/goodput", timeout=5).read())
        assert body["incidents"] == 1 and body["conservation_ok"]
    trace_path = os.path.join(args.out_dir, "trace.json")
    with telemetry.ChromeTraceExporter(trace_path,
                                       registry=plane.registry) as exp:
        exp.add_goodput(ledger)
        exp.add_router_decisions(plane.router.decisions)
    print(
        f"done: {summary['goodput_fraction']:.2%} of "
        f"{summary['wall_seconds']:.2f} fleet replica-seconds were "
        f"productive; the crash cost "
        f"{inc['capacity_gap_integral_s'] * 1e3:.1f} replica-ms of "
        f"capacity (MTTR {inc['mttr_s'] * 1e3:.1f}ms); open "
        f"{trace_path} in ui.perfetto.dev for the state bands"
    )


if __name__ == "__main__":
    main()
