"""End-to-end telemetry demo: one observed training run + one observed
serving run, exported as a JSONL event stream and a Prometheus textfile
snapshot (pipegoose_tpu/telemetry/, docs/observability.md).

The artifacts carry: per-step train spans (``span.train.step.seconds``)
and events, a tokens/s gauge, an MFU gauge derived from the compiler's
own FLOP count of the jitted train step (``compiled_step_stats``), the
per-step comm-bytes gauge, and the serving engine's TTFT /
per-token-decode-latency histograms plus its occupancy time series.
Also cross-checks that engine telemetry agrees with the legacy
aggregate metrics dict (tokens/s within 1%).

    python examples/telemetry_demo.py --fake-devices 8 --tp 2 --dp 4
    JAX_PLATFORMS=cpu python examples/telemetry_demo.py --steps 5
"""
from __future__ import annotations

import argparse
import json
import os


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--out-dir", default="telemetry_out")
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works even where a "
                         "sitecustomize pins an accelerator platform)")
    args = ap.parse_args()
    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices
        force_cpu_devices(args.fake_devices)

    import jax
    import numpy as np
    import optax

    from pipegoose_tpu import telemetry
    from pipegoose_tpu.distributed import ParallelContext
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.optim.zero import DistributedOptimizer
    from pipegoose_tpu.serving import Request, ServingEngine
    from pipegoose_tpu.telemetry import TelemetryCallback
    from pipegoose_tpu.trainer import Trainer

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl_path = os.path.join(args.out_dir, "telemetry.jsonl")
    prom_path = os.path.join(args.out_dir, "metrics.prom")
    reg = telemetry.get_registry()
    exporter = telemetry.JSONLExporter(jsonl_path, registry=reg)

    cfg = bloom.BloomConfig(vocab_size=512, hidden_size=128, n_layer=2,
                            n_head=4)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))

    # -- observed training run -------------------------------------------
    ctx = ParallelContext(tensor_parallel_size=args.tp,
                          data_parallel_size=args.dp)

    def loss_fn(p, ids):
        return bloom.loss_fn(p, ids, None, ids, cfg, tp_axis="tensor")

    def batches():
        rng = np.random.RandomState(0)
        for _ in range(args.steps):
            yield rng.randint(0, cfg.vocab_size, (args.batch, args.seq))

    trainer = Trainer(
        loss_fn,
        params,
        bloom.tp_specs(params),
        DistributedOptimizer(optax.adam(1e-3), axis_name="data"),
        ctx,
        callbacks=[TelemetryCallback(
            jsonl=exporter,     # shared stream: serving lands in it too
            auto_cost=True,     # MFU + comm bytes from the compiled step
            fence=True,         # exact per-step device attribution
        )],
    )
    state = trainer.fit(batches(), max_steps=args.steps)

    # -- observed serving run (same registry, same JSONL stream) ---------
    rng = np.random.RandomState(7)
    reqs = []
    for _ in range(args.requests):
        plen = int(rng.randint(2, 12))
        reqs.append(Request(prompt=rng.randint(1, cfg.vocab_size, (plen,)),
                            max_new_tokens=int(rng.randint(2, 10))))
    engine = ServingEngine(params, cfg, num_slots=2, num_pages=32,
                           page_size=4, max_context=64, registry=reg)
    outs, metrics = engine.run(reqs)

    # telemetry must agree with the legacy aggregate dict (within 1%)
    tel_tps = reg.gauge("serving.tokens_per_s").value
    legacy_tps = metrics["decode_tokens_per_s"]
    drift = abs(tel_tps - legacy_tps) / max(legacy_tps, 1e-9)
    assert drift < 0.01, (tel_tps, legacy_tps)

    # -- export -----------------------------------------------------------
    exporter.export_snapshot(reg)
    exporter.close()
    telemetry.PrometheusTextfileExporter(prom_path).write(reg)

    snap = reg.snapshot()
    mfu = snap["gauges"].get("train.mfu")
    summary = {
        "train_steps": state.step,
        "final_loss": round(float(state.last_loss), 4),
        "train_tokens_per_s": round(snap["gauges"]["train.tokens_per_s"], 1),
        "train_mfu": round(mfu, 6) if mfu is not None else None,
        "step_p50_s": round(
            snap["histograms"]["span.train.step.seconds"]["p50"], 6),
        "serving_ttft_p50_s": round(
            snap["histograms"]["serving.ttft_seconds"]["p50"], 6),
        "serving_decode_token_p50_s": round(
            snap["histograms"]["serving.decode_token_seconds"]["p50"], 6),
        "serving_tokens_per_s": round(tel_tps, 2),
        "legacy_tokens_per_s": legacy_tps,
        "jsonl": jsonl_path,
        "prom": prom_path,
    }
    print(json.dumps(summary, indent=2))
    print(
        f"done: {state.step} train steps + {len(outs)} served requests "
        f"observed; tokens/s agreement drift {drift:.2%}; artifacts in "
        f"{args.out_dir}/"
    )


if __name__ == "__main__":
    main()
