"""Sharded token data loading.

The analog of the reference's input pipeline (torch DataLoader +
DistributedSampler, examples/hybrid_parallelism.py:26-28), standalone:

- ``TokenDataset``: a flat binary uint32 token file (the standard
  pre-tokenized corpus format), mmap'd;
- per-data-rank disjoint strided sharding with deterministic per-epoch
  shuffling (DistributedSampler semantics);
- a NATIVE C++ loader (native/dataloader.cpp) with a background
  prefetch thread and batch ring, compiled on demand via g++ and bound
  with ctypes (no pybind11 in the image); a pure-numpy fallback keeps
  everything working where no toolchain exists.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, Optional

import numpy as np

_NATIVE_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "dataloader.cpp",
)
_NATIVE_SO = os.path.join(os.path.dirname(_NATIVE_SRC), "libpgt_dataloader.so")
_lib = None
_lib_tried = False


def _load_native() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the native loader; None on any failure."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        if not os.path.exists(_NATIVE_SO) or os.path.getmtime(
            _NATIVE_SO
        ) < os.path.getmtime(_NATIVE_SRC):
            # compile to a private tmp path + atomic rename: concurrent
            # data-parallel rank processes racing g++ on the shared path
            # would otherwise dlopen a half-written file
            tmp = f"{_NATIVE_SO}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
                 _NATIVE_SRC, "-o", tmp],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, _NATIVE_SO)
        lib = ctypes.CDLL(_NATIVE_SO)
        lib.pgt_loader_open.restype = ctypes.c_void_p
        lib.pgt_loader_open.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.pgt_loader_windows.restype = ctypes.c_uint64
        lib.pgt_loader_windows.argtypes = [ctypes.c_void_p]
        lib.pgt_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32)
        ]
        lib.pgt_loader_set_epoch.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.pgt_loader_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def _splitmix64(x: int) -> int:
    M = (1 << 64) - 1
    x = (x + 0x9E3779B97F4A7C15) & M
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & M
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & M
    return x ^ (x >> 31)


def _permute(idx: int, n: int, key: int) -> int:
    """Bijection on [0, n): affine map mod 2^k cycle-walked into range —
    bit-identical to native/dataloader.cpp:permute, so native and
    fallback loaders yield the SAME batches."""
    mask = 1
    while mask < n:
        mask <<= 1
    mask -= 1
    a = _splitmix64(key) | 1
    b = _splitmix64(key ^ 0xDA3E39CB94B95BDB)
    x = idx
    while True:
        x = (a * x + b) & mask
        if x < n:
            return x


def write_token_file(tokens: np.ndarray, path: str) -> None:
    """Write a flat uint32 token corpus file."""
    np.asarray(tokens, dtype=np.uint32).tofile(path)


class TokenDataset:
    """Deterministic, sharded (batch, seq) windows over a token file.

    ``rank``/``world`` shard windows disjointly across data(-parallel)
    ranks, strided like torch's DistributedSampler; ``set_epoch``
    reshuffles (reference examples call sampler.set_epoch identically).
    """

    def __init__(
        self,
        path: str,
        batch: int,
        seq: int,
        rank: int = 0,
        world: int = 1,
        seed: int = 0,
        native: Optional[bool] = None,
    ):
        self.path, self.batch, self.seq = path, batch, seq
        self.rank, self.world, self.seed = rank, world, seed
        self.epoch = 0
        self._iter_token = 0  # newest live iterator wins (see __iter__)
        self._epoch_gen = 0  # bumped on EVERY set_epoch (even same epoch)
        self._closed = False
        self._handle = None
        self._lib = _load_native() if native in (None, True) else None
        if native is True and self._lib is None:
            raise RuntimeError("native loader requested but unavailable")
        if self._lib is not None:
            self._handle = self._lib.pgt_loader_open(
                path.encode(), batch, seq, rank, world, seed
            )
            if not self._handle:
                self._lib = None  # tiny file etc. -> fallback
        if self._lib is None:
            self._tokens = np.fromfile(path, dtype=np.uint32)

    # -- geometry -----------------------------------------------------------

    @property
    def windows_per_epoch(self) -> int:
        if self._closed:
            raise RuntimeError("TokenDataset is closed")
        if self._handle:
            return int(self._lib.pgt_loader_windows(self._handle))
        w = self._tokens.size // self.seq
        return (w // self.world) // self.batch * self.batch

    def steps_per_epoch(self) -> int:
        return self.windows_per_epoch // self.batch

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle for a new epoch; the native loader discards any
        prefetched old-epoch batches and restarts at step 0 (the fallback
        iterator observes the epoch change and resets its own counter)."""
        self.epoch = epoch
        self._epoch_gen += 1  # every call restarts at step 0, like native
        if self._handle:
            self._lib.pgt_loader_set_epoch(self._handle, epoch)

    # -- iteration ----------------------------------------------------------

    def _fill_numpy(self, step: int) -> np.ndarray:
        """Bit-identical mirror of the native fill() (same permutation,
        pinned by tests/data/test_dataloader.py::test_native_matches_fallback)."""
        per_rank = self.windows_per_epoch
        key = _splitmix64(self.seed) ^ _splitmix64(self.epoch + 1)
        out = np.empty((self.batch, self.seq), np.uint32)
        for b in range(self.batch):
            linear = (step * self.batch + b) % per_rank
            widx = _permute(linear, per_rank, key)
            gw = widx * self.world + self.rank
            out[b] = self._tokens[gw * self.seq : (gw + 1) * self.seq]
        return out

    def __iter__(self) -> Iterator[np.ndarray]:
        """Single live iterator: the native prefetch ring is one shared
        stream, and two interleaving iterators would silently steal each
        other's batches. Creating a new iterator invalidates the old one
        (it raises on its next pull instead of corrupting the epoch).
        The fallback's step counter is per-iterator and resets on EVERY
        ``set_epoch`` call (same-epoch restarts included — matching the
        native loader's unconditional step reset)."""
        self._iter_token += 1
        token = self._iter_token
        step = 0
        gen_seen = self._epoch_gen
        buf = np.empty(self.batch * self.seq, np.uint32)
        while True:
            if self._closed:
                raise RuntimeError("TokenDataset is closed")
            if token != self._iter_token:
                raise RuntimeError(
                    "a newer iterator was created for this TokenDataset; only "
                    "one live iterator is supported (shared prefetch stream)"
                )
            if self._handle:
                self._lib.pgt_loader_next(
                    self._handle, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
                )
                yield buf.reshape(self.batch, self.seq).copy()
            else:
                if gen_seen != self._epoch_gen:
                    gen_seen = self._epoch_gen
                    step = 0
                yield self._fill_numpy(step)
                step += 1

    def take(self, n: int):
        it = iter(self)
        return [next(it) for _ in range(n)]

    def close(self) -> None:
        if self._handle:
            self._lib.pgt_loader_close(self._handle)
            self._handle = None
        self._closed = True

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
