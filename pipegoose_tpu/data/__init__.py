from pipegoose_tpu.data.dataloader import TokenDataset, write_token_file

__all__ = ["TokenDataset", "write_token_file"]
