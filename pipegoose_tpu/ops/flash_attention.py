"""Fused flash attention (Pallas, TPU).

The hot op of every model here is causal self-attention with an additive
ALiBi bias. XLA's default lowering materializes the (S, S) score matrix
in HBM; this kernel computes softmax(QK^T * scale + alibi + causal) V
blockwise in VMEM with the online-softmax recurrence — O(S) memory, MXU
matmuls, one pass over K/V per Q block.

Kernel structure (canonical TPU flash attention):
- grid = (batch*heads, n_q_blocks, n_kv_blocks); the kv dimension is
  sequential ("arbitrary") so the (m, l, acc) scratch carries across kv
  steps for a fixed (bh, q) program;
- per-head ALiBi slope arrives via scalar prefetch (SMEM);
- fully-masked kv blocks (entirely above the causal diagonal) are
  skipped with pl.when — ~2x fewer FLOPs for causal attention;
- backward: custom_vjp falls back to the XLA attention expression with
  rematerialization (correct gradients; a fused backward kernel is a
  planned optimization).

Reference framework has no kernels at all (its README advertises "fused
kernels"; grep finds none — SURVEY.md, "Scale/completeness caveat").
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e9


def _pick_block(n: int, target: int = 128) -> int:
    for b in (target, 64, 32, 16, 8):
        if n % b == 0:
            return b
    return n


def _flash_fwd_pallas(q, k, v, slopes, scale, causal, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s, hd = q.shape  # (batch*heads, seq, head_dim)
    nq, nk = s // block_q, s // block_k

    def kernel(slope_ref, q_ref, k_ref, v_ref, o_ref, m_sc, l_sc, acc_sc):
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            m_sc[:] = jnp.full_like(m_sc, NEG_INF)
            l_sc[:] = jnp.zeros_like(l_sc)
            acc_sc[:] = jnp.zeros_like(acc_sc)

        q_start = qi * block_q
        k_start = ki * block_k

        # skip blocks fully above the causal diagonal
        @pl.when(k_start <= q_start + block_q - 1 if causal else True)
        def _compute():
            qb = q_ref[0].astype(jnp.float32)  # (BQ, hd)
            kb = k_ref[0].astype(jnp.float32)  # (BK, hd)
            vb = v_ref[0].astype(jnp.float32)
            s_blk = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # (BQ, BK)

            k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            slope = slope_ref[0]
            s_blk = s_blk + slope * k_pos.astype(jnp.float32)
            if causal:
                q_pos = q_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                s_blk = jnp.where(k_pos <= q_pos, s_blk, NEG_INF)

            m_prev = m_sc[:, 0]
            m_new = jnp.maximum(m_prev, s_blk.max(axis=1))
            p = jnp.exp(s_blk - m_new[:, None])
            alpha = jnp.exp(m_prev - m_new)
            l_sc[:, 0] = l_sc[:, 0] * alpha + p.sum(axis=1)
            acc_sc[:] = acc_sc[:] * alpha[:, None] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_sc[:, 0] = m_new

        @pl.when(ki == nk - 1)
        def _finish():
            denom = jnp.maximum(l_sc[:, 0], 1e-30)
            o_ref[0] = (acc_sc[:] / denom[:, None]).astype(o_ref.dtype)

    grid = (bh, nq, nk)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1,), lambda b, i, j: (b,), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(slopes, q, k, v)
    return out


def _xla_reference(q, k, v, slopes, scale, causal):
    """Plain XLA attention with the same semantics (used for backward and
    as the non-TPU fallback)."""
    bh, s, hd = q.shape
    scores = jnp.einsum(
        "bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    k_pos = jnp.arange(s)
    scores = scores + slopes[:, None, None] * k_pos[None, None, :].astype(jnp.float32)
    if causal:
        keep = k_pos[None, :] <= jnp.arange(s)[:, None]
        scores = jnp.where(keep[None], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, slopes, scale, causal, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s = q.shape[1]
    bq, bk = _pick_block(s), _pick_block(s)
    return _flash_fwd_pallas(q, k, v, slopes, scale, causal, bq, bk, interpret)


def _flash_fwd(q, k, v, slopes, scale, causal, interpret):
    return _flash(q, k, v, slopes, scale, causal, interpret), (q, k, v, slopes)


def _flash_bwd(scale, causal, interpret, res, g):
    q, k, v, slopes = res
    _, vjp = jax.vjp(lambda q, k, v: _xla_reference(q, k, v, slopes, scale, causal), q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, jnp.zeros_like(slopes)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,  # (B, S, nh, hd)
    k: jax.Array,
    v: jax.Array,
    alibi_slopes: Optional[jax.Array] = None,  # (nh,)
    causal: bool = True,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """BLOOM-shaped fused attention. Returns (B, S, nh, hd)."""
    b, s, nh, hd = q.shape
    if scale is None:
        scale = hd**-0.5
    if alibi_slopes is None:
        alibi_slopes = jnp.zeros((nh,), jnp.float32)
    slopes = jnp.broadcast_to(alibi_slopes[None], (b, nh)).reshape(b * nh)

    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(b * nh, s, hd)

    out = _flash(flat(q), flat(k), flat(v), slopes.astype(jnp.float32),
                 float(scale), causal, interpret)
    return out.reshape(b, nh, s, hd).transpose(0, 2, 1, 3)
