"""Fused flash attention (Pallas, TPU) — forward AND backward.

The hot op of every model here is causal self-attention with an additive
ALiBi bias. XLA's default lowering materializes the (S, S) score matrix
in HBM; these kernels compute softmax(QK^T * scale + bias) V blockwise
in VMEM with the online-softmax recurrence — O(S) memory, MXU matmuls,
one pass over K/V per Q block.

Kernel structure (canonical TPU flash attention):
- forward: grid = (batch*heads, n_q_blocks, n_kv_blocks); the kv
  dimension is sequential ("arbitrary") so the (m, l, acc) scratch
  carries across kv steps for a fixed (bh, q) program. Also emits the
  per-row logsumexp for the backward.
- backward: two kernels recomputing the probabilities from the saved
  logsumexp (no (S,S) materialization):
  dq:  grid (bh, nq, nk), kv sequential, accumulates dS @ K;
  dkv: grid (bh, nk, nq), q sequential, accumulates dS^T @ Q and P^T @ dO;
  with delta = rowsum(dO * O) computed in plain XLA.
- per-head ALiBi slope arrives via scalar prefetch (SMEM);
- padding masks are supported via two per-key arrays: ``kv_pos`` (the
  mask-aware ALiBi position, matching BLOOM's (cumsum(mask)-1)*mask)
  and ``kv_neg`` (0 for valid keys, NEG_INF for padded ones). The
  finite NEG_INF keeps fully-masked rows NaN-free (uniform garbage
  probs; those rows are masked out of the loss downstream).
- blocks fully above the causal diagonal are skipped with pl.when —
  ~2x fewer FLOPs for causal attention.

Reference framework has no kernels at all (its README advertises "fused
kernels"; grep finds none — SURVEY.md, "Scale/completeness caveat").
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9


def _pick_block(n: int, target: int = 128) -> int:
    """Largest power-of-two block <= target dividing n (sequence lengths
    here are powers of two in practice; tiny/odd n fall back to n).

    Defaults tuned on a v5e (scripts/sweep_tpu_perf.py, S=2048 bf16):
    kv blocks of 512 run the fwd kernel 2.5x faster than 128 (fewer
    grid steps per (bh, q) program, better MXU occupancy); 1024 wedges
    the remote compiler. Query blocks stay at 128 (the parallel dim)."""
    b = target
    while b >= 8:
        if n % b == 0:
            return b
        b //= 2
    return n


def mask_to_kv_bias(attention_mask: jax.Array):
    """(B, S) 1/0 mask -> (kv_pos, kv_neg) f32 kernel bias inputs:
    mask-aware ALiBi position (BLOOM's (cumsum(mask)-1)*mask) and 0 /
    NEG_INF key validity. Single source for the kernel and the models."""
    m = attention_mask.astype(jnp.float32)
    kv_pos = (jnp.cumsum(m, axis=-1) - 1.0) * m
    kv_neg = (1.0 - m) * NEG_INF
    return kv_pos, kv_neg


def _bias_block(slope, kpos_ref, kneg_ref, q_start, k_start, block_q, block_k,
                causal, window=None):
    """Additive bias for one (BQ, BK) score block: ALiBi + padding +
    causal (+ optional sliding window: key within ``window`` positions
    behind the query, Mistral/Mixtral semantics)."""
    kp = kpos_ref[0, 0].astype(jnp.float32)  # (BK,)
    kn = kneg_ref[0, 0].astype(jnp.float32)
    bias = slope * kp[None, :] + kn[None, :]
    if causal or window is not None:
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_idx = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        keep = jnp.ones((block_q, block_k), bool)
        if causal:
            keep = keep & (k_idx <= q_pos)
        if window is not None:
            keep = keep & (q_pos - k_idx < window)
        bias = jnp.where(keep, bias, NEG_INF)
    return bias


def _flash_fwd_pallas(q, k, v, slopes, kpos, kneg, scale, causal,
                      block_q, block_k, interpret, g=1, window=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s, hd = q.shape  # (batch*query_heads, seq, head_dim)
    # GQA: k/v (and their per-key biases) carry batch*kv_heads rows and
    # are shared by g query heads each via the index maps — never
    # repeated in HBM (g=1 is plain MHA)
    nq, nk = s // block_q, s // block_k

    def kernel(slope_ref, q_ref, k_ref, v_ref, kpos_ref, kneg_ref,
               o_ref, lse_ref, m_sc, l_sc, acc_sc):
        qi = pl.program_id(1)
        ki = pl.program_id(2)
        slope = slope_ref[pl.program_id(0)]

        @pl.when(ki == 0)
        def _init():
            m_sc[:] = jnp.full_like(m_sc, NEG_INF)
            l_sc[:] = jnp.zeros_like(l_sc)
            acc_sc[:] = jnp.zeros_like(acc_sc)

        q_start = qi * block_q
        k_start = ki * block_k

        # skip blocks fully above the causal diagonal or fully below
        # the sliding window
        keep_blk = k_start <= q_start + block_q - 1 if causal else True
        if window is not None:
            keep_blk = keep_blk & (k_start + block_k - 1 >= q_start - window + 1)

        @pl.when(keep_blk)
        def _compute():
            qb = q_ref[0].astype(jnp.float32)  # (BQ, hd)
            kb = k_ref[0].astype(jnp.float32)  # (BK, hd)
            vb = v_ref[0].astype(jnp.float32)
            s_blk = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # (BQ, BK)
            s_blk = s_blk + _bias_block(
                slope, kpos_ref, kneg_ref,
                q_start, k_start, block_q, block_k, causal, window,
            )

            m_prev = m_sc[:, 0]
            m_new = jnp.maximum(m_prev, s_blk.max(axis=1))
            p = jnp.exp(s_blk - m_new[:, None])
            alpha = jnp.exp(m_prev - m_new)
            l_sc[:, 0] = l_sc[:, 0] * alpha + p.sum(axis=1)
            acc_sc[:] = acc_sc[:] * alpha[:, None] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_sc[:, 0] = m_new

        @pl.when(ki == nk - 1)
        def _finish():
            l = jnp.maximum(l_sc[:, 0], 1e-30)
            o_ref[0] = (acc_sc[:] / l[:, None]).astype(o_ref.dtype)
            lse_ref[0, 0] = m_sc[:, 0] + jnp.log(l)

    grid = (bh, nq, nk)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=[
                pl.BlockSpec((bh,), lambda b, i, j: (0,), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, i, j: (b // g, j, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, i, j: (b // g, j, 0)),
                pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b // g, 0, j)),
                pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b // g, 0, j)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, hd), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(slopes, q, k, v, kpos[:, None, :], kneg[:, None, :])
    return out, lse[:, 0, :]


def _flash_dq_pallas(q, k, v, do, lse, delta, slopes, kpos, kneg,
                     scale, causal, block_q, block_k, interpret, g=1, window=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s, hd = q.shape
    nq, nk = s // block_q, s // block_k

    def kernel(slope_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               kpos_ref, kneg_ref, dq_ref, dq_sc):
        qi = pl.program_id(1)
        ki = pl.program_id(2)
        slope = slope_ref[pl.program_id(0)]

        @pl.when(ki == 0)
        def _init():
            dq_sc[:] = jnp.zeros_like(dq_sc)

        q_start = qi * block_q
        k_start = ki * block_k

        keep_blk = k_start <= q_start + block_q - 1 if causal else True
        if window is not None:
            keep_blk = keep_blk & (k_start + block_k - 1 >= q_start - window + 1)

        @pl.when(keep_blk)
        def _compute():
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            dob = do_ref[0].astype(jnp.float32)
            s_blk = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            s_blk = s_blk + _bias_block(
                slope, kpos_ref, kneg_ref,
                q_start, k_start, block_q, block_k, causal, window,
            )
            p = jnp.exp(s_blk - lse_ref[0, 0][:, None])  # (BQ, BK)
            dp = jax.lax.dot_general(
                dob, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (BQ, BK)
            ds = p * (dp - delta_ref[0, 0][:, None])
            dq_sc[:] += scale * jax.lax.dot_general(
                ds, kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        @pl.when(ki == nk - 1)
        def _finish():
            dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)

    grid = (bh, nq, nk)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=[
                pl.BlockSpec((bh,), lambda b, i, j: (0,), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, i, j: (b // g, j, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, i, j: (b // g, j, 0)),
                pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b // g, 0, j)),
                pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b // g, 0, j)),
            ],
            out_specs=pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, hd), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(slopes, q, k, v, do, lse[:, None, :], delta[:, None, :],
      kpos[:, None, :], kneg[:, None, :])


def _flash_dkv_pallas(q, k, v, do, lse, delta, slopes, kpos, kneg,
                      scale, causal, block_q, block_k, interpret, g=1, window=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s, hd = q.shape
    # outputs are PER QUERY HEAD (b*nh rows) even under GQA — the caller
    # sums the g group contributions into the (b*nkv)-row dk/dv (a write
    # race inside the kernel is not expressible; the XLA sum is fused)
    nq, nk = s // block_q, s // block_k

    def kernel(slope_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               kpos_ref, kneg_ref, dk_ref, dv_ref, dk_sc, dv_sc):
        kj = pl.program_id(1)
        qi = pl.program_id(2)
        slope = slope_ref[pl.program_id(0)]

        @pl.when(qi == 0)
        def _init():
            dk_sc[:] = jnp.zeros_like(dk_sc)
            dv_sc[:] = jnp.zeros_like(dv_sc)

        q_start = qi * block_q
        k_start = kj * block_k

        keep_blk = k_start <= q_start + block_q - 1 if causal else True
        if window is not None:
            keep_blk = keep_blk & (k_start + block_k - 1 >= q_start - window + 1)

        @pl.when(keep_blk)
        def _compute():
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            dob = do_ref[0].astype(jnp.float32)
            s_blk = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            s_blk = s_blk + _bias_block(
                slope, kpos_ref, kneg_ref,
                q_start, k_start, block_q, block_k, causal, window,
            )
            p = jnp.exp(s_blk - lse_ref[0, 0][:, None])  # (BQ, BK)
            dv_sc[:] += jax.lax.dot_general(
                p, dob, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # P^T @ dO -> (BK, hd)
            dp = jax.lax.dot_general(
                dob, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta_ref[0, 0][:, None])
            dk_sc[:] += scale * jax.lax.dot_general(
                ds, qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # dS^T @ Q -> (BK, hd)

        @pl.when(qi == nq - 1)
        def _finish():
            dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)

    grid = (bh, nk, nq)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=[
                pl.BlockSpec((bh,), lambda b, j, i: (0,), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, block_q, hd), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, j, i: (b // g, j, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, j, i: (b // g, j, 0)),
                pl.BlockSpec((1, block_q, hd), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
                pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b // g, 0, j)),
                pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b // g, 0, j)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, hd), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, j, i: (b, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, hd), jnp.float32),
                pltpu.VMEM((block_k, hd), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, hd), k.dtype),
            jax.ShapeDtypeStruct((bh, s, hd), v.dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(slopes, q, k, v, do, lse[:, None, :], delta[:, None, :],
      kpos[:, None, :], kneg[:, None, :])


def _flash_chunk_pallas(q, k, v, slopes, qpos, kpos, kneg, m0, l0, acc0,
                        scale, block_q, block_k, interpret, g=1):
    """Stateful flash chunk for ring attention: consume the incoming
    online-softmax state (m, l, acc), attend local Q against ONE K/V
    chunk, and return the updated UNNORMALIZED state. The causal mask is
    value-based (global position arrays ``qpos``/``kpos``), so the same
    kernel serves any ring rotation; normalization happens once after
    the last ring step."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, hd = q.shape
    skv = k.shape[1]
    nq, nk = sq // block_q, skv // block_k

    def kernel(slope_ref, q_ref, k_ref, v_ref, qpos_ref, kpos_ref, kneg_ref,
               m0_ref, l0_ref, acc0_ref, m_ref, l_ref, acc_ref,
               m_sc, l_sc, acc_sc):
        ki = pl.program_id(2)
        slope = slope_ref[pl.program_id(0)]

        @pl.when(ki == 0)
        def _init():
            m_sc[:, 0] = m0_ref[0, 0]
            l_sc[:, 0] = l0_ref[0, 0]
            acc_sc[:] = acc0_ref[0].astype(jnp.float32)

        qp = qpos_ref[0, 0].astype(jnp.float32)  # (BQ,)
        kp = kpos_ref[0, 0].astype(jnp.float32)  # (BK,)

        # value-based causal block skip (positions are dynamic here, so
        # the non-ring kernel's static index skip doesn't apply): a block
        # whose every key is in the future of every query adds NEG_INF
        # everywhere — skip both matmuls, ~2x fewer FLOPs causal
        @pl.when(jnp.min(kp) <= jnp.max(qp))
        def _compute():
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            s_blk = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            kn = kneg_ref[0, 0].astype(jnp.float32)
            s_blk = s_blk + slope * kp[None, :] + kn[None, :]
            s_blk = s_blk + jnp.where(kp[None, :] <= qp[:, None], 0.0, NEG_INF)

            m_prev = m_sc[:, 0]
            m_new = jnp.maximum(m_prev, s_blk.max(axis=1))
            p = jnp.exp(s_blk - m_new[:, None])
            alpha = jnp.exp(m_prev - m_new)
            l_sc[:, 0] = l_sc[:, 0] * alpha + p.sum(axis=1)
            acc_sc[:] = acc_sc[:] * alpha[:, None] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_sc[:, 0] = m_new

        @pl.when(ki == nk - 1)
        def _finish():
            m_ref[0, 0] = m_sc[:, 0]
            l_ref[0, 0] = l_sc[:, 0]
            acc_ref[0] = acc_sc[:]

    grid = (bh, nq, nk)
    m, l, acc = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=[
                pl.BlockSpec((bh,), lambda b, i, j: (0,), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, i, j: (b // g, j, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, i, j: (b // g, j, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b // g, 0, j)),
                pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b // g, 0, j)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, hd), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
            jax.ShapeDtypeStruct((bh, sq, hd), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(slopes, q, k, v, qpos[:, None, :], kpos[:, None, :], kneg[:, None, :],
      m0[:, None, :], l0[:, None, :], acc0)
    return m[:, 0, :], l[:, 0, :], acc


def _xla_chunk(q, k, v, slopes, qpos, kpos, kneg, m, l, acc, scale):
    """Dense-math mirror of the chunk kernel's online-softmax update —
    the backward of :func:`flash_ring_chunk` differentiates THIS (one
    transient (Sq, Skv) block per ring step, rematerialized)."""
    s = jnp.einsum(
        "bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    s = s + slopes[:, None, None] * kpos[:, None, :] + kneg[:, None, :]
    s = s + jnp.where(kpos[:, None, :] <= qpos[:, :, None], 0.0, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + p.sum(axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bqk,bkd->bqd", p, v.astype(jnp.float32)
    )
    return m_new, l_new, acc_new


def flash_ring_chunk(q, k, v, slopes, qpos, kpos, kneg, m, l, acc,
                     scale, interpret, g=1):
    """One FORWARD ring step of flash attention: fused Pallas update of
    the online-softmax state over the resident K/V chunk (no (Sq, Skv)
    score materialization). NOT differentiable on its own — the ring
    owns the backward (see nn/sequence_parallel/ring_attention.py:
    ring_flash_attention, which runs a second gradient ring using
    flash_chunk_dq / flash_chunk_dkv with the FINAL logsumexp), so no
    per-step residuals are stacked by the forward scan. All arrays are
    in the flattened (batch*heads, seq, head_dim) layout; state is f32."""
    interpret = _resolve_interpret(interpret)
    bq, bk = _pick_block(q.shape[1], 128), _pick_block(k.shape[1], 512)
    return _flash_chunk_pallas(
        q, k, v, slopes, qpos, kpos, kneg, m, l, acc, scale, bq, bk, interpret, g
    )


def _chunk_dq_pallas(q, k, v, do, lse, delta, slopes, qpos, kpos, kneg,
                     scale, block_q, block_k, interpret, g=1):
    """dQ contribution of ONE ring chunk, from the FINAL logsumexp (the
    standard flash backward identity p = exp(s - lse) holds globally, so
    per-chunk contributions just add). Position-array causal mask with a
    value-based fully-future block skip, like the forward chunk."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, hd = q.shape
    skv = k.shape[1]
    nq, nk = sq // block_q, skv // block_k

    def kernel(slope_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               qpos_ref, kpos_ref, kneg_ref, dq_ref, dq_sc):
        ki = pl.program_id(2)
        slope = slope_ref[pl.program_id(0)]

        @pl.when(ki == 0)
        def _init():
            dq_sc[:] = jnp.zeros_like(dq_sc)

        qp = qpos_ref[0, 0].astype(jnp.float32)
        kp = kpos_ref[0, 0].astype(jnp.float32)

        @pl.when(jnp.min(kp) <= jnp.max(qp))
        def _compute():
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            dob = do_ref[0].astype(jnp.float32)
            s_blk = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            s_blk = s_blk + slope * kp[None, :] + kneg_ref[0, 0][None, :]
            s_blk = s_blk + jnp.where(kp[None, :] <= qp[:, None], 0.0, NEG_INF)
            p = jnp.exp(s_blk - lse_ref[0, 0][:, None])
            dp = jax.lax.dot_general(
                dob, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta_ref[0, 0][:, None])
            dq_sc[:] += scale * jax.lax.dot_general(
                ds, kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        @pl.when(ki == nk - 1)
        def _finish():
            dq_ref[0] = dq_sc[:]

    grid = (bh, nq, nk)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=[
                pl.BlockSpec((bh,), lambda b, i, j: (0,), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, i, j: (b // g, j, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, i, j: (b // g, j, 0)),
                pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
                pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b // g, 0, j)),
                pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b // g, 0, j)),
            ],
            out_specs=pl.BlockSpec((1, block_q, hd), lambda b, i, j: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, hd), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, sq, hd), jnp.float32),  # per q-head
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(slopes, q, k, v, do, lse[:, None, :], delta[:, None, :],
      qpos[:, None, :], kpos[:, None, :], kneg[:, None, :])


def _chunk_dkv_pallas(q, k, v, do, lse, delta, slopes, qpos, kpos, kneg,
                      scale, block_q, block_k, interpret, g=1):
    """dK/dV contributions of ONE ring chunk from THIS rank's queries
    (accumulated into ring-riding gradient carriers by the caller)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, hd = q.shape
    skv = k.shape[1]
    nq, nk = sq // block_q, skv // block_k

    def kernel(slope_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               qpos_ref, kpos_ref, kneg_ref, dk_ref, dv_ref, dk_sc, dv_sc):
        qi = pl.program_id(2)
        slope = slope_ref[pl.program_id(0)]

        @pl.when(qi == 0)
        def _init():
            dk_sc[:] = jnp.zeros_like(dk_sc)
            dv_sc[:] = jnp.zeros_like(dv_sc)

        qp = qpos_ref[0, 0].astype(jnp.float32)
        kp = kpos_ref[0, 0].astype(jnp.float32)

        @pl.when(jnp.min(kp) <= jnp.max(qp))
        def _compute():
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            dob = do_ref[0].astype(jnp.float32)
            s_blk = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            s_blk = s_blk + slope * kp[None, :] + kneg_ref[0, 0][None, :]
            s_blk = s_blk + jnp.where(kp[None, :] <= qp[:, None], 0.0, NEG_INF)
            p = jnp.exp(s_blk - lse_ref[0, 0][:, None])
            dv_sc[:] += jax.lax.dot_general(
                p, dob, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                dob, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta_ref[0, 0][:, None])
            dk_sc[:] += scale * jax.lax.dot_general(
                ds, qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        @pl.when(qi == nq - 1)
        def _finish():
            dk_ref[0] = dk_sc[:]
            dv_ref[0] = dv_sc[:]

    grid = (bh, nk, nq)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=[
                pl.BlockSpec((bh,), lambda b, j, i: (0,), memory_space=pltpu.SMEM),
                pl.BlockSpec((1, block_q, hd), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, j, i: (b // g, j, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, j, i: (b // g, j, 0)),
                pl.BlockSpec((1, block_q, hd), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
                pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b // g, 0, j)),
                pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b // g, 0, j)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, hd), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_k, hd), lambda b, j, i: (b, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, hd), jnp.float32),
                pltpu.VMEM((block_k, hd), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, skv, hd), jnp.float32),
            jax.ShapeDtypeStruct((bh, skv, hd), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(slopes, q, k, v, do, lse[:, None, :], delta[:, None, :],
      qpos[:, None, :], kpos[:, None, :], kneg[:, None, :])


def flash_chunk_dq(q, k, v, do, lse, delta, slopes, qpos, kpos, kneg,
                   scale, interpret, g=1):
    interpret = _resolve_interpret(interpret)
    bq, bk = _pick_block(q.shape[1], 128), _pick_block(k.shape[1], 512)
    return _chunk_dq_pallas(q, k, v, do, lse, delta, slopes, qpos, kpos, kneg,
                            scale, bq, bk, interpret, g)


def flash_chunk_dkv(q, k, v, do, lse, delta, slopes, qpos, kpos, kneg,
                    scale, interpret, g=1):
    """dK/dV contributions are PER QUERY HEAD (b*nh rows) even under GQA
    — the caller sums each g-group into the (b*nkv)-row carriers (same
    contract as the non-ring dkv kernel)."""
    interpret = _resolve_interpret(interpret)
    bq, bk = _pick_block(q.shape[1], 128), _pick_block(k.shape[1], 512)
    return _chunk_dkv_pallas(q, k, v, do, lse, delta, slopes, qpos, kpos, kneg,
                             scale, bq, bk, interpret, g)


def _xla_reference(q, k, v, slopes, scale, causal, kpos=None, kneg=None):
    """Plain XLA attention with the same semantics (non-TPU fallback and
    the reference the kernels are tested against)."""
    bh, s, hd = q.shape
    scores = jnp.einsum(
        "bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if kpos is None:
        kpos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.float32)[None], (bh, s))
    if kneg is None:
        kneg = jnp.zeros((bh, s), jnp.float32)
    scores = scores + slopes[:, None, None] * kpos[:, None, :] + kneg[:, None, :]
    if causal:
        keep = jnp.arange(s)[None, :] <= jnp.arange(s)[:, None]
        scores = jnp.where(keep[None], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _resolve_interpret(interpret):
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _flash(q, k, v, slopes, kpos, kneg, scale, causal, interpret, g=1,
           window=None):
    out, _ = _flash_fwd_pallas(
        q, k, v, slopes, kpos, kneg, scale, causal,
        _pick_block(q.shape[1], 128), _pick_block(q.shape[1], 512),
        _resolve_interpret(interpret), g, window,
    )
    return out


def _flash_fwd(q, k, v, slopes, kpos, kneg, scale, causal, interpret, g=1,
               window=None):
    out, lse = _flash_fwd_pallas(
        q, k, v, slopes, kpos, kneg, scale, causal,
        _pick_block(q.shape[1], 128), _pick_block(q.shape[1], 512),
        _resolve_interpret(interpret), g, window,
    )
    return out, (q, k, v, slopes, kpos, kneg, out, lse)


def _flash_bwd(scale, causal, interpret, g, window, res, ct):
    q, k, v, slopes, kpos, kneg, out, lse = res
    interpret = _resolve_interpret(interpret)
    bq, bk = _pick_block(q.shape[1], 128), _pick_block(q.shape[1], 512)
    delta = (ct.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)  # (bh, s)
    dq = _flash_dq_pallas(
        q, k, v, ct, lse, delta, slopes, kpos, kneg, scale, causal, bq, bk,
        interpret, g, window,
    )
    dk, dv = _flash_dkv_pallas(
        q, k, v, ct, lse, delta, slopes, kpos, kneg, scale, causal, bq, bk,
        interpret, g, window,
    )
    if g > 1:
        # per-query-head contributions -> shared kv heads (rows ordered
        # so g consecutive query heads share one kv row)
        s, hd = k.shape[1], k.shape[2]
        dk = dk.reshape(-1, g, s, hd).sum(1).astype(k.dtype)
        dv = dv.reshape(-1, g, s, hd).sum(1).astype(v.dtype)
    return dq, dk, dv, jnp.zeros_like(slopes), jnp.zeros_like(kpos), jnp.zeros_like(kneg)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,  # (B, S, nh, hd)
    k: jax.Array,  # (B, S, nh | nkv, hd) — fewer kv heads = native GQA
    v: jax.Array,
    alibi_slopes: Optional[jax.Array] = None,  # (nh,)
    attention_mask: Optional[jax.Array] = None,  # (B, S) 1=keep 0=pad
    kv_pos: Optional[jax.Array] = None,  # (B, S) ALiBi position per key
    kv_neg: Optional[jax.Array] = None,  # (B, S) 0 valid / NEG_INF padded
    causal: bool = True,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,  # sliding window (Mistral semantics)
) -> jax.Array:
    """Fused attention. Returns (B, S, nh, hd).

    Padding: pass either ``attention_mask`` (positions derived with
    BLOOM's mask-aware cumsum, matching ``models.bloom.build_alibi``) or
    precomputed ``kv_pos``/``kv_neg`` arrays.

    GQA: when ``k``/``v`` carry fewer heads than ``q`` (``nh = g *
    nkv``, query head h sharing kv head h // g like HF), the kernels
    read the shared K/V directly via grouped index maps — K/V are never
    repeated in HBM, so KV read traffic shrinks by g.
    """
    b, s, nh, hd = q.shape
    nkv = k.shape[2]
    if nh % nkv:
        raise ValueError(f"n_head={nh} must be a multiple of n_kv_head={nkv}")
    g = nh // nkv
    if scale is None:
        scale = hd**-0.5
    if alibi_slopes is None:
        alibi_slopes = jnp.zeros((nh,), jnp.float32)
    if attention_mask is not None and (kv_pos is None or kv_neg is None):
        # fill only what the caller did not provide (a custom kv_pos may
        # legitimately accompany a mask, e.g. offset decode positions)
        pos, neg = mask_to_kv_bias(attention_mask)
        kv_pos = pos if kv_pos is None else kv_pos
        kv_neg = neg if kv_neg is None else kv_neg
    if kv_pos is None:
        kv_pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.float32)[None], (b, s))
    if kv_neg is None:
        kv_neg = jnp.zeros((b, s), jnp.float32)

    slopes = jnp.broadcast_to(alibi_slopes[None], (b, nh)).reshape(b * nh)

    def flat(x):
        h = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, hd)

    def flat_bs(x, h):  # (B, S) -> (B*h, S)
        return jnp.broadcast_to(
            x.astype(jnp.float32)[:, None, :], (b, h, s)
        ).reshape(b * h, s)

    out = _flash(
        flat(q), flat(k), flat(v), slopes.astype(jnp.float32),
        flat_bs(kv_pos, nkv), flat_bs(kv_neg, nkv), float(scale), causal,
        interpret, g, int(window) if window is not None else None,
    )
    return out.reshape(b, nh, s, hd).transpose(0, 2, 1, 3)
