"""Fused paged attention (Pallas, TPU): one HBM pass over the KV pool.

The serving decode step is memory-bound twice over on the gather path:
``serving/kv_pool.py:gather_pages`` materializes every page of a slot's
page table into a contiguous (B, W*ps, nh, hd) KV buffer (dequantizing
int8 to fp in XLA on the way), and ``_attn_core`` then re-reads that
buffer — two full HBM passes at fp precision for a step whose
arithmetic intensity is ~1. This kernel walks the page table directly:

- the page table and per-row start positions ride SCALAR PREFETCH
  (``PrefetchScalarGridSpec``), so each grid step's BlockSpec index map
  returns the PHYSICAL page id ``page_table[b, w]`` — the DMA engine
  fetches raw pages straight out of the pool, no contiguous copy;
- an int8 pool's ``{q, scale}`` planes are DMA'd at WIRE precision
  (1 byte/value + one f32 per (position, head)) and dequantized
  in-register, so the quantized pool's bandwidth saving reaches the
  attention read, not just the storage;
- the ALiBi-over-global-position bias, the causal/validity mask, and
  the online-softmax recurrence (the ops/flash_attention.py idiom:
  m/l/acc scratch carried across the sequential page axis) are fused
  behind the same pass.

Ragged multi-token contract: ``q`` is (B, C, nh, hd) and row ``b``'s
query ``c`` sits at GLOBAL position ``start[b] + c``. A key at logical
position ``w*ps + o`` (independent of which physical page the table
maps it to) is kept iff ``key_pos <= q_pos`` — one mask that subsumes
causality, not-yet-written page offsets, stale tails from a previous
page owner, and NULL-page garbage, exactly mirroring the gather path's
``_paged_bias``. C=1 with ``start=seq_lens`` is the decode step; C>1
serves speculative verify bundles and chunked prefill. Pad queries
(beyond a row's ``n_valid``) produce garbage rows the CALLER zeroes
via its qmask, matching ``_attn_core``'s contract.

Tiles are (page_size, head_dim) per grid step — the page IS the block.
``check_paged_tile`` is the fused_ce-style feasibility guard: compiled
runs raise loudly when the tile cannot fit VMEM (never a silent
fallback to the gather path); the interpreter is exempt (no VMEM).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9

# Conservative per-core VMEM working-set budget. v4/v5 cores expose
# ~16 MiB; Mosaic needs headroom for double buffering beyond what the
# estimate below already doubles, so the guard trips at 3/4 of it.
VMEM_BUDGET_BYTES = 12 * 1024 * 1024

_LANE = 128     # last-dim tile width, every dtype
_SUBLANE = {4: 8, 2: 16, 1: 32}   # itemsize -> second-to-last tile height


def _resolve_interpret(interpret):
    # same convention as ops/flash_attention.py / ops/fused_ce.py —
    # None = auto (compiled on TPU, interpreter elsewhere)
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _is_quantized(pages) -> bool:
    return isinstance(pages, dict)


def _pad_up(n: int, to: int) -> int:
    return -(-n // to) * to


def paged_tile_geometry(page_size: int, head_dim: int, n_queries: int,
                        *, quantized: bool) -> dict:
    """Host-side tile picker report for one kernel instantiation: the
    (page_size, head_dim) KV tile the page-table walk DMAs per grid
    step, with the VMEM working-set estimate the feasibility guard
    checks. All inputs are trace-time constants (array shapes), so this
    runs once per compiled program shape, never per step. The estimate
    pads every buffer to Mosaic's physical tiles ((8|16|32) x 128 by
    itemsize) and doubles the streamed operands for double buffering."""
    kv_itemsize = 1 if quantized else 4      # int8 wire vs f32 in VMEM
    ps_pad = _pad_up(page_size, _SUBLANE[kv_itemsize])
    hd_pad = _pad_up(head_dim, _LANE)
    c_pad = _pad_up(n_queries, _SUBLANE[4])
    kv_tile = ps_pad * hd_pad * kv_itemsize
    scale_tile = _pad_up(page_size, _SUBLANE[4]) * _LANE * 4
    streamed = 2 * kv_tile + (2 * scale_tile if quantized else 0)
    resident = (
        c_pad * hd_pad * 4            # q tile (f32 in-register)
        + c_pad * hd_pad * 4          # acc scratch
        + 2 * c_pad * _LANE * 4       # m/l scratch ((C,1) padded)
        + c_pad * hd_pad * 4          # output tile
    )
    vmem_bytes = 2 * streamed + resident   # x2: double-buffered stream
    return {
        "block_kv": page_size,
        "head_dim": head_dim,
        "n_queries": n_queries,
        "quantized": quantized,
        "vmem_bytes": int(vmem_bytes),
        "vmem_budget_bytes": VMEM_BUDGET_BYTES,
        "fits": vmem_bytes <= VMEM_BUDGET_BYTES,
    }


def check_paged_tile(page_size: int, head_dim: int, n_queries: int, *,
                     quantized: bool,
                     interpret: Optional[bool] = None) -> dict:
    """The fused_ce-style loud guard: returns the geometry dict when the
    (page_size, head_dim) tile fits the VMEM budget, raises ValueError
    for COMPILED runs when it cannot — never a silent fallback to the
    gather path (a half-switched fleet would silently lose the perf the
    config claims). Interpret-mode runs are exempt: the interpreter has
    no VMEM limit, and the CPU test mesh must keep covering oversized
    geometries."""
    geom = paged_tile_geometry(page_size, head_dim, n_queries,
                               quantized=quantized)
    if not geom["fits"] and not _resolve_interpret(interpret):
        raise ValueError(
            f"paged attention: a (page_size={page_size} x "
            f"head_dim={head_dim}) KV tile with C={n_queries} queries "
            f"needs ~{geom['vmem_bytes']} bytes of VMEM "
            f"(budget {VMEM_BUDGET_BYTES}) on hardware. Shrink "
            f"page_size (the page IS the kernel block) or keep "
            f"attn_kernel='gather' for this geometry — the kernel "
            f"never falls back silently."
        )
    return geom


def _ref_attention(q, keys, vals, start, slopes):
    """Plain-XLA reference over an already-gathered contiguous KV view
    — the gather path's ``_attn_core`` + ``_paged_bias`` math, minus
    the caller-side qmask. Shared by the interpret tests and the parity
    suite so the kernel is always pinned against the exact production
    semantics."""
    b, c, nh, hd = q.shape
    n_keys = keys.shape[1]
    key_pos = jnp.arange(n_keys)
    q_pos = start[:, None] + jnp.arange(c)[None, :]           # (B, C)
    keep = key_pos[None, None, :] <= q_pos[:, :, None]        # (B, C, K)
    bias = slopes[None, :, None, None] * key_pos[None, None, None, :].astype(
        jnp.float32
    )
    bias = bias + jnp.where(keep[:, None, :, :], 0.0, NEG_INF)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, keys, preferred_element_type=jnp.float32
    ) * (hd ** -0.5)
    probs = jax.nn.softmax(scores + bias, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, vals,
                      preferred_element_type=jnp.float32)


def _xla_one_pass(q, k_pages, v_pages, page_table, start, slopes):
    """Compiled one-pass lane for non-TPU backends: the kernel's exact
    algorithm — scan the logical page axis, gather each step's B pages
    through the table, dequantize per page, masked online-softmax
    update — expressed in XLA. Neither the contiguous (B, W*ps) KV view
    nor the dense (B, nh, C, S) score matrix ever exists, so the CPU
    smoke benches the same memory shape the TPU kernel has, minus the
    Pallas interpreter's per-grid-step Python overhead."""
    b, c, nh, hd = q.shape
    w_pages = page_table.shape[1]
    quantized = _is_quantized(k_pages)
    ps = (k_pages["q"] if quantized else k_pages).shape[1]
    qf = q.astype(jnp.float32)
    scale = hd ** -0.5
    slopes = slopes.astype(jnp.float32)
    q_pos = start.astype(jnp.int32)[:, None] + jnp.arange(c)[None, :]

    def dequant(pages, ids):
        if quantized:
            return (pages["q"][ids].astype(jnp.float32)
                    * pages["scale"][ids][..., None])
        return pages[ids].astype(jnp.float32)

    def step(carry, wi):
        m, l, acc = carry
        ids = jax.lax.dynamic_index_in_dim(page_table, wi, 1, False)
        kb = dequant(k_pages, ids)                       # (B, ps, nh, hd)
        vb = dequant(v_pages, ids)
        s = jnp.einsum("bchd,bkhd->bchk", qf, kb,
                       preferred_element_type=jnp.float32) * scale
        key_pos = wi * ps + jnp.arange(ps)
        bias = slopes[None, None, :, None] * key_pos.astype(jnp.float32)
        keep = key_pos[None, None, :] <= q_pos[:, :, None]
        s = s + bias + jnp.where(keep[:, :, None, :], 0.0, NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bchk,bkhd->bchd", p, vb, preferred_element_type=jnp.float32)
        return (m_new, l, acc), None

    init = (jnp.full((b, c, nh), NEG_INF, jnp.float32),
            jnp.zeros((b, c, nh), jnp.float32),
            jnp.zeros((b, c, nh, hd), jnp.float32))
    (_, l, acc), _ = jax.lax.scan(step, init, jnp.arange(w_pages))
    return acc / jnp.maximum(l, 1e-30)[..., None]


def paged_attention_reference(q, k_pages, v_pages, page_table, start, *,
                              slopes):
    """XLA reference implementation (two HBM passes): gather the page
    view, then attend. Used as the parity oracle; returns f32
    (B, C, nh, hd) like the kernel."""
    from pipegoose_tpu.serving.kv_pool import gather_pages

    keys = gather_pages(k_pages, page_table)
    vals = gather_pages(v_pages, page_table)
    return _ref_attention(q.astype(jnp.float32), keys.astype(jnp.float32),
                          vals.astype(jnp.float32), start, slopes)


def paged_attention(q, k_pages, v_pages, page_table, start, *, slopes,
                    interpret: Optional[bool] = None):
    """Fused one-pass paged attention over a per-layer page bank.

    Args:
      q: (B, C, nh_local, hd) queries (any float dtype; upcast to f32
        in-register). C=1 is a decode step, C>1 a verify bundle or
        prefill chunk.
      k_pages / v_pages: ONE layer's bank — fp (P, ps, nh_local, hd) or
        the int8 pytree {"q": int8 (P, ps, nh_local, hd),
        "scale": f32 (P, ps, nh_local)}.
      page_table: (B, W) int32 physical page ids; entries beyond a
        row's live prefix must be NULL (0), like everywhere else.
      start: (B,) int32 global position of each row's FIRST query token
        (decode: seq_lens; chunk/verify: the chunk start).
      slopes: (nh_local,) f32 ALiBi slopes for THIS shard's heads.

    Returns f32 (B, C, nh_local, hd) context. Callers cast/reshape and
    apply their pad-query mask, mirroring ``_attn_core``.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, c, nh, hd = q.shape
    _, w_pages = page_table.shape
    quantized = _is_quantized(k_pages)
    ps = (k_pages["q"] if quantized else k_pages).shape[1]
    check_paged_tile(ps, hd, c, quantized=quantized, interpret=interpret)
    if interpret is None and jax.default_backend() != "tpu":
        # auto mode off-TPU takes the compiled one-pass lane — same
        # algorithm, XLA-jitted. interpret=True still forces the Pallas
        # interpreter (the kernel-logic tests pin that path).
        return _xla_one_pass(q, k_pages, v_pages, page_table,
                             start.astype(jnp.int32), slopes)
    interpret = _resolve_interpret(interpret)
    scale = hd ** -0.5
    page_table = page_table.astype(jnp.int32)
    start = start.astype(jnp.int32)

    def kernel(pt_ref, start_ref, slopes_ref, q_ref, *rest):
        if quantized:
            (kq_ref, ks_ref, vq_ref, vs_ref,
             o_ref, m_sc, l_sc, acc_sc) = rest
        else:
            k_ref, v_ref, o_ref, m_sc, l_sc, acc_sc = rest
        bi = pl.program_id(0)
        hi = pl.program_id(1)
        wi = pl.program_id(2)
        slope = slopes_ref[hi]
        row_start = start_ref[bi]

        @pl.when(wi == 0)
        def _init():
            m_sc[:] = jnp.full_like(m_sc, NEG_INF)
            l_sc[:] = jnp.zeros_like(l_sc)
            acc_sc[:] = jnp.zeros_like(acc_sc)

        # pages whose FIRST key position exceeds the row's last query
        # position are fully masked: skip the whole tile. Their table
        # entries are NULL, so consecutive skipped steps revisit block
        # (0, 0, hi, 0) and Pallas elides the redundant DMAs too.
        @pl.when(wi * ps <= row_start + (c - 1))
        def _compute():
            qb = q_ref[0, :, 0, :].astype(jnp.float32)       # (C, hd)
            if quantized:
                kb = (kq_ref[0, :, 0, :].astype(jnp.float32)
                      * ks_ref[0])                           # (ps, hd)
                vb = (vq_ref[0, :, 0, :].astype(jnp.float32)
                      * vs_ref[0])
            else:
                kb = k_ref[0, :, 0, :].astype(jnp.float32)
                vb = v_ref[0, :, 0, :].astype(jnp.float32)
            s_blk = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                        # (C, ps)
            # logical key position = w*ps + offset: the grid's w IS the
            # logical page index — physical indirection lives only in
            # the index maps, so the mask math matches _paged_bias
            key_pos = wi * ps + jax.lax.broadcasted_iota(
                jnp.int32, (c, ps), 1
            )
            q_pos = row_start + jax.lax.broadcasted_iota(
                jnp.int32, (c, ps), 0
            )
            bias = slope * key_pos.astype(jnp.float32)
            s_blk = s_blk + bias + jnp.where(
                key_pos <= q_pos, 0.0, NEG_INF
            )
            m_prev = m_sc[:, 0]
            m_new = jnp.maximum(m_prev, s_blk.max(axis=1))
            p = jnp.exp(s_blk - m_new[:, None])
            alpha = jnp.exp(m_prev - m_new)
            l_sc[:, 0] = l_sc[:, 0] * alpha + p.sum(axis=1)
            acc_sc[:] = acc_sc[:] * alpha[:, None] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_sc[:, 0] = m_new

        @pl.when(wi == w_pages - 1)
        def _finish():
            l = jnp.maximum(l_sc[:, 0], 1e-30)
            o_ref[0, :, 0, :] = (acc_sc[:] / l[:, None]).astype(o_ref.dtype)

    def qidx(bi, hi, wi, pt_ref, start_ref):
        return (bi, 0, hi, 0)

    def kvidx(bi, hi, wi, pt_ref, start_ref):
        return (pt_ref[bi, wi], 0, hi, 0)

    def scidx(bi, hi, wi, pt_ref, start_ref):
        return (pt_ref[bi, wi], 0, hi)

    pl_ = pl  # keep the closure explicit for the spec builders below
    q_spec = pl_.BlockSpec((1, c, 1, hd), qidx)
    slope_spec = pl_.BlockSpec((nh,), lambda bi, hi, wi, pt, st: (0,),
                               memory_space=pltpu.SMEM)
    if quantized:
        in_specs = [
            slope_spec, q_spec,
            pl_.BlockSpec((1, ps, 1, hd), kvidx),   # k int8 plane
            pl_.BlockSpec((1, ps, 1), scidx),       # k scale plane
            pl_.BlockSpec((1, ps, 1, hd), kvidx),   # v int8 plane
            pl_.BlockSpec((1, ps, 1), scidx),       # v scale plane
        ]
        operands = (slopes.astype(jnp.float32), q,
                    k_pages["q"], k_pages["scale"],
                    v_pages["q"], v_pages["scale"])
    else:
        in_specs = [
            slope_spec, q_spec,
            pl_.BlockSpec((1, ps, 1, hd), kvidx),
            pl_.BlockSpec((1, ps, 1, hd), kvidx),
        ]
        operands = (slopes.astype(jnp.float32), q, k_pages, v_pages)

    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, nh, w_pages),
            in_specs=in_specs,
            out_specs=pl_.BlockSpec((1, c, 1, hd), qidx),
            scratch_shapes=[
                pltpu.VMEM((c, 1), jnp.float32),
                pltpu.VMEM((c, 1), jnp.float32),
                pltpu.VMEM((c, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, c, nh, hd), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(page_table, start, *operands)
