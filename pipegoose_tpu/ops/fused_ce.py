"""Fused vocab-parallel cross-entropy (Pallas, TPU) — forward AND backward.

The single largest HBM consumer of the bloom-560m train step is the
(B, S, V) fp32 logits buffer (~8 GB at b8 x s1024 x v250880 —
docs/perf_tpu_v5e.md); the chunked-CE fallback (chunked_ce_sums) bounds
it but pays ~7% throughput for the chunk-boundary logit recompute. This
kernel computes the loss STRAIGHT from (hidden, embedding) with an
online log-sum-exp over vocab tiles — the full logits tensor never
exists in HBM, forward or backward:

- forward: grid (token_blocks, vocab_blocks), vocab sequential; per
  token-block scratch carries the online (max, sumexp, target-logit)
  triple; emits per-token local ``lse`` and ``target_logit``.
- backward: dlogits = softmax - onehot is rematerialized tile-by-tile
  from the saved GLOBAL lse (Megatron's analytic CE backward, reference
  loss.py:71-89, without ever holding more than one (BT, BV) tile):
  dhidden: grid (token_blocks, vocab_blocks), vocab sequential,
  accumulates dlogits @ W_tile; dweight: grid (vocab_blocks,
  token_blocks), tokens sequential, accumulates dlogits^T @ h_tile.

Tensor-parallel semantics match ``vocab_parallel_cross_entropy``
(nn/tensor_parallel/layers.py): the kernel works on the LOCAL vocab
shard; the wrapper combines shards with a max+log-sum-exp reduction and
a psum of the (exactly-one-shard-hit) target logit, and the hand-written
VJP psums the hidden cotangent over the axis — the same load-bearing
all-reduce as logits_fn's f-operator (models/bloom.py:366-373), here
fused into the custom backward. Padded vocab slots (pad_vocab) are
masked by GLOBAL column index against ``valid_size``.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9


def _resolve_interpret(interpret):
    # same convention as ops/flash_attention.py:728-732 — None = auto
    # (compiled on TPU, interpreter elsewhere e.g. the CPU test mesh)
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _pick_block(n: int, target: int):
    """Largest halving of ``target`` (>= 8) dividing ``n``. Returns
    ``(block, exact)`` — ``exact=False`` means NO such divisor exists
    and the fallback is the whole dim as one tile, which callers must
    treat as infeasible for compiled TPU runs (a non-8-aligned or
    whole-vocab tile dies in Mosaic; ADVICE r5)."""
    b = target
    while b >= 8:
        if n % b == 0:
            return b, True
        b //= 2
    return n, False


def _fwd_pallas(h, w, targets, offset, valid, block_t, block_v, interpret,
                vh):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_tot, hd = h.shape
    v_loc = w.shape[0] if vh else w.shape[1]
    nt, nv = t_tot // block_t, v_loc // block_v

    def kernel(off_ref, h_ref, w_ref, t_ref, lse_ref, tl_ref,
               m_sc, l_sc, t_sc):
        vi = pl.program_id(1)

        @pl.when(vi == 0)
        def _init():
            m_sc[:] = jnp.full_like(m_sc, NEG_INF)
            l_sc[:] = jnp.zeros_like(l_sc)
            t_sc[:] = jnp.zeros_like(t_sc)

        hb = h_ref[...].astype(jnp.float32)  # (BT, H)
        wb = w_ref[...].astype(jnp.float32)  # (BV, H) | (H, BV)
        logits = jax.lax.dot_general(
            hb, wb, (((1,), (1,) if vh else (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BT, BV)
        col = off_ref[0] + vi * block_v + jax.lax.broadcasted_iota(
            jnp.int32, (block_t, block_v), 1
        )
        if valid is not None:
            logits = jnp.where(col < valid, logits, NEG_INF)
        tb = t_ref[0]  # (BT,) int32
        hit = tb[:, None] == col
        t_sc[:, 0] += jnp.where(hit, logits, 0.0).sum(axis=1)

        m_prev = m_sc[:, 0]
        m_new = jnp.maximum(m_prev, logits.max(axis=1))
        p = jnp.exp(logits - m_new[:, None])
        l_sc[:, 0] = l_sc[:, 0] * jnp.exp(m_prev - m_new) + p.sum(axis=1)
        m_sc[:, 0] = m_new

        @pl.when(vi == nv - 1)
        def _finish():
            lse_ref[0] = m_sc[:, 0] + jnp.log(jnp.maximum(l_sc[:, 0], 1e-30))
            tl_ref[0] = t_sc[:, 0]

    lse, tl = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(nt, nv),
            in_specs=[
                pl.BlockSpec((1,), lambda i, j: (0,),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((block_t, hd), lambda i, j: (i, 0)),
                pl.BlockSpec((block_v, hd), lambda i, j: (j, 0))
                if vh else
                pl.BlockSpec((hd, block_v), lambda i, j: (0, j)),
                pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
                pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_t, 1), jnp.float32),
                pltpu.VMEM((block_t, 1), jnp.float32),
                pltpu.VMEM((block_t, 1), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((1, t_tot), jnp.float32),
            jax.ShapeDtypeStruct((1, t_tot), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(offset, h, w, targets[None, :])
    return lse[0], tl[0]


def _dlogits_tile(hb, wb, tb, lse_b, g_b, off, vi, block_t, block_v, valid,
                  vh=True):
    """One (BT, BV) dlogits tile: g * (softmax - onehot), rebuilt from
    the saved global lse. Shared by the dh and dw kernels. ``vh``: the
    weight tile is (BV, H) (tied embedding) vs (H, BV) (untied head)."""
    logits = jax.lax.dot_general(
        hb, wb, (((1,), (1,) if vh else (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    col = off + vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_t, block_v), 1
    )
    if valid is not None:
        logits = jnp.where(col < valid, logits, NEG_INF)
    p = jnp.exp(logits - lse_b[:, None])  # padded cols: exp(-inf) = 0
    hit = tb[:, None] == col
    return g_b[:, None] * (p - jnp.where(hit, 1.0, 0.0))


def _dh_pallas(h, w, targets, lse, g, offset, valid, block_t, block_v,
               interpret, vh):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_tot, hd = h.shape
    v_loc = w.shape[0] if vh else w.shape[1]
    nt, nv = t_tot // block_t, v_loc // block_v

    def kernel(off_ref, h_ref, w_ref, t_ref, lse_ref, g_ref, dh_ref, dh_sc):
        vi = pl.program_id(1)

        @pl.when(vi == 0)
        def _init():
            dh_sc[:] = jnp.zeros_like(dh_sc)

        hb = h_ref[...].astype(jnp.float32)
        wb = w_ref[...].astype(jnp.float32)
        dl = _dlogits_tile(
            hb, wb, t_ref[0], lse_ref[0], g_ref[0],
            off_ref[0], vi, block_t, block_v, valid, vh,
        )
        dh_sc[:] += jax.lax.dot_general(
            dl, wb, (((1,), (0,) if vh else (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        @pl.when(vi == nv - 1)
        def _finish():
            dh_ref[...] = dh_sc[:].astype(dh_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(nt, nv),
            in_specs=[
                pl.BlockSpec((1,), lambda i, j: (0,),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((block_t, hd), lambda i, j: (i, 0)),
                pl.BlockSpec((block_v, hd), lambda i, j: (j, 0))
                if vh else
                pl.BlockSpec((hd, block_v), lambda i, j: (0, j)),
                pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
                pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
                pl.BlockSpec((1, block_t), lambda i, j: (0, i)),
            ],
            out_specs=pl.BlockSpec((block_t, hd), lambda i, j: (i, 0)),
            scratch_shapes=[pltpu.VMEM((block_t, hd), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct(h.shape, h.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(offset, h, w, targets[None, :], lse[None, :], g[None, :])


def _dw_pallas(h, w, targets, lse, g, offset, valid, block_t, block_v,
               interpret, vh):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_tot, hd = h.shape
    v_loc = w.shape[0] if vh else w.shape[1]
    nt, nv = t_tot // block_t, v_loc // block_v

    def kernel(off_ref, h_ref, w_ref, t_ref, lse_ref, g_ref, dw_ref, dw_sc):
        ti = pl.program_id(1)

        @pl.when(ti == 0)
        def _init():
            dw_sc[:] = jnp.zeros_like(dw_sc)

        hb = h_ref[...].astype(jnp.float32)
        wb = w_ref[...].astype(jnp.float32)
        dl = _dlogits_tile(
            hb, wb, t_ref[0], lse_ref[0], g_ref[0],
            off_ref[0], pl.program_id(0), block_t, block_v, valid, vh,
        )
        if vh:
            dw_sc[:] += jax.lax.dot_general(
                dl, hb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (BV, H)
        else:
            dw_sc[:] += jax.lax.dot_general(
                hb, dl, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (H, BV)

        @pl.when(ti == nt - 1)
        def _finish():
            dw_ref[...] = dw_sc[:].astype(dw_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(nv, nt),
            in_specs=[
                pl.BlockSpec((1,), lambda j, i: (0,),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((block_t, hd), lambda j, i: (i, 0)),
                pl.BlockSpec((block_v, hd), lambda j, i: (j, 0))
                if vh else
                pl.BlockSpec((hd, block_v), lambda j, i: (0, j)),
                pl.BlockSpec((1, block_t), lambda j, i: (0, i)),
                pl.BlockSpec((1, block_t), lambda j, i: (0, i)),
                pl.BlockSpec((1, block_t), lambda j, i: (0, i)),
            ],
            out_specs=pl.BlockSpec((block_v, hd), lambda j, i: (j, 0))
            if vh else
            pl.BlockSpec((hd, block_v), lambda j, i: (0, j)),
            scratch_shapes=[pltpu.VMEM(
                (block_v, hd) if vh else (hd, block_v), jnp.float32
            )],
        ),
        out_shape=jax.ShapeDtypeStruct(w.shape, w.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(offset, h, w, targets[None, :], lse[None, :], g[None, :])


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9)
)
def _fused_ce(h, w, targets, token_w, axis_name, valid_size, block_t,
              block_v, interpret, vh):
    out, _ = _fused_ce_fwd(
        h, w, targets, token_w, axis_name, valid_size, block_t, block_v,
        interpret, vh,
    )
    return out


def _shard_offset(axis_name, v_local):
    off = jax.lax.axis_index(axis_name) * v_local if axis_name else 0
    return jnp.asarray([off], jnp.int32)


def _combine(lse_l, tl_l, axis_name):
    """Local-shard (lse, target_logit) -> global: max + log-sum-exp over
    shards for lse; the target column lives on exactly one shard (hits
    elsewhere sum to 0), so its psum is the true pick."""
    if not axis_name:
        return lse_l, tl_l
    m = jax.lax.pmax(lse_l, axis_name)
    lse = m + jnp.log(jax.lax.psum(jnp.exp(lse_l - m), axis_name))
    return lse, jax.lax.psum(tl_l, axis_name)


def _fused_ce_fwd(h, w, targets, token_w, axis_name, valid_size, block_t,
                  block_v, interpret, vh):
    offset = _shard_offset(axis_name, w.shape[0] if vh else w.shape[1])
    lse_l, tl_l = _fwd_pallas(
        h, w, targets, offset, valid_size, block_t, block_v, interpret, vh
    )
    lse, tl = _combine(lse_l, tl_l, axis_name)
    loss_sum = ((lse - tl) * token_w).sum()
    return (loss_sum, token_w.sum()), (h, w, targets, token_w, lse)


def _fused_ce_bwd(axis_name, valid_size, block_t, block_v, interpret, vh,
                  res, cts):
    h, w, targets, token_w, lse = res
    ct_loss, _ = cts  # weight_sum is a non-diff count
    g = (ct_loss * token_w).astype(jnp.float32)
    offset = _shard_offset(axis_name, w.shape[0] if vh else w.shape[1])
    dh = _dh_pallas(
        h, w, targets, lse, g, offset, valid_size, block_t, block_v,
        interpret, vh,
    )
    if axis_name:
        # each shard's dh holds only its vocab rows' contribution; the
        # true hidden cotangent is the sum — the f-operator all-reduce
        # (models/bloom.py logits_fn), fused into this backward
        dh = jax.lax.psum(dh, axis_name)
    dw = _dw_pallas(
        h, w, targets, lse, g, offset, valid_size, block_t, block_v,
        interpret, vh,
    )
    return dh, dw, None, None


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def fused_ce_sums(
    hidden: jax.Array,   # (T, H) tokens already aligned with targets
    weight: jax.Array,   # (V_local, H) (tied) embedding shard
    targets: jax.Array,  # (T,) GLOBAL target ids
    token_w: jax.Array,  # (T,) float weights (0 = ignored position)
    axis_name: Optional[str] = None,
    valid_size: Optional[int] = None,
    block_t: int = 256,
    block_v: int = 512,
    interpret: Optional[bool] = None,
    weight_layout: str = "vh",
):
    """(weighted loss sum, weight sum) of the vocab-parallel CE, fused.

    Same contract as chunked_ce_sums' return (callers divide), same TP
    and padded-vocab semantics as vocab_parallel_cross_entropy — but no
    logits buffer and no chunk recompute. Pads T up to the token block
    (weight-0 pad tokens).

    ``weight_layout``: "vh" = (V_local, H) (bloom's tied embedding),
    "hv" = (H, V_local) (llama/mixtral's untied column-parallel head) —
    both read the weight in its native layout, no transpose copy."""
    if weight_layout not in ("vh", "hv"):
        raise ValueError(f"weight_layout must be 'vh' or 'hv', got "
                         f"{weight_layout!r}")
    vh = weight_layout == "vh"
    t = hidden.shape[0]
    # token blocks stay powers of two (pad T up); vocab blocks must
    # divide V_local (pad_vocab guarantees power-of-two-friendly shards)
    pow2 = 8
    while pow2 < min(t, block_t):
        pow2 *= 2
    block_t = min(pow2, block_t)
    v_loc = weight.shape[0] if vh else weight.shape[1]
    requested_v = block_v
    block_v, exact_v = _pick_block(v_loc, block_v)
    interpret = _resolve_interpret(interpret)
    if not exact_v and not interpret:
        # _pick_block's fallback is the WHOLE vocab dim as one tile.
        # Whether V_local is larger than the requested block (a
        # (V_local, H) fp32 tile cannot fit VMEM) or merely smaller but
        # not 8-aligned (Mosaic rejects the ragged tile), the compiled
        # run would die with an opaque Mosaic error that interpret-mode
        # tests never see (ADVICE r5) — fail loudly here, but only for
        # compiled runs: the interpreter has no VMEM limit or tile
        # alignment and the whole-vocab tile is valid there.
        raise ValueError(
            f"fused CE: no block size >= 8 among halvings of "
            f"{requested_v} divides V_local={v_loc}, and a single "
            f"(V_local={v_loc}, H) whole-vocab tile is VMEM-infeasible "
            f"(or not 8-aligned) on hardware. Pad the vocab shard to a "
            f"power-of-two-friendly size (pad_for_tp / pad_vocab) or "
            f"pass a block_v dividing it."
        )
    if t % block_t:
        pad = block_t - t % block_t
        hidden = jnp.pad(hidden, ((0, pad), (0, 0)))
        targets = jnp.pad(targets, (0, pad))
        token_w = jnp.pad(token_w, (0, pad))
    return _fused_ce(
        hidden, weight, targets, token_w.astype(jnp.float32), axis_name,
        valid_size, block_t, block_v, interpret, vh,
    )


def fused_ce_shifted_sums(
    hidden: jax.Array,  # (B, S, H) final-LN output
    weight: jax.Array,
    labels: jax.Array,  # (B, S)
    attention_mask,     # (B, S) or None
    axis_name: Optional[str] = None,
    valid_size: Optional[int] = None,
    weight_layout: str = "vh",
):
    """Shift-by-one causal-LM (weighted loss sum, weight sum) via the
    fused kernel — the shared convention for the dense losses AND the
    pipeline heads (which combine per-microbatch sums themselves)."""
    b, s, hd = hidden.shape
    w = (
        attention_mask[:, 1:]
        if attention_mask is not None
        else jnp.ones_like(labels[:, 1:])
    ).astype(jnp.float32)
    return fused_ce_sums(
        hidden[:, :-1].reshape(b * (s - 1), hd), weight,
        labels[:, 1:].reshape(-1), w.reshape(-1),
        axis_name, valid_size, weight_layout=weight_layout,
    )


def fused_ce_shifted_loss(
    hidden: jax.Array,  # (B, S, H) final-LN output
    weight: jax.Array,
    labels: jax.Array,  # (B, S)
    attention_mask,     # (B, S) or None
    axis_name: Optional[str] = None,
    valid_size: Optional[int] = None,
    weight_layout: str = "vh",
) -> jax.Array:
    """Causal-LM mean loss (shift-by-one, mask-weighted) via the fused
    kernel — the single dispatch shared by the bloom/llama/mixtral
    ``config.fused_ce`` paths so the shift/mask/normalize convention
    lives in exactly one place."""
    tot, cnt = fused_ce_shifted_sums(
        hidden, weight, labels, attention_mask, axis_name, valid_size,
        weight_layout,
    )
    return tot / jnp.maximum(cnt, 1)


def fused_ce_masked_sums(
    hidden: jax.Array,   # (B, S, H) — targets ALREADY aligned (no shift)
    weight: jax.Array,
    labels: jax.Array,   # (B, S)
    weights: jax.Array,  # (B, S) float mask
    axis_name: Optional[str] = None,
    valid_size: Optional[int] = None,
    weight_layout: str = "vh",
):
    """(weighted loss sum, weight sum) over pre-aligned positions — the
    sequence-parallel head adapter: under SP the shift-by-one already
    happened globally (nn/sequence_parallel/targets.py), and the local
    (B, S_local, V) logits buffer this replaces is exactly the tensor
    that explodes at the long-context shapes SP exists for."""
    b, s, hd = hidden.shape
    return fused_ce_sums(
        hidden.reshape(b * s, hd), weight, labels.reshape(-1),
        weights.reshape(-1).astype(jnp.float32), axis_name, valid_size,
        weight_layout=weight_layout,
    )
