"""Hand-written Pallas TPU kernels (flash attention, fused
cross-entropy, fused paged attention) with ``interpret=`` CPU
fallbacks. Heavy modules stay import-on-demand
(``from pipegoose_tpu.ops import flash_attention as fa``); the paged
decode kernel's public surface is re-exported here because serving
code and scripts reach for it by name."""
from pipegoose_tpu.ops.paged_attention import (
    check_paged_tile,
    paged_attention,
    paged_attention_reference,
    paged_tile_geometry,
)

__all__ = [
    "check_paged_tile",
    "paged_attention",
    "paged_attention_reference",
    "paged_tile_geometry",
]
