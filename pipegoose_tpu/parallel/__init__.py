from pipegoose_tpu.parallel.auto import make_auto_train_step
from pipegoose_tpu.parallel.hybrid import (
    build_hybrid_train_step,
    hybrid_build_config,
    hybrid_step_kwargs,
    make_hybrid_train_step,
    parallel_context_sizes,
    sync_replicated_grads,
    train_step_intended_specs,
    zero_state_spec,
)

__all__ = [
    "build_hybrid_train_step",
    "hybrid_build_config",
    "hybrid_step_kwargs",
    "make_hybrid_train_step",
    "make_auto_train_step",
    "parallel_context_sizes",
    "sync_replicated_grads",
    "train_step_intended_specs",
    "zero_state_spec",
]
