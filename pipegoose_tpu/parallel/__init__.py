from pipegoose_tpu.parallel.hybrid import make_hybrid_train_step

__all__ = ["make_hybrid_train_step"]
