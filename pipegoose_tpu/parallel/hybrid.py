"""Hybrid-parallel train-step composition.

The TPU-native replacement for the reference's wrapper-chaining pattern
(examples/hybrid_parallelism.py: TensorParallel(...).parallelize() ->
DataParallel(...).parallelize() -> DistributedOptimizer(...)): here the
same composition is ONE compiled SPMD program — a ``shard_map`` over the
mesh in which the loss/grad runs tensor-parallel, the batch is sharded
over the data axis, and the ZeRO-1 optimizer reduce-scatters grads and
all-gathers params. No hooks, no module mutation, no per-param
collectives.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from pipegoose_tpu.distributed.compat import shard_map
from pipegoose_tpu.distributed.parallel_context import ParallelContext
from pipegoose_tpu.optim.zero import (
    DistributedOptimizer,
    ZeroState,
    ef_state_specs,
    shard_shapes,
    state_specs,
)

def spec_mentions(spec: P, axis: str) -> bool:
    """Whether a PartitionSpec shards any dim over ``axis`` — the one
    axis-membership helper (telemetry/health.py imports it from here;
    the reverse direction would cycle through the telemetry package
    __init__ back into trainer/hybrid)."""
    for entry in spec:
        if entry == axis:
            return True
        if isinstance(entry, (tuple, list)) and axis in entry:
            return True
    return False


_spec_mentions = spec_mentions  # module-internal alias


def sync_replicated_grads(grads: Any, param_specs: Any, axes: tuple) -> Any:
    """Reduce grads of params NOT sharded over an axis, for each entry in
    ``axes`` — either a plain axis name (psum) or ``(axis, op)`` with op
    in {"sum", "mean"}.

    - "sum" (pipe): a replicated param *used* on only some ranks of the
      axis (embedding on the first stage, ln_f/LM head on the last) —
      each rank holds a partial contribution, the true grad is the sum.
    - "mean" (expert): the axis carries *different tokens* (expert-data
      parallelism) — replicated params average like DP (the reference's
      EXPERT_DATA routing, data_parallel.py:35-43).
    """

    for entry in axes:
        _, op = entry if isinstance(entry, tuple) else (entry, "sum")
        if op not in ("sum", "mean"):
            raise ValueError(f"grad sync op must be 'sum' or 'mean', got {op!r}")

    def f(g, spec):
        for entry in axes:
            ax, op = entry if isinstance(entry, tuple) else (entry, "sum")
            if not _spec_mentions(spec, ax):
                g = lax.psum(g, ax) if op == "sum" else lax.pmean(g, ax)
        return g

    return jax.tree_util.tree_map(
        f, grads, param_specs, is_leaf=lambda x: isinstance(x, P)
    )


def zero_state_spec(
    optimizer: DistributedOptimizer, params: Any, param_specs: Any, mesh
) -> ZeroState:
    """PartitionSpec tree for the ZeRO-1 optimizer state on ``mesh`` —
    used by the train step's in/out specs and by checkpoint restore
    (restoring without these would replicate the sharded state)."""
    dp = optimizer.axis_name and mesh.shape.get(optimizer.axis_name, 1) or 1
    shapes = jax.eval_shape(optimizer.inner.init, shard_shapes(params, dp))
    inner_spec = state_specs(shapes, params, param_specs, optimizer.axis_name or "data")
    ef_spec = None
    if getattr(optimizer, "error_feedback", False) and optimizer.axis_name:
        ef_spec = ef_state_specs(params, param_specs, optimizer.axis_name)
    return ZeroState(inner_spec, ef_spec)


def train_step_intended_specs(
    optimizer: DistributedOptimizer,
    params: Any,
    param_specs: Any,
    mesh,
    batch_spec: P = P("data"),
    with_rng: bool = False,
) -> tuple:
    """The INTENDED PartitionSpec tuple for a hybrid train step's
    ``(params, opt_state, batch[, rng])`` arguments — what the mesh
    doctor (telemetry/doctor.py) diffs the compiled program against.
    One source of truth: the same ``param_specs`` the step was built
    with plus the derived ZeRO state specs, so a drifted spec shows up
    as a compile-time diff instead of a slow step."""
    specs = (
        param_specs,
        zero_state_spec(optimizer, params, param_specs, mesh),
        batch_spec,
    )
    return specs + ((P(),) if with_rng else ())


def parallel_context_sizes(candidate: Any) -> dict:
    """``ParallelContext`` kwargs implied by one planner candidate
    (duck-typed: anything with ``dp``/``tp``/``pp``/``ep`` attributes,
    normally a ``pipegoose_tpu.planner.Candidate``). The enumeration
    hook lives HERE so the layout-to-mesh mapping has one source of
    truth — the planner, the CLIs, and tests all build their contexts
    through it instead of hand-assembling axis sizes."""
    return dict(
        tensor_parallel_size=int(getattr(candidate, "tp", 1)),
        pipeline_parallel_size=int(getattr(candidate, "pp", 1)),
        data_parallel_size=int(getattr(candidate, "dp", 1)),
        expert_parallel_size=int(getattr(candidate, "ep", 1)),
    )


def hybrid_step_kwargs(candidate: Any) -> dict:
    """:func:`make_hybrid_train_step` kwargs implied by one planner
    candidate: the gradient wire precision, the overlap declaration,
    and — for a pipelined candidate — the ``("pipe",)`` grad sync the
    stage-partial gradients need (test_3d_parallel's composition)."""
    kw: dict = dict(
        grad_comm=getattr(candidate, "grad_comm", None),
        overlap_tp=bool(getattr(candidate, "overlap_tp", False)),
    )
    if int(getattr(candidate, "pp", 1)) > 1:
        kw["grad_sync_axes"] = ("pipe",)
    return kw


def hybrid_build_config(
    loss_fn: Callable[..., jax.Array],
    param_specs: Any,
    optimizer: DistributedOptimizer,
    batch_spec: P = P("data"),
    loss_axis: Any = "data",
    grad_sync_axes: tuple = (),
    with_rng: bool = False,
    n_accum: int = 1,
    with_health: bool = False,
    grad_comm: Optional[str] = None,
    overlap_tp: bool = False,
) -> dict:
    """Capture everything :func:`make_hybrid_train_step` needs EXCEPT
    the ``ParallelContext`` — the step-rebuild hook. The trainer stores
    this dict at construction; after an elastic mesh change
    (``trainer/elastic.py``: device loss shrank the cluster), the SAME
    config re-lowered through :func:`build_hybrid_train_step` on the
    new context yields the recompiled step — one source of truth, no
    drift between the original build and the rebuild."""
    return dict(
        loss_fn=loss_fn,
        param_specs=param_specs,
        optimizer=optimizer,
        batch_spec=batch_spec,
        loss_axis=loss_axis,
        grad_sync_axes=grad_sync_axes,
        with_rng=with_rng,
        n_accum=n_accum,
        with_health=with_health,
        grad_comm=grad_comm,
        overlap_tp=overlap_tp,
    )


def build_hybrid_train_step(config: dict, parallel_context: ParallelContext):
    """(init_fn, make_step) for a stored :func:`hybrid_build_config` on
    ``parallel_context`` — the other half of the rebuild hook."""
    cfg = dict(config)
    return make_hybrid_train_step(
        cfg.pop("loss_fn"), cfg.pop("param_specs"), cfg.pop("optimizer"),
        parallel_context, **cfg,
    )


def _set_comm_gauges(params, mesh, optimizer, comm_mode: str,
                     overlap_tp: bool, dp_axis: str) -> None:
    """Export the communication-engine config/savings next to the MFU
    gauges: ``comm.overlap_enabled`` (0/1) and, for a compressed
    gradient reduction, the analytic per-step ``comm.bytes_saved``
    (distributed/compressed.py). One registry branch when telemetry is
    disabled — the library-instrumentation contract."""
    from pipegoose_tpu.telemetry.registry import get_registry

    reg = get_registry()
    if not reg.enabled:
        return
    reg.gauge(
        "comm.overlap_enabled",
        help="1 when the TP ring collective-matmul overlap path is on",
    ).set(1.0 if overlap_tp else 0.0)
    ax = getattr(optimizer, "axis_name", None) or dp_axis
    n = mesh.shape.get(ax, 1)
    # always write all three (last-build-wins): an fp32 build after a
    # quantized one must not leave stale savings on the exporters
    saved = 0.0
    if comm_mode != "fp32" and n > 1:
        from pipegoose_tpu.distributed.compressed import grad_comm_bytes_saved

        saved = float(grad_comm_bytes_saved(params, n, comm_mode))
    reg.gauge(
        "comm.bytes_saved",
        help="analytic per-step gradient-reduction wire bytes saved "
             "vs fp32 by grad_comm compression",
    ).set(saved)
    reg.gauge("comm.grad_wire_bits").set(
        {"fp32": 32.0, "bf16": 16.0, "int8": 8.0}[comm_mode]
    )


def make_hybrid_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    param_specs: Any,
    optimizer: DistributedOptimizer,
    parallel_context: Optional[ParallelContext] = None,
    batch_spec: P = P("data"),
    loss_axis: str = "data",
    grad_sync_axes: tuple = (),
    with_rng: bool = False,
    n_accum: int = 1,
    with_health: bool = False,
    grad_comm: Optional[str] = None,
    overlap_tp: bool = False,
):
    """Build (init_fn, step_fn), both jitted over the context's mesh.

    - ``loss_fn(params, batch) -> scalar`` runs on per-device shards
      inside shard_map (use tp_axis='tensor' collectives inside it).
    - ``param_specs``: PartitionSpec pytree for params (e.g.
      ``bloom.tp_specs``).
    - ``optimizer``: ZeRO-1 ``DistributedOptimizer``; its state lives
      sharded over the data axis for the whole run.

    step_fn(params, opt_state, batch) -> (params, opt_state, loss);
    params and opt_state buffers are donated.

    ``with_rng=True``: ``loss_fn(params, batch, rng)`` and
    ``step_fn(params, opt_state, batch, rng)`` — pass a FRESH key every
    step (e.g. ``jax.random.fold_in(base, step)``); fold the data/expert
    axis indices inside ``loss_fn`` for per-rank diversity (the
    reference seeded every rank identically, parallel_context.py:253-261,
    which SURVEY.md §7 flags as wrong for router noise).

    ``n_accum > 1``: gradient accumulation — the per-device batch shard
    is split into ``n_accum`` microbatches scanned with rematerialization
    (core/accumulation.py), so peak activation memory is one
    microbatch's while the optimizer sees the full-batch gradient.

    ``with_health=True``: step_fn additionally returns a small
    replicated pytree of in-graph health scalars (global and
    per-top-level-module grad norms, applied-update max-abs/norm,
    nonfinite-leaf counts, update/param norm ratio —
    telemetry/health.py), fused into the SAME compiled program. The
    flag is resolved at build time, so the off path lowers to a
    byte-identical program (zero recompiles, zero per-step cost —
    pinned by tests/telemetry/test_health.py); on, it costs one grad
    all-reduce tree plus two scalar-vector collectives.

    ``grad_comm``: wire precision of the DP/ZeRO gradient reduction —
    "fp32" | "bf16" | "int8" (distributed/compressed.py). None (the
    default) inherits the optimizer's own setting. With a ZeRO
    ``axis_name`` the compressed reduce-scatter replaces the fp32
    ``psum_scatter`` inside the optimizer; with ``axis_name=None``
    (plain unsharded optimizer, i.e. plain DP) a compressed mean
    all-reduce runs on the grads before the optimizer step, over every
    loss axis, for params not sharded over that axis (the compressed
    analog of ``grad_sync_axes=((ax, "mean"), ...)`` — combining both
    for the same axis raises). Docs: docs/comm.md.

    ``overlap_tp``: declare that ``loss_fn`` runs the ring
    collective-matmul path (``config.overlap_tp`` on the model) — the
    flag only drives telemetry (``comm.overlap_enabled``) and the
    doctor's expectations; the overlap path's gradients are exact by
    construction, so no grad-sync change is needed.
    """
    ctx = parallel_context or ParallelContext.get_context()
    if ctx is None:
        raise ValueError("no ParallelContext; construct one first")
    mesh = ctx.mesh

    from pipegoose_tpu.distributed.compressed import check_grad_comm

    if grad_comm is not None and grad_comm != getattr(
        optimizer, "grad_comm", "fp32"
    ):
        optimizer = optimizer.replace(grad_comm=check_grad_comm(grad_comm))
    comm_mode = check_grad_comm(getattr(optimizer, "grad_comm", "fp32"))
    # plain-DP path: no ZeRO axis to fold the compression into — the
    # compressed mean all-reduce runs on the whole grad tree instead
    plain_dp_comm = comm_mode != "fp32" and optimizer.axis_name is None

    if n_accum > 1:
        from pipegoose_tpu.core.accumulation import make_accumulating_loss

        loss_fn = make_accumulating_loss(loss_fn, n_accum)

    def _state_spec_for(params):
        return zero_state_spec(optimizer, params, param_specs, mesh)

    def init_fn(params):
        spec = _state_spec_for(params)
        f = shard_map(
            optimizer.init,
            mesh=mesh,
            in_specs=(param_specs,),
            out_specs=spec,
            check_vma=False,
        )
        return jax.jit(f)(params)

    loss_axes = loss_axis if isinstance(loss_axis, tuple) else (loss_axis,)
    if plain_dp_comm:
        # the compressed path below already mean-syncs over every loss
        # axis (for params not sharded over it) — a ("data", "mean")
        # grad_sync entry on top would average twice
        for entry in grad_sync_axes:
            ax, op = entry if isinstance(entry, tuple) else (entry, "sum")
            if ax in loss_axes and op == "mean":
                raise ValueError(
                    f"grad_comm={comm_mode!r} with an unsharded optimizer "
                    f"already mean-syncs grads over {loss_axes}; drop "
                    f"({ax!r}, 'mean') from grad_sync_axes"
                )
    if with_health:
        from pipegoose_tpu.telemetry.health import health_stats

        # grads of params replicated over an already-synced axis
        # (grad_sync_axes ran first) are exact; the remaining loss axes
        # still hold per-rank partials and need the health pmean
        synced = {e[0] if isinstance(e, tuple) else e for e in grad_sync_axes}
        health_mean_axes = tuple(a for a in loss_axes if a not in synced)

    def _step(params, opt_state, batch, *rng):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, *rng)
        if grad_sync_axes:
            grads = sync_replicated_grads(grads, param_specs, grad_sync_axes)
        if plain_dp_comm:
            from pipegoose_tpu.distributed.compressed import (
                compressed_all_reduce_mean,
            )

            # the compressed analog of sync_replicated_grads with
            # (axis, "mean") for every loss axis: params SHARDED over
            # an axis hold genuinely different grads there (e.g.
            # expert weights on an expert axis) and must not be mixed
            def comp_sync(g, spec):
                for ax in loss_axes:
                    if not _spec_mentions(spec, ax):
                        g = compressed_all_reduce_mean(g, ax, comm_mode)[0]
                return g

            grads = jax.tree_util.tree_map(
                comp_sync, grads, param_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
        new_params, new_state = optimizer.step(grads, opt_state, params)
        for ax in loss_axes:
            loss = lax.pmean(loss, ax)
        if not with_health:
            return new_params, new_state, loss
        health = health_stats(
            grads, params, new_params, param_specs,
            axes=tuple(mesh.axis_names), mean_axes=health_mean_axes,
        )
        return new_params, new_state, loss, health

    def make_step(params):
        _set_comm_gauges(params, mesh, optimizer, comm_mode, overlap_tp,
                         loss_axes[0])
        spec = _state_spec_for(params)
        in_specs = (param_specs, spec, batch_spec) + ((P(),) if with_rng else ())
        # the health tree is all replicated scalars: one P() prefix spec
        out_specs = (param_specs, spec, P()) + ((P(),) if with_health else ())
        f = shard_map(
            _step,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
        return jax.jit(f, donate_argnums=(0, 1))

    return init_fn, make_step
