"""GSPMD auto-parallel train step (the pjit path).

The manual path (parallel/hybrid.py) expresses every collective
explicitly under ``shard_map`` — required for pipeline scans, ring
attention, and MoE all_to_all. For plain TP x DP, XLA's GSPMD partitioner
can derive the collectives itself from array shardings (the
Mesh-TensorFlow/GSPMD lineage — PAPERS.md): write the model as
SINGLE-DEVICE code (``tp_axis=None``), put PartitionSpecs on params and
batch, and ``jit`` inserts the all-reduces/gathers.

This module provides that alternative front end. It is the direct analog
of BASELINE.json's north-star phrasing ("ParallelMode mesh maps onto a
jax.sharding.Mesh ... dispatch to XLA collectives"), and doubles as an
oracle: tests assert manual and auto paths produce the same training
trajectory.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from pipegoose_tpu.distributed.parallel_context import ParallelContext


def _shardings(tree_specs: Any, mesh) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        tree_specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def make_auto_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    param_specs: Any,
    optimizer: optax.GradientTransformation,
    parallel_context: Optional[ParallelContext] = None,
    batch_spec: P = P("data"),
):
    """(init_fn, step_fn) with GSPMD-derived parallelism.

    ``loss_fn(params, batch) -> scalar`` must be plain single-device
    model code (no axis names / collectives) — e.g.
    ``bloom.loss_fn(..., tp_axis=None)``. Optimizer state inherits each
    param's sharding (replicate over data; ZeRO-style state sharding is
    the manual path's job). step_fn donates its params/opt_state buffers
    — keep only the returned arrays.
    """
    ctx = parallel_context or ParallelContext.get_context()
    if ctx is None:
        raise ValueError("no ParallelContext; construct one first")
    mesh = ctx.mesh
    p_sh = _shardings(param_specs, mesh)
    b_sh = NamedSharding(mesh, batch_spec)
    rep = NamedSharding(mesh, P())

    def init_fn(params):
        from pipegoose_tpu.nn.parallel import shard_tree

        params = shard_tree(params, param_specs, ctx)
        # let GSPMD choose optimizer-state layouts: momentum-like leaves
        # inherit their param's sharding through the init computation
        opt_state = jax.jit(optimizer.init)(params)
        return params, opt_state

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # pin param shardings so they don't drift across steps
        params = jax.lax.with_sharding_constraint(params, p_sh)
        return params, opt_state, loss

    def step_fn(params, opt_state, batch):
        batch = jax.device_put(batch, b_sh)
        return step(params, opt_state, batch)

    return init_fn, step_fn
