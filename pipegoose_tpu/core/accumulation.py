"""Gradient accumulation.

Occupies the role of the reference's ``core/bucket`` subsystem (Bucket:
bucket.py:6-88, BucketDistributor: dist.py:26-67 — a fixed-size grad
buffer meant to batch DP all-reduces, left unfinished and unwired,
SURVEY.md §2.1). On TPU the *communication* half of bucketing is moot —
the whole grad pytree is reduced by one fused XLA collective per step —
so what remains genuinely useful is the *memory* half: accumulating
gradients over K microbatches to train with large effective batches.
Here that is a ``lax.scan`` inside the compiled step: the accumulator
buffer is the scan carry, no host-side bucket bookkeeping.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def accumulate_gradients(
    loss_fn: Callable[[Any, Any], jax.Array],
    params: Any,
    microbatches: Any,  # pytree with leading dim K
    mean: bool = True,
):
    """(mean_loss, accumulated_grads) over the K leading-dim microbatches.

    One compiled scan: grads for microbatch i are formed and folded into
    the running sum before microbatch i+1's activations exist — the same
    peak-memory effect the reference's Bucket.add_tensor re-pointing
    chased (bucket.py:53-55), without mutation.
    """
    K = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    grad_fn = jax.value_and_grad(loss_fn)

    def body(carry, mb):
        loss_sum, gsum = carry
        loss, grads = grad_fn(params, mb)
        gsum = jax.tree_util.tree_map(jnp.add, gsum, grads)
        return (loss_sum + loss, gsum), None

    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    (loss_sum, gsum), _ = lax.scan(body, (jnp.zeros(()), zeros), microbatches)
    if mean:
        loss_sum = loss_sum / K
        gsum = jax.tree_util.tree_map(lambda g: g / K, gsum)
    return loss_sum, gsum


def make_accumulating_loss(
    loss_fn: Callable[..., jax.Array], n_accum: int
) -> Callable[..., jax.Array]:
    """Wrap a per-batch loss into one that splits its batch into
    ``n_accum`` microbatches and averages — drop-in for
    make_hybrid_train_step's loss_fn (grads then accumulate through the
    scan automatically under value_and_grad). An optional rng argument
    (the ``with_rng`` train-step form) is folded per microbatch so e.g.
    router noise differs across them.

    Exactness caveat: microbatch losses are averaged with EQUAL weight.
    For unmasked batches (or any loss linear in its examples) this
    reproduces the one-shot large-batch step exactly; for
    attention-masked losses whose microbatches carry different
    valid-token counts, the equal-weight average differs from the
    global token-weighted mean — arrange microbatching so token counts
    match (e.g. length-grouped batches) if exactness matters."""
    from pipegoose_tpu.nn.pipeline_parallel.microbatch import split

    def wrapped(params, batch, *rng):
        mbs = split(batch, n_accum)

        # remat each microbatch: without it, differentiating through the
        # scan stores every microbatch's residuals and peak activation
        # memory equals the full batch — no accumulation benefit
        @jax.checkpoint
        def body(loss_sum, mb_and_i):
            mb, i = mb_and_i
            extra = (jax.random.fold_in(rng[0], i),) if rng else ()
            return loss_sum + loss_fn(params, mb, *extra), None

        total, _ = lax.scan(
            body, jnp.zeros(()), (mbs, jnp.arange(n_accum))
        )
        return total / n_accum

    return wrapped
