"""Quantized inference: int8/int4 weights + the dequant-fused matmul.

ROADMAP item 4's serving half. The gradient wire already runs int8
(distributed/compressed.py, EQuARX); this package brings the same
byte-halving to the WEIGHTS a serving engine holds resident and — via
serving/kv_pool.py's ``kv_dtype`` — to the paged KV pool, so HBM stops
capping concurrent users before compute does.

- :mod:`pipegoose_tpu.quant.weights` — ``quantize_params`` turns a
  Bloom param tree's block kernels into ``{"q", "scale", "bias"}``
  leaves (per-channel symmetric int8, or grouped int4 packed two
  nibbles per int8byte) that the tensor-parallel layers dispatch on
  transparently; ``quantize_param_specs`` derives the matching
  PartitionSpec tree so tp=2 serving needs no new sharding knowledge.
- :mod:`pipegoose_tpu.quant.matmul` — ``quantized_matmul``: the Pallas
  dequant-fused kernel in ops/fused_ce.py's tiling idiom (weights stay
  int8 in HBM; dequant happens per-tile on the way through VMEM) with
  a numerically identical XLA reference that CPU tier-1 runs.

Everything defaults OFF: an engine without ``weight_dtype``/``kv_dtype``
never imports a kernel from here and stays byte-identical to PR 1/6.
"""
from pipegoose_tpu.quant.matmul import (
    dequantize_weight,
    quantized_matmul,
    unpack_int4,
)
from pipegoose_tpu.quant.weights import (
    QuantSpec,
    dequantize_params,
    quantize_param_specs,
    quantize_params,
    quantized_weight_bytes,
)

__all__ = [
    "QuantSpec",
    "dequantize_params",
    "dequantize_weight",
    "quantize_param_specs",
    "quantize_params",
    "quantized_matmul",
    "quantized_weight_bytes",
    "unpack_int4",
]
