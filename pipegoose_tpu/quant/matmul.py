"""Dequant-fused matmul: y = x @ dequantize(q, scale), weights int8 in HBM.

The Pallas kernel follows ops/fused_ce.py's tiling idiom: grid
(token_blocks, out_blocks), the full contraction dim per tile (Bloom's
h / 4h fit VMEM comfortably at the block sizes used), fp32 MXU
accumulation via ``preferred_element_type``. The weight tile crosses
HBM -> VMEM as int8 (half/quarter the bytes of the fp kernel — on a
bandwidth-bound decode step that IS the speedup) and is dequantized
in VMEM per tile; a full-precision copy of the weight never exists in
HBM. Per-tile scale rows ride alongside as (1|G, block_o) tiles.

Two numerically identical implementations behind one call:

- ``impl="pallas"`` — the fused kernel (compiled on TPU; interpret
  mode anywhere, the same fallback convention as ops/flash_attention).
- ``impl="xla"`` — a jnp reference with the SAME math and scaling
  order, the default off-TPU so CPU tier-1 pays vectorized-numpy cost
  rather than interpreter cost. Kernel-vs-reference equivalence is
  pinned by tests/quant/test_quant_matmul.py.

int8 applies the per-out-channel scale AFTER the int8-as-fp32 dot
(mathematically the same column scaling, one multiply per output
element instead of per weight); int4 must dequantize before the dot
(scales vary along the contraction dim). Both paths share the
``unpack_int4`` nibble convention of quant/weights.py:pack_int4.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from pipegoose_tpu.ops.fused_ce import _pick_block, _resolve_interpret


def _resolve_impl(impl: Optional[str]) -> str:
    if impl is None:
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl not in ("pallas", "xla"):
        raise ValueError(f"impl must be 'pallas' or 'xla', got {impl!r}")
    return impl


def unpack_int4(packed: jax.Array) -> jax.Array:
    """(..., K//2, N) int8 -> (..., K, N) int8 values in [-8, 7]: the
    low nibble is row 2i, the high nibble row 2i+1 (arithmetic shifts
    sign-extend, matching pack_int4's two's-complement nibbles)."""
    low = jnp.right_shift(jnp.left_shift(packed, 4), 4)
    high = jnp.right_shift(packed, 4)
    inter = jnp.stack([low, high], axis=-2)  # (..., K//2, 2, N)
    return inter.reshape(
        packed.shape[:-2] + (packed.shape[-2] * 2, packed.shape[-1])
    )


def dequantize_weight(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Quantized leaf -> fp32 kernel (reference/testing; the fused
    paths never materialize this at model scale). Layout is detected
    from the shapes: int8 per-channel scales have one dim fewer than
    ``q``; int4 grouped scales have the same rank (a grouped
    contraction dim)."""
    if scale.ndim == q.ndim - 1:
        return q.astype(jnp.float32) * scale[..., None, :]
    if scale.ndim != q.ndim:
        raise ValueError(
            f"scale rank {scale.ndim} matches neither int8 (rank "
            f"{q.ndim - 1}) nor int4 (rank {q.ndim}) for q rank {q.ndim}"
        )
    q4 = unpack_int4(q)
    k = q4.shape[-2]
    groups = scale.shape[-2]
    if k % groups:
        raise ValueError(
            f"unpacked contraction dim {k} not divisible by "
            f"{groups} scale groups"
        )
    g = k // groups
    grouped = q4.reshape(q4.shape[:-2] + (groups, g, q4.shape[-1]))
    w = grouped.astype(jnp.float32) * scale[..., None, :]
    return w.reshape(q4.shape)


def _matmul_xla(x32: jax.Array, q: jax.Array, scale: jax.Array,
                int4: bool) -> jax.Array:
    if int4:
        return jnp.dot(x32, dequantize_weight(q, scale),
                       preferred_element_type=jnp.float32)
    y = jnp.dot(x32, q.astype(jnp.float32),
                preferred_element_type=jnp.float32)
    return y * scale[None, :]


def _matmul_int8_pallas(x, q, scale, block_t, block_o, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_tot, k = x.shape
    n = q.shape[-1]
    nt, no = t_tot // block_t, n // block_o

    def kernel(x_ref, q_ref, s_ref, o_ref):
        xb = x_ref[...].astype(jnp.float32)          # (BT, K)
        qb = q_ref[...].astype(jnp.float32)          # (K, BO) from int8
        acc = jax.lax.dot_general(
            xb, qb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[...] = acc * s_ref[...]                # per-out-channel

    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(nt, no),
            in_specs=[
                pl.BlockSpec((block_t, k), lambda i, j: (i, 0)),
                pl.BlockSpec((k, block_o), lambda i, j: (0, j)),
                pl.BlockSpec((1, block_o), lambda i, j: (0, j)),
            ],
            out_specs=pl.BlockSpec((block_t, block_o),
                                   lambda i, j: (i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((t_tot, n), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(x, q, scale[None, :])


def _matmul_int4_pallas(x, q, scale, block_t, block_o, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t_tot, k = x.shape
    kp, n = q.shape
    groups = scale.shape[-2]
    g = k // groups
    nt, no = t_tot // block_t, n // block_o

    def kernel(x_ref, q_ref, s_ref, o_ref):
        xb = x_ref[...].astype(jnp.float32)          # (BT, K)
        q4 = unpack_int4(q_ref[...])                 # (K, BO) int8
        sb = s_ref[...]                              # (G, BO) f32
        w = q4.astype(jnp.float32).reshape(groups, g, block_o)
        w = (w * sb[:, None, :]).reshape(k, block_o)
        o_ref[...] = jax.lax.dot_general(
            xb, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(nt, no),
            in_specs=[
                pl.BlockSpec((block_t, k), lambda i, j: (i, 0)),
                pl.BlockSpec((kp, block_o), lambda i, j: (0, j)),
                pl.BlockSpec((groups, block_o), lambda i, j: (0, j)),
            ],
            out_specs=pl.BlockSpec((block_t, block_o),
                                   lambda i, j: (i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((t_tot, n), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(x, q, scale)


def quantized_matmul(
    x: jax.Array,       # (..., K) activations (any float dtype)
    q: jax.Array,       # (K, N) int8 | (K//2, N) int4-packed int8
    scale: jax.Array,   # (N,) int8 per-channel | (K//G, N) int4 grouped
    *,
    block_t: int = 128,
    block_o: int = 256,
    impl: Optional[str] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """fp32 ``x @ dequantize(q, scale)`` without an fp weight in HBM.

    Leading dims of ``x`` are batch (flattened through the kernel and
    restored); the int8-vs-int4 layout is detected from the shapes the
    same way as :func:`dequantize_weight`. ``impl=None`` resolves to
    the Pallas kernel on TPU and the XLA reference elsewhere;
    ``interpret`` follows ops/fused_ce's convention (None = compiled
    on TPU, interpreter off-TPU) and only matters for ``"pallas"``.
    Returns fp32 — callers cast, matching the TP layers' convention.
    """
    k_in = x.shape[-1]
    int4 = scale.ndim == q.ndim
    if not int4 and q.shape[-2] != k_in:
        raise ValueError(
            f"int8 weight contraction dim {q.shape[-2]} != x's {k_in}"
        )
    if int4 and q.shape[-2] * 2 != k_in:
        raise ValueError(
            f"int4-packed contraction dim {q.shape[-2]}*2 != x's {k_in}"
        )
    batch = x.shape[:-1]
    x2 = x.reshape((-1, k_in)).astype(jnp.float32)
    n = q.shape[-1]
    impl = _resolve_impl(impl)
    if impl == "xla":
        y = _matmul_xla(x2, q, scale, int4)
        return y.reshape(batch + (n,))
    interpret = _resolve_interpret(interpret)
    t = x2.shape[0]
    # token blocks: largest power of two <= block_t covering t (pad up)
    pow2 = 8
    while pow2 < min(t, block_t):
        pow2 *= 2
    bt = min(pow2, block_t)
    if t % bt:
        x2 = jnp.pad(x2, ((0, bt - t % bt), (0, 0)))
    bo, exact = _pick_block(n, block_o)
    if not exact and not interpret:
        raise ValueError(
            f"quantized matmul: no block size >= 8 among halvings of "
            f"{block_o} divides N={n}; pad the out dim or pass a "
            f"block_o dividing it (compiled TPU runs reject the "
            f"whole-dim fallback tile — same contract as fused CE)"
        )
    fn = _matmul_int4_pallas if int4 else _matmul_int8_pallas
    y = fn(x2, q, scale, bt, bo, interpret)
    return y[:t].reshape(batch + (n,))
