"""Weight quantization of a Bloom param tree for serving.

``quantize_params(params, spec)`` walks the tree and replaces every
transformer-block kernel — ``blocks/attn/qkv``, ``blocks/attn/out``,
``blocks/mlp/up``, ``blocks/mlp/down`` — with a quantized leaf the
tensor-parallel layers (nn/tensor_parallel/layers.py) dispatch on by
shape of the dict, not by global mode:

    {"kernel": (L, in, out) fp, "bias": ...}
      -> int8: {"q": (L, in, out) int8,
                "scale": (L, out) f32,            # per-OUT-channel
                "bias": ...}
      -> int4: {"q": (L, in//2, out) int8,        # 2 nibbles per byte
                "scale": (L, in//G, out) f32,     # per (group, out)
                "bias": ...}

Embedding, layer norms, and biases stay full precision: the embedding
doubles as the lm head (logits_fn) where per-channel error lands
directly on the greedy argmax, and the rest is byte-noise. This is the
standard weight-only serving trade (W8A16 — LLM.int8(), AWQ): compute
stays fp32/bf16, only the resident bytes shrink.

Scaling is SYMMETRIC max-abs, the same convention as the gradient wire
(distributed/compressed.py): int8 per output channel over the
contraction dim, int4 per ``group_size`` slice of the contraction dim
(finer scales because 4-bit buckets are 16x coarser). int4 values live
in [-8, 7] and pack two adjacent contraction rows per int8 byte (row
2i in the low nibble, 2i+1 high), so the packed array shards along the
contraction dim exactly like the fp kernel it replaces —
``quantize_param_specs`` maps the fp PartitionSpec tree to the
quantized layout so tp engines keep their sharding contract unchanged.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

WEIGHT_DTYPES = ("int8", "int4")

_INT8_MAX = 127.0
_INT4_MAX = 7.0


@dataclasses.dataclass(frozen=True)
class QuantSpec:
    """One weight-quantization recipe.

    ``weight_dtype``: "int8" (per-out-channel scales) or "int4"
    (grouped: one scale per ``group_size`` contraction rows per out
    channel, values packed two per byte). ``group_size`` must be even
    and divide every quantized kernel's contraction dim — and, under
    tensor parallelism, the PER-SHARD contraction dim of the
    row-parallel kernels (groups must not straddle shard boundaries)."""

    weight_dtype: str = "int8"
    group_size: int = 32

    def __post_init__(self):
        if self.weight_dtype not in WEIGHT_DTYPES:
            raise ValueError(
                f"weight_dtype must be one of {WEIGHT_DTYPES}, got "
                f"{self.weight_dtype!r}"
            )
        if self.group_size < 2 or self.group_size % 2:
            raise ValueError(
                f"group_size must be an even int >= 2, got {self.group_size}"
            )


def _is_target(path: Tuple[str, ...], node: dict) -> bool:
    """Quantize exactly the stacked block kernels: a dict leaf holding
    a ``kernel`` of rank >= 2 under the ``blocks`` subtree."""
    return (
        len(path) > 0
        and path[0] == "blocks"
        and "kernel" in node
        and getattr(node["kernel"], "ndim", 0) >= 2
    )


def pack_int4(q4: jax.Array) -> jax.Array:
    """(..., K, N) int values in [-8, 7] -> (..., K//2, N) int8, row 2i
    in the low nibble and row 2i+1 in the high nibble of each byte."""
    if q4.shape[-2] % 2:
        raise ValueError(
            f"int4 packing needs an even contraction dim, got {q4.shape}"
        )
    pairs = q4.reshape(q4.shape[:-2] + (q4.shape[-2] // 2, 2, q4.shape[-1]))
    low = pairs[..., 0, :].astype(jnp.int32) & 0xF
    high = pairs[..., 1, :].astype(jnp.int32) & 0xF
    return jax.lax.bitcast_convert_type(
        (low | (high << 4)).astype(jnp.uint8), jnp.int8
    )


def _quantize_kernel(kernel: jax.Array, spec: QuantSpec) -> dict:
    k32 = kernel.astype(jnp.float32)
    tiny = jnp.finfo(jnp.float32).tiny
    if spec.weight_dtype == "int8":
        # per-out-channel symmetric: scale over the contraction dim
        scale = jnp.maximum(
            jnp.max(jnp.abs(k32), axis=-2) / _INT8_MAX, tiny
        )
        q = jnp.clip(
            jnp.round(k32 / scale[..., None, :]), -_INT8_MAX, _INT8_MAX
        ).astype(jnp.int8)
        return {"q": q, "scale": scale}
    g = spec.group_size
    k_in = kernel.shape[-2]
    if k_in % g:
        raise ValueError(
            f"int4 group_size={g} must divide the contraction dim "
            f"{k_in} of kernel shape {kernel.shape}"
        )
    grouped = k32.reshape(
        kernel.shape[:-2] + (k_in // g, g, kernel.shape[-1])
    )
    scale = jnp.maximum(
        jnp.max(jnp.abs(grouped), axis=-2) / _INT4_MAX, tiny
    )  # (..., K//G, N)
    q4 = jnp.clip(
        jnp.round(grouped / scale[..., None, :]), -8.0, _INT4_MAX
    ).astype(jnp.int8)
    return {
        "q": pack_int4(q4.reshape(kernel.shape)),
        "scale": scale,
    }


def quantize_params(params: dict, spec: QuantSpec) -> dict:
    """The one-call API: the same tree with every block kernel replaced
    by its quantized ``{"q", "scale"[, "bias"]}`` leaf (bias and every
    non-target leaf pass through untouched, same objects)."""

    def walk(node: Any, path: Tuple[str, ...]) -> Any:
        if isinstance(node, dict):
            if _is_target(path, node):
                out = _quantize_kernel(node["kernel"], spec)
                for k, v in node.items():
                    if k != "kernel":
                        out[k] = v
                return out
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        return node

    return walk(params, ())


def _dequantize_kernel(leaf: dict, dtype) -> jax.Array:
    from pipegoose_tpu.quant.matmul import dequantize_weight

    return dequantize_weight(leaf["q"], leaf["scale"]).astype(dtype)


def dequantize_params(qparams: dict, dtype=jnp.float32) -> dict:
    """Inverse for tests and accuracy studies: quantized leaves back to
    ``{"kernel", ...}`` fp trees (lossy — the round-trip error is what
    the accuracy-contract tests bound)."""

    def walk(node: Any) -> Any:
        if isinstance(node, dict):
            if "q" in node and "scale" in node:
                out = {"kernel": _dequantize_kernel(node, dtype)}
                for k, v in node.items():
                    if k not in ("q", "scale"):
                        out[k] = v
                return out
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(qparams)


def quantize_param_specs(param_specs: dict, params: dict,
                         spec: QuantSpec) -> dict:
    """The PartitionSpec tree matching ``quantize_params``' layout.

    ``q`` inherits the kernel's spec (int4's packed contraction dim is
    the same axis, halved — contiguous shards stay contiguous). The
    scale spec drops the contraction entry for int8 (scales are
    per-out-channel) and keeps the kernel's spec for int4 (scales carry
    a grouped contraction dim that shards with the kernel). ``params``
    is the ORIGINAL fp tree — it decides which paths are targets, so
    specs and params cannot drift."""
    from jax.sharding import PartitionSpec as P

    def walk(spec_node: Any, param_node: Any, path: Tuple[str, ...]) -> Any:
        if isinstance(param_node, dict):
            if _is_target(path, param_node):
                kspec = spec_node["kernel"]
                ndim = param_node["kernel"].ndim
                entries = list(kspec) + [None] * (ndim - len(kspec))
                if spec.weight_dtype == "int8":
                    sspec = P(*(entries[:-2] + [entries[-1]]))
                else:
                    sspec = P(*entries)
                out = {"q": kspec, "scale": sspec}
                for k, v in spec_node.items():
                    if k != "kernel":
                        out[k] = v
                return out
            return {
                k: walk(spec_node[k], v, path + (k,))
                for k, v in param_node.items()
            }
        return spec_node

    return walk(param_specs, params, ())


def quantized_weight_bytes(params: dict) -> dict:
    """Host-side byte census of a (possibly quantized) param tree,
    grouped by dtype string — the serving memory report's weights half
    (doctor satellite). Works on fp trees too (one fp entry)."""
    by_dtype: dict = {}
    for leaf in jax.tree_util.tree_leaves(params):
        arr = leaf if hasattr(leaf, "dtype") else jnp.asarray(leaf)
        nbytes = int(arr.size) * int(jnp.dtype(arr.dtype).itemsize)
        key = str(arr.dtype)
        by_dtype[key] = by_dtype.get(key, 0) + nbytes
    return {
        "bytes_by_dtype": by_dtype,
        "total_bytes": int(sum(by_dtype.values())),
    }


def validate_tp_compat(config: Any, tp: int, spec: Optional[QuantSpec]) -> None:
    """Fail at engine construction, not inside shard_map: int4 groups
    must divide the row-parallel kernels' PER-SHARD contraction dims
    (h/tp for attn.out, 4h/tp for mlp.down), and the packed dim must
    split evenly over the shards."""
    if spec is None or spec.weight_dtype != "int4" or tp <= 1:
        return
    h = config.hidden_size
    for name, k_in in (("attn.out", h), ("mlp.down", 4 * h)):
        local = k_in // tp
        if k_in % tp or local % spec.group_size or local % 2:
            raise ValueError(
                f"int4 group_size={spec.group_size} incompatible with "
                f"tp={tp}: {name} kernel's per-shard contraction dim "
                f"{k_in}/{tp} must be even and a multiple of the group"
            )
