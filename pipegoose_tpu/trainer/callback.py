"""Trainer callbacks.

Real implementation of the reference's no-op ``Callback``
(pipegoose/trainer/callback.py:4-14). Hooks mirror and extend its
on_fit_start/on_fit_end surface with per-step and checkpoint events.
"""
from __future__ import annotations

from typing import Any, Optional


def _host_scalar(x: Any) -> float:
    """Scalar (possibly multi-host sharded) -> host float.

    ``float()``/``bool()`` raise on non-fully-addressable arrays, which
    a multihost trainer produces (tests/distributed/test_multihost.py);
    fall back to a replicated all-gather in that case (advisor r4).
    """
    try:
        return float(x)
    except RuntimeError:
        from jax.experimental import multihost_utils

        import numpy as np

        return float(np.asarray(multihost_utils.process_allgather(x)).reshape(-1)[0])


class Callback:
    order: int = 0

    def on_fit_start(self, trainer: Any) -> None: ...

    def on_fit_end(self, trainer: Any) -> None: ...

    # teardown on the FAILURE path: on_fit_end only runs when fit
    # finishes, so process-global state a callback armed (e.g. the
    # chaos checkpoint-fault seam) needs a hook that fires when fit
    # raises. Called best-effort; exceptions here never mask the
    # original one.
    def on_fit_abort(self, trainer: Any, exc: BaseException) -> None: ...

    def on_step_start(self, trainer: Any, step: int) -> None: ...

    def on_step_end(self, trainer: Any, step: int, loss: float) -> None: ...

    def on_checkpoint(self, trainer: Any, step: int, path: str) -> None: ...


class LossLoggerCallback(Callback):
    """Periodic loss/throughput logging via the trainer's logger."""

    def __init__(self, every: int = 10):
        self.every = every
        self._t0: Optional[float] = None
        self._tokens = 0

    def on_step_end(self, trainer: Any, step: int, loss: float) -> None:
        import time

        self._tokens += trainer.tokens_per_step
        if self._t0 is None:
            self._t0 = time.perf_counter()
            self._tokens = 0
            return
        if step % self.every == 0:
            dt = time.perf_counter() - self._t0
            tps = self._tokens / dt if dt > 0 else float("nan")
            trainer.logger.info(
                f"step {step} loss {_host_scalar(loss):.4f} tokens/s {tps:,.0f}"
            )
            self._t0 = time.perf_counter()
            self._tokens = 0


class CheckpointCallback(Callback):
    """Periodic sharded checkpointing of the full train state."""

    def __init__(self, directory: str, every: int = 1000, save_final: bool = True):
        self.directory = directory
        self.every = every
        self.save_final = save_final
        self._last_saved = -1

    def _save(self, trainer: Any, step: int) -> None:
        import math

        import jax
        import jax.numpy as jnp

        from pipegoose_tpu.utils.checkpoint import (
            available_steps,
            save_train_state,
        )

        # a COMPLETE checkpoint for this step already on disk means the
        # state came FROM it (recovery rolled back and restored it —
        # the only path that revisits a step number): re-saving would
        # hit save_pretrained's exists-check and kill the run. Quick
        # dir listing, only on steps that passed the `every` gate.
        if step in available_steps(self.directory):
            self._last_saved = max(self._last_saved, step)
            return

        # persisting non-finite params would poison every later restore
        # (AutoRecovery would loop restoring the poisoned checkpoint
        # until max_restores). Two guards:
        # 1. the last recorded loss — catches divergence that happened on
        #    an earlier step (e.g. slipped past a FailureDetector with
        #    check_every > 1) at zero extra device work;
        if trainer.state.last_loss is not None:
            last_loss = _host_scalar(trainer.state.last_loss)
            if not math.isfinite(last_loss):
                trainer.logger.warning(
                    f"step {step}: refusing to checkpoint non-finite state "
                    f"(loss {last_loss})"
                )
                return
        # 2. the params AND optimizer state — the loss canary is computed
        #    from PRE-update params, so a step whose optimizer update
        #    itself overflowed (finite loss, NaN update) would slip past
        #    it; and opt_state (e.g. overflowed Adam moments under still-
        #    finite params) is restored too, so a poisoned moment would
        #    re-poison training on resume (advisor r4). One fused
        #    reduction per checkpoint; negligible next to the write.
        import functools

        float_leaves = [
            l
            for l in jax.tree_util.tree_leaves((trainer.params, trainer.opt_state))
            if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)
        ]
        finite = functools.reduce(
            jnp.logical_and,
            [jnp.isfinite(l).all() for l in float_leaves],
            jnp.asarray(True),
        )
        if not _host_scalar(finite):
            trainer.logger.warning(
                f"step {step}: refusing to checkpoint non-finite params/opt_state"
            )
            return
        path = save_train_state(self.directory, step, trainer.params, trainer.opt_state)
        self._last_saved = step
        trainer.logger.info(f"checkpointed step {step} -> {path}")
        for cb in trainer.callbacks:
            cb.on_checkpoint(trainer, step, path)

    def on_step_end(self, trainer: Any, step: int, loss: float) -> None:
        # trust the TRAINER's step, not the argument: AutoRecovery (which
        # runs earlier in this callback round, order=-10) may have rolled
        # state.step back — saving the restored old state under the
        # failing step's label would poison later restores, and saving
        # the already-on-disk step again would collide
        step = trainer.state.step
        if step > 0 and step % self.every == 0 and step > self._last_saved:
            self._save(trainer, step)

    def on_fit_end(self, trainer: Any) -> None:
        # short runs would otherwise end with NO checkpoint despite the
        # user configuring a checkpoint directory
        from pipegoose_tpu.utils.checkpoint import latest_step

        existing = latest_step(self.directory)
        already = max(self._last_saved, existing if existing is not None else -1)
        if self.save_final and trainer.state.step > already:
            self._save(trainer, trainer.state.step)
