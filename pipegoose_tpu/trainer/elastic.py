"""Elastic recovery: survive device loss by resharding onto the
survivors and resuming — no manual restart.

``AutoRecovery`` (trainer/recovery.py) survives one failure shape:
numerical divergence, restored onto the SAME mesh. The failure that
actually ends long multi-slice runs is the mesh itself changing under
the job — a preempted slice, a failed chip — and recovering from that
needs four moves the same-mesh path never makes ("On Optimizing the
Communication of Model Parallelism", arxiv 2211.05322, treats the
cross-mesh reshard at the center of this as a first-class op):

1. **replan**: ask the compile-time parallelism planner
   (``pipegoose_tpu/planner/``) for the best FEASIBLE (dp, tp, pp)
   layout at the surviving device count — the same static search that
   ranks layouts before a run ranks them again at recovery time;
2. **rebuild**: construct a fresh ``ParallelContext`` over exactly the
   surviving devices and re-lower the hybrid train step on it through
   the trainer's stored build config (``Trainer.rebuild``, the
   ``parallel/hybrid.py`` rebuild hook);
3. **cross-mesh restore**: ``restore_train_state`` reads the orbax
   checkpoint — written layout-independent — sharded directly onto the
   NEW mesh (the thing the reference's per-(tp,pp)-file checkpoints
   could never do);
4. **verify + resume**: optionally diff the rebuilt compiled step with
   the mesh doctor (zero partitioner-inserted resharding on the new
   mesh), dump an ``elastic_resume`` black box naming the lost
   devices, the chosen layout, and the rewind step, and let ``fit``
   continue — the SAME Python loop, now driving the new program.

The device-loss signal arrives as a structured ``device_loss``
flight-recorder trigger (fired in production by a cluster watcher; in
tests by the chaos harness, ``testing/chaos.py``) whose details carry
the surviving device ids. Everything else — divergence, loss spikes —
falls through to ``AutoRecovery``'s same-mesh restore untouched.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

from pipegoose_tpu.trainer.recovery import AutoRecovery, TrainingDiverged


class NoFeasibleLayout(TrainingDiverged):
    """No layout fits the surviving device count — elastic recovery is
    impossible and the failure must surface to the operator."""


def shrink_layout(trainer: Any, n_devices: int) -> Any:
    """Planner-free fallback layout: keep the model axes (tp, pp, ep —
    changing them needs model-divisibility knowledge this function
    doesn't have) and shrink dp to what the survivors allow. Raises
    :class:`NoFeasibleLayout` when the survivors can't hold even dp=1.

    The planner-backed :func:`planner_layout_fn` is strictly better
    when a builder for the model exists — this is the floor that works
    for any model the trainer can hold."""
    from pipegoose_tpu.planner.space import Candidate

    ctx = trainer.parallel_context
    fixed = (ctx.tensor_parallel_size * ctx.pipeline_parallel_size
             * ctx.expert_parallel_size * ctx.sequence_parallel_size
             * ctx.diloco_parallel_size)
    dp = n_devices // fixed
    if dp < 1:
        raise NoFeasibleLayout(
            f"{n_devices} surviving device(s) cannot hold the current "
            f"non-data axes (tp*pp*ep*sp*diloco = {fixed}); pass a "
            f"planner-backed layout_fn that may also change tp/pp"
        )
    return Candidate(
        dp=dp, tp=ctx.tensor_parallel_size, pp=ctx.pipeline_parallel_size,
        ep=ctx.expert_parallel_size,
    )


def planner_layout_fn(
    builder: Any, **plan_kwargs: Any
) -> Callable[[Any, int], Any]:
    """``layout_fn`` backed by the parallelism planner: at recovery
    time, rank every feasible layout at the surviving count through
    ``planner.best_layout_at`` (real steps, shape-only compiles) and
    return the winner. ``builder`` is the run's plan model (e.g.
    ``planner.BloomPlanModel`` at the run's batch/seq)."""

    def layout_fn(trainer: Any, n_devices: int) -> Any:
        from pipegoose_tpu.planner import best_layout_at

        cand = best_layout_at(builder, n_devices, **plan_kwargs)
        if cand is None:
            raise NoFeasibleLayout(
                f"planner found no feasible layout at {n_devices} "
                f"surviving device(s)"
            )
        return cand

    return layout_fn


class ElasticRecovery(AutoRecovery):
    """``AutoRecovery`` that additionally survives DEVICE LOSS by
    replanning, rebuilding, and cross-mesh-restoring (module
    docstring). Non-device-loss failures take the inherited same-mesh
    path, including the older-checkpoint fallback.

    ``layout_fn(trainer, n_devices) -> layout`` chooses the new
    (dp, tp, pp[, ep]) — any object with those attributes, normally a
    ``planner.Candidate``. Default: :func:`planner_layout_fn` when
    ``planner_builder`` is given, else :func:`shrink_layout` (keep
    tp/pp, shrink dp). ``min_devices`` refuses recovery below a floor
    (a 1-device "recovery" of a 256-chip run is usually worse than
    paging someone). ``verify_doctor``: after the rebuild, diff the
    recompiled step with the mesh doctor and raise on
    partitioner-inserted resharding — a recovery onto a slow program
    is a silent outage.

    Each elastic recovery consumes one restore budget (shared with the
    divergence path: a flapping cluster must exhaust loudly)."""

    def __init__(
        self,
        directory: str,
        max_restores: int = 3,
        check_every: int = 1,
        spike_factor: Optional[float] = None,
        window: int = 50,
        recorder: Optional[Any] = None,
        layout_fn: Optional[Callable[[Any, int], Any]] = None,
        planner_builder: Optional[Any] = None,
        min_devices: int = 1,
        verify_doctor: bool = True,
    ):
        super().__init__(directory, max_restores, check_every,
                         spike_factor, window, recorder)
        if layout_fn is not None and planner_builder is not None:
            raise ValueError(
                "pass layout_fn OR planner_builder, not both"
            )
        if planner_builder is not None:
            layout_fn = planner_layout_fn(planner_builder)
        self.layout_fn = layout_fn
        self.min_devices = min_devices
        self.verify_doctor = verify_doctor
        # forensics: one record per elastic recovery, in order
        self.resumes: List[dict] = []

    # -- dispatch ----------------------------------------------------------

    def handle_failure(self, trainer: Any, step: int, reason: str) -> None:
        trig = self.active_trigger
        if trig is not None and getattr(trig, "name", None) == "device_loss":
            self._handle_device_loss(trainer, step, reason, trig)
            return
        super().handle_failure(trainer, step, reason)

    # -- the elastic path --------------------------------------------------

    def _surviving_devices(self, trig: Any) -> Sequence[Any]:
        import jax

        ids = trig.details.get("surviving_device_ids")
        if not ids:
            raise TrainingDiverged(
                f"device_loss trigger at step {trig.step} names no "
                f"surviving devices (details keys: "
                f"{sorted(trig.details)}) — cannot reshard"
            )
        by_id = {int(d.id): d for d in jax.devices()}
        missing = [i for i in ids if int(i) not in by_id]
        if missing:
            raise TrainingDiverged(
                f"surviving device ids {missing} not present in the "
                f"backend's device list — cannot reshard"
            )
        return [by_id[int(i)] for i in ids]

    def _handle_device_loss(
        self, trainer: Any, step: int, reason: str, trig: Any
    ) -> None:
        if self.restores >= self.max_restores:
            raise TrainingDiverged(
                f"step {step}: {reason} — {self.restores} restores already "
                "spent; the cluster is flapping, aborting"
            )
        surviving = self._surviving_devices(trig)
        n = len(surviving)
        if n < self.min_devices:
            raise TrainingDiverged(
                f"step {step}: {reason} — only {n} device(s) survive, "
                f"below the elastic floor min_devices={self.min_devices}"
            )
        trainer.logger.warning(
            f"step {step}: {reason} — elastic recovery onto {n} "
            f"surviving device(s)"
        )
        # 1) replan: the best feasible layout at the surviving count
        layout_fn = self.layout_fn or shrink_layout
        layout = layout_fn(trainer, n)
        layout_desc = {
            "dp": int(getattr(layout, "dp", 1)),
            "tp": int(getattr(layout, "tp", 1)),
            "pp": int(getattr(layout, "pp", 1)),
            "ep": int(getattr(layout, "ep", 1)),
        }
        world = 1
        for v in layout_desc.values():
            world *= v
        if world > n:
            raise TrainingDiverged(
                f"step {step}: layout_fn chose {layout_desc} needing "
                f"{world} devices but only {n} survive"
            )
        trainer.logger.info(
            f"elastic: chosen layout dp={layout_desc['dp']} "
            f"tp={layout_desc['tp']} pp={layout_desc['pp']} "
            f"ep={layout_desc['ep']} on {n} device(s)"
        )
        # 2) rebuild: fresh context over EXACTLY the survivors + the
        # hybrid step re-lowered through the trainer's stored config
        from pipegoose_tpu.distributed.parallel_context import ParallelContext
        from pipegoose_tpu.parallel.hybrid import parallel_context_sizes

        new_ctx = ParallelContext(
            **parallel_context_sizes(layout), devices=list(surviving)
        )
        trainer.rebuild(new_ctx)
        # 3) cross-mesh restore (orbax reshards onto the new mesh),
        # with the inherited older-checkpoint fallback — a device loss
        # colliding with a torn newest checkpoint is exactly when
        # recovery must not give up
        restored_step = self._restore_with_fallback(trainer, step, reason)
        # 4) verify: the recompiled step must be clean on the new mesh
        doctor_ok = None
        if self.verify_doctor and trainer.last_batch is not None:
            doctor_ok = self._doctor_check(trainer)
        self._after_restore(trainer, step, restored_step)
        record = {
            "step": step,
            "restored_step": restored_step,
            "lost_device_ids": trig.details.get("lost_device_ids"),
            "surviving_device_ids": [int(d.id) for d in surviving],
            "layout": layout_desc,
            "n_devices": n,
            "doctor_zero_resharding": doctor_ok,
        }
        self.resumes.append(record)
        if self.recorder is not None:
            # the acceptance black box: names the lost devices, the
            # chosen layout, and the rewind step in ONE artifact.
            # recorder.dump (not fire_trigger) — a pending trigger
            # would be consumed next round as a fresh failure
            from pipegoose_tpu.telemetry.flightrec import TriggerEvent

            ev = TriggerEvent(
                "elastic_resume",
                f"lost device(s) {record['lost_device_ids']}; resumed "
                f"from step {restored_step} on {n} device(s) as "
                f"dp={layout_desc['dp']} tp={layout_desc['tp']} "
                f"pp={layout_desc['pp']}",
                step,
                dict(record),
            )
            ev.dump_path = self.recorder.dump(
                ev, context={"mesh_axes": {
                    k: int(v) for k, v in dict(new_ctx.mesh.shape).items()
                }},
            )
            record["dump_path"] = ev.dump_path
        trainer.logger.info(
            f"elastic: resumed at step {restored_step} on {n} device(s) "
            f"({self.restores}/{self.max_restores} restores spent)"
        )

    def _doctor_check(self, trainer: Any) -> bool:
        """Shape-only doctor diff of the REBUILT step (batch shapes from
        the in-flight batch); raises ``ShardingRegressionError`` on
        partitioner-inserted resharding when ``verify_doctor``."""
        import jax

        from pipegoose_tpu.telemetry.doctor import assert_no_resharding

        batch_sds = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            trainer.last_batch,
        )
        report = trainer.doctor(batch_sds)
        assert_no_resharding(report)
        return True
