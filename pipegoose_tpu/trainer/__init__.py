from pipegoose_tpu.trainer.callback import Callback, CheckpointCallback, LossLoggerCallback
from pipegoose_tpu.trainer.elastic import (
    ElasticRecovery,
    NoFeasibleLayout,
    planner_layout_fn,
    shrink_layout,
)
from pipegoose_tpu.trainer.logger import DistributedLogger
from pipegoose_tpu.trainer.recovery import (
    AutoRecovery,
    FailureDetector,
    TrainingDiverged,
)
from pipegoose_tpu.trainer.state import TrainerState, TrainerStatus
from pipegoose_tpu.trainer.trainer import Trainer

__all__ = [
    "Trainer",
    "Callback",
    "LossLoggerCallback",
    "CheckpointCallback",
    "DistributedLogger",
    "TrainerState",
    "TrainerStatus",
    "FailureDetector",
    "AutoRecovery",
    "ElasticRecovery",
    "NoFeasibleLayout",
    "TrainingDiverged",
    "planner_layout_fn",
    "shrink_layout",
]
