"""Failure detection and automatic recovery for the training loop.

The reference has NO failure handling of any kind — no retry, no health
checks; ``destroy()`` is its only lifecycle management (SURVEY.md §5,
reference parallel_context.py:390-407). This module fills that gap with
the failure mode that actually ends large training runs: numerical
divergence (NaN/Inf loss, loss spikes from bad batches or optimizer
blow-ups).

Two composable callbacks:

- :class:`FailureDetector` watches the per-step loss and raises
  :class:`TrainingDiverged` on non-finite values or spikes beyond
  ``spike_factor`` x the running median. Detection costs one device
  fetch per checked step (set ``check_every`` > 1 to keep JAX's async
  dispatch pipelined between checks).
- :class:`AutoRecovery` extends detection with self-healing: on failure
  it restores params + optimizer state from the newest checkpoint in
  ``directory`` (pair it with ``CheckpointCallback`` writing there),
  rewinds ``trainer.state.step``, and lets ``fit`` continue with the
  incoming data stream — the diverging update never reaches the
  surviving state, and the batches that triggered it are naturally
  skipped (the iterator has moved past them). After ``max_restores``
  restores it re-raises: a deterministic NaN (bad lr, broken data) must
  surface, not loop forever.

Single-controller SPMD makes this simpler than the reference's world
would have allowed: there is ONE process to detect and ONE state to
restore — no distributed consensus about who failed.
"""
from __future__ import annotations

import math
from collections import deque
from typing import Any, Optional

from pipegoose_tpu.trainer.callback import Callback


class TrainingDiverged(RuntimeError):
    """Loss went non-finite (or spiked) and recovery was impossible or
    exhausted."""


class FailureDetector(Callback):
    """Detect numerical divergence from the loss stream.

    ``spike_factor``: optional; flag loss > spike_factor * median of the
    last ``window`` finite losses (needs at least ``window // 2``
    history before it arms — startup loss drops must not trip it).

    ``recorder``: optional ``telemetry.FlightRecorder`` sharing the
    callback list (the recorder runs at order -20, this detector at
    -10, so a trigger fired this step is already recorded AND dumped by
    the time it is consumed here). When a structured trigger is
    pending, ``handle_failure`` fires with the trigger's reason —
    "nonfinite: non-finite gradients in module group 'embed'" — instead
    of this detector's bare loss check, so recovery reacts to *which*
    signal fired (grad overflow, update overflow, loss spike) and the
    black-box path lands in the raised/logged message.
    """

    order = -10  # run before logging/checkpoint callbacks see the step

    def __init__(
        self,
        check_every: int = 1,
        spike_factor: Optional[float] = None,
        window: int = 50,
        recorder: Optional[Any] = None,
    ):
        if check_every < 1:
            raise ValueError(f"check_every must be >= 1, got {check_every}")
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        self.check_every = check_every
        self.spike_factor = spike_factor
        self.window = window
        self.recorder = recorder
        self._history: deque = deque(maxlen=window)
        # the structured trigger being handled RIGHT NOW (set for the
        # duration of a handle_failure call driven by the recorder):
        # subclasses that react to WHICH signal fired (ElasticRecovery's
        # device_loss path) read it instead of parsing the reason string
        self.active_trigger: Optional[Any] = None

    def _is_divergent(self, loss: float) -> Optional[str]:
        if not math.isfinite(loss):
            return f"non-finite loss {loss}"
        if self.spike_factor is not None and len(self._history) >= max(1, self.window // 2):
            med = sorted(self._history)[len(self._history) // 2]
            if loss > self.spike_factor * med:
                return (
                    f"loss spike {loss:.4g} > {self.spike_factor} x "
                    f"median {med:.4g}"
                )
        self._history.append(loss)
        return None

    def on_step_end(self, trainer: Any, step: int, loss) -> None:
        if self.recorder is not None:
            trig = self.recorder.take_trigger()
            if trig is not None:
                where = (
                    f" (black box: {trig.dump_path})" if trig.dump_path else ""
                )
                self.active_trigger = trig
                try:
                    self.handle_failure(
                        trainer, step, f"{trig.name}: {trig.reason}{where}"
                    )
                finally:
                    self.active_trigger = None
                return
        if step % self.check_every:
            return
        from pipegoose_tpu.trainer.callback import _host_scalar

        reason = self._is_divergent(_host_scalar(loss))
        if reason is not None:
            self.handle_failure(trainer, step, reason)

    def handle_failure(self, trainer: Any, step: int, reason: str) -> None:
        raise TrainingDiverged(f"step {step}: {reason}")


class AutoRecovery(FailureDetector):
    """FailureDetector that restores the last checkpoint instead of
    aborting. ``directory`` must be the ``CheckpointCallback`` target (or
    any directory ``save_train_state`` wrote). If no checkpoint exists
    yet when divergence hits, there is nothing to restore — raises.

    A newest checkpoint that FAILS to restore (corrupt or partial —
    torn writes predating the atomic-rename contract, storage rot) is
    skipped with a logged warning and the next-older one is tried;
    every attempt, failed or successful, consumes one of
    ``max_restores`` so a directory of corrupt checkpoints exhausts
    loudly instead of looping."""

    def __init__(
        self,
        directory: str,
        max_restores: int = 3,
        check_every: int = 1,
        spike_factor: Optional[float] = None,
        window: int = 50,
        recorder: Optional[Any] = None,
    ):
        super().__init__(check_every, spike_factor, window, recorder)
        self.directory = directory
        self.max_restores = max_restores
        self.restores = 0

    def handle_failure(self, trainer: Any, step: int, reason: str) -> None:
        if self.restores >= self.max_restores:
            raise TrainingDiverged(
                f"step {step}: {reason} — {self.restores} restores already "
                "spent; divergence is persistent (check lr/data), aborting"
            )
        trainer.logger.warning(f"step {step}: {reason} — restoring last checkpoint")
        restored_step = self._restore_with_fallback(trainer, step, reason)
        self._after_restore(trainer, step, restored_step)

    def _restore_with_fallback(
        self, trainer: Any, step: int, reason: str
    ) -> int:
        """Restore the newest COMPLETE checkpoint, falling back to the
        next-older one when a restore fails (corrupt/partial newest —
        e.g. a torn write from before the atomic-rename contract, or
        storage bit rot). Every attempt, failed or not, consumes one
        restore budget: a directory full of corrupt checkpoints must
        exhaust and surface, not loop. A checkpoint that failed to
        restore is quarantined (renamed ``step_N.corrupt``) so it stops
        shadowing the step: training replays forward after the fallback
        and must be able to RE-save ``step_N`` — against a lingering
        dir, ``save_pretrained``'s exists-check would kill the run at
        the exact step recovery meant to heal. Returns the restored
        step."""
        from pipegoose_tpu.utils.checkpoint import available_steps

        steps = available_steps(self.directory)
        if not steps:
            raise TrainingDiverged(
                f"step {step}: {reason} — and no checkpoint under "
                f"{self.directory!r} to restore from"
            )
        for cand in steps:  # newest -> oldest
            if self.restores >= self.max_restores:
                raise TrainingDiverged(
                    f"step {step}: {reason} — {self.restores} restores "
                    "already spent; divergence is persistent (check "
                    "lr/data), aborting"
                )
            try:
                restored_step = trainer.restore_from(self.directory, cand)
            except Exception as e:  # noqa: BLE001 - any restore failure
                # falls back; only the budget bounds the walk
                self.restores += 1
                import os

                skipped = os.path.join(self.directory, f"step_{cand}")
                quarantine = skipped + ".corrupt"
                n = 1
                while os.path.exists(quarantine):
                    quarantine = f"{skipped}.corrupt{n}"
                    n += 1
                try:
                    os.replace(skipped, quarantine)
                    where = f"quarantined to {quarantine!r}"
                except OSError:
                    where = "quarantine rename failed; left in place"
                trainer.logger.warning(
                    f"checkpoint {skipped!r} failed to restore "
                    f"({type(e).__name__}: {e}) — {where}; falling back "
                    f"to the next-older checkpoint "
                    f"({self.restores}/{self.max_restores} budget spent)"
                )
                continue
            self.restores += 1
            return restored_step
        raise TrainingDiverged(
            f"step {step}: {reason} — every checkpoint under "
            f"{self.directory!r} failed to restore"
        )

    def _after_restore(
        self, trainer: Any, step: int, restored_step: int
    ) -> None:
        self._history.clear()
        if self.recorder is not None:
            # the spike/explosion baselines span the rolled-back steps;
            # also drops any still-pending trigger so the NEXT round
            # doesn't re-fire on the pre-restore evidence
            self.recorder.reset_after_restore(restored_step)
        # drop the post-restore-invalid tail of the loss record so later
        # consumers (plots, early stopping) don't see the divergence.
        # losses counts entries since THIS trainer started (a resumed
        # trainer's list doesn't begin at step 0), so truncate by the
        # number of rolled-back steps, not by the absolute step
        rolled_back = step - restored_step
        keep = max(len(trainer.state.losses) - rolled_back, 0)
        del trainer.state.losses[keep:]
        trainer.state.last_loss = (
            trainer.state.losses[-1] if trainer.state.losses else None
        )
        trainer.logger.info(
            f"restored step {restored_step} ({self.restores}/{self.max_restores})"
        )
