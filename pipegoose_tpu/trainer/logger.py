"""Distributed-aware logger.

Real implementation of the reference's empty ``DistributedLogger`` stub
(pipegoose/trainer/logger.py:4-14): same constructor shape
(name, rank-filtering), actually logs. In JAX's single-controller model
"rank" means the host process (``jax.process_index``) — by default only
process 0 emits, matching the reference's intended rank-0 filtering.
"""
from __future__ import annotations

import logging
import os
import sys
from typing import Optional

from pipegoose_tpu.utils.procindex import RankFilter


class DistributedLogger:
    def __init__(
        self,
        name: str = "pipegoose_tpu",
        rank: Optional[int] = 0,
        level: int = logging.INFO,
        logfile: Optional[str] = None,
    ):
        """``rank``: only this process index logs; None = all processes."""
        self.name = name
        self.rank = rank
        self._rank_ok = RankFilter(rank)  # cached process-index check
        self._logger = logging.getLogger(name)
        self._logger.setLevel(level)
        self._logger.propagate = False  # avoid duplicate lines via root
        fmt = logging.Formatter("[%(asctime)s %(name)s %(levelname)s] %(message)s")
        if not any(
            isinstance(h, logging.StreamHandler)
            and not isinstance(h, logging.FileHandler)
            for h in self._logger.handlers
        ):
            h = logging.StreamHandler(sys.stdout)
            h.setFormatter(fmt)
            self._logger.addHandler(h)
        if logfile and not any(
            isinstance(h, logging.FileHandler)
            and getattr(h, "baseFilename", None) == os.path.abspath(logfile)
            for h in self._logger.handlers
        ):
            fh = logging.FileHandler(logfile)
            fh.setFormatter(fmt)
            self._logger.addHandler(fh)

    def _should_log(self) -> bool:
        # process_index() cached after the first successful lookup (why
        # that is safe: utils/procindex.py, shared with the telemetry
        # exporters) instead of re-queried per line
        return self._rank_ok()

    def info(self, msg: str) -> None:
        if self._should_log():
            self._logger.info(msg)

    def warning(self, msg: str) -> None:
        if self._should_log():
            self._logger.warning(msg)

    def error(self, msg: str) -> None:
        if self._should_log():
            self._logger.error(msg)

    def debug(self, msg: str) -> None:
        if self._should_log():
            self._logger.debug(msg)
