"""Trainer lifecycle state.

Analog of the reference's ``TrainerStatus``/``TrainerState`` enums
(pipegoose/trainer/state.py:4-19), extended with the actual mutable
run-state (step, last loss, loss history) the reference never filled in.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional


class TrainerStatus(str, enum.Enum):
    INITIALIZING = "initializing"
    RUNNING = "running"
    FINISHED = "finished"
    INTERRUPTED = "interrupted"
    FAILED = "failed"


class LossHistory(list):
    """Bounded per-step loss record.

    ``fit`` appends the step's loss as a DEVICE array (fetching it
    would stall JAX's async dispatch every step), so an unbounded list
    pins one live device buffer per step for the whole run. This list
    subclass keeps the plain-list API the consumers rely on
    (``losses[-1]``, ``del losses[k:]`` in AutoRecovery's rollback,
    iteration in plots/early-stopping) while:

    - keeping at most ``maxlen`` entries (ring semantics: oldest
      dropped on append), and
    - opportunistically converting the entry ``sync_lag`` steps behind
      the head to a host float on each append — by then that step's
      device work has long retired, so the ``float()`` doesn't block,
      and the ring holds device handles only for the most recent
      ``sync_lag`` steps.
    """

    def __init__(self, iterable=(), maxlen: int = 4096, sync_lag: int = 16):
        super().__init__(iterable)
        if maxlen < 1:
            raise ValueError(f"maxlen must be >= 1, got {maxlen}")
        self.maxlen = maxlen
        self.sync_lag = max(int(sync_lag), 0)

    def append(self, value) -> None:
        super().append(value)
        if len(self) > self.maxlen:
            del self[: len(self) - self.maxlen]
        i = len(self) - 1 - self.sync_lag
        if i >= 0 and not isinstance(self[i], float):
            try:
                self[i] = float(self[i])
            except (TypeError, RuntimeError):
                # non-fully-addressable multihost scalar (float() raises)
                # or a non-numeric entry: keep the original object
                pass


@dataclasses.dataclass
class TrainerState:
    status: TrainerStatus = TrainerStatus.INITIALIZING
    step: int = 0
    last_loss: Optional[float] = None
    losses: LossHistory = dataclasses.field(default_factory=LossHistory)
    # most recent in-graph health pytree (device scalars) when the
    # trainer runs with with_health=True; None otherwise
    last_health: Optional[Any] = None
