"""Trainer lifecycle state.

Analog of the reference's ``TrainerStatus``/``TrainerState`` enums
(pipegoose/trainer/state.py:4-19), extended with the actual mutable
run-state (step, last loss, loss history) the reference never filled in.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional


class TrainerStatus(str, enum.Enum):
    INITIALIZING = "initializing"
    RUNNING = "running"
    FINISHED = "finished"
    INTERRUPTED = "interrupted"
    FAILED = "failed"


@dataclasses.dataclass
class TrainerState:
    status: TrainerStatus = TrainerStatus.INITIALIZING
    step: int = 0
    last_loss: Optional[float] = None
    losses: List[float] = dataclasses.field(default_factory=list)
