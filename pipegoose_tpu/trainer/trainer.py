"""Trainer: the end-user training loop.

Real implementation of the reference's empty ``Trainer`` stub
(pipegoose/trainer/trainer.py:13-35). One object wires together the
hybrid-parallel compiled train step (parallel/hybrid.py), the ZeRO-1
optimizer, callbacks, logging, and checkpoint/resume — the composition
the reference's examples hand-roll (examples/hybrid_parallelism.py).
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Sequence

import jax
from jax.sharding import PartitionSpec as P

from pipegoose_tpu.distributed.parallel_context import ParallelContext
from pipegoose_tpu.optim.zero import DistributedOptimizer
from pipegoose_tpu.parallel.hybrid import make_hybrid_train_step
from pipegoose_tpu.trainer.callback import Callback
from pipegoose_tpu.trainer.logger import DistributedLogger
from pipegoose_tpu.trainer.state import TrainerState, TrainerStatus


class Trainer:
    def __init__(
        self,
        loss_fn: Callable[..., jax.Array],
        params: Any,
        param_specs: Any,
        optimizer: DistributedOptimizer,
        parallel_context: Optional[ParallelContext] = None,
        batch_spec: P = P("data"),
        loss_axis: Any = "data",
        grad_sync_axes: tuple = (),
        with_rng: bool = False,
        n_accum: int = 1,
        callbacks: Sequence[Callback] = (),
        logger: Optional[DistributedLogger] = None,
        resume_dir: Optional[str] = None,
    ):
        self.parallel_context = parallel_context or ParallelContext.get_context()
        self.logger = logger or DistributedLogger()
        self.callbacks = sorted(callbacks, key=lambda c: c.order)
        self.state = TrainerState()
        self.with_rng = with_rng
        self.tokens_per_step = 0  # updated from batch shapes each step

        init_fn, make_step = make_hybrid_train_step(
            loss_fn,
            param_specs,
            optimizer,
            self.parallel_context,
            batch_spec=batch_spec,
            loss_axis=loss_axis,
            grad_sync_axes=grad_sync_axes,
            with_rng=with_rng,
            n_accum=n_accum,
        )
        self.param_specs = param_specs
        self.optimizer = optimizer
        # place params on the mesh in FRESH buffers: the jitted step
        # donates its params argument, and donating the caller's arrays
        # would invalidate them (device_put can alias, a jitted identity
        # can't)
        from jax.sharding import NamedSharding

        out_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.parallel_context.mesh, s),
            param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self.params = jax.jit(lambda t: t, out_shardings=out_shardings)(params)
        self._step_fn = make_step(params)

        resumed = False
        if resume_dir is not None:
            # shapes only — materializing a full ZeRO state just to
            # overwrite it from the checkpoint would waste a compile +
            # the whole optimizer memory
            state_shapes = jax.eval_shape(init_fn, params)
            resumed = self._try_resume(resume_dir, state_shapes)
        if not resumed:
            self.opt_state = init_fn(params)

    def _try_resume(self, directory: str, opt_state_shapes) -> bool:
        from pipegoose_tpu.parallel.hybrid import zero_state_spec
        from pipegoose_tpu.utils.checkpoint import latest_step, restore_train_state

        step = latest_step(directory)
        if step is None:
            self.logger.info(f"no checkpoint under {directory}; starting fresh")
            return False
        like = {"params": self.params, "opt_state": opt_state_shapes}
        # restore SHARDED onto this mesh — without specs every leaf (incl.
        # the ZeRO state, which exists precisely because it can't live
        # replicated) would materialize on all devices
        specs = {
            "params": self.param_specs,
            "opt_state": zero_state_spec(
                self.optimizer, self.params, self.param_specs,
                self.parallel_context.mesh,
            ),
        }
        restored = restore_train_state(
            directory, step, like, specs, self.parallel_context
        )
        self.params = restored["params"]
        self.opt_state = restored["opt_state"]
        self.state.step = step
        self.logger.info(f"resumed from {directory} at step {step}")
        return True

    def fit(
        self,
        batches: Iterable[Any],
        max_steps: Optional[int] = None,
        rng: Optional[jax.Array] = None,
    ) -> TrainerState:
        """Run the training loop (reference Trainer.fit stub,
        trainer.py:18-30). ``batches`` yields pytrees matching the
        batch_spec; with ``with_rng`` a fresh folded key goes to every
        step."""
        self.state.status = TrainerStatus.RUNNING
        for cb in self.callbacks:
            cb.on_fit_start(self)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        it = iter(batches)
        try:
            while True:
                # check BEFORE pulling: a pull consumes the caller's
                # iterator (and may tokenize a whole batch) for nothing
                if max_steps is not None and self.state.step >= max_steps:
                    break
                try:
                    batch = next(it)
                except StopIteration:
                    break
                step = self.state.step
                for cb in self.callbacks:
                    cb.on_step_start(self, step)
                leaves = jax.tree_util.tree_leaves(batch)
                self.tokens_per_step = int(leaves[0].size) if leaves else 0
                args = (self.params, self.opt_state, batch)
                if self.with_rng:
                    args = args + (jax.random.fold_in(rng, step),)
                self.params, self.opt_state, loss = self._step_fn(*args)
                # keep loss as a device array: float() here would block the
                # host every step and kill JAX's async dispatch; callbacks
                # convert only when they actually log
                self.state.step = step + 1
                self.state.last_loss = loss
                self.state.losses.append(loss)
                for cb in self.callbacks:
                    cb.on_step_end(self, self.state.step, loss)
        except KeyboardInterrupt:
            self.state.status = TrainerStatus.INTERRUPTED
            self.logger.warning("interrupted")
            raise
        self.state.status = TrainerStatus.FINISHED
        for cb in self.callbacks:
            cb.on_fit_end(self)
        return self.state
