"""Trainer: the end-user training loop.

Real implementation of the reference's empty ``Trainer`` stub
(pipegoose/trainer/trainer.py:13-35). One object wires together the
hybrid-parallel compiled train step (parallel/hybrid.py), the ZeRO-1
optimizer, callbacks, logging, and checkpoint/resume — the composition
the reference's examples hand-roll (examples/hybrid_parallelism.py).
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Sequence

import jax
from jax.sharding import PartitionSpec as P

from pipegoose_tpu.distributed.parallel_context import ParallelContext
from pipegoose_tpu.optim.zero import DistributedOptimizer
from pipegoose_tpu.telemetry.spans import span
from pipegoose_tpu.trainer.callback import Callback
from pipegoose_tpu.trainer.logger import DistributedLogger
from pipegoose_tpu.trainer.state import TrainerState, TrainerStatus


class Trainer:
    def __init__(
        self,
        loss_fn: Callable[..., jax.Array],
        params: Any,
        param_specs: Any,
        optimizer: DistributedOptimizer,
        parallel_context: Optional[ParallelContext] = None,
        batch_spec: P = P("data"),
        loss_axis: Any = "data",
        grad_sync_axes: tuple = (),
        with_rng: bool = False,
        n_accum: int = 1,
        with_health: bool = False,
        callbacks: Sequence[Callback] = (),
        logger: Optional[DistributedLogger] = None,
        resume_dir: Optional[str] = None,
    ):
        self.parallel_context = parallel_context or ParallelContext.get_context()
        self.logger = logger or DistributedLogger()
        self.callbacks = sorted(callbacks, key=lambda c: c.order)
        self.state = TrainerState()
        self.with_rng = with_rng
        # with_health: the compiled step also returns the in-graph
        # health pytree (telemetry/health.py), kept on-device in
        # state.last_health for callbacks (FlightRecorder) to consume
        self.with_health = with_health
        self.tokens_per_step = 0  # updated from batch shapes each step
        # TelemetryCallback's cost-probe input: valid only DURING the
        # step-end callback round, cleared right after so the trainer
        # never pins a batch past its step
        self.last_batch: Any = None
        # refreshed by profile() — the ops server's /debug/profile
        # provider (lambda: trainer.last_step_profile)
        self.last_step_profile: Any = None

        from pipegoose_tpu.parallel.hybrid import (
            build_hybrid_train_step,
            hybrid_build_config,
        )

        # the step-rebuild hook (parallel/hybrid.py): everything the
        # compiled step was built from, minus the context — an elastic
        # mesh change (trainer/elastic.py) re-lowers the SAME config on
        # the surviving-device context via rebuild()
        self._hybrid_config = hybrid_build_config(
            loss_fn,
            param_specs,
            optimizer,
            batch_spec=batch_spec,
            loss_axis=loss_axis,
            grad_sync_axes=grad_sync_axes,
            with_rng=with_rng,
            n_accum=n_accum,
            with_health=with_health,
        )
        init_fn, make_step = build_hybrid_train_step(
            self._hybrid_config, self.parallel_context
        )
        self._init_fn = init_fn
        self.param_specs = param_specs
        self.optimizer = optimizer
        # place params on the mesh in FRESH buffers: the jitted step
        # donates its params argument, and donating the caller's arrays
        # would invalidate them (device_put can alias, a jitted identity
        # can't)
        from jax.sharding import NamedSharding

        out_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.parallel_context.mesh, s),
            param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self.params = jax.jit(lambda t: t, out_shardings=out_shardings)(params)
        self._step_fn = make_step(params)

        # lazily-built jitted eval step (loss only, no grads/update);
        # evaluate() must run the SAME accumulated loss as training —
        # n_accum exists because the un-microbatched forward doesn't fit
        if n_accum > 1:
            from pipegoose_tpu.core.accumulation import make_accumulating_loss

            self._loss_fn = make_accumulating_loss(loss_fn, n_accum)
        else:
            self._loss_fn = loss_fn
        self._batch_spec = batch_spec
        self._loss_axis = loss_axis
        self._eval_fn = None

        resumed = False
        if resume_dir is not None:
            # shapes only — materializing a full ZeRO state just to
            # overwrite it from the checkpoint would waste a compile +
            # the whole optimizer memory
            state_shapes = jax.eval_shape(init_fn, params)
            resumed = self._try_resume(resume_dir, state_shapes)
        if not resumed:
            self.opt_state = init_fn(params)

    def _try_resume(self, directory: str, opt_state_shapes) -> bool:
        from pipegoose_tpu.utils.checkpoint import latest_step

        step = latest_step(directory)
        if step is None:
            self.logger.info(f"no checkpoint under {directory}; starting fresh")
            return False
        self._restore(directory, step, opt_state_shapes)
        self.logger.info(f"resumed from {directory} at step {step}")
        return True

    def restore_from(self, directory: str, step: Optional[int] = None) -> int:
        """Restore params + optimizer state from a checkpoint into the
        LIVE trainer (used by ``AutoRecovery`` to roll back a diverged
        run mid-fit; also usable interactively). Rewinds
        ``state.step``; returns the restored step. Raises
        ``FileNotFoundError`` when the directory holds no checkpoint."""
        from pipegoose_tpu.utils.checkpoint import latest_step

        if step is None:
            step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory!r}")
        # shapes from the CURRENT init_fn, not the live opt_state: after
        # an elastic rebuild() the live state still has the OLD mesh's
        # ZeRO padding (global dim0 = ceil(d/dp)*dp depends on dp), and
        # the restore must target what the rebuilt step expects
        self._restore(directory, step, jax.eval_shape(self._init_fn, self.params))
        return step

    def _restore(self, directory: str, step: int, opt_state_like) -> None:
        from pipegoose_tpu.parallel.hybrid import zero_state_spec
        from pipegoose_tpu.utils.checkpoint import restore_train_state

        like = {"params": self.params, "opt_state": opt_state_like}
        # restore SHARDED onto this mesh — without specs every leaf (incl.
        # the ZeRO state, which exists precisely because it can't live
        # replicated) would materialize on all devices
        specs = {
            "params": self.param_specs,
            "opt_state": zero_state_spec(
                self.optimizer, self.params, self.param_specs,
                self.parallel_context.mesh,
            ),
        }
        restored = restore_train_state(
            directory, step, like, specs, self.parallel_context
        )
        self.params = restored["params"]
        self.opt_state = restored["opt_state"]
        self.state.step = step

    def rebuild(self, parallel_context: ParallelContext) -> None:
        """Recompile the hybrid train step on a NEW ``ParallelContext``
        — the elastic-recovery entry point (``trainer/elastic.py``):
        after a device loss shrinks the cluster, the same stored build
        config (``parallel/hybrid.py`` ``hybrid_build_config``) is
        re-lowered on the surviving-device mesh. Params and optimizer
        state are NOT migrated here (they still live on the old mesh's
        buffers); follow with :meth:`restore_from`, whose cross-mesh
        orbax restore places the checkpointed state sharded onto the
        new mesh."""
        from pipegoose_tpu.parallel.hybrid import build_hybrid_train_step

        self.parallel_context = parallel_context
        init_fn, make_step = build_hybrid_train_step(
            self._hybrid_config, parallel_context
        )
        self._init_fn = init_fn
        # current params serve as a shape/dtype source only: make_step
        # reads them through eval_shape (state specs) and size
        # arithmetic (comm gauges) — planner precedent, bloom_builder
        # passes pure SDS trees through the same path
        self._step_fn = make_step(self.params)
        self._eval_fn = None  # compiled for the OLD mesh; rebuild lazily

    def evaluate(
        self,
        batches: Iterable[Any],
        rng: Optional[jax.Array] = None,
        weight_fn: Optional[Any] = None,
    ) -> float:
        """Mean loss over ``batches`` with the CURRENT params — no
        gradients, no optimizer update (the eval half the reference's
        Trainer stub never got, trainer.py:13-35). Runs the same
        sharded loss_fn as training, jitted once.

        ``weight_fn(batch) -> float`` weights each batch's (internally
        normalized) loss in the running mean. For ragged eval sets pass
        the batch's valid-token count — e.g.
        ``lambda b: float(b["attention_mask"][:, 1:].sum())`` — and the
        result is the corpus TOKEN-weighted mean, the number eval
        reports should quote. Default: equal batch weights (exact when
        every batch carries the same token count)."""
        if self._eval_fn is None:
            from pipegoose_tpu.parallel.hybrid import shard_map  # jax<0.6-safe

            in_specs = (self.param_specs, self._batch_spec) + (
                (P(),) if self.with_rng else ()
            )

            def eval_step(params, batch, *rng):
                loss = self._loss_fn(params, batch, *rng)
                axes = (
                    self._loss_axis
                    if isinstance(self._loss_axis, tuple)
                    else (self._loss_axis,)
                )
                for ax in axes:
                    loss = jax.lax.pmean(loss, ax)
                return loss

            self._eval_fn = jax.jit(
                shard_map(
                    eval_step,
                    mesh=self.parallel_context.mesh,
                    in_specs=in_specs,
                    out_specs=P(),
                    check_vma=False,
                )
            )
        if rng is None:
            rng = jax.random.PRNGKey(0)
        total, n = 0.0, 0.0
        for i, batch in enumerate(batches):
            args = (self.params, batch)
            if self.with_rng:
                args = args + (jax.random.fold_in(rng, i),)
            w = float(weight_fn(batch)) if weight_fn is not None else 1.0
            total += w * float(self._eval_fn(*args))
            n += w
        if n == 0:
            raise ValueError(
                "evaluate() received no batches (an exhausted generator?) or "
                "all batch weights were zero — 0.0 would be "
                "indistinguishable from perfect convergence"
            )
        return total / n

    def doctor(
        self,
        batch: Any,
        large_bytes: int = 1 << 20,
        registry: Any = None,
    ):
        """Mesh-doctor report (telemetry/doctor.py) for THIS trainer's
        compiled train step: actual vs intended shardings of every
        param/optimizer-state/batch leaf, the collective schedule split
        into intentional vs partitioner-inserted traffic, and the
        per-device HBM budget. ``batch`` only provides shapes — a
        ``jax.ShapeDtypeStruct`` pytree works; nothing executes.
        Headline numbers land as ``doctor.*`` gauges on ``registry``
        (default: the global one, only if enabled)."""
        from pipegoose_tpu.parallel.hybrid import train_step_intended_specs
        from pipegoose_tpu.telemetry.doctor import diagnose, set_doctor_gauges

        args = (self.params, self.opt_state, batch)
        labels = ["params", "opt_state", "batch"]
        intended = train_step_intended_specs(
            self.optimizer, self.params, self.param_specs,
            self.parallel_context.mesh, batch_spec=self._batch_spec,
            with_rng=self.with_rng,
        )
        if self.with_rng:
            args = args + (jax.random.PRNGKey(0),)
            labels.append("rng")
        report = diagnose(
            self._step_fn, *args,
            intended=intended, labels=labels,
            mesh=self.parallel_context.mesh, large_bytes=large_bytes,
        )
        set_doctor_gauges(report, registry=registry)
        return report

    def profile(
        self,
        batch: Any,
        steps: int = 3,
        warmup: int = 2,
        trace_dir: Optional[str] = None,
        registry: Any = None,
    ):
        """Measured device-time attribution (telemetry/xprof.py) of
        THIS trainer's compiled train step — the runtime twin of
        :meth:`doctor`: runs the real step ``warmup + steps`` times
        under the XLA profiler on ``batch`` (REAL arrays — unlike the
        doctor, the step executes) and returns the
        :class:`~pipegoose_tpu.telemetry.xprof.StepProfile` splitting
        each fenced step into compute / per-mesh-axis collectives /
        idle, with measured MFU.

        The profiled steps are REAL optimizer steps: params and
        optimizer state advance (the step donates its buffers, so the
        trainer adopts the final ones), exactly as ``fit`` over the
        same batches would — ``state.step`` is not bumped, since no
        callbacks ran. The result is cached on ``last_step_profile``
        (the ops server's ``/debug/profile`` provider)."""
        from pipegoose_tpu.telemetry.xprof import profile_step

        args: tuple = (self.params, self.opt_state, batch)
        if self.with_rng:
            args = args + (jax.random.PRNGKey(0),)
        final: dict = {}

        def update(out, cur):
            # out = (params, opt_state, loss[, health]); batch and rng
            # (when present) repeat — profiling measures the step, not
            # the data pipeline
            final["params"], final["opt_state"] = out[0], out[1]
            return (out[0], out[1]) + tuple(cur[2:])

        try:
            profile = profile_step(
                self._step_fn, *args, steps=steps, warmup=warmup,
                update_args=update, mesh=self.parallel_context.mesh,
                trace_dir=trace_dir, registry=registry,
            )
        finally:
            # the compiled step DONATED the params/opt-state buffers on
            # every call: adopt the final generation — even when trace
            # parsing raises mid-profile — or the trainer's next step
            # would touch deleted arrays
            if final:
                self.params = final["params"]
                self.opt_state = final["opt_state"]
        self.last_step_profile = profile
        return profile

    def fit(
        self,
        batches: Iterable[Any],
        max_steps: Optional[int] = None,
        rng: Optional[jax.Array] = None,
        profiler_trace_dir: Optional[str] = None,
    ) -> TrainerState:
        """Run the training loop (reference Trainer.fit stub,
        trainer.py:18-30). ``batches`` yields pytrees matching the
        batch_spec; with ``with_rng`` a fresh folded key goes to every
        step. ``profiler_trace_dir``: wrap the whole fit in
        ``jax.profiler.trace(dir)`` so an XLA timeline
        (TensorBoard/Perfetto viewable) is one flag away."""
        if profiler_trace_dir is not None:
            from pipegoose_tpu.utils.profiler import trace

            with trace(profiler_trace_dir):
                return self._fit(batches, max_steps, rng)
        return self._fit(batches, max_steps, rng)

    def _fire_fit_abort(self, exc: BaseException) -> None:
        """Teardown hooks for the failure path — a callback holding
        process-global state (the chaos checkpoint-fault seam) must get
        a chance to release it when fit raises. Best-effort and
        getattr-guarded: duck-typed callbacks predating the hook keep
        working, and a teardown error never masks the original."""
        for cb in self.callbacks:
            hook = getattr(cb, "on_fit_abort", None)
            if hook is None:
                continue
            try:
                hook(self, exc)
            except Exception as cleanup_err:  # noqa: BLE001
                self.logger.warning(
                    f"on_fit_abort of {type(cb).__name__} raised "
                    f"{type(cleanup_err).__name__}: {cleanup_err} "
                    "(suppressed; original error propagates)"
                )

    def _fit(
        self,
        batches: Iterable[Any],
        max_steps: Optional[int] = None,
        rng: Optional[jax.Array] = None,
    ) -> TrainerState:
        self.state.status = TrainerStatus.RUNNING
        for cb in self.callbacks:
            cb.on_fit_start(self)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        it = iter(batches)
        try:
            while True:
                # check BEFORE pulling: a pull consumes the caller's
                # iterator (and may tokenize a whole batch) for nothing
                if max_steps is not None and self.state.step >= max_steps:
                    break
                try:
                    # disabled-registry spans are one branch; enabled,
                    # they split host-side data time from step dispatch
                    # in the JSONL stream (telemetry/spans.py)
                    with span("train.data"):
                        batch = next(it)
                except StopIteration:
                    break
                step = self.state.step
                for cb in self.callbacks:
                    cb.on_step_start(self, step)
                leaves = jax.tree_util.tree_leaves(batch)
                self.tokens_per_step = int(leaves[0].size) if leaves else 0
                self.last_batch = batch
                args = (self.params, self.opt_state, batch)
                if self.with_rng:
                    args = args + (jax.random.fold_in(rng, step),)
                # UNFENCED: measures dispatch; in steady state the queue
                # backpressures to device step time. TelemetryCallback
                # (fence=True) gives exact per-step device attribution.
                with span("train.step"):
                    if self.with_health:
                        self.params, self.opt_state, loss, health = (
                            self._step_fn(*args)
                        )
                        # device pytree, same async-dispatch rule as the
                        # loss: consumers fetch when they actually look
                        self.state.last_health = health
                    else:
                        self.params, self.opt_state, loss = self._step_fn(*args)
                # keep loss as a device array: float() here would block the
                # host every step and kill JAX's async dispatch; callbacks
                # convert only when they actually log
                self.state.step = step + 1
                self.state.last_loss = loss
                self.state.losses.append(loss)
                for cb in self.callbacks:
                    cb.on_step_end(self, self.state.step, loss)
                self.last_batch = None  # don't pin the batch past its step
        except KeyboardInterrupt as e:
            self.state.status = TrainerStatus.INTERRUPTED
            self.logger.warning("interrupted")
            self._fire_fit_abort(e)
            raise
        except Exception as e:
            # a divergence abort (TrainingDiverged from a callback) or any
            # other mid-fit error must not leave state.status at RUNNING —
            # callers inspect trainer.state after fit() raises
            self.state.status = TrainerStatus.FAILED
            self._fire_fit_abort(e)
            raise
        finally:
            # the per-iteration clear misses aborted steps (an OOM raise
            # or interrupt between assignment and clear would pin the
            # batch for the trainer's lifetime)
            self.last_batch = None
        self.state.status = TrainerStatus.FINISHED
        for cb in self.callbacks:
            cb.on_fit_end(self)
        return self.state
