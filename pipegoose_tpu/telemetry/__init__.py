"""Unified telemetry: metrics registry, span tracing, derived gauges
(MFU / tokens/s / HBM / comm bytes), and JSONL + Prometheus exporters.

The observability layer the reference never had (its
``DistributedLogger`` was an empty stub and it had no timeline tracing,
SURVEY.md §5). Library hot paths (trainer fit loop, serving engine,
decode driver) are instrumented against the GLOBAL registry, which
starts disabled — un-observed runs pay one branch per site. Turn it on
with ``telemetry.enable()`` (or by adding a ``TelemetryCallback`` /
constructing an engine with an enabled registry) and attach exporters:

    from pipegoose_tpu import telemetry

    telemetry.enable()
    jsonl = telemetry.JSONLExporter("run.jsonl",
                                    registry=telemetry.get_registry())
    ...train / serve...
    jsonl.export_snapshot()
    telemetry.PrometheusTextfileExporter("run.prom").write(
        telemetry.get_registry())

On top of the substrate sits the health/forensics layer: in-graph
health stats fused into the compiled train step (``health_stats``,
``make_hybrid_train_step(with_health=True)``), the anomaly
``FlightRecorder`` (ring buffer + structured triggers + atomic JSON
black-box dumps, feeding ``FailureDetector``/``AutoRecovery``), and
Perfetto/Chrome trace export (``ChromeTraceExporter``,
``pipeline_trace_events``, the ``pipeline.bubble_fraction`` gauge).
The MEASURED layer closes the loop: ``profile_step``/``StepProfile``
(telemetry/xprof.py) attribute a real step's device time to compute /
per-mesh-axis collectives / idle from XLA profiler traces, and
``PerfSentinel`` (telemetry/sentinel.py) watches runs against a
rolling baseline, firing ``perf_regression`` black boxes that name
the regressed component. ``MemoryLedger`` (telemetry/memledger.py)
keeps a byte-exact per-owner-class account of the serving KV pool —
conservation-checked every tick, with leak audits, exhaustion
forecasting, and Perfetto counter tracks (``memory_trace_events``).
``GoodputLedger`` (telemetry/goodput.py) is the wall-clock sibling:
every replica-second attributed to productive / badput classes
(conservation-exact), one ``Incident`` per failure episode with MTTR
and capacity-gap accounting, availability SLO counters
(``availability_slo_target``), Perfetto state bands
(``goodput_trace_events``), and the ``TrainerGoodput`` callback
mirroring the taxonomy onto training fit loops.

See docs/observability.md for the metric catalog and the MFU
methodology.
"""
from pipegoose_tpu.telemetry.callback import TelemetryCallback
from pipegoose_tpu.telemetry.chrometrace import (
    ChromeTraceExporter,
    goodput_trace_events,
    memory_trace_events,
    pipeline_trace_events,
    register_pipeline_gauges,
    router_trace_events,
    span_events_to_trace,
    trace_from_jsonl,
)
from pipegoose_tpu.telemetry.goodput import (
    GoodputLedger,
    Incident,
    TrainerGoodput,
    availability_slo_target,
)
from pipegoose_tpu.telemetry.fleet import (
    FleetRegistry,
    merge_histograms,
    merge_metrics,
)
from pipegoose_tpu.telemetry.fleettrace import (
    FleetTracer,
    TailSampler,
    fleet_trace_events,
)
from pipegoose_tpu.telemetry.opsserver import OpsServer, parse_prometheus_text
from pipegoose_tpu.telemetry.reqtrace import (
    RequestTimeline,
    RequestTracer,
    request_trace_events,
)
from pipegoose_tpu.telemetry.slo import (
    SLOMonitor,
    SLOTarget,
    default_serving_slos,
)
from pipegoose_tpu.telemetry.derived import (
    HBM_BYTES,
    PEAK_DCI_BYTES,
    PEAK_FLOPS,
    PEAK_ICI_BYTES,
    collective_bytes,
    dci_bytes_per_s_for,
    hbm_bytes_for,
    ici_bytes_per_s_for,
    compiled_step_stats,
    hbm_utilization,
    iter_collectives,
    mfu,
    peak_flops_for,
    step_flops,
    tokens_per_second,
)
from pipegoose_tpu.telemetry.doctor import (
    DoctorReport,
    MemoryReport,
    ShardingRegressionError,
    ShardingReport,
    assert_fully_sharded,
    assert_matches_intended,
    assert_no_resharding,
    diagnose,
    estimated_wire_bytes,
    set_doctor_gauges,
    wire_bytes_by_axes,
    wire_bytes_by_op,
)
from pipegoose_tpu.telemetry.exporters import (
    JSONLExporter,
    PrometheusTextfileExporter,
)
from pipegoose_tpu.telemetry.flightrec import FlightRecorder, TriggerEvent
from pipegoose_tpu.telemetry.memledger import MemoryLedger
from pipegoose_tpu.telemetry.health import health_stats, host_health
from pipegoose_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    disable,
    enable,
    get_registry,
)
from pipegoose_tpu.telemetry.sentinel import (
    PerfSentinel,
    read_bench_history,
)
from pipegoose_tpu.telemetry.spans import current_span_path, span
from pipegoose_tpu.telemetry.xprof import (
    StepProfile,
    profile_step,
    set_profile_gauges,
)

__all__ = [
    "ChromeTraceExporter",
    "Counter",
    "DoctorReport",
    "FleetRegistry",
    "FleetTracer",
    "FlightRecorder",
    "Gauge",
    "GoodputLedger",
    "Histogram",
    "Incident",
    "JSONLExporter",
    "MemoryLedger",
    "MemoryReport",
    "MetricsRegistry",
    "HBM_BYTES",
    "OpsServer",
    "PEAK_DCI_BYTES",
    "PEAK_FLOPS",
    "PEAK_ICI_BYTES",
    "PerfSentinel",
    "PrometheusTextfileExporter",
    "RequestTimeline",
    "RequestTracer",
    "StepProfile",
    "SLOMonitor",
    "SLOTarget",
    "TailSampler",
    "ShardingRegressionError",
    "ShardingReport",
    "TelemetryCallback",
    "TrainerGoodput",
    "TriggerEvent",
    "assert_fully_sharded",
    "assert_matches_intended",
    "assert_no_resharding",
    "availability_slo_target",
    "collective_bytes",
    "compiled_step_stats",
    "current_span_path",
    "default_serving_slos",
    "diagnose",
    "disable",
    "enable",
    "fleet_trace_events",
    "get_registry",
    "goodput_trace_events",
    "hbm_utilization",
    "health_stats",
    "host_health",
    "iter_collectives",
    "memory_trace_events",
    "merge_histograms",
    "merge_metrics",
    "mfu",
    "parse_prometheus_text",
    "router_trace_events",
    "peak_flops_for",
    "pipeline_trace_events",
    "profile_step",
    "read_bench_history",
    "register_pipeline_gauges",
    "request_trace_events",
    "set_doctor_gauges",
    "set_profile_gauges",
    "estimated_wire_bytes",
    "wire_bytes_by_axes",
    "wire_bytes_by_op",
    "dci_bytes_per_s_for",
    "hbm_bytes_for",
    "ici_bytes_per_s_for",
    "span",
    "span_events_to_trace",
    "step_flops",
    "tokens_per_second",
    "trace_from_jsonl",
]
