"""Unified telemetry: metrics registry, span tracing, derived gauges
(MFU / tokens/s / HBM / comm bytes), and JSONL + Prometheus exporters.

The observability layer the reference never had (its
``DistributedLogger`` was an empty stub and it had no timeline tracing,
SURVEY.md §5). Library hot paths (trainer fit loop, serving engine,
decode driver) are instrumented against the GLOBAL registry, which
starts disabled — un-observed runs pay one branch per site. Turn it on
with ``telemetry.enable()`` (or by adding a ``TelemetryCallback`` /
constructing an engine with an enabled registry) and attach exporters:

    from pipegoose_tpu import telemetry

    telemetry.enable()
    jsonl = telemetry.JSONLExporter("run.jsonl",
                                    registry=telemetry.get_registry())
    ...train / serve...
    jsonl.export_snapshot()
    telemetry.PrometheusTextfileExporter("run.prom").write(
        telemetry.get_registry())

See docs/observability.md for the metric catalog and the MFU
methodology.
"""
from pipegoose_tpu.telemetry.callback import TelemetryCallback
from pipegoose_tpu.telemetry.derived import (
    PEAK_FLOPS,
    collective_bytes,
    compiled_step_stats,
    hbm_utilization,
    mfu,
    peak_flops_for,
    step_flops,
    tokens_per_second,
)
from pipegoose_tpu.telemetry.exporters import (
    JSONLExporter,
    PrometheusTextfileExporter,
)
from pipegoose_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    disable,
    enable,
    get_registry,
)
from pipegoose_tpu.telemetry.spans import current_span_path, span

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "JSONLExporter",
    "MetricsRegistry",
    "PEAK_FLOPS",
    "PrometheusTextfileExporter",
    "TelemetryCallback",
    "collective_bytes",
    "compiled_step_stats",
    "current_span_path",
    "disable",
    "enable",
    "get_registry",
    "hbm_utilization",
    "mfu",
    "peak_flops_for",
    "span",
    "step_flops",
    "tokens_per_second",
]
