"""Telemetry exporters: JSONL event stream + Prometheus textfile.

(The third exporter shape — Perfetto/Chrome ``trace_event`` JSON — has
its own module, ``telemetry.chrometrace``: ``ChromeTraceExporter``
follows the same sink/rank conventions as ``JSONLExporter`` here, and
``trace_from_jsonl`` converts an existing JSONL stream offline.)

Two complementary shapes, both plain files (no daemon, no deps):

- ``JSONLExporter`` — an append-only event stream (one JSON object per
  line). Attach it to a registry and every ``registry.event(...)`` /
  span exit lands as a line; ``export_snapshot`` additionally embeds a
  full metrics snapshot as a ``"snapshot"`` event. The format bench.py
  and scripts consume for time series (occupancy, step durations).
- ``PrometheusTextfileExporter`` — the node-exporter textfile-collector
  convention: one atomic snapshot file a scraper ingests. Written via
  tmp+rename so a concurrent scrape never sees a torn file.

Both reuse ``DistributedLogger``'s rank convention: only the process
with ``jax.process_index() == rank`` writes (``rank=None`` = all
processes, each should then get its own path). The process index is
looked up lazily and cached after the first success, so constructing an
exporter never forces backend initialization.
"""
from __future__ import annotations

import json
import math
import os
import tempfile
import threading
from typing import IO, Optional

from pipegoose_tpu.telemetry.registry import MetricsRegistry
from pipegoose_tpu.utils.procindex import RankFilter as _RankFilter


def atomic_write_text(path: str, text: str, suffix: str = ".tmp") -> None:
    """tmp + rename so a concurrent reader never sees a torn file — the
    one atomic-write implementation every telemetry artifact writer
    (Prometheus textfile, black-box dumps, Chrome traces) shares."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=suffix)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class JSONLExporter:
    """Append-only JSONL event sink (see module docstring).

    Callable — satisfies the registry sink protocol — and attaches
    itself when constructed with ``registry=``.
    """

    def __init__(self, path: str, registry: Optional[MetricsRegistry] = None,
                 rank: Optional[int] = 0, mode: str = "a"):
        """``mode="a"`` (default) appends across exporter lifetimes —
        one long-lived stream; ``mode="w"`` truncates on first write,
        for per-run artifacts (bench.py) where stale events from a
        previous attempt must not interleave."""
        if mode not in ("a", "w"):
            raise ValueError(f"mode must be 'a' or 'w', got {mode!r}")
        self.path = path
        self._mode = mode
        self._rank_ok = _RankFilter(rank)
        self._file: Optional[IO[str]] = None
        self._lock = threading.Lock()
        self._registry = registry
        if registry is not None:
            registry.attach(self)

    def _handle(self) -> Optional[IO[str]]:
        if not self._rank_ok():
            return None
        if self._file is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._file = open(self.path, self._mode)
        return self._file

    def __call__(self, event: dict) -> None:
        # serialize OUTSIDE the lock, then one locked write+flush: two
        # threads sharing this sink (serving engine + trainer callback)
        # must not interleave bytes into torn JSONL lines
        line = safe_json_dumps(event) + "\n"
        with self._lock:
            f = self._handle()
            if f is None:
                return
            f.write(line)
            f.flush()

    def export_snapshot(self, registry: Optional[MetricsRegistry] = None) -> None:
        """Write the full metrics snapshot as one ``"snapshot"`` event."""
        reg = registry or self._registry
        if reg is None:
            raise ValueError("no registry to snapshot")
        import time

        self({"ts": time.time(), "kind": "snapshot", **reg.snapshot()})

    def close(self) -> None:
        if self._registry is not None:
            self._registry.detach(self)
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "JSONLExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PrometheusTextfileExporter:
    """Atomic Prometheus text-exposition snapshot writer."""

    def __init__(self, path: str, rank: Optional[int] = 0):
        self.path = path
        self._rank_ok = _RankFilter(rank)

    def write(self, registry: MetricsRegistry) -> Optional[str]:
        """Render ``registry`` and atomically replace ``self.path``;
        returns the path written, or None when rank-filtered out."""
        if not self._rank_ok():
            return None
        atomic_write_text(self.path, registry.to_prometheus(),
                          suffix=".prom.tmp")
        return self.path


def _jsonable(x):
    """Best-effort conversion for numpy/jax scalars reaching the stream.
    Non-finite values become strings: json.dumps would otherwise emit
    bare ``Infinity``/``NaN`` tokens, which are NOT JSON — jq, JS
    ``JSON.parse``, and log pipelines reject the artifact exactly when
    a nonfinite anomaly (the interesting case) is in it."""
    try:
        f = float(x)
    except (TypeError, ValueError):
        return repr(x)
    return f if math.isfinite(f) else repr(f)


def _sanitize(obj):
    """Recursively stringify non-finite floats (see ``_jsonable``) —
    plain python floats never reach a ``default=`` hook, so payloads
    holding inf/nan (health trees, NaN-loss events) need this pass."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else repr(obj)
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return obj


def safe_json_dumps(obj, **kwargs) -> str:
    """``json.dumps`` that emits strictly valid (RFC 8259) JSON: every
    non-finite float — nested or numpy/jax-scalar — lands as the string
    ``'inf'``/``'-inf'``/``'nan'``. All telemetry artifact writers
    (JSONL stream, black-box dumps, Chrome traces) route through it."""
    return json.dumps(_sanitize(obj), default=_jsonable, **kwargs)
