"""Telemetry exporters: JSONL event stream + Prometheus textfile.

Two complementary shapes, both plain files (no daemon, no deps):

- ``JSONLExporter`` — an append-only event stream (one JSON object per
  line). Attach it to a registry and every ``registry.event(...)`` /
  span exit lands as a line; ``export_snapshot`` additionally embeds a
  full metrics snapshot as a ``"snapshot"`` event. The format bench.py
  and scripts consume for time series (occupancy, step durations).
- ``PrometheusTextfileExporter`` — the node-exporter textfile-collector
  convention: one atomic snapshot file a scraper ingests. Written via
  tmp+rename so a concurrent scrape never sees a torn file.

Both reuse ``DistributedLogger``'s rank convention: only the process
with ``jax.process_index() == rank`` writes (``rank=None`` = all
processes, each should then get its own path). The process index is
looked up lazily and cached after the first success, so constructing an
exporter never forces backend initialization.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import IO, Optional

from pipegoose_tpu.telemetry.registry import MetricsRegistry
from pipegoose_tpu.utils.procindex import RankFilter as _RankFilter


class JSONLExporter:
    """Append-only JSONL event sink (see module docstring).

    Callable — satisfies the registry sink protocol — and attaches
    itself when constructed with ``registry=``.
    """

    def __init__(self, path: str, registry: Optional[MetricsRegistry] = None,
                 rank: Optional[int] = 0, mode: str = "a"):
        """``mode="a"`` (default) appends across exporter lifetimes —
        one long-lived stream; ``mode="w"`` truncates on first write,
        for per-run artifacts (bench.py) where stale events from a
        previous attempt must not interleave."""
        if mode not in ("a", "w"):
            raise ValueError(f"mode must be 'a' or 'w', got {mode!r}")
        self.path = path
        self._mode = mode
        self._rank_ok = _RankFilter(rank)
        self._file: Optional[IO[str]] = None
        self._lock = threading.Lock()
        self._registry = registry
        if registry is not None:
            registry.attach(self)

    def _handle(self) -> Optional[IO[str]]:
        if not self._rank_ok():
            return None
        if self._file is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._file = open(self.path, self._mode)
        return self._file

    def __call__(self, event: dict) -> None:
        # serialize OUTSIDE the lock, then one locked write+flush: two
        # threads sharing this sink (serving engine + trainer callback)
        # must not interleave bytes into torn JSONL lines
        line = json.dumps(event, default=_jsonable) + "\n"
        with self._lock:
            f = self._handle()
            if f is None:
                return
            f.write(line)
            f.flush()

    def export_snapshot(self, registry: Optional[MetricsRegistry] = None) -> None:
        """Write the full metrics snapshot as one ``"snapshot"`` event."""
        reg = registry or self._registry
        if reg is None:
            raise ValueError("no registry to snapshot")
        import time

        self({"ts": time.time(), "kind": "snapshot", **reg.snapshot()})

    def close(self) -> None:
        if self._registry is not None:
            self._registry.detach(self)
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "JSONLExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PrometheusTextfileExporter:
    """Atomic Prometheus text-exposition snapshot writer."""

    def __init__(self, path: str, rank: Optional[int] = 0):
        self.path = path
        self._rank_ok = _RankFilter(rank)

    def write(self, registry: MetricsRegistry) -> Optional[str]:
        """Render ``registry`` and atomically replace ``self.path``;
        returns the path written, or None when rank-filtered out."""
        if not self._rank_ok():
            return None
        text = registry.to_prometheus()
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".prom.tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(text)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return self.path


def _jsonable(x):
    """Best-effort conversion for numpy/jax scalars reaching the stream."""
    try:
        return float(x)
    except (TypeError, ValueError):
        return repr(x)
