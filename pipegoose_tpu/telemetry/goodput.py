"""Fleet goodput & incident ledger: wall-clock attribution + MTTR.

The stack can trace one request across replicas (telemetry/fleettrace)
and account every KV byte (telemetry/memledger); this module accounts
where the FLEET'S WALL-CLOCK goes. A :class:`GoodputLedger` is driven
synchronously from ``ControlPlane.run``'s tick loop and attributes
every replica-second into an exhaustive taxonomy:

==================  ====================================================
class               meaning
==================  ====================================================
productive          the tick made decode/prefill progress (goodput)
compile_warmup      progress, but a program family x shape ran for the
                    FIRST time this tick (XLA compile + warmup wall)
idle                SERVING, no work queued
probation           post-rejoin cooldown with no work (not yet routed)
admission_blocked   work queued but admission deferred (memory/capacity
                    — the ``Scheduler.admission_deferrals`` seam)
stall               work queued, no progress, no deferral (wedge-like)
suspect_probing     SUSPECT: heartbeat missed, probe backoff running
failed_quarantine   FAILED: quarantined until rejoin/scale-up
draining            DRAINING/STOPPED: planned migration wall
==================  ====================================================

**Conservation contract** (the house invariant, same shape as the
memory ledger's): per replica, class-seconds sum to that replica's
alive wall within 1e-6 — every tick, including crash/rejoin/scale-up
paths. It holds by construction: each replica carries ONE monotone
``last_mark`` timestamp and every attribution books exactly
``t - last_mark`` into exactly one class, so the per-class sums
telescope to ``last_mark - t0``.

On top of the state account sit :class:`Incident` records — one per
failure episode (crash, wedge, transfer flap, or an explicitly minted
SLO-breach/pool-death episode) — joined to the ``chaos.injection``
flight-recorder ring for detection latency, carrying MTTR (detection
-> accepting-again via rejoin or scale-up), the capacity-gap integral
in replica-seconds, the salvaged/resubmitted/lost uids, and the SLO
burn over the incident window. The control plane embeds each incident
in its ``replica_failure`` black box and closes it from ``rejoin`` /
``scale_up``.

The trainer mirror is :class:`TrainerGoodput`: a callback partitioning
``fit`` wall into step compute vs compile, checkpoint save, restore
rewind, and recovery replay (replayed steps are badput), with the same
conservation contract over the fit wall and an incident per rewind.

Off by default; with no ledger attached the control plane's per-tick
cost is one attribute read + branch (guard-tested under 5 microseconds,
the memory ledger's contract).
"""
from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: the exhaustive taxonomy (order is the report/doc order)
CLASSES: Tuple[str, ...] = (
    "productive", "compile_warmup", "idle", "probation",
    "admission_blocked", "stall", "suspect_probing",
    "failed_quarantine", "draining",
)
#: classes counted as goodput; everything else is badput
GOOD_CLASSES: Tuple[str, ...] = ("productive",)

#: replica-state -> class used when booking wall OUTSIDE the tick loop
#: (between runs, at rejoin): the state the replica sat in IS the class
_STATE_CLASS = {
    "serving": "idle",
    "suspect": "suspect_probing",
    "failed": "failed_quarantine",
    "draining": "draining",
    "stopped": "draining",
}

#: episode ring bound per replica (newest kept; Perfetto export reads
#: these — a week-long fleet must not grow the band unboundedly)
MAX_EPISODES = 4096


class Incident:
    """One failure episode: detection -> capacity restored.

    ``detection_latency_ticks`` is the ring distance to the matching
    ``chaos.injection`` record (None when the failure was organic or no
    recorder is attached). ``mttr_s``/``mttr_ticks`` close at rejoin or
    scale-up; ``capacity_gap_integral_s`` accrues one replica-second
    per second the lost capacity stays uncompensated. ``slo_burn``
    snapshots the ledger's own availability ratio over the window.
    """

    def __init__(self, incident_id: int, kind: str, replica: str,
                 tick: int, t: float, reason: str = "",
                 detection_latency_ticks: Optional[int] = None,
                 injection_step: Optional[int] = None,
                 salvaged_uids: Iterable[int] = (),
                 resubmitted_uids: Iterable[int] = (),
                 completed_uids: Iterable[int] = (),
                 lost_uids: Iterable[int] = (),
                 capacity_gap: int = 0):
        self.id = incident_id
        self.kind = kind
        self.replica = replica
        self.tick_detected = tick
        self.t_detected = t
        self.reason = reason
        self.detection_latency_ticks = detection_latency_ticks
        self.injection_step = injection_step
        self.salvaged_uids = list(salvaged_uids)
        self.resubmitted_uids = list(resubmitted_uids)
        self.completed_uids = list(completed_uids)
        self.lost_uids = list(lost_uids)
        self.capacity_gap_at_open = capacity_gap
        self.capacity_gap_integral_s = 0.0
        self.open = True
        self.resolved_by: Optional[str] = None
        self.tick_resolved: Optional[int] = None
        self.mttr_s: Optional[float] = None
        self.mttr_ticks: Optional[int] = None
        self.events = 1                      # flap-burst merge counter
        self._burn_open: Tuple[float, float] = (0.0, 0.0)
        self.slo_burn: Optional[Dict[str, float]] = None

    def resolve(self, tick: int, t: float, resolved_by: str,
                burn_close: Tuple[float, float]) -> None:
        self.open = False
        self.resolved_by = resolved_by
        self.tick_resolved = tick
        self.mttr_s = max(t - self.t_detected, 0.0)
        self.mttr_ticks = max(tick - self.tick_detected, 0)
        bad0, wall0 = self._burn_open
        bad1, wall1 = burn_close
        dbad, dwall = bad1 - bad0, wall1 - wall0
        self.slo_burn = {
            "badput_s": round(dbad, 9),
            "wall_s": round(dwall, 9),
            "availability": (round(1.0 - dbad / dwall, 6)
                             if dwall > 0 else 1.0),
        }

    def as_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "kind": self.kind,
            "replica": self.replica,
            "tick_detected": self.tick_detected,
            "t_detected": self.t_detected,
            "reason": self.reason,
            "detection_latency_ticks": self.detection_latency_ticks,
            "injection_step": self.injection_step,
            "salvaged_uids": self.salvaged_uids,
            "resubmitted_uids": self.resubmitted_uids,
            "completed_uids": self.completed_uids,
            "lost_uids": self.lost_uids,
            "capacity_gap_at_open": self.capacity_gap_at_open,
            "capacity_gap_integral_s": round(
                self.capacity_gap_integral_s, 9),
            "open": self.open,
            "resolved_by": self.resolved_by,
            "tick_resolved": self.tick_resolved,
            "mttr_s": (None if self.mttr_s is None
                       else round(self.mttr_s, 9)),
            "mttr_ticks": self.mttr_ticks,
            "events": self.events,
            "slo_burn": self.slo_burn,
        }


class _ReplicaAccount:
    """One replica's wall account: a single monotone mark plus the
    per-class / per-state second buckets and the episode band."""

    __slots__ = ("name", "t0", "tick0", "last_mark", "classes",
                 "states", "episodes", "episodes_dropped", "closed")

    def __init__(self, name: str, t: float, tick: int):
        self.name = name
        self.t0 = t
        self.tick0 = tick
        self.last_mark = t
        self.classes: Dict[str, float] = {}
        self.states: Dict[str, float] = {}
        self.episodes: List[Dict[str, Any]] = []
        self.episodes_dropped = 0
        self.closed = False

    @property
    def alive_wall_s(self) -> float:
        return self.last_mark - self.t0

    def account(self, t: float, klass: str, state: str,
                tick: int) -> None:
        dt = t - self.last_mark
        self.classes[klass] = self.classes.get(klass, 0.0) + dt
        self.states[state] = self.states.get(state, 0.0) + dt
        eps = self.episodes
        if eps and eps[-1]["class"] == klass and eps[-1]["state"] == state:
            eps[-1]["t1"] = t
            eps[-1]["tick1"] = tick
            eps[-1]["ticks"] += 1
        else:
            eps.append({"class": klass, "state": state,
                        "t0": self.last_mark, "t1": t,
                        "tick0": tick, "tick1": tick, "ticks": 1})
            if len(eps) > MAX_EPISODES:
                del eps[0]
                self.episodes_dropped += 1
        self.last_mark = t

    def conservation(self) -> Dict[str, Any]:
        total = sum(self.classes.values())
        err = abs(total - self.alive_wall_s)
        return {"ok": err <= 1e-6, "error_s": err,
                "class_sum_s": total, "alive_wall_s": self.alive_wall_s}


class GoodputLedger:
    """The fleet wall-clock account + incident ledger (module
    docstring). Drive it from a control plane (``goodput=True``) or by
    hand: :meth:`touch` opens/extends a replica account outside the
    tick loop, :meth:`account` books one tick's classification,
    :meth:`on_tick` accrues open incidents and publishes gauges."""

    def __init__(self, *, registry: Any = None,
                 max_incidents: int = 256):
        self.registry = registry
        self.replicas: Dict[str, _ReplicaAccount] = {}
        self.incidents: List[Incident] = []
        self.max_incidents = int(max_incidents)
        self.incidents_dropped = 0
        self._open: List[Incident] = []
        self._next_id = 0
        self._last_tick_t: Optional[float] = None
        self._flap_last_tick: Dict[str, int] = {}
        self._flap_last_inc: Dict[str, Incident] = {}
        self._claimed: set = set()          # id(ring record) already joined
        self._pub_bad = 0.0                 # counter high-water marks
        self._pub_wall = 0.0

    # -- wall attribution --------------------------------------------------

    def touch(self, name: str, t: float, state: str,
              tick: int = 0) -> None:
        """Open a replica account (run start, scale-up) or book the
        wall since its last mark into the class its CURRENT state
        implies (between-runs gaps, the moment before a rejoin flips
        FAILED back to SERVING) — conservation stays exact across every
        lifecycle path because the gap is booked, never skipped."""
        acct = self.replicas.get(name)
        if acct is None:
            self.replicas[name] = _ReplicaAccount(name, t, tick)
            return
        if t > acct.last_mark:
            acct.account(t, _STATE_CLASS.get(state, "idle"), state, tick)

    def account(self, name: str, t: float, klass: str, state: str,
                tick: int) -> None:
        """Book ``t - last_mark`` seconds of ``name``'s wall into
        ``klass`` (one call per replica per control-plane tick)."""
        acct = self.replicas.get(name)
        if acct is None:
            acct = self.replicas[name] = _ReplicaAccount(name, t, tick)
        acct.account(t, klass, state, tick)

    def classify(self, rep: Any, pre: Optional[Tuple[int, int, int]],
                 had_work: bool, ticked: bool, took: bool) -> str:
        """One tick's class for ``rep`` (a control-plane ``Replica``),
        priority-ordered; ``pre`` is the (programs_run,
        admission_deferrals, kv_fallbacks) snapshot taken before the
        tick so first-compiles and admission deferrals are deltas, not
        absolutes."""
        state = rep.state.value
        if state == "failed":
            return "failed_quarantine"
        if state in ("draining", "stopped"):
            return "draining"
        eng = rep.engine
        if ticked or took:
            if (pre is not None
                    and getattr(eng, "programs_run", 0) > pre[0]):
                return "compile_warmup"
            return "productive"
        if had_work:
            if state == "suspect":
                return "suspect_probing"
            if (pre is not None
                    and getattr(eng.sched, "admission_deferrals", 0)
                    > pre[1]):
                return "admission_blocked"
            return "stall"
        if rep.probation_ticks_left > 0:
            return "probation"
        if state == "suspect":
            return "suspect_probing"
        return "idle"

    def pre_tick(self, rep: Any) -> Tuple[int, int, int]:
        """Snapshot the per-tick delta sources before a replica
        ticks: programs run (compile detection), admission deferrals
        (memory/capacity blockage), KV-tier fallbacks (transfer
        flaps)."""
        eng = rep.engine
        kvt = getattr(eng, "kv_tier", None)
        return (getattr(eng, "programs_run", 0),
                getattr(eng.sched, "admission_deferrals", 0),
                getattr(kvt, "fallbacks", 0) if kvt is not None else 0)

    def on_tick(self, tick: int, t: float) -> None:
        """End-of-tick accrual: every open incident's capacity-gap
        integral grows by the tick wall, gauges refresh."""
        if self._open and self._last_tick_t is not None:
            dt = max(t - self._last_tick_t, 0.0)
            for inc in self._open:
                inc.capacity_gap_integral_s += dt
        self._last_tick_t = t
        self.publish()

    # -- incidents ---------------------------------------------------------

    def _join_injection(self, recorder: Any, victim: Optional[str],
                        kinds: Tuple[str, ...],
                        tick: int) -> Tuple[Optional[int], Optional[int]]:
        """Claim the newest UNCLAIMED ``chaos.injection`` ring record
        matching ``kinds`` (and ``victim`` when the record names one);
        returns (detection_latency_ticks, injection_step). The latency
        is the ring distance in ticks: detection tick minus the
        injection's own step."""
        if recorder is None:
            return None, None
        try:
            records = list(recorder.records)
        except Exception:  # noqa: BLE001 - forensics must not raise
            return None, None
        for rec in reversed(records):
            if not isinstance(rec, dict):
                continue
            if rec.get("kind") != "chaos.injection":
                continue
            if rec.get("injection") not in kinds:
                continue
            if (victim is not None and rec.get("victim") is not None
                    and rec.get("victim") != victim):
                continue
            if id(rec) in self._claimed:
                continue
            self._claimed.add(id(rec))
            step = rec.get("step")
            if step is None:
                return None, None
            return max(tick - int(step), 0), int(step)
        return None, None

    def open_incident(self, kind: str, replica: str, tick: int,
                      t: float, *, reason: str = "",
                      recorder: Any = None,
                      injection_kinds: Tuple[str, ...] = (),
                      salvaged_uids: Iterable[int] = (),
                      resubmitted_uids: Iterable[int] = (),
                      completed_uids: Iterable[int] = (),
                      lost_uids: Iterable[int] = (),
                      capacity_gap: int = 0) -> Incident:
        latency, inj_step = self._join_injection(
            recorder, replica, injection_kinds or (kind,), tick)
        inc = Incident(
            self._next_id, kind, replica, tick, t, reason=reason,
            detection_latency_ticks=latency, injection_step=inj_step,
            salvaged_uids=salvaged_uids, resubmitted_uids=resubmitted_uids,
            completed_uids=completed_uids, lost_uids=lost_uids,
            capacity_gap=capacity_gap,
        )
        self._next_id += 1
        inc._burn_open = self._burn_point()
        self.incidents.append(inc)
        if len(self.incidents) > self.max_incidents:
            dropped = self.incidents.pop(0)
            self.incidents_dropped += 1
            if dropped in self._open:      # pathological but bounded
                self._open.remove(dropped)
        self._open.append(inc)
        return inc

    def note_transfer_flap(self, replica: str, tick: int, t: float,
                           fallbacks: int,
                           recorder: Any = None) -> Optional[Incident]:
        """A KV transfer flap surfaced as ``fallbacks`` new restore
        fallbacks on ``replica`` this tick. Consecutive-tick bursts
        merge into ONE incident (a fail_times=3 fault is one flap, not
        three); the incident closes at detection — a fallback IS the
        recovery (the replica recomputed instead of pulling), so MTTR
        is zero and no capacity was lost."""
        last = self._flap_last_tick.get(replica)
        self._flap_last_tick[replica] = tick
        if last is not None and tick - last <= 1:
            prev = self._flap_last_inc.get(replica)
            if prev is not None:
                prev.events += fallbacks
                return None
        inc = self.open_incident(
            "transfer_flap", replica, tick, t,
            reason=f"{fallbacks} KV transfer fallback(s)",
            recorder=recorder, injection_kinds=("transfer_flap",),
        )
        inc.events = fallbacks
        self._open.remove(inc)
        inc.resolve(tick, t, "fallback", self._burn_point())
        self._flap_last_inc[replica] = inc
        return inc

    def resolve_incident(self, replica: Optional[str], tick: int,
                         t: float, resolved_by: str) -> Optional[Incident]:
        """Close the open incident for ``replica`` (rejoin), or the
        OLDEST open one (scale-up replaces capacity, not a specific
        replica). No-op when nothing is open."""
        inc = None
        if replica is not None:
            for cand in self._open:
                if cand.replica == replica:
                    inc = cand
                    break
        if inc is None and self._open:
            inc = self._open[0]
        if inc is None:
            return None
        self._open.remove(inc)
        inc.resolve(tick, t, resolved_by, self._burn_point())
        return inc

    @property
    def open_incidents(self) -> List[Incident]:
        return list(self._open)

    # -- rollups -----------------------------------------------------------

    def _burn_point(self) -> Tuple[float, float]:
        tot = self.totals()
        return tot["badput_seconds"], tot["wall_seconds"]

    def totals(self) -> Dict[str, Any]:
        classes: Dict[str, float] = {}
        wall = 0.0
        for acct in self.replicas.values():
            wall += sum(acct.classes.values())
            for k, v in acct.classes.items():
                classes[k] = classes.get(k, 0.0) + v
        good = sum(classes.get(k, 0.0) for k in GOOD_CLASSES)
        return {
            "wall_seconds": wall,
            "productive_seconds": good,
            "badput_seconds": wall - good,
            "fraction": good / wall if wall > 0 else 1.0,
            "classes": classes,
        }

    def state_seconds(self, name: str) -> Dict[str, float]:
        """Per-state dwell for one replica (``/debug/fleet`` rows)."""
        acct = self.replicas.get(name)
        if acct is None:
            return {}
        return {k: round(v, 9) for k, v in acct.states.items()}

    def conservation(self) -> Dict[str, Any]:
        per = {n: a.conservation() for n, a in self.replicas.items()}
        return {"ok": all(c["ok"] for c in per.values()),
                "max_error_s": max(
                    (c["error_s"] for c in per.values()), default=0.0),
                "replicas": per}

    def summary(self) -> Dict[str, Any]:
        """The compact rollup ``fleet_status()["goodput"]`` carries."""
        tot = self.totals()
        return {
            "goodput_fraction": round(tot["fraction"], 6),
            "productive_seconds": round(tot["productive_seconds"], 9),
            "wall_seconds": round(tot["wall_seconds"], 9),
            "badput_seconds": round(tot["badput_seconds"], 9),
            "classes": {k: round(v, 9)
                        for k, v in sorted(tot["classes"].items())},
            "conservation_ok": self.conservation()["ok"],
            "incidents": len(self.incidents),
            "open_incidents": len(self._open),
        }

    def report(self) -> Dict[str, Any]:
        """The full ``/debug/goodput`` payload: fleet rollup,
        per-replica class/state seconds + conservation verdicts, and
        every incident."""
        out = self.summary()
        out["replicas"] = {
            n: {
                "alive_wall_s": round(a.alive_wall_s, 9),
                "classes": {k: round(v, 9)
                            for k, v in sorted(a.classes.items())},
                "states": {k: round(v, 9)
                           for k, v in sorted(a.states.items())},
                "conservation": a.conservation(),
                "episodes": len(a.episodes),
                "episodes_dropped": a.episodes_dropped,
            }
            for n, a in self.replicas.items()
        }
        out["incident_log"] = [i.as_dict() for i in self.incidents]
        out["incidents_dropped"] = self.incidents_dropped
        return out

    def publish(self) -> None:
        """Refresh the registry surface: ``goodput.fraction`` /
        ``goodput.productive_seconds`` gauges, per-class badput gauges,
        and the MONOTONE ``goodput.{badput,wall}_seconds_total``
        counters the availability ratio SLO reads."""
        reg = self.registry
        if reg is None or not getattr(reg, "enabled", False):
            return
        tot = self.totals()
        reg.gauge("goodput.fraction").set(tot["fraction"])
        reg.gauge("goodput.productive_seconds").set(
            tot["productive_seconds"])
        reg.gauge("goodput.open_incidents").set(float(len(self._open)))
        reg.gauge("goodput.incidents_total").set(
            float(len(self.incidents)))
        for k in CLASSES:
            if k in GOOD_CLASSES:
                continue
            reg.gauge(f"goodput.badput.{k}_seconds").set(
                tot["classes"].get(k, 0.0))
        # counters only ever move forward: publish the delta since the
        # last publish (both sums are monotone in real time)
        dbad = tot["badput_seconds"] - self._pub_bad
        dwall = tot["wall_seconds"] - self._pub_wall
        if dbad > 0:
            reg.counter("goodput.badput_seconds_total").inc(dbad)
            self._pub_bad = tot["badput_seconds"]
        if dwall > 0:
            reg.counter("goodput.wall_seconds_total").inc(dwall)
            self._pub_wall = tot["wall_seconds"]


def availability_slo_target(target: float = 0.95) -> Any:
    """The availability ratio SLO over the ledger's counters: good =
    wall that wasn't badput. Feed it to an ``SLOMonitor`` over the
    registry the ledger publishes into (the control plane's own)."""
    from pipegoose_tpu.telemetry.slo import SLOTarget

    return SLOTarget(
        name="fleet_availability", kind="ratio",
        bad_metric="goodput.badput_seconds_total",
        total_metric="goodput.wall_seconds_total",
        target=target,
    )


class TrainerGoodput:
    """The training-side mirror: partition ``fit`` wall into step
    compute vs compile, checkpoint save, restore rewind, and recovery
    replay — with the serving ledger's conservation contract over the
    fit wall and one incident per recovery rewind (MTTR = rewind
    detection -> the step counter re-reaching its pre-rewind
    high-water; every replayed step is badput).

    Order -100: its ``on_step_end`` stamps the step wall BEFORE
    ``AutoRecovery`` (order -10) can roll the step counter back and
    before ``CheckpointCallback`` (order 0) spends save wall — so the
    between-step gap that follows is attributable to them.
    """

    order = -100

    #: trainer-side taxonomy (conservation: these sum to fit wall)
    CLASSES: Tuple[str, ...] = (
        "step_compute", "compile_warmup", "rewind_replay",
        "checkpoint_save", "restore", "other",
    )
    GOOD: Tuple[str, ...] = ("step_compute",)

    def __init__(self, *, clock=time.perf_counter, registry: Any = None):
        self.clock = clock
        self.registry = registry
        self.classes: Dict[str, float] = {}
        self.incidents: List[Dict[str, Any]] = []
        self.replayed_steps = 0
        self._t_fit0: Optional[float] = None
        self._last: Optional[float] = None
        self._t_step0: Optional[float] = None
        self._high_water = 0
        self._next_expected: Optional[int] = None
        self._first_step_done = False
        self._ckpt_pending = False
        self._open: Optional[Dict[str, Any]] = None
        self._fit_wall: Optional[float] = None

    def _book(self, klass: str, dt: float) -> None:
        self.classes[klass] = self.classes.get(klass, 0.0) + dt

    # -- Callback protocol (duck-typed; order attribute sorts it) ----------

    def on_fit_start(self, trainer: Any) -> None:
        t = self.clock()
        self._t_fit0 = t
        self._last = t
        step = int(getattr(getattr(trainer, "state", None), "step", 0) or 0)
        self._high_water = step
        self._next_expected = None
        self._fit_wall = None

    def on_step_start(self, trainer: Any, step: int) -> None:
        t = self.clock()
        gap = max(t - (self._last if self._last is not None else t), 0.0)
        if (self._next_expected is not None
                and step < self._next_expected):
            # the step counter went BACKWARD between steps: recovery
            # restored an older checkpoint — the gap is restore wall,
            # and an incident opens with the pre-rewind high-water as
            # its recovery target
            self._book("restore", gap)
            if self._open is None:
                self._open = {
                    "kind": "recovery_rewind",
                    "step_detected": self._high_water,
                    "rewound_to": step,
                    "t_detected": t,
                    "replayed_steps": 0,
                    "open": True,
                    "mttr_s": None,
                }
                self.incidents.append(self._open)
        elif self._ckpt_pending:
            self._book("checkpoint_save", gap)
        else:
            self._book("other", gap)
        self._ckpt_pending = False
        self._t_step0 = t
        self._last = t

    def on_step_end(self, trainer: Any, step: int, loss: Any) -> None:
        t = self.clock()
        dt = max(t - (self._t_step0 if self._t_step0 is not None else t),
                 0.0)
        if step <= self._high_water and self._next_expected is not None:
            # re-running a step number already passed: rewind replay
            self._book("rewind_replay", dt)
            self.replayed_steps += 1
            if self._open is not None:
                self._open["replayed_steps"] += 1
        elif not self._first_step_done:
            self._book("compile_warmup", dt)
            self._first_step_done = True
        else:
            self._book("step_compute", dt)
        if (self._open is not None and step >= self._high_water):
            # recovered: the counter re-reached its pre-rewind mark
            self._open["open"] = False
            self._open["mttr_s"] = max(
                t - self._open["t_detected"], 0.0)
            self._open = None
        self._high_water = max(self._high_water, step)
        self._next_expected = step
        self._last = t

    def on_checkpoint(self, trainer: Any, step: int, path: str) -> None:
        self._ckpt_pending = True

    def _finish(self, trainer: Any) -> None:
        t = self.clock()
        if self._last is not None:
            self._book("other", max(t - self._last, 0.0))
            self._last = t
        if self._t_fit0 is not None:
            self._fit_wall = t - self._t_fit0
        reg = self.registry
        if reg is not None and getattr(reg, "enabled", False):
            rep = self.report()
            reg.gauge("train.goodput.fraction").set(
                rep["goodput_fraction"])
            for k, v in rep["classes"].items():
                reg.gauge(f"train.goodput.{k}_seconds").set(v)

    def on_fit_end(self, trainer: Any) -> None:
        self._finish(trainer)

    def on_fit_abort(self, trainer: Any, exc: BaseException) -> None:
        self._finish(trainer)

    # -- rollup ------------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        total = sum(self.classes.values())
        good = sum(self.classes.get(k, 0.0) for k in self.GOOD)
        wall = (self._fit_wall if self._fit_wall is not None
                else total)
        return {
            "fit_wall_s": round(wall, 9),
            "goodput_fraction": round(good / total, 6) if total else 1.0,
            "classes": {k: round(v, 9)
                        for k, v in sorted(self.classes.items())},
            "conservation_ok": abs(total - wall) <= 1e-6,
            "conservation_error_s": abs(total - wall),
            "replayed_steps": self.replayed_steps,
            "incidents": [dict(i) for i in self.incidents],
        }
