"""Process-local metrics registry: counters, gauges, histograms.

The reference pipegoose has no metrics at all (its ``DistributedLogger``
is an empty stub, SURVEY.md §5); operating the ROADMAP's "heavy
traffic" north star needs them. Design constraints, in order:

1. **Near-zero overhead when disabled.** Library code (trainer loop,
   serving engine, decode driver) is instrumented UNCONDITIONALLY; the
   global registry starts disabled, so the un-observed cost of a
   ``counter.inc()`` or ``span()`` entry is one attribute read and a
   branch (< 5 µs guarded by tests/telemetry/test_registry.py). There
   is no "if telemetry:" litter at call sites.
2. **Safe under jit tracing.** Host-side metric mutation inside a
   traced function would record trace-time (once per COMPILE, not per
   execution) — every mutation no-ops when the value is a
   ``jax.core.Tracer`` or a trace is in progress, so instrumented
   helpers can be called from inside ``jax.jit`` bodies without either
   crashing or double counting.
3. **Thread-safe.** The serving engine and exporters may run on
   different threads; each metric carries its own lock, taken only on
   the enabled path.

Metrics are identified by dotted names (``serving.ttft_seconds``); the
Prometheus exporter sanitizes them. Histograms keep BOTH fixed bucket
counts (cheap, exporter-friendly) and a bounded reservoir (exact
quantiles for small runs, statistically sound for long ones).
"""
from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax


def _tracing(value: Any = None) -> bool:
    """True when recording must no-op: a jit trace is in progress or the
    value itself is a tracer (mutating host state then would count per
    compile, not per execution)."""
    if isinstance(value, jax.core.Tracer):
        return True
    try:
        return not jax.core.trace_state_clean()
    except Exception:  # noqa: BLE001 - exotic jax builds: fail open
        return False


class _AlwaysEnabled:
    """Enabled-flag stand-in for metrics constructed WITHOUT a registry
    (standalone use of the exported Counter/Gauge/Histogram classes):
    they record unconditionally, since there is no registry to toggle."""

    _enabled = True


_STANDALONE = _AlwaysEnabled()


class Counter:
    """Monotonic float counter."""

    __slots__ = ("name", "help", "_value", "_lock", "_registry")

    def __init__(self, name: str, help: str = "", registry: "MetricsRegistry" = None):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()
        self._registry = registry if registry is not None else _STANDALONE

    def inc(self, amount: float = 1.0) -> None:
        if not self._registry._enabled:
            return
        if _tracing(amount):
            return
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment {amount}")
        with self._lock:
            self._value += float(amount)

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time float value (last write wins)."""

    __slots__ = ("name", "help", "_value", "_lock", "_registry")

    def __init__(self, name: str, help: str = "", registry: "MetricsRegistry" = None):
        self.name = name
        self.help = help
        self._value = float("nan")
        self._lock = threading.Lock()
        self._registry = registry if registry is not None else _STANDALONE

    def set(self, value: float) -> None:
        if not self._registry._enabled:
            return
        if _tracing(value):
            return
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


# span/step durations in seconds: 10 µs dispatch noise up to minute-long
# compiles all land in a distinguishable bucket
DEFAULT_TIME_BUCKETS = (
    1e-5, 1e-4, 1e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Histogram:
    """Bucketed + reservoir histogram.

    Fixed cumulative-style bucket counts back the Prometheus export;
    a bounded reservoir (algorithm R, deterministic seed per metric so
    repeat runs export identical snapshots) backs exact-ish quantiles.
    """

    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count",
                 "_min", "_max", "_reservoir", "_cap", "_rng", "_lock",
                 "_registry")

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
                 reservoir: int = 512, registry: "MetricsRegistry" = None):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +1: +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._min = float("inf")
        self._max = float("-inf")
        self._reservoir: List[float] = []
        self._cap = int(reservoir)
        # crc32, not hash(): str hashing is salted per process, and the
        # whole point of the fixed seed is identical reservoirs (hence
        # identical exported quantiles) across repeat runs
        self._rng = random.Random(zlib.crc32(name.encode()))
        self._lock = threading.Lock()
        self._registry = registry if registry is not None else _STANDALONE

    def observe(self, value: float) -> None:
        if not self._registry._enabled:
            return
        if _tracing(value):
            return
        v = float(value)
        with self._lock:
            i = 0
            for i, b in enumerate(self.buckets):  # noqa: B007
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            if len(self._reservoir) < self._cap:
                self._reservoir.append(v)
            else:
                j = self._rng.randint(0, self._count - 1)
                if j < self._cap:
                    self._reservoir[j] = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        with self._lock:
            sample = sorted(self._reservoir)
        if not sample:
            return float("nan")
        idx = min(int(q * len(sample)), len(sample) - 1)
        return sample[idx]

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            n, s = self._count, self._sum
            lo, hi = self._min, self._max
        out = {
            "count": n,
            "sum": s,
            "mean": s / n if n else float("nan"),
            "min": lo if n else float("nan"),
            "max": hi if n else float("nan"),
            "p50": self.quantile(0.5),
            "p90": self.quantile(0.9),
            "p99": self.quantile(0.99),
            "buckets": {
                **{str(b): c for b, c in zip(self.buckets, counts)},
                "+Inf": counts[-1],
            },
        }
        return out


class MetricsRegistry:
    """Named home for counters/gauges/histograms plus an event fan-out.

    Metric getters are idempotent (same name -> same object) and
    type-checked: asking for ``counter("x")`` after ``gauge("x")`` is a
    programming error worth failing loudly on. ``event()`` dispatches a
    timestamped dict to attached sinks (exporters.JSONLExporter) — the
    time-series half of telemetry that aggregate metrics can't carry.
    """

    def __init__(self, enabled: bool = True):
        self._enabled = bool(enabled)
        self._metrics: Dict[str, Any] = {}
        self._sinks: List[Callable[[dict], None]] = []
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def clear(self) -> None:
        """Drop all metrics and sinks (tests). Metric handles resolved
        BEFORE the clear stay functional but detach from the registry —
        their later updates are invisible to snapshot()/to_prometheus().
        Long-lived holders (e.g. a ServingEngine) must be rebuilt, or
        the registry replaced, rather than cleared under them."""
        with self._lock:
            self._metrics.clear()
            self._sinks = []

    # -- metric getters ----------------------------------------------------

    def _get(self, name: str, cls, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, registry=self, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, help=help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
                  reservoir: int = 512) -> Histogram:
        return self._get(name, Histogram, help=help, buckets=buckets,
                         reservoir=reservoir)

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._metrics)

    # -- events ------------------------------------------------------------

    def attach(self, sink: Callable[[dict], None]) -> None:
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def detach(self, sink: Callable[[dict], None]) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def event(self, kind: str, **fields: Any) -> None:
        """Dispatch one timestamped event dict to every attached sink."""
        if not self._enabled or not self._sinks:
            return
        if _tracing():
            return
        ev = {"ts": time.time(), "kind": kind, **fields}
        for sink in list(self._sinks):
            sink(ev)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """Plain-dict view of every metric (utils/profiler.py's JSON-able
        convention)."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in self.metrics().items():
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            elif isinstance(m, Histogram):
                out["histograms"][name] = m.snapshot()
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (textfile-collector flavor)."""
        lines: List[str] = []
        for name, m in sorted(self.metrics().items()):
            pname = _prom_name(name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pname} counter")
                if m.help:
                    lines.append(f"# HELP {pname} {m.help}")
                lines.append(f"{pname} {_prom_value(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {pname} gauge")
                if m.help:
                    lines.append(f"# HELP {pname} {m.help}")
                lines.append(f"{pname} {_prom_value(m.value)}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {pname} histogram")
                if m.help:
                    lines.append(f"# HELP {pname} {m.help}")
                with m._lock:  # consistent counts/sum/count vs observe()
                    counts = list(m._counts)
                    h_sum, h_count = m._sum, m._count
                cum = 0
                for b, c in zip(m.buckets, counts):
                    cum += c
                    lines.append(f'{pname}_bucket{{le="{b}"}} {cum}')
                cum += counts[-1]
                lines.append(f'{pname}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{pname}_sum {_prom_value(h_sum)}")
                lines.append(f"{pname}_count {h_count}")
        return "\n".join(lines) + "\n"


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_value(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    return repr(float(v))


# -- global default -------------------------------------------------------
#
# Library instrumentation targets this registry; it starts DISABLED so
# un-observed runs pay only the enabled-flag branch. Entry points that
# want telemetry (TelemetryCallback, bench.py, examples/telemetry_demo)
# call enable().
_default = MetricsRegistry(enabled=False)


def get_registry() -> MetricsRegistry:
    return _default


def enable() -> None:
    _default.enable()


def disable() -> None:
    _default.disable()
