"""Live memory ledger: byte-exact KV/page accounting across the HBM
pool, the host tier, and transfer staging — with leak audits and
exhaustion forecasting.

The fleet moves KV pages through five owners — live request tables,
COW prefix-cache pins, scheduler reservations, disagg transfer
staging, and the host-DRAM tier — and a single refcount leak or
reservation strand silently shrinks the pool until admission stalls
with no attribution. The :class:`MemoryLedger` closes that gap:

- **Per-owner-class byte account.** The ledger mirrors every
  ``PagePool`` refcount as an owner-tag multiset (``("req", uid)`` /
  ``("stage", uid)`` / ``("cow", uid)`` / ``("cache",)`` /
  ``("restore",)``), fed synchronously by the pool's event stream (the
  same (event, pages, delta) triples ``PagePool.history`` records —
  delivered as an observer, not parsed from the lossy ring, so
  accounting is exact even after the ring wraps). Each allocated page
  classifies by owner priority request ≻ staged ≻ cow ≻ cached, so
  a physically shared page is counted ONCE, under its strongest owner.

- **Hard conservation contract.** On every tick, classified pages +
  reserved-unmaterialized + free-unreserved == pool capacity exactly
  (integer pages x the measured bytes-per-page — no 1e-6 slack
  needed: everything here is integral). Reservations can exceed the
  physically free pages (the admission ledger spends evictable cache
  pages too), so ``reserved_unmaterialized = min(outstanding, free)``
  keeps the sum exact while ``reserved_evictable_backed`` reports the
  overlap separately. The host tier is a SECOND byte account (wire-
  precision slabs in host DRAM), never part of the HBM sum.

- **``audit()`` leak detector.** Cross-checks three ground truths —
  pool refcounts, the reachable holders (live requests' page tables +
  COW pins, transfer stages, prefix-trie nodes), and the scheduler's
  reservation ledger — and fires a ``memory_leak`` / ``double_owner``
  / ``stranded_reservation`` black box through the flight recorder
  naming the page and its last-N ownership trail. testing/chaos.py's
  ``page_leak`` / ``stranded_reservation`` kinds prove the detection
  path end-to-end.

- **Exhaustion forecaster.** A rolling window of admission headroom
  (free + evictable - reserved) against the recent consumption rate
  and the typical admission need yields ``steps_to_exhaustion`` — a
  gauge that goes monotonically to zero BEFORE the first admission
  deferral, wired into the autoscaler's capacity signal and the
  control-plane router's per-replica load.

Everything defaults OFF: an unattached engine pays one attribute read
+ branch per tick (the tracer/recorder <5µs convention, guard-tested),
and the pool's alloc/share/release pay the same when no ledger is
attached. Host-side only — nothing here touches device memory or any
jitted program.
"""
from __future__ import annotations

import math
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

Tag = Tuple  # ("req", uid) | ("stage", uid) | ("cow", uid) | ("cache",) ...

#: owner-class names, strongest first — a shared page counts once,
#: under the first class below that holds a reference to it
CLASSES = ("request", "staged", "cow", "cached")

#: tag kind -> owner class (restore-in-flight pages are staged
#: transfers from the host tier / a peer; untracked refs — adopted by
#: a warm ``resync`` — conservatively count as request KV)
_KIND_CLASS = {
    "req": "request",
    "stage": "staged",
    "restore": "staged",
    "cow": "cow",
    "cache": "cached",
    "untracked": "request",
}

#: classification priority of tag kinds (index = strength)
_PRIORITY = {"req": 0, "stage": 1, "restore": 2, "cow": 3, "cache": 4,
             "untracked": 5}


class MemoryLedger:
    """Byte-exact per-owner-class account of a ``PagePool``'s pages.

    Construct, then :meth:`bind` to a pool (and optionally scheduler /
    prefix cache / host tier / recorder / registry), or let
    ``ServingEngine(..., memledger=...)`` / ``attach_memledger`` do
    the binding. ``audit_every=N`` runs the leak audit every N ticks
    (0 = only when called explicitly — the default, keeping the tick
    cost to the classification bookkeeping)."""

    def __init__(self, *, trail_len: int = 8, window: int = 32,
                 audit_every: int = 0, max_samples: int = 4096):
        if trail_len < 1:
            raise ValueError(f"trail_len must be >= 1, got {trail_len}")
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        self.trail_len = trail_len
        self.window = window
        self.audit_every = audit_every
        # page -> owner-tag multiset (mirrors the pool refcount) and
        # the derived class; counts are maintained incrementally so a
        # tick never walks every page
        self._tags: Dict[int, List[Tag]] = {}
        self._class: Dict[int, str] = {}
        self._counts: Dict[str, int] = {c: 0 for c in CLASSES}
        # page -> last-N (seq, event, tag) ownership transitions; kept
        # after free — the trail is exactly what a leak box needs
        self._trail: Dict[int, Deque[Tuple[int, str, Optional[Tag]]]] = {}
        self._seq = 0
        self.mismatched_releases = 0   # release tag absent from the page
        # bound collaborators (all optional except the pool)
        self.pool = None
        self.sched = None
        self.cache = None
        self.host_tier = None
        self.recorder = None
        self.registry = None
        self.bytes_per_page = 1
        # conservation + audit state
        self.ticks = 0
        self.conservation_failures = 0
        self.last_audit: Optional[dict] = None
        self.audits_run = 0
        self._fired: set = set()       # (trigger, key) — fire each once
        # exhaustion forecaster state
        self._needs: Deque[int] = deque(maxlen=window)
        self._avail_hist: Deque[int] = deque(maxlen=window)
        self.steps_to_exhaustion: float = math.inf
        self.min_steps_to_exhaustion: float = math.inf
        self.first_admission_block_tick: Optional[int] = None
        # per-tick occupancy samples (Perfetto counter tracks /
        # /debug/memory trend) + run peaks
        self.samples: Deque[dict] = deque(maxlen=max_samples)
        self.peak_pages: Dict[str, int] = {c: 0 for c in CLASSES}
        self.peak_fragmentation = 0.0
        self._m = None                 # resolved gauge handles

    # -- binding -----------------------------------------------------------

    def bind(self, pool, *, sched=None, cache=None, host_tier=None,
             recorder=None, registry=None, bytes_per_page: int = 1):
        """Attach to ``pool`` as its synchronous event observer (sets
        ``pool.ledger``) and remember the ground-truth sources the
        audit cross-checks. ``bytes_per_page`` is the MEASURED wire
        size of one page in the pool's dtype (q+scale planes for int8
        — engine.attach_memledger computes it from the live arrays).
        A warm pool is adopted via :meth:`resync`."""
        self.pool = pool
        self.sched = sched
        self.cache = cache
        self.host_tier = host_tier
        self.recorder = recorder
        self.registry = registry
        self.bytes_per_page = int(bytes_per_page)
        pool.ledger = self
        if registry is not None:
            g = registry.gauge
            self._m = {
                "request": g("serving.memledger.request_bytes"),
                "staged": g("serving.memledger.staged_bytes"),
                "cow": g("serving.memledger.cow_bytes"),
                "cached": g("serving.memledger.cached_bytes"),
                "reserved": g("serving.memledger.reserved_bytes"),
                "free": g("serving.memledger.free_bytes"),
                "host": g("serving.memledger.host_tier_bytes"),
                "forecast": g("serving.memledger.steps_to_exhaustion"),
            }
        if pool.used_count:
            self.resync()
        return self

    def unbind(self) -> None:
        if self.pool is not None and getattr(self.pool, "ledger", None) is self:
            self.pool.ledger = None

    def resync(self) -> None:
        """Adopt a pool with live allocations (post-hoc attachment to
        a warm engine): rebuild the tag multisets from the reachable
        holders; refcounts nobody reachable explains become
        ``("untracked",)`` request-class tags — visible, not hidden."""
        self._tags.clear()
        self._class.clear()
        self._counts = {c: 0 for c in CLASSES}
        holders = self._reachable_holders()
        for page, ref in self.pool._ref.items():
            tags = list(holders.get(page, ()))[:ref]
            tags += [("untracked",)] * (ref - len(tags))
            self._tags[page] = tags
            self._reclass(page)

    # -- pool event feed ---------------------------------------------------

    def on_pool_event(self, event: str, pages, tag: Optional[Tag]) -> None:
        """Synchronous observer called by the pool inside alloc /
        share / release — same triples ``history`` records, plus the
        owner tag the call site declared (None = untagged)."""
        self._seq += 1
        seq = self._seq
        if event == "alloc":
            t = tag or ("untracked",)
            for p in pages:
                self._tags[p] = [t]
                self._note(p, seq, event, t)
                self._reclass(p)
        elif event == "share":
            t = tag or ("untracked",)
            for p in pages:
                self._tags.setdefault(p, []).append(t)
                self._note(p, seq, event, t)
                self._reclass(p)
        elif event == "release":
            for p in pages:
                tags = self._tags.get(p)
                if not tags:
                    # release of a page the ledger never saw (warm
                    # attach gap) — count it, don't crash the run
                    self.mismatched_releases += 1
                    continue
                if tag is not None and tag in tags:
                    tags.remove(tag)
                else:
                    if tag is not None:
                        self.mismatched_releases += 1
                    # drop the WEAKEST tag: losing an anonymous
                    # reference should never demote a page out of its
                    # strongest owner class
                    tags.remove(max(tags, key=self._strength))
                self._note(p, seq, event, tag)
                if not tags:
                    del self._tags[p]
                self._reclass(p)

    def retag(self, pages, old: Tag, new: Tag) -> None:
        """Ownership transition without a refcount change — the disagg
        ``admit_with_pages`` moment where staged transfer pages become
        request KV."""
        self._seq += 1
        for p in pages:
            tags = self._tags.get(p)
            if tags is None or old not in tags:
                self.mismatched_releases += 1
                continue
            tags[tags.index(old)] = new
            self._note(p, self._seq, "retag", new)
            self._reclass(p)

    @staticmethod
    def _strength(tag: Tag) -> int:
        return _PRIORITY.get(tag[0], 9)

    def _note(self, page: int, seq: int, event: str,
              tag: Optional[Tag]) -> None:
        trail = self._trail.get(page)
        if trail is None:
            trail = self._trail[page] = deque(maxlen=self.trail_len)
        trail.append((seq, event, tag))

    def _reclass(self, page: int) -> None:
        tags = self._tags.get(page)
        new = None
        if tags:
            best = min(tags, key=self._strength)
            new = _KIND_CLASS.get(best[0], "request")
        old = self._class.get(page)
        if old == new:
            return
        if old is not None:
            self._counts[old] -= 1
        if new is not None:
            self._counts[new] += 1
            self._class[page] = new
        else:
            del self._class[page]

    # -- admission pressure feed ------------------------------------------

    def note_admission(self, need_pages: int, admitted: bool) -> None:
        """Scheduler admission feed: the queue head's worst-case page
        need, and whether it got in. The needs size the forecaster's
        "typical request"; the first memory deferral timestamps the
        ground-truth exhaustion event the forecast must beat."""
        self._needs.append(int(need_pages))
        if not admitted and self.first_admission_block_tick is None:
            self.first_admission_block_tick = self.ticks

    # -- accounting views --------------------------------------------------

    def outstanding_total(self) -> int:
        return self.sched._outstanding_total if self.sched is not None else 0

    def evictable_count(self) -> int:
        return self.cache.evictable_count() if self.cache is not None else 0

    def counts(self) -> Dict[str, int]:
        """Per-class page counts INCLUDING the free-side split: the
        full partition of pool capacity."""
        pool = self.pool
        out = self.outstanding_total()
        reserved = min(out, pool.free_count)
        c = dict(self._counts)
        c["reserved_unmaterialized"] = reserved
        c["free"] = pool.free_count - reserved
        return c

    def conservation(self) -> dict:
        """The hard contract, checked two ways: the classified pages
        must equal the pool's used count EXACTLY (the ledger saw every
        event), and the full partition must sum to capacity EXACTLY
        (the free split is consistent). Integer arithmetic — no
        epsilon."""
        pool = self.pool
        c = self.counts()
        classified = sum(self._counts.values())
        total = classified + c["reserved_unmaterialized"] + c["free"]
        ok = classified == pool.used_count and total == pool.capacity
        return {
            "ok": ok,
            "classified_pages": classified,
            "used_pages": pool.used_count,
            "sum_pages": total,
            "capacity_pages": pool.capacity,
            # reservations the admission ledger backs with EVICTABLE
            # cache pages rather than free ones — overlap, reported
            # separately so the capacity sum stays a partition
            "reserved_evictable_backed": max(
                0, self.outstanding_total() - pool.free_count),
        }

    def trail(self, page: int) -> List[dict]:
        """Last-N ownership transitions of ``page`` (kept after free)
        — what a ``memory_leak`` black box embeds."""
        return [
            {"seq": s, "event": e,
             "owner": list(t) if t is not None else None}
            for s, e, t in self._trail.get(page, ())
        ]

    # -- per-tick hook -----------------------------------------------------

    def on_tick(self, step: int, t: Optional[float] = None) -> None:
        """Engine tick hook: verify conservation, advance the
        forecaster, refresh gauges, record one occupancy sample. A
        conservation break fires ONE ``ledger_conservation`` black box
        and counts — it never raises into the serving loop."""
        self.ticks += 1
        cons = self.conservation()
        if not cons["ok"]:
            self.conservation_failures += 1
            self._fire(
                "ledger_conservation",
                f"memory ledger conservation broken: "
                f"{cons['classified_pages']} classified != "
                f"{cons['used_pages']} used "
                f"(sum {cons['sum_pages']}/{cons['capacity_pages']})",
                key=("conservation",), details=cons,
            )
        c = self.counts()
        for name in CLASSES:
            if c[name] > self.peak_pages[name]:
                self.peak_pages[name] = c[name]
        frag = self.pool.fragmentation()
        if frag > self.peak_fragmentation:
            self.peak_fragmentation = frag
        self._forecast(c)
        bpp = self.bytes_per_page
        if self._m is not None:
            m = self._m
            m["request"].set(float(c["request"] * bpp))
            m["staged"].set(float(c["staged"] * bpp))
            m["cow"].set(float(c["cow"] * bpp))
            m["cached"].set(float(c["cached"] * bpp))
            m["reserved"].set(float(c["reserved_unmaterialized"] * bpp))
            m["free"].set(float(c["free"] * bpp))
            if self.host_tier is not None:
                m["host"].set(float(self.host_tier.resident_bytes))
            m["forecast"].set(
                -1.0 if math.isinf(self.steps_to_exhaustion)
                else float(self.steps_to_exhaustion))
        sample = {"step": step, "t": t, "fragmentation": round(frag, 4),
                  "steps_to_exhaustion": (
                      None if math.isinf(self.steps_to_exhaustion)
                      else self.steps_to_exhaustion)}
        sample.update({k: c[k] for k in
                       (*CLASSES, "reserved_unmaterialized", "free")})
        if self.host_tier is not None:
            sample["host_tier_bytes"] = self.host_tier.resident_bytes
        self.samples.append(sample)
        if self.audit_every and self.ticks % self.audit_every == 0:
            self.audit()

    def _forecast(self, c: Dict[str, int]) -> None:
        """Steps-to-exhaustion: admission headroom (free + evictable -
        reserved) over the recent consumption rate, minus the typical
        admission need — so the gauge reaches ZERO one step before a
        typical request is deferred, not after. Clamped monotone while
        headroom keeps shrinking (a forecast that bounces on noise is
        useless to an autoscaler); any recovery resets the clamp."""
        avail = max(
            0, self.pool.free_count + self.evictable_count()
            - self.outstanding_total())
        hist = self._avail_hist
        prev = hist[-1] if hist else None
        hist.append(avail)
        drops = [max(0, a - b) for a, b in zip(hist, list(hist)[1:])]
        rate = max(drops) if drops else 0
        need = (sum(self._needs) / len(self._needs)) if self._needs else 0.0
        if avail <= need:
            est = 0.0
        elif rate <= 0:
            est = math.inf
        else:
            est = float(int((avail - need) // rate))
        if prev is not None and avail <= prev:
            est = min(est, self.steps_to_exhaustion)
        self.steps_to_exhaustion = est
        if est < self.min_steps_to_exhaustion:
            self.min_steps_to_exhaustion = est

    # -- leak audit --------------------------------------------------------

    def _reachable_holders(self) -> Dict[int, List[Tag]]:
        """Ground-truth page holders, recomputed from the live data
        structures (NOT from the ledger's own mirror): active
        requests' page tables and COW pins, disagg transfer stages,
        and the prefix trie's nodes."""
        holders: Dict[int, List[Tag]] = {}

        def add(page, tag):
            holders.setdefault(page, []).append(tag)

        sched = self.sched
        if sched is not None:
            for req in sched.active():
                for p in req.pages:
                    add(p, ("req", req.uid))
                if req.cow is not None:
                    add(req.cow[0], ("cow", req.uid))
            for uid, stage in sched.transfers.items():
                for p in stage["pages"]:
                    add(p, ("stage", uid))
        cache = self.cache
        if cache is not None:
            for node in cache._nodes.values():
                add(node.page, ("cache",))
        return holders

    def audit(self) -> dict:
        """Cross-check the ledger against ground truth and fire black
        boxes for what it finds. Three checks:

        - pool refcount > reachable holders → ``memory_leak`` (a
          reference nobody reachable owns keeps the page allocated
          forever), box names the page + its ownership trail;
        - reachable holders > pool refcount → ``double_owner`` (two
          owners believe they hold a reference the pool never
          granted — a future double-free);
        - scheduler ``_outstanding_total`` != Σ request/stage
          outstanding → ``stranded_reservation`` (phantom pages the
          admission ledger withholds from every future request).

        Each finding fires ONCE per (kind, page); re-audits count but
        stay quiet. Returns the report dict (also kept on
        ``last_audit`` for ``/debug/memory``)."""
        self.audits_run += 1
        pool = self.pool
        holders = self._reachable_holders()
        leaks: List[dict] = []
        doubles: List[dict] = []
        drift: List[dict] = []
        for page, ref in sorted(pool._ref.items()):
            held = len(holders.get(page, ()))
            mirrored = len(self._tags.get(page, ()))
            if ref > held:
                leaks.append({
                    "page": page, "refcount": ref, "holders": held,
                    "owners": [list(t) for t in
                               sorted(self._tags.get(page, ()),
                                      key=self._strength)],
                    "trail": self.trail(page),
                })
            elif held > ref:
                doubles.append({
                    "page": page, "refcount": ref, "holders": held,
                    "claimants": [list(t) for t in holders[page]],
                    "trail": self.trail(page),
                })
            if mirrored != ref:
                drift.append({"page": page, "refcount": ref,
                              "mirrored": mirrored})
        stranded = 0
        if self.sched is not None:
            sched = self.sched
            expected = sum(r.outstanding for r in sched.active())
            expected += sum(s["outstanding"]
                            for s in sched.transfers.values())
            stranded = sched._outstanding_total - expected
        report = {
            "ok": not leaks and not doubles and not stranded,
            "leaks": leaks,
            "double_owners": doubles,
            "ledger_drift": drift,
            "stranded_reserved_pages": stranded,
            "mismatched_releases": self.mismatched_releases,
            "tick": self.ticks,
        }
        self.last_audit = report
        for leak in leaks:
            self._fire(
                "memory_leak",
                f"page {leak['page']} refcount {leak['refcount']} but "
                f"only {leak['holders']} reachable holder(s) — the "
                f"extra reference is owned by nobody",
                key=("memory_leak", leak["page"]), details=leak,
            )
        for d in doubles:
            self._fire(
                "double_owner",
                f"page {d['page']} claimed by {d['holders']} holders "
                f"but refcount is {d['refcount']} — a double free is "
                f"coming",
                key=("double_owner", d["page"]), details=d,
            )
        if stranded:
            self._fire(
                "stranded_reservation",
                f"scheduler reservation ledger off by {stranded} "
                f"page(s): _outstanding_total no longer matches the "
                f"live requests' + stages' outstanding sums",
                key=("stranded_reservation",),
                details={"stranded_pages": stranded, "tick": self.ticks},
            )
        return report

    def _fire(self, name: str, reason: str, key, details: dict) -> None:
        if key in self._fired:
            return
        self._fired.add(key)
        if self.recorder is not None:
            self.recorder.fire_trigger(name, reason, self.ticks,
                                       details=details)

    # -- reports -----------------------------------------------------------

    def report(self) -> dict:
        """The ``/debug/memory`` payload: per-class bytes + pages, the
        conservation verdict, the forecast, the host-tier account, the
        last audit, and the (bounded) occupancy trend tail."""
        c = self.counts()
        bpp = self.bytes_per_page
        classes = {
            name: {"pages": c[name], "bytes": c[name] * bpp}
            for name in (*CLASSES, "reserved_unmaterialized", "free")
        }
        report = {
            "ticks": self.ticks,
            "bytes_per_page": bpp,
            "capacity_pages": self.pool.capacity,
            "capacity_bytes": self.pool.capacity * bpp,
            "classes": classes,
            "conservation": self.conservation(),
            "conservation_failures": self.conservation_failures,
            "fragmentation": round(self.pool.fragmentation(), 4),
            "forecast": {
                "steps_to_exhaustion": (
                    None if math.isinf(self.steps_to_exhaustion)
                    else self.steps_to_exhaustion),
                "min_steps_to_exhaustion": (
                    None if math.isinf(self.min_steps_to_exhaustion)
                    else self.min_steps_to_exhaustion),
                "first_admission_block_tick":
                    self.first_admission_block_tick,
            },
            "history_dropped": getattr(self.pool, "history_dropped", 0),
            "audits_run": self.audits_run,
            "last_audit": self.last_audit,
            "peak_pages": dict(self.peak_pages),
            "peak_fragmentation": round(self.peak_fragmentation, 4),
        }
        if self.host_tier is not None:
            report["host_tier"] = {
                "resident_pages": self.host_tier.resident_pages,
                "resident_bytes": self.host_tier.resident_bytes,
                "budget_bytes": self.host_tier.byte_budget,
            }
        return report

    def run_summary(self) -> dict:
        """Compact per-run block for ``finish_run`` metrics and the
        bench rows: peaks, conservation verdict, audit tallies, and
        the forecast floor — the memory trajectory one JSONL row can
        carry."""
        bpp = self.bytes_per_page
        return {
            "peak_pages": dict(self.peak_pages),
            "peak_bytes": {k: v * bpp for k, v in self.peak_pages.items()},
            "peak_fragmentation": round(self.peak_fragmentation, 4),
            "conservation_failures": self.conservation_failures,
            "audits_run": self.audits_run,
            "leaks": (len(self.last_audit["leaks"])
                      if self.last_audit else 0),
            "min_steps_to_exhaustion": (
                None if math.isinf(self.min_steps_to_exhaustion)
                else self.min_steps_to_exhaustion),
        }
