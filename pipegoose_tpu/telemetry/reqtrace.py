"""Request-scoped tracing and tail-latency attribution for serving.

The PR 2-4 telemetry stack is step-scoped: it can say p99 TTFT is high
without saying *why* — queue wait vs chunked-prefill interleave vs
cache miss vs preemption. ``RequestTracer`` closes that gap with a
bounded per-request event timeline fed by the serving engine's existing
tick path (submit, admit, each prefill chunk with its cache-hit token
counts and COW copies, first token, decode ticks, speculative cycles,
preempt/re-admit, done), plus an ATTRIBUTION pass that decomposes each
request's latency into additive wall-clock components:

- ``queue_s``     submit → first admission (never-admitted wait)
- ``prefill_s``   admitted, prefill in flight (incl. re-prefill after a
                  preemption — that re-work is prefill compute too)
- ``decode_s``    first token (or resume) → done/preempt
- ``stall_s``     preempted, waiting to be re-admitted

The four components are CONTIGUOUS lifecycle segments, accumulated at
each phase transition, so by construction they sum to the measured
submit→done e2e exactly (the replay bench pins the sum within 1%).
TTFT decomposes the same way: ``ttft_components`` snapshots the
accumulators at the first-token instant, so ``ttft = queue + prefill
(+ stall)`` — the question "is p99 TTFT queueing or compute?" becomes a
field lookup. Cache savings cannot be a wall segment of the SAME run
(the hit time never happened); it is estimated from the per-token
prefill rate this request actually paid:
``cache_saved_est_s = prefill_s * hit_tokens / forwarded_tokens``, and
the replay bench's per-arm summary cross-checks that estimate against
the baseline arm's measured TTFT.

Completed timelines land in ``serving.attrib.*`` histograms (one
observation per request per component), a bounded ``completed`` ring
(the flight recorder embeds the last N in black-box dumps so a
``decode_stall`` dump names the stuck request), and
:func:`request_trace_events` renders them as Perfetto rows — one track
per decode slot with instant markers for preempt/COW/spec-reject —
next to the host spans and the pipeline timetable in
``ChromeTraceExporter``.

Everything defaults OFF: the engine takes ``tracer=None`` and its hot
path then pays one attribute read + branch per tick (same budget as a
disabled registry metric, guard-tested < 5 µs). The per-request event
ring is bounded (``max_events``; drops are counted, attribution never
depends on the ring), so a million-token stream cannot grow host
memory. Host-side only — nothing here runs under jit.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from pipegoose_tpu.telemetry.registry import MetricsRegistry, get_registry

#: lifecycle phases a request's wall clock is attributed to (additive).
#: ``transfer_s`` is the disaggregated-serving phase (serving/disagg/):
#: prefill handed off on one pool, decode not yet admitted on the other
#: — the critical-path share of the cross-mesh KV page streaming.
#: ``restore_s`` is the kv_tier phase (serving/kv_tier/): host-tier
#: slabs scattering back into pool pages before admission (a
#: cross-replica pull books as ``transfer_s`` — it rides the same
#: staging path as disagg). Both are always present (0.0 when unused)
#: so the sum-to-e2e contract is one invariant everywhere.
COMPONENTS = ("queue_s", "prefill_s", "restore_s", "transfer_s",
              "decode_s", "stall_s")

_PHASE_TO_COMPONENT = {
    "queue": "queue_s",
    "prefill": "prefill_s",
    "restore": "restore_s",
    "transfer": "transfer_s",
    "decode": "decode_s",
    "stall": "stall_s",
}


class RequestTimeline:
    """One request's bounded event ring + phase-attribution accumulators.

    Events are forensics (rendered by :func:`request_trace_events`,
    embedded in black boxes); the ``components`` dict is accounting and
    is updated incrementally at every phase transition, so it stays
    exact even after the ring drops old events.
    """

    __slots__ = (
        "uid", "trace_id", "tenant", "prompt_len", "max_new_tokens",
        "slot", "events", "dropped",
        "t_submit", "t_first_token", "t_done", "finish_reason",
        "components", "ttft_s", "ttft_components", "e2e_s",
        "hit_tokens", "prefill_tokens", "prefill_chunks", "cow_copies",
        "decode_ticks", "decode_compute_s", "prefill_compute_s",
        "spec_drafted", "spec_accepted", "preemptions",
        "transfer_chunks", "transfer_pages", "transfer_bytes",
        "transfer_compute_s",
        "restore_pages", "restore_bytes", "restore_compute_s",
        "cache_saved_est_s", "_phase", "_t_phase",
    )

    def __init__(self, uid: int, max_events: int,
                 trace_id: Optional[int] = None):
        self.uid = uid
        # fleet-trace join key (telemetry/fleettrace.py): None outside
        # a control plane. uids are replica-local AND deliberately
        # reused on salvage, so cross-replica stitching keys on this.
        self.trace_id = trace_id
        self.tenant: Optional[str] = None
        self.prompt_len = 0
        self.max_new_tokens = 0
        self.slot: Optional[int] = None
        self.events: deque = deque(maxlen=max_events)
        self.dropped = 0
        self.t_submit: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.finish_reason: Optional[str] = None
        self.components: Dict[str, float] = {c: 0.0 for c in COMPONENTS}
        self.ttft_s: Optional[float] = None
        self.ttft_components: Optional[Dict[str, float]] = None
        self.e2e_s: Optional[float] = None
        self.hit_tokens = 0
        self.prefill_tokens = 0        # tokens actually forwarded
        self.prefill_chunks = 0
        self.cow_copies = 0
        self.decode_ticks = 0
        self.decode_compute_s = 0.0    # measured device-work share
        self.prefill_compute_s = 0.0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.preemptions = 0
        self.transfer_chunks = 0       # cross-pool page shipments
        self.transfer_pages = 0
        self.transfer_bytes = 0        # wire bytes (q+scale / bf16 / fp)
        self.transfer_compute_s = 0.0  # measured export+import share
        self.restore_pages = 0         # host-tier pages scattered back
        self.restore_bytes = 0
        self.restore_compute_s = 0.0
        self.cache_saved_est_s = 0.0
        self._phase: Optional[str] = None
        self._t_phase: Optional[float] = None

    # -- recording ---------------------------------------------------------

    def add_event(self, kind: str, t: float, **fields: Any) -> None:
        if len(self.events) == self.events.maxlen:
            self.dropped += 1  # deque drops the oldest on append
        self.events.append({"t": t, "kind": kind, **fields})

    def transition(self, phase: Optional[str], t: float) -> None:
        """Close the current phase into its component and open ``phase``."""
        if self._phase is not None and self._t_phase is not None:
            self.components[_PHASE_TO_COMPONENT[self._phase]] += max(
                t - self._t_phase, 0.0
            )
        self._phase, self._t_phase = phase, t

    @property
    def phase(self) -> Optional[str]:
        return self._phase

    # -- views -------------------------------------------------------------

    def attribution(self) -> Dict[str, Any]:
        """JSON-able attribution record (the ``serving.attrib.*`` shape)."""
        out: Dict[str, Any] = {
            "uid": self.uid,
            "trace_id": self.trace_id,
            "tenant": self.tenant,
            "prompt_len": self.prompt_len,
            "components": dict(self.components),
            "ttft_s": self.ttft_s,
            "ttft_components": (
                dict(self.ttft_components) if self.ttft_components else None
            ),
            "e2e_s": self.e2e_s,
            "hit_tokens": self.hit_tokens,
            "prefill_tokens": self.prefill_tokens,
            "cache_saved_est_s": self.cache_saved_est_s,
            "preemptions": self.preemptions,
            "finish_reason": self.finish_reason,
        }
        return out

    def to_json(self) -> Dict[str, Any]:
        return {
            **self.attribution(),
            "max_new_tokens": self.max_new_tokens,
            "slot": self.slot,
            "phase": self._phase,
            "t_submit": self.t_submit,
            "t_first_token": self.t_first_token,
            "t_done": self.t_done,
            "prefill_chunks": self.prefill_chunks,
            "cow_copies": self.cow_copies,
            "transfer_chunks": self.transfer_chunks,
            "transfer_pages": self.transfer_pages,
            "transfer_bytes": self.transfer_bytes,
            "transfer_compute_s": self.transfer_compute_s,
            "restore_pages": self.restore_pages,
            "restore_bytes": self.restore_bytes,
            "restore_compute_s": self.restore_compute_s,
            "decode_ticks": self.decode_ticks,
            "prefill_compute_s": self.prefill_compute_s,
            "decode_compute_s": self.decode_compute_s,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "events_dropped": self.dropped,
            "events": list(self.events),
        }


class NullRequestTracer:
    """The hook contract, as a no-op base class: subclass this (or
    :class:`RequestTracer`) to build a custom tracer and override only
    the hooks you need. The engine itself holds ``None`` when tracing
    is off and branch-guards every call site, so the disabled cost is
    one attribute read + branch — the same budget as a disabled
    registry metric (guard-tested in tests/telemetry/
    test_reqtrace.py)."""

    __slots__ = ()

    enabled = False

    def set_clock(self, clock: Callable[[], float]) -> None:
        pass

    def on_submit(self, req: Any, t: float) -> None:
        pass

    def on_admit(self, req: Any, t: float) -> None:
        pass

    def on_preempt(self, req: Any, t: Optional[float] = None) -> None:
        pass

    def on_cow(self, req: Any, t: float) -> None:
        pass

    def on_prefill_chunk(self, req: Any, t: float, dur_s: float,
                         tokens: int) -> None:
        pass

    def on_first_token(self, req: Any, t: float) -> None:
        pass

    def on_resume(self, req: Any, t: float) -> None:
        pass

    def on_decode_tick(self, req: Any, t: float, dur_s: float,
                       tokens: int = 1) -> None:
        pass

    def on_spec(self, req: Any, t: float, dur_s: float, drafted: int,
                accepted: int) -> None:
        pass

    def on_transfer_start(self, req: Any, t: float) -> None:
        pass

    def on_transfer_chunk(self, req: Any, t: float, dur_s: float,
                          tokens: int, pages: int, nbytes: int) -> None:
        pass

    def on_transfer_done(self, req: Any, t: float,
                         resume: str = "decode") -> None:
        pass

    def on_restore_start(self, req: Any, t: float) -> None:
        pass

    def on_restore_chunk(self, req: Any, t: float, dur_s: float,
                         tokens: int, pages: int, nbytes: int) -> None:
        pass

    def on_restore_done(self, req: Any, t: float) -> None:
        pass

    def on_done(self, req: Any, t: float) -> None:
        pass

    def on_shed(self, req: Any, t: float) -> None:
        pass

    def annotate(self, req: Any, kind: str, t: Optional[float] = None,
                 **fields: Any) -> None:
        """Free-form forensic marker (no phase change, no accounting) —
        the fleet paths use it to stamp routing context onto the
        replica-side timeline: ``pull_hint`` (peer a kv-tier pull was
        hinted from), ``disagg_fallback`` (shipment failed, local
        re-prefill), ``tier_fallback`` (host-tier read failed,
        recompute)."""
        pass


#: Shared no-op instance — handy where an always-callable tracer is
#: wanted instead of a ``None`` guard (the engine itself guards).
NULL_TRACER = NullRequestTracer()


class RequestTracer(NullRequestTracer):
    """Per-request lifecycle recorder + latency attributor.

    Hooks are driven by ``Scheduler`` (submit/admit/preempt/first-token/
    done — the lifecycle authority) and ``ServingEngine`` (prefill
    chunks, COW copies, decode ticks, speculative cycles — the work
    authority); see the module docstring for the component semantics.

    ``registry``: attribution histograms land here (default: the global
    registry — disabled unless enabled, like every other instrument).
    ``max_events`` bounds each request's event ring; ``keep_completed``
    bounds the completed-timeline history the ops endpoint and black
    boxes read. ``clock`` must match the engine's ``now`` (the engine
    re-points it at run start) so components and the engine's own
    ``t_*`` fields share one time domain.
    """

    __slots__ = (
        "registry", "clock", "max_events", "keep_completed", "name",
        "in_flight", "completed", "_wall_offset", "_lock",
        "_h_queue", "_h_prefill", "_h_restore", "_h_transfer",
        "_h_decode", "_h_stall",
        "_h_saved", "_c_requests", "_c_preempts", "_c_saved",
    )

    enabled = True

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 max_events: int = 256, keep_completed: int = 64,
                 clock: Callable[[], float] = time.perf_counter,
                 name: Optional[str] = None):
        if max_events < 8:
            raise ValueError(f"max_events must be >= 8, got {max_events}")
        if keep_completed < 1:
            raise ValueError(
                f"keep_completed must be >= 1, got {keep_completed}"
            )
        self.registry = registry if registry is not None else get_registry()
        self.clock = clock
        self.max_events = int(max_events)
        self.keep_completed = int(keep_completed)
        # display identity for multi-tracer exports: the control plane
        # names each replica's tracer after the replica so the merged
        # Perfetto export gets one labelled process per replica
        self.name = name
        # keyed by (trace_id, uid), NOT bare uid: a salvaged reuse_uid
        # request keeps its uid across replicas by design, so two
        # replicas sharing one tracer would otherwise silently merge
        # two half-timelines into one record (regression-pinned in
        # tests/telemetry/test_fleettrace.py)
        self.in_flight: Dict[Any, RequestTimeline] = {}
        self.completed: deque = deque(maxlen=self.keep_completed)
        # wall-clock anchor so Perfetto rows line up with the span rows
        # (which timestamp with time.time()) despite the perf_counter
        # event domain
        self._wall_offset = time.time() - clock()
        self._lock = threading.Lock()
        reg = self.registry
        self._h_queue = reg.histogram("serving.attrib.queue_seconds")
        self._h_prefill = reg.histogram("serving.attrib.prefill_seconds")
        self._h_restore = reg.histogram("serving.attrib.restore_seconds")
        self._h_transfer = reg.histogram("serving.attrib.transfer_seconds")
        self._h_decode = reg.histogram("serving.attrib.decode_seconds")
        self._h_stall = reg.histogram("serving.attrib.stall_seconds")
        self._h_saved = reg.histogram("serving.attrib.cache_saved_seconds")
        self._c_requests = reg.counter("serving.attrib.requests_total")
        self._c_preempts = reg.counter("serving.attrib.preemptions_total")
        self._c_saved = reg.counter(
            "serving.attrib.cache_saved_seconds_total"
        )

    # -- plumbing ----------------------------------------------------------

    def set_clock(self, clock: Callable[[], float]) -> None:
        """Re-point the tracer's clock (the engine passes its ``now``)
        and re-anchor the wall-clock offset for Perfetto alignment."""
        if clock is self.clock:
            return
        self.clock = clock
        self._wall_offset = time.time() - clock()

    @property
    def wall_offset(self) -> float:
        return self._wall_offset

    @staticmethod
    def _key(req: Any) -> Any:
        """In-flight map key: (trace_id, uid). For untraced requests
        (trace_id None — any engine outside a control plane) this
        degrades to the historical bare-uid keying; for fleet requests
        it keeps a salvaged reuse_uid request's second-replica fragment
        distinct from any same-uid stranger on a shared tracer."""
        return (getattr(req, "trace_id", None), req.uid)

    def _get(self, req: Any, t: float) -> RequestTimeline:
        """Timeline for ``req`` (created lazily: a tracer attached
        mid-flight starts accounting from the first event it sees)."""
        key = self._key(req)
        tl = self.in_flight.get(key)
        if tl is None:
            tl = RequestTimeline(req.uid, self.max_events,
                                 trace_id=key[0])
            tl.tenant = getattr(req, "tenant", None)
            tl.prompt_len = int(req.prompt_len)
            tl.max_new_tokens = int(req.max_new_tokens)
            tl.t_submit = t
            self.in_flight[key] = tl
        return tl

    # -- lifecycle hooks (Scheduler) ---------------------------------------

    def on_submit(self, req: Any, t: float) -> None:
        with self._lock:
            tl = self._get(req, t)
            tl.transition("queue", t)
            tl.add_event("submit", t, prompt_len=tl.prompt_len,
                         max_new_tokens=tl.max_new_tokens,
                         tenant=tl.tenant)

    def on_admit(self, req: Any, t: float) -> None:
        with self._lock:
            tl = self._get(req, t)
            readmit = tl.phase == "stall"
            tl.transition("prefill", t)
            tl.slot = req.slot
            hit = int(getattr(req, "hit_tokens", 0) or 0)
            # First admission only: a re-admission re-prefills the
            # request's OWN prompt+generated tokens, so its hits are
            # self-hits, not cross-request sharing — counting them would
            # inflate the user-visible cache benefit. (The engine's
            # run-level hit counter does include them, which is why the
            # cache_hit_share == prefill_token_reduction pin lives on
            # the preemption-free replay arms.)
            if not readmit:
                tl.hit_tokens = hit
            tl.add_event("admit", t, slot=req.slot, hit_tokens=hit,
                         readmit=readmit)

    def on_preempt(self, req: Any, t: Optional[float] = None) -> None:
        if t is None:
            t = self.clock()
        with self._lock:
            tl = self._get(req, t)
            tl.transition("stall", t)
            tl.preemptions += 1
            tl.add_event("preempt", t, generated=len(req.generated))
            self._c_preempts.inc()

    def on_first_token(self, req: Any, t: float) -> None:
        with self._lock:
            tl = self._get(req, t)
            tl.transition("decode", t)
            tl.t_first_token = t
            if tl.t_submit is not None:
                tl.ttft_s = t - tl.t_submit
            tl.ttft_components = dict(tl.components)
            tl.add_event("first_token", t)

    def on_done(self, req: Any, t: float) -> None:
        with self._lock:
            tl = self.in_flight.pop(self._key(req), None)
            if tl is None:
                return
            tl.transition(None, t)
            tl.t_done = t
            tl.finish_reason = req.finish_reason
            if tl.t_submit is not None:
                tl.e2e_s = t - tl.t_submit
            tl.add_event("done", t, finish_reason=req.finish_reason)
            fwd = max(tl.prefill_tokens, 1)
            tl.cache_saved_est_s = (
                tl.components["prefill_s"] * tl.hit_tokens / fwd
            )
            self.completed.append(tl)
        c = tl.components
        self._h_queue.observe(c["queue_s"])
        self._h_prefill.observe(c["prefill_s"])
        self._h_restore.observe(c["restore_s"])
        self._h_transfer.observe(c["transfer_s"])
        self._h_decode.observe(c["decode_s"])
        self._h_stall.observe(c["stall_s"])
        self._h_saved.observe(tl.cache_saved_est_s)
        self._c_saved.inc(tl.cache_saved_est_s)
        self._c_requests.inc()

    def on_shed(self, req: Any, t: float) -> None:
        """Deadline shed: the OTHER terminal transition (scheduler
        dropped a queued request past its ``deadline_s``). The timeline
        completes with ``finish_reason="shed"`` and its (entirely
        queue-side) wall time books normally — so shed requests are
        visible in ``/debug/requests``, black boxes, and the
        attribution rows, distinguishable by finish reason rather than
        silently absent. No latency histograms are observed: a shed
        request has no serving latency, and polluting the TTFT/e2e
        distributions with it would mask exactly the degradation
        shedding is supposed to make visible."""
        with self._lock:
            tl = self.in_flight.pop(self._key(req), None)
            if tl is None:
                return
            tl.transition(None, t)
            tl.t_done = t
            tl.finish_reason = "shed"
            if tl.t_submit is not None:
                tl.e2e_s = t - tl.t_submit
            tl.add_event("shed", t)
            self.completed.append(tl)
        self._c_requests.inc()

    # -- work hooks (ServingEngine) ----------------------------------------

    def on_cow(self, req: Any, t: float) -> None:
        with self._lock:
            tl = self._get(req, t)
            tl.cow_copies += 1
            tl.add_event("cow", t)

    def on_prefill_chunk(self, req: Any, t: float, dur_s: float,
                         tokens: int) -> None:
        with self._lock:
            tl = self._get(req, t)
            tl.prefill_chunks += 1
            tl.prefill_tokens += int(tokens)
            tl.prefill_compute_s += dur_s
            tl.add_event("prefill_chunk", t, dur_s=dur_s, tokens=int(tokens))

    def on_resume(self, req: Any, t: float) -> None:
        """Re-admitted request finished its re-prefill: decoding resumes
        on the already-pending token (no new first token)."""
        with self._lock:
            tl = self._get(req, t)
            tl.transition("decode", t)
            tl.add_event("resume", t)

    def on_decode_tick(self, req: Any, t: float, dur_s: float,
                       tokens: int = 1) -> None:
        with self._lock:
            tl = self._get(req, t)
            tl.decode_ticks += 1
            tl.decode_compute_s += dur_s
            tl.add_event("decode", t, dur_s=dur_s, tokens=int(tokens))

    def on_spec(self, req: Any, t: float, dur_s: float, drafted: int,
                accepted: int) -> None:
        with self._lock:
            tl = self._get(req, t)
            tl.decode_ticks += 1
            tl.decode_compute_s += dur_s
            tl.spec_drafted += int(drafted)
            tl.spec_accepted += int(accepted)
            tl.add_event("spec", t, dur_s=dur_s, drafted=int(drafted),
                         accepted=int(accepted))

    def annotate(self, req: Any, kind: str, t: Optional[float] = None,
                 **fields: Any) -> None:
        """Forensic marker on the request's timeline: one ring event,
        no phase transition, no component accounting — so fleet paths
        (pull hints, fallback verdicts) can stamp context without ever
        perturbing the sum-to-e2e contract."""
        if t is None:
            t = self.clock()
        with self._lock:
            tl = self._get(req, t)
            tl.add_event(kind, t, **fields)

    # -- disagg transfer hooks (serving/disagg/) ---------------------------

    def on_transfer_start(self, req: Any, t: float) -> None:
        """Prefill handed off: the request's wall clock now belongs to
        the cross-pool transfer until the decode pool admits it. Fired
        by the PREFILL scheduler's ``finish_handoff`` right after the
        first-token hook (so TTFT = queue + prefill, and transfer time
        is its own additive component)."""
        with self._lock:
            tl = self._get(req, t)
            tl.transition("transfer", t)
            tl.add_event("transfer_start", t)

    def on_transfer_chunk(self, req: Any, t: float, dur_s: float,
                          tokens: int, pages: int, nbytes: int) -> None:
        """One page shipment imported on the decode pool. Streamed
        chunks land while the phase is still ``prefill`` (they overlap
        it off the critical path); only the counters accumulate —
        phases stay exclusive so the sum-to-e2e contract holds."""
        with self._lock:
            tl = self._get(req, t)
            tl.transfer_chunks += 1
            tl.transfer_pages += int(pages)
            tl.transfer_bytes += int(nbytes)
            tl.transfer_compute_s += dur_s
            tl.add_event("transfer_chunk", t, dur_s=dur_s,
                         tokens=int(tokens), pages=int(pages),
                         nbytes=int(nbytes))

    def on_transfer_done(self, req: Any, t: float,
                         resume: str = "decode") -> None:
        """Decode pool admitted the transferred pages: the transfer
        phase closes and ``resume`` opens — ``"decode"`` for the disagg
        handoff (fired by ``admit_with_pages`` just before the handoff
        token is recorded), ``"prefill"`` for a partial kv_tier pull
        (the request resumes chunked prefill at the pulled length)."""
        with self._lock:
            tl = self._get(req, t)
            tl.transition(resume, t)
            tl.add_event("transfer_done", t, resume=resume)

    # -- kv_tier restore hooks (serving/kv_tier/) --------------------------

    def on_restore_start(self, req: Any, t: float) -> None:
        """Host-tier restore opened for a still-QUEUED request (the
        engine's pre-admission intercept): its wall clock belongs to
        the restore until the pages are back in HBM."""
        with self._lock:
            tl = self._get(req, t)
            tl.transition("restore", t)
            tl.add_event("restore_start", t)

    def on_restore_chunk(self, req: Any, t: float, dur_s: float,
                         tokens: int, pages: int, nbytes: int) -> None:
        """One page scattered back from the host tier (local restore),
        or one peer TIER entry imported during a pull (the phase is
        whatever the surrounding path opened — only counters move)."""
        with self._lock:
            tl = self._get(req, t)
            tl.restore_pages += int(pages)
            tl.restore_bytes += int(nbytes)
            tl.restore_compute_s += dur_s
            tl.add_event("restore_chunk", t, dur_s=dur_s,
                         tokens=int(tokens), pages=int(pages),
                         nbytes=int(nbytes))

    def on_restore_done(self, req: Any, t: float) -> None:
        """Restore finished (fully or degraded): the request goes back
        to waiting for ordinary admission — the restored pages are
        cache hits now, so what follows books as queue time again."""
        with self._lock:
            tl = self._get(req, t)
            tl.transition("queue", t)
            tl.add_event("restore_done", t)

    # -- views -------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view of in-flight + recent completed timelines (the
        ``/debug/requests`` payload). Snapshot-under-lock: the engine
        thread may be mutating while the ops server reads."""
        with self._lock:
            return {
                "in_flight": [
                    tl.to_json() for tl in self.in_flight.values()
                ],
                "completed": [tl.to_json() for tl in self.completed],
            }

    def blackbox_payload(self, last_n: int = 8) -> Dict[str, Any]:
        """The flight-recorder embed: in-flight timelines (a stuck dump
        must name the stuck request) + the last ``last_n`` completed."""
        with self._lock:
            done = list(self.completed)[-last_n:]
            return {
                "in_flight": [
                    tl.to_json() for tl in self.in_flight.values()
                ],
                "last_completed": [tl.to_json() for tl in done],
            }

    def attribution_summary(self) -> Dict[str, Any]:
        """Aggregate attribution over the completed ring: per-request
        rows plus component means and the cache-hit share — the per-arm
        block ``bench_request_trace.json`` is built from."""
        with self._lock:
            done = list(self.completed)
        rows = [tl.attribution() for tl in done]
        n = len(rows)
        mean: Dict[str, float] = {c: 0.0 for c in COMPONENTS}
        mean_ttft_c: Dict[str, float] = {c: 0.0 for c in COMPONENTS}
        hit = fwd = 0
        ttfts: List[float] = []
        for tl in done:
            for c in COMPONENTS:
                mean[c] += tl.components[c]
                if tl.ttft_components is not None:
                    mean_ttft_c[c] += tl.ttft_components[c]
            hit += tl.hit_tokens
            fwd += tl.prefill_tokens
            if tl.ttft_s is not None:
                ttfts.append(tl.ttft_s)
        if n:
            for c in COMPONENTS:
                mean[c] = mean[c] / n
                mean_ttft_c[c] = mean_ttft_c[c] / n
        return {
            "requests": rows,
            "n": n,
            "mean_components": mean,
            "mean_ttft_components": mean_ttft_c,
            "mean_ttft_s": sum(ttfts) / len(ttfts) if ttfts else None,
            "hit_tokens": hit,
            "prefill_tokens": fwd,
            "cache_hit_share": hit / (hit + fwd) if hit + fwd else 0.0,
            "mean_cache_saved_est_s": (
                sum(tl.cache_saved_est_s for tl in done) / n if n else 0.0
            ),
        }


def request_trace_events(tracer: RequestTracer, *,
                         pid: Optional[int] = None,
                         process_name: Optional[str] = None) -> List[dict]:
    """Render a tracer's timelines as Perfetto ``trace_event`` rows —
    ONE TRACK PER DECODE SLOT (plus a queue track for pre-admission and
    preempted waits), phase slices (``req<uid> prefill`` /
    ``req<uid> decode``) with nested per-chunk slices, and instant
    markers for preempt / COW / spec-reject / first-token — loadable in
    ui.perfetto.dev next to the host spans and the pipeline timetable
    (``ChromeTraceExporter.add_request_timelines``)."""
    from pipegoose_tpu.telemetry.chrometrace import PID_REQUESTS

    if pid is None:
        pid = PID_REQUESTS
    if process_name is None:
        name = getattr(tracer, "name", None)
        process_name = (f"serving requests ({name})" if name
                        else "serving requests (per-slot timelines)")
    off = tracer.wall_offset
    queue_tid = 1_000  # after any realistic slot count
    transfer_tid = 2_000  # disagg cross-pool page streaming track
    restore_tid = 3_000   # kv_tier host-tier restore track
    events: List[dict] = [
        {
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": process_name},
        },
        {
            "name": "thread_name", "ph": "M", "pid": pid, "tid": queue_tid,
            "args": {"name": "queue / preempted"},
        },
    ]
    seen_slots: set = set()
    seen_transfer = False
    seen_restore = False

    def us(t: float) -> float:
        return (t + off) * 1e6

    def slice_(name, cat, t0, t1, tid, **args):
        events.append({
            "name": name, "cat": cat, "ph": "X", "ts": us(t0),
            "dur": max(t1 - t0, 0.0) * 1e6, "pid": pid, "tid": tid,
            "args": args,
        })

    def marker(name, t, tid, **args):
        events.append({
            "name": name, "cat": "request.marker", "ph": "i", "s": "t",
            "ts": us(t), "pid": pid, "tid": tid, "args": args,
        })

    snap = tracer.snapshot()
    for tl in snap["completed"] + snap["in_flight"]:
        uid = tl["uid"]
        evs = tl["events"]
        if not evs:
            continue
        slot = tl.get("slot")
        tid = slot if slot is not None else queue_tid
        seen_slots.add(tid)
        t_open = evs[0]["t"]       # current phase's start
        phase = None
        t_end = evs[-1]["t"]       # in-flight timelines close here
        for ev in evs:
            t, kind = ev["t"], ev["kind"]
            if kind == "submit":
                phase, t_open = "queue", t
            elif kind == "admit":
                if phase in ("queue", "stall"):
                    slice_(f"req{uid} {phase}", f"request.{phase}",
                           t_open, t, queue_tid, uid=uid)
                phase, t_open = "prefill", t
                if ev.get("slot") is not None:
                    tid = ev["slot"]
                    seen_slots.add(tid)
            elif kind in ("first_token", "resume"):
                if phase == "prefill":
                    slice_(f"req{uid} prefill", "request.prefill",
                           t_open, t, tid, uid=uid,
                           hit_tokens=tl.get("hit_tokens", 0))
                if kind == "first_token":
                    marker(f"req{uid} first_token", t, tid, uid=uid)
                phase, t_open = "decode", t
            elif kind == "preempt":
                if phase in ("prefill", "decode"):
                    slice_(f"req{uid} {phase}", f"request.{phase}",
                           t_open, t, tid, uid=uid)
                marker(f"req{uid} preempt", t, tid, uid=uid)
                phase, t_open = "stall", t
            elif kind == "done":
                if phase in ("prefill", "decode"):
                    slice_(f"req{uid} {phase}", f"request.{phase}",
                           t_open, t, tid, uid=uid,
                           finish_reason=ev.get("finish_reason"))
                phase, t_open = None, t
            elif kind == "shed":
                if phase in ("queue", "stall"):
                    slice_(f"req{uid} {phase}", f"request.{phase}",
                           t_open, t, queue_tid, uid=uid,
                           finish_reason="shed")
                marker(f"req{uid} shed", t, queue_tid, uid=uid)
                phase, t_open = None, t
            elif kind == "transfer_start":
                if phase in ("prefill", "decode"):
                    slice_(f"req{uid} {phase}", f"request.{phase}",
                           t_open, t, tid, uid=uid)
                phase, t_open = "transfer", t
                seen_transfer = True
            elif kind == "transfer_done":
                if phase == "transfer":
                    slice_(f"req{uid} transfer", "request.transfer",
                           t_open, t, transfer_tid, uid=uid,
                           pages=tl.get("transfer_pages", 0),
                           nbytes=tl.get("transfer_bytes", 0))
                phase, t_open = "decode", t
                seen_transfer = True
            elif kind == "transfer_chunk":
                dur = float(ev.get("dur_s", 0.0))
                slice_(f"req{uid} xfer chunk", "request.transfer_chunk",
                       t - dur, t, transfer_tid, uid=uid,
                       pages=ev.get("pages"), nbytes=ev.get("nbytes"))
                seen_transfer = True
            elif kind == "restore_start":
                if phase == "queue":
                    slice_(f"req{uid} queue", "request.queue",
                           t_open, t, queue_tid, uid=uid)
                phase, t_open = "restore", t
                seen_restore = True
            elif kind == "restore_done":
                if phase == "restore":
                    slice_(f"req{uid} restore", "request.restore",
                           t_open, t, restore_tid, uid=uid,
                           pages=tl.get("restore_pages", 0),
                           nbytes=tl.get("restore_bytes", 0))
                phase, t_open = "queue", t
                seen_restore = True
            elif kind == "restore_chunk":
                dur = float(ev.get("dur_s", 0.0))
                slice_(f"req{uid} restore chunk", "request.restore_chunk",
                       t - dur, t, restore_tid, uid=uid,
                       pages=ev.get("pages"), nbytes=ev.get("nbytes"))
                seen_restore = True
            elif kind == "prefill_chunk":
                dur = float(ev.get("dur_s", 0.0))
                slice_(f"req{uid} chunk", "request.prefill_chunk",
                       t - dur, t, tid, uid=uid, tokens=ev.get("tokens"))
            elif kind == "cow":
                marker(f"req{uid} cow", t, tid, uid=uid)
            elif kind == "spec":
                if ev.get("accepted", 0) < ev.get("drafted", 0):
                    marker(f"req{uid} spec_reject", t, tid,
                           uid=uid, drafted=ev.get("drafted"),
                           accepted=ev.get("accepted"))
        if phase is not None:  # in-flight: close the open phase slice
            track = (queue_tid if phase in ("queue", "stall")
                     else transfer_tid if phase == "transfer"
                     else restore_tid if phase == "restore" else tid)
            slice_(f"req{uid} {phase}", f"request.{phase}",
                   t_open, t_end, track, uid=uid, open=True)
    if seen_restore:
        events.insert(1, {
            "name": "thread_name", "ph": "M", "pid": pid,
            "tid": restore_tid,
            "args": {"name": "restore (host KV tier)"},
        })
    if seen_transfer:
        events.insert(1, {
            "name": "thread_name", "ph": "M", "pid": pid,
            "tid": transfer_tid,
            "args": {"name": "transfer (cross-pool KV streaming)"},
        })
    for tid in sorted(s for s in seen_slots if s != queue_tid):
        events.insert(1, {
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": f"slot {tid}"},
        })
    return events
