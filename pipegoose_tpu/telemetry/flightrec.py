"""Anomaly flight recorder: a black box for diverging runs.

When a multi-chip run dies today the only artifact is a host-synced
NaN loss — no record of which module's gradients exploded, what the
last N healthy steps looked like, or what the serving engine was doing
when decode stopped making progress. The flight recorder is the
crash-forensics layer on top of the PR-2 telemetry substrate:

- a HOST-SIDE ring buffer of the last ``capacity`` step records —
  loss, fenced step time, the in-graph health pytree
  (telemetry/health.py, host-converted), and per-step span summaries
  drained from the registry's event stream;
- STRUCTURED triggers evaluated on every checked step: non-finite
  anywhere (loss, grads, optimizer updates — the reason names the
  offending top-level module group), loss-spike z-score, grad-norm
  explosion vs. the running median, and a serving no-decode-progress
  watchdog (driven by ``ServingEngine``);
- on trigger, an ATOMIC JSON "black box" dump: the ring contents, the
  trigger (name + reason + details), mesh/topology context, and
  jax/library versions — everything a post-mortem needs and the
  donated-buffer train step can no longer provide after the fact.

Recovery integration: ``FailureDetector``/``AutoRecovery``
(trainer/recovery.py) accept ``recorder=``; a fired trigger is
consumed by the detector in the SAME callback round (the recorder runs
at order -20, before the detector's -10), so recovery reacts to
*which* signal fired — "nonfinite gradients in module group 'embed'"
— instead of a bare NaN loss, and the black box is already on disk
before any restore rewinds the evidence.

The recorder is opt-in and host-synced by design: converting the loss
and health tree to floats each checked step drains the dispatch
pipeline exactly like ``TelemetryCallback(fence=True)`` — which is
also what makes the recorded step time a FENCED device time. Use
``check_every > 1`` to amortize when that matters.
"""
from __future__ import annotations

import dataclasses
import math
import os
import statistics
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from pipegoose_tpu.trainer.callback import Callback, _host_scalar


@dataclasses.dataclass
class TriggerEvent:
    """One fired anomaly trigger (and its black-box dump, if written)."""

    name: str          # "nonfinite" | "loss_spike" | "grad_explosion" |
    #                    "decode_stall" | "slo_burn" | custom (fire_trigger)
    reason: str        # human-readable; names the offending module group
    step: int
    details: Dict[str, Any] = dataclasses.field(default_factory=dict)
    dump_path: Optional[str] = None


def _finite(x: Optional[float]) -> bool:
    return x is not None and isinstance(x, (int, float)) and math.isfinite(x)


class FlightRecorder(Callback):
    """Ring-buffer step recorder with structured anomaly triggers.

    As a trainer callback it records every ``check_every``-th step and
    evaluates the training triggers; ``ServingEngine`` drives the same
    object through :meth:`observe_serving_step` /
    :meth:`trigger_decode_stall`. A fired trigger is held in
    ``last_trigger`` until a consumer (``FailureDetector`` with
    ``recorder=``) calls :meth:`take_trigger`.

    ``loss_spike_z``: z-score of the step loss against the trailing
    ``window`` finite losses (arms at ``window // 2`` history).
    ``grad_explosion_factor``: global grad norm vs. the trailing
    median (needs the trainer's ``with_health=True``; silently ignored
    otherwise). ``max_dumps`` bounds disk usage under a persistent
    failure loop.
    """

    order = -20  # record + trigger BEFORE FailureDetector (-10) consumes

    def __init__(
        self,
        directory: str,
        capacity: int = 128,
        check_every: int = 1,
        loss_spike_z: Optional[float] = 6.0,
        grad_explosion_factor: Optional[float] = 25.0,
        window: int = 50,
        max_dumps: int = 8,
        registry=None,
        context: Optional[Dict[str, Any]] = None,
        doctor_report: Optional[Any] = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if check_every < 1:
            raise ValueError(f"check_every must be >= 1, got {check_every}")
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        self.directory = directory
        self.check_every = check_every
        self.loss_spike_z = loss_spike_z
        self.grad_explosion_factor = grad_explosion_factor
        self.window = window
        self.max_dumps = max_dumps
        self.context = dict(context or {})
        # compiled-program context for the black box: the mesh-doctor
        # report (telemetry/doctor.py) of the step being recorded, so a
        # post-mortem sees the partitioning plan that produced the
        # anomaly (set at construction or via set_doctor_report)
        self.doctor_report = doctor_report
        # request-lifecycle context (telemetry/reqtrace.py): when set, a
        # black box embeds the in-flight + last-N completed request
        # timelines, so a decode_stall dump NAMES the stuck request
        self._req_tracer = None
        # fleet-trace context (telemetry/fleettrace.py): when set, a
        # black box embeds the stitched cross-replica tail exemplars,
        # so an slo_burn/replica_failure dump NAMES the dominant hop
        self._fleet_tracer = None
        self.records: deque = deque(maxlen=capacity)
        self.dumps: List[str] = []
        self.last_trigger: Optional[TriggerEvent] = None
        self._loss_hist: deque = deque(maxlen=window)
        self._grad_hist: deque = deque(maxlen=window)
        self._registry = registry
        self._span_acc: Dict[str, List[float]] = {}
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        self._attached = False

    # -- ring --------------------------------------------------------------

    def record(self, kind: str, **fields: Any) -> dict:
        """Append one timestamped record to the ring and return it."""
        rec = {"ts": time.time(), "kind": kind, **fields}
        with self._lock:
            self.records.append(rec)
        return rec

    # -- span summaries (registry event sink) ------------------------------

    def _sink(self, event: dict) -> None:
        if event.get("kind") != "span":
            return
        with self._lock:
            acc = self._span_acc.setdefault(event.get("span", "?"), [0, 0.0])
            acc[0] += 1
            acc[1] += float(event.get("dur_s", 0.0))

    def _drain_spans(self) -> Dict[str, dict]:
        with self._lock:
            out = {
                k: {"n": int(n), "total_s": t}
                for k, (n, t) in self._span_acc.items()
            }
            self._span_acc.clear()
        return out

    # -- trainer callback interface ----------------------------------------

    def _maybe_attach(self) -> None:
        from pipegoose_tpu.telemetry.registry import get_registry

        if self._attached:
            return
        reg = self._registry if self._registry is not None else get_registry()
        # span summaries ride the event stream; a disabled registry
        # emits none, and attaching would change nothing — skip so the
        # recorder never implicitly turns telemetry on. Re-checked every
        # step (one branch when attached): a TelemetryCallback in the
        # same callback list enables the registry AFTER this recorder's
        # on_fit_start (it runs at order 5, the recorder at -20), so a
        # fit-start-only check would silently drop all span summaries
        # in exactly the documented wiring.
        if reg.enabled:
            reg.attach(self._sink)
            self._registry = reg
            self._attached = True

    def on_fit_start(self, trainer: Any) -> None:
        self._maybe_attach()

    def on_fit_end(self, trainer: Any) -> None:
        if self._attached and self._registry is not None:
            self._registry.detach(self._sink)
            self._attached = False

    def on_step_start(self, trainer: Any, step: int) -> None:
        self._maybe_attach()
        self._t0 = time.perf_counter()

    def on_step_end(self, trainer: Any, step: int, loss: Any) -> None:
        if step % self.check_every:
            return
        from pipegoose_tpu.telemetry.health import host_health

        loss_f = _host_scalar(loss)  # syncs the step: the time below is fenced
        dt = (
            time.perf_counter() - self._t0 if self._t0 is not None else None
        )
        health = host_health(getattr(trainer.state, "last_health", None))
        self.record(
            "train.step", step=step, loss=loss_f, step_time_s=dt,
            health=health, spans=self._drain_spans(),
        )
        trig = self._train_trigger(step, loss_f, health)
        if trig is not None:
            trig.dump_path = self.dump(trig, context=self._train_context(trainer))
            self.last_trigger = trig
            return
        # only healthy steps feed the baselines (a spike must not
        # poison the median it is judged against)
        if _finite(loss_f):
            self._loss_hist.append(loss_f)
        if health is not None and _finite(health.get("grad_norm")):
            self._grad_hist.append(health["grad_norm"])

    # -- triggers ----------------------------------------------------------

    def _train_trigger(
        self, step: int, loss: Optional[float], health: Optional[dict]
    ) -> Optional[TriggerEvent]:
        # 1) non-finite anywhere — name the module group, not just "NaN"
        bad_bits = []
        details: Dict[str, Any] = {}
        if health is not None:
            per_mod = health.get("grad_norm_per_module", {}) or {}
            bad_mods = sorted(
                m for m, v in per_mod.items() if not _finite(v)
            )
            if health.get("nonfinite_grad_leaves", 0) or bad_mods:
                mods = (
                    f" in module group(s) {', '.join(repr(m) for m in bad_mods)}"
                    if bad_mods else ""
                )
                bad_bits.append(
                    f"non-finite gradients{mods} "
                    f"({health.get('nonfinite_grad_leaves', 0):.0f} leaves)"
                )
                details["bad_modules"] = bad_mods
            if health.get("nonfinite_update_leaves", 0):
                bad_bits.append(
                    "non-finite optimizer updates "
                    f"({health['nonfinite_update_leaves']:.0f} leaves)"
                )
            details["health"] = health
        if loss is not None and not _finite(loss):
            bad_bits.append(f"non-finite loss {loss}")
        if bad_bits:
            return TriggerEvent(
                "nonfinite", "; ".join(bad_bits), step, details
            )

        # 2) grad-norm explosion vs. the trailing median
        if (
            self.grad_explosion_factor is not None
            and health is not None
            and _finite(health.get("grad_norm"))
            and len(self._grad_hist) >= max(2, self.window // 2)
        ):
            gn = health["grad_norm"]
            med = statistics.median(self._grad_hist)
            if med > 0 and gn > self.grad_explosion_factor * med:
                per_mod = {
                    m: v
                    for m, v in (health.get("grad_norm_per_module") or {}).items()
                    if _finite(v)
                }
                worst = max(per_mod, key=per_mod.get) if per_mod else None
                at = (
                    f" (largest module group {worst!r} = {per_mod[worst]:.3g})"
                    if worst else ""
                )
                return TriggerEvent(
                    "grad_explosion",
                    f"grad norm {gn:.3g} > {self.grad_explosion_factor} x "
                    f"median {med:.3g}{at}",
                    step,
                    {"grad_norm": gn, "median": med, "health": health},
                )

        # 3) loss-spike z-score
        if (
            self.loss_spike_z is not None
            and _finite(loss)
            and len(self._loss_hist) >= max(2, self.window // 2)
        ):
            mean = statistics.fmean(self._loss_hist)
            std = statistics.pstdev(self._loss_hist)
            if std > 0:
                z = (loss - mean) / std
                if z > self.loss_spike_z:
                    return TriggerEvent(
                        "loss_spike",
                        f"loss {loss:.4g} is {z:.1f} sigma above the "
                        f"trailing mean {mean:.4g} (window {len(self._loss_hist)})",
                        step,
                        {"z": z, "mean": mean, "std": std},
                    )
        return None

    def set_doctor_report(self, report: Any) -> None:
        """Attach (or replace) the mesh-doctor report included in every
        subsequent black-box dump — e.g. ``trainer.doctor(batch)``
        right after construction, or a re-diagnosis after a recompile."""
        self.doctor_report = report

    def set_request_tracer(self, tracer: Any) -> None:
        """Attach a ``telemetry.reqtrace.RequestTracer`` whose in-flight
        and recent completed timelines every subsequent black-box dump
        embeds (``ServingEngine`` wires this when given both)."""
        self._req_tracer = tracer

    def set_fleet_tracer(self, tracer: Any) -> None:
        """Attach a ``telemetry.fleettrace.FleetTracer`` whose stitched
        tail exemplars every subsequent black-box dump embeds
        (``ControlPlane`` wires this when given both)."""
        self._fleet_tracer = tracer

    def fire_trigger(
        self, name: str, reason: str, step: int,
        context: Optional[dict] = None,
        details: Optional[Dict[str, Any]] = None,
    ) -> TriggerEvent:
        """Fire a structured trigger by name (black-box dump + pending
        ``last_trigger``) — the generic path custom monitors (e.g. the
        SLO burn-rate monitor, telemetry/slo.py) raise through; the
        built-in training/serving triggers are thin wrappers over it."""
        trig = TriggerEvent(name, reason, step, dict(details or {}))
        trig.dump_path = self.dump(trig, context=context)
        self.last_trigger = trig
        return trig

    def take_trigger(self) -> Optional[TriggerEvent]:
        """Consume the pending trigger (recovery's entry point)."""
        trig, self.last_trigger = self.last_trigger, None
        return trig

    def reset_after_restore(self, restored_step: int) -> None:
        """Called by ``AutoRecovery`` after a checkpoint rollback: the
        spike/explosion baselines span the rolled-back timeline and a
        marker record keeps the ring's history interpretable."""
        self._loss_hist.clear()
        self._grad_hist.clear()
        self.last_trigger = None
        self.record("restore", step=restored_step)

    # -- serving -----------------------------------------------------------

    def observe_serving_step(self, step: int, **fields: Any) -> None:
        self.record("serving.step", step=step, **fields)

    def trigger_decode_stall(
        self, step: int, reason: str, context: Optional[dict] = None,
        **details: Any,
    ) -> TriggerEvent:
        """Fire the serving watchdog trigger and dump the black box."""
        return self.fire_trigger(
            "decode_stall", reason, step, context=context, details=details
        )

    # -- dump --------------------------------------------------------------

    def _train_context(self, trainer: Any) -> Dict[str, Any]:
        out: Dict[str, Any] = {"tokens_per_step": getattr(trainer, "tokens_per_step", None)}
        ctx = getattr(trainer, "parallel_context", None)
        mesh = getattr(ctx, "mesh", None)
        if mesh is not None:
            out["mesh_axes"] = {k: int(v) for k, v in dict(mesh.shape).items()}
            devs = mesh.devices.reshape(-1)
            out["n_devices"] = int(devs.size)
            d0 = devs[0]
            out["device_kind"] = getattr(d0, "device_kind", getattr(d0, "platform", "?"))
        return out

    @staticmethod
    def _environment() -> Dict[str, Any]:
        env: Dict[str, Any] = {"python": sys.version.split()[0]}
        try:
            import jax

            env["jax"] = jax.__version__
            try:
                import jaxlib

                env["jaxlib"] = jaxlib.__version__
            except Exception:  # noqa: BLE001
                pass
            env["backend"] = jax.default_backend()
            env["device_count"] = jax.device_count()
            env["process_index"] = jax.process_index()
        except Exception:  # noqa: BLE001 - never let forensics crash the run
            pass
        try:
            import numpy

            env["numpy"] = numpy.__version__
        except Exception:  # noqa: BLE001
            pass
        return env

    def dump(
        self, trigger: TriggerEvent, context: Optional[dict] = None
    ) -> Optional[str]:
        """Atomically write the black-box JSON; returns its path (None
        once ``max_dumps`` is exhausted — the ring keeps recording)."""
        if len(self.dumps) >= self.max_dumps:
            return None
        from pipegoose_tpu.telemetry.exporters import (
            atomic_write_text,
            safe_json_dumps,
        )

        path = os.path.join(
            self.directory,
            f"blackbox_step{trigger.step:08d}_{trigger.name}.json",
        )
        with self._lock:
            records = list(self.records)
        payload = {
            "trigger": {
                "name": trigger.name,
                "reason": trigger.reason,
                "step": trigger.step,
                "details": trigger.details,
            },
            "records": records,
            "context": {**self.context, **(context or {})},
            "environment": self._environment(),
            "created_ts": time.time(),
        }
        if self.doctor_report is not None:
            rep = self.doctor_report
            payload["doctor"] = rep.to_json() if hasattr(rep, "to_json") else rep
        if self._req_tracer is not None:
            try:
                payload["request_timelines"] = (
                    self._req_tracer.blackbox_payload()
                )
            except Exception:  # noqa: BLE001 - never let forensics crash
                pass
        if self._fleet_tracer is not None:
            try:
                payload["fleet_traces"] = (
                    self._fleet_tracer.blackbox_payload()
                )
            except Exception:  # noqa: BLE001 - never let forensics crash
                pass
        atomic_write_text(
            path, safe_json_dumps(payload, indent=1), suffix=".blackbox.tmp"
        )
        self.dumps.append(path)
        return path
