"""Span tracing: named, nestable wall-time regions with device fencing.

``with span("decode_step") as sp: ...`` records the region's wall time
into the active registry as both a histogram
(``span.<dotted.path>.seconds``) and a ``"span"`` event for the JSONL
stream. Spans nest through a thread-local stack — a span opened inside
another records under the joined path (``step.forward``) — which is how
the per-step breakdown (data/forward/backward/optimizer/comms) is
assembled without any global schema.

**Fencing.** JAX dispatch is asynchronous: the host returns from a
jitted call long before the device finishes, so a naive wall-time span
around a dispatch measures enqueue cost, not work. ``sp.fence(x)``
registers arrays to ``jax.block_until_ready`` at span exit so the
device work that produced them is attributed to THIS span. Fencing only
happens when the span is live (registry enabled) — disabled runs keep
full async pipelining.

**Jit safety.** ``span()`` returns a shared no-op when the registry is
disabled OR a jit trace is in progress: entering a span inside a traced
function must neither crash nor record trace-time (the fence would also
be meaningless — you cannot block on a tracer). Guarded by
tests/telemetry/test_spans.py.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Optional

import jax

from pipegoose_tpu.telemetry.registry import (
    MetricsRegistry,
    _tracing,
    get_registry,
)

_tls = threading.local()


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


class _NoopSpan:
    """Shared disabled/trace-time span: every operation is a no-op."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def fence(self, *arrays: Any) -> None:
        pass


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("name", "path", "_registry", "_attrs", "_t0", "_fences")

    def __init__(self, name: str, registry: MetricsRegistry,
                 attrs: Optional[dict] = None):
        self.name = name
        self.path = name  # finalized on __enter__ (nesting)
        self._registry = registry
        self._attrs = attrs
        self._t0 = 0.0
        self._fences: list = []

    def fence(self, *arrays: Any) -> None:
        """Block on these arrays at span exit so their device work lands
        in this span's duration."""
        self._fences.extend(arrays)

    def __enter__(self) -> "Span":
        stack = _stack()
        self.path = ".".join([s.path for s in stack[-1:]] + [self.name])
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        for x in self._fences:
            try:
                jax.block_until_ready(x)
            except Exception:  # noqa: BLE001 - non-array fence targets
                pass
        dur = time.perf_counter() - self._t0
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        if exc_type is StopIteration:
            # iterator-protocol control flow, not work: a span around
            # `next(it)` (trainer.fit's data span) would otherwise log a
            # phantom near-zero sample for the final exhausted pull,
            # skewing the data-time quantiles it exists to report
            return False
        reg = self._registry
        reg.histogram(f"span.{self.path}.seconds").observe(dur)
        reg.event("span", span=self.path, dur_s=dur,
                  **(self._attrs or {}))
        return False


def span(name: str, *, registry: Optional[MetricsRegistry] = None,
         attrs: Optional[dict] = None):
    """Context manager timing a named region (see module docstring).

    Returns a shared no-op object when telemetry is disabled or a jit
    trace is in progress — the disabled cost is one branch, safe to
    leave in library hot loops.
    """
    reg = registry if registry is not None else get_registry()
    if not reg._enabled or _tracing():
        return _NOOP
    return Span(name, reg, attrs)


def current_span_path() -> Optional[str]:
    """Dotted path of the innermost live span on this thread, or None."""
    stack = _stack()
    return stack[-1].path if stack else None
