"""Perf-regression sentinel: rolling-baseline watch over measured runs.

The flight recorder (telemetry/flightrec.py) catches CORRECTNESS
anomalies — NaNs, loss spikes, decode stalls. Nothing watches for the
quieter failure: the run still converges, the tokens still stream, but
a component got slower — a PartitionSpec regression re-routed a
collective, a new allocation pattern doubled dispatch time, a noisy
neighbor stole the fabric. The sentinel is the measured-performance
twin of the doctor's compile-time guards:

- :meth:`PerfSentinel.observe` takes one run's measurement — a
  ``telemetry.xprof.StepProfile``, or any flat component dict
  (``tokens_per_s`` plus ``*_s`` time components, e.g. the serving
  engine's per-run decode-step/idle split) — and compares each
  component against the rolling median of the last ``window`` healthy
  runs;
- a component at ``>= ratio_threshold`` x its baseline (or tokens/s at
  ``<= drop_threshold`` x) fires ONE ``perf_regression`` black box
  through the attached ``FlightRecorder`` whose reason NAMES the
  regressed component — "tensor-axis collective time 2.1x baseline" —
  with every component's ratio in the details;
- regressed runs do NOT enter the baseline (the flightrec convention:
  a spike must not poison the median it is judged against);
- every observation exports the ``perf.{compute,comm,idle}_fraction``
  gauges (when the run carries a profile) and ``perf.tokens_per_s``.

Baselines can be seeded from ``BENCH_HISTORY.jsonl`` — the one-row-
per-bench-run perf trajectory bench.py appends — via
:func:`read_bench_history` / :meth:`PerfSentinel.from_history`, so a
fresh process compares its first run against the recorded trajectory
instead of flying blind. Everything is opt-in and host-side: nothing
observes unless a caller (``ServingEngine(sentinel=...)``, bench.py)
passes a sentinel, and the disabled cost is one attribute read +
branch (guard-tested < 5 µs, the established contract).
"""
from __future__ import annotations

import json
import os
from collections import deque
from typing import Any, Dict, List, Optional

# component key -> human label for the trigger reason
_LABELS = {
    "tokens_per_s": "tokens/s",
    "compute_s": "compute time",
    "idle_s": "idle time",
    "decode_step_s": "decode-step time",
    "wall_step_s": "step wall time",
}


def _label(key: str) -> str:
    if key in _LABELS:
        return _LABELS[key]
    if key.startswith("comm[") and key.endswith("]_s"):
        return f"{key[5:-3]}-axis collective time"
    return key


def _components_of(run: Any) -> Dict[str, float]:
    """Flatten one observation into comparable components: a
    ``StepProfile`` contributes its attribution components + derived
    tokens/s only if the caller added one; a dict passes through
    (``profile`` sub-dicts flattened the same way)."""
    if hasattr(run, "components"):  # StepProfile
        return dict(run.components())
    out: Dict[str, float] = {}
    for k, v in dict(run).items():
        if k == "profile" and isinstance(v, dict):
            out["compute_s"] = float(v.get("compute_s", 0.0))
            out["idle_s"] = float(v.get("idle_s", 0.0))
            for axes, t in (v.get("comm_by_axes") or {}).items():
                out[f"comm[{axes}]_s"] = float(t)
            continue
        if isinstance(v, (int, float)) and (k.endswith("_s")
                                            or k == "tokens_per_s"):
            out[k] = float(v)
    return out


def read_bench_history(
    path: str, tail: Optional[int] = None
) -> List[Dict[str, Any]]:
    """Parse BENCH_HISTORY.jsonl (one JSON object per line; malformed
    lines skipped — an interrupted append must not poison the reader).
    ``tail`` keeps only the newest N rows — the sentinel's baseline
    window."""
    rows: List[Dict[str, Any]] = []
    if not path or not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                continue
    return rows[-tail:] if tail else rows


class PerfSentinel:
    """Rolling-baseline perf-regression watch (module docstring).

    ``recorder``: optional ``FlightRecorder`` — regressions dump a
    ``perf_regression`` black box through it (without one they are
    still returned + counted). ``window``: healthy runs the rolling
    median spans. ``min_baseline``: observations required before any
    verdict (a 1-run "baseline" would page on startup noise).
    ``ratio_threshold``: a time component this many times its baseline
    median regresses. ``drop_threshold``: tokens/s at or below this
    fraction of its baseline regresses.
    """

    def __init__(
        self,
        recorder: Any = None,
        registry: Any = None,
        window: int = 8,
        min_baseline: int = 2,
        ratio_threshold: float = 1.5,
        drop_threshold: float = 0.7,
    ):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if min_baseline < 1:
            raise ValueError(f"min_baseline must be >= 1, got {min_baseline}")
        if ratio_threshold <= 1.0:
            raise ValueError(
                f"ratio_threshold must be > 1, got {ratio_threshold}")
        if not 0.0 < drop_threshold < 1.0:
            raise ValueError(
                f"drop_threshold must be in (0, 1), got {drop_threshold}")
        self.recorder = recorder
        self.registry = registry
        self.window = window
        self.min_baseline = min_baseline
        self.ratio_threshold = ratio_threshold
        self.drop_threshold = drop_threshold
        self._hist: deque = deque(maxlen=window)
        self.regressions = 0
        self.last_verdict: Optional[Dict[str, Any]] = None

    @classmethod
    def from_history(
        cls, path: str, device: Optional[str] = None, **kwargs: Any
    ) -> "PerfSentinel":
        """A sentinel whose baseline window is seeded from the tail of
        ``BENCH_HISTORY.jsonl`` — the machine-readable perf trajectory
        bench.py appends one row per run to.

        Rows carrying a ``perf_regression`` stamp are SKIPPED (the
        regressed-runs-never-enter-the-baseline invariant holds across
        processes, not just within one sentinel's lifetime — otherwise
        a persistent regression fires once, poisons the next process's
        median, and goes quiet). ``device`` (when given) keeps only
        rows whose ``device`` field matches — a CPU-fallback bench run
        must not be judged against (or drag down) a TPU baseline."""
        s = cls(**kwargs)
        rows = [
            r for r in read_bench_history(path)
            if not r.get("perf_regression")
            and (device is None or r.get("device") == device)
        ]
        for row in rows[-s.window:]:
            comps = _components_of(row)
            if comps:
                s._hist.append(comps)
        return s

    @property
    def baseline_size(self) -> int:
        return len(self._hist)

    def baseline(self) -> Dict[str, float]:
        """{component -> rolling median} over the healthy window."""
        import statistics

        keys = set()
        for comps in self._hist:
            keys.update(comps)
        out = {}
        for k in keys:
            vals = [c[k] for c in self._hist if k in c]
            if vals:
                out[k] = statistics.median(vals)
        return out

    def _gauges(self, run: Any, comps: Dict[str, float]) -> None:
        from pipegoose_tpu.telemetry.registry import get_registry

        reg = self.registry if self.registry is not None else get_registry()
        if not reg.enabled:
            return
        prof = run if hasattr(run, "compute_fraction") else None
        if prof is None and isinstance(run, dict) \
                and isinstance(run.get("profile"), dict):
            p = run["profile"]
            wall = float(p.get("wall_step_s") or 0.0)
            if wall > 0:
                reg.gauge("perf.compute_fraction").set(
                    float(p.get("compute_s", 0.0)) / wall)
                reg.gauge("perf.comm_fraction").set(
                    float(p.get("comm_s", 0.0)) / wall)
                reg.gauge("perf.idle_fraction").set(
                    float(p.get("idle_s", 0.0)) / wall)
        elif prof is not None:
            reg.gauge("perf.compute_fraction").set(prof.compute_fraction)
            reg.gauge("perf.comm_fraction").set(prof.comm_fraction)
            reg.gauge("perf.idle_fraction").set(prof.idle_fraction)
        if "tokens_per_s" in comps:
            reg.gauge(
                "perf.tokens_per_s",
                help="last observed run throughput (perf sentinel)",
            ).set(comps["tokens_per_s"])
        reg.gauge(
            "perf.regressions_total",
            help="perf_regression verdicts fired by the sentinel",
        ).set(float(self.regressions))

    def observe(
        self,
        run: Any,
        step: int = 0,
        tokens_per_s: Optional[float] = None,
        context: Optional[dict] = None,
    ) -> Optional[Any]:
        """Compare one run against the rolling baseline; returns the
        fired ``TriggerEvent`` (or a verdict dict when no recorder is
        attached) on regression, else None. ``run``: a ``StepProfile``
        or flat component dict; ``tokens_per_s`` merges into the
        components when the run object does not carry one."""
        comps = _components_of(run)
        if tokens_per_s is not None:
            comps["tokens_per_s"] = float(tokens_per_s)
        self._gauges(run, comps)
        if not comps:
            return None
        regressions: List[Dict[str, Any]] = []
        if len(self._hist) >= self.min_baseline:
            base = self.baseline()
            for k, v in comps.items():
                b = base.get(k)
                if b is None or b <= 0:
                    continue
                ratio = v / b
                if k == "tokens_per_s":
                    if ratio <= self.drop_threshold:
                        regressions.append(
                            {"component": k, "ratio": ratio, "baseline": b,
                             "value": v,
                             "reason": f"{_label(k)} {ratio:.2f}x baseline "
                                       f"({v:.1f} vs {b:.1f})"})
                elif ratio >= self.ratio_threshold:
                    regressions.append(
                        {"component": k, "ratio": ratio, "baseline": b,
                         "value": v,
                         "reason": f"{_label(k)} {ratio:.1f}x baseline "
                                   f"({v * 1e3:.2f}ms vs {b * 1e3:.2f}ms)"})
        if not regressions:
            self._hist.append(comps)
            self.last_verdict = None
            return None
        # worst offender names the trigger; tokens/s drops sort by
        # severity of the drop, time components by the blowup
        def severity(r: Dict[str, Any]) -> float:
            return (1.0 / r["ratio"] if r["component"] == "tokens_per_s"
                    else r["ratio"])

        regressions.sort(key=severity, reverse=True)
        worst = regressions[0]
        self.regressions += 1
        verdict = {
            "reason": worst["reason"],
            "regressions": regressions,
            "components": comps,
            "baseline_size": len(self._hist),
        }
        self.last_verdict = verdict
        self._gauges(run, comps)  # refresh the regressions_total gauge
        if self.recorder is not None:
            return self.recorder.fire_trigger(
                "perf_regression", worst["reason"], step,
                context=context,
                details={
                    "regressions": regressions,
                    "components": comps,
                    "baseline": self.baseline(),
                },
            )
        return verdict
