"""Measured step attribution from XLA profiler traces.

Everything the stack measured so far is either host-side wall clock
(fenced ``span()``s, request tracing) or a compile-time estimate (the
mesh doctor's wire bytes, the planner's static cost model). This module
closes the gap GSPMD-lineage systems (arxiv 2105.04663, 2211.05322)
close with profiler feedback: run the REAL compiled step under
``jax.profiler.trace(..., create_perfetto_trace=True)``, parse the
emitted ``*.trace.json.gz``, and attribute the measured device time of
one step to

- **compute** — every HLO instruction that is not a collective;
- **per-mesh-axis collectives** — trace op events joined against the
  mesh doctor's :class:`~pipegoose_tpu.telemetry.doctor.CollectiveInfo`
  schedule by HLO instruction name, so each measured collective lands
  on the axes its replica groups span (``derived.py``'s fabric tables
  then turn bytes/seconds into utilization);
- **idle** — the fenced step wall time not covered by either (host
  gaps between dispatches, dispatch latency, pipeline bubbles).

The join works because the trace's op events carry
``args = {"hlo_module": <module>, "hlo_op": <instruction name>}`` —
the same instruction names ``compiled.as_text()`` prints, which is what
``parse_collective_schedule`` tables. On backends whose trace carries
no op events at all, :func:`profile_step` degrades to a HOST-CLOCK
fallback (``source="host_clock"``): the fenced wall time is attributed
wholesale to compute, so CI on exotic platforms still gets a finite,
clearly-labelled profile instead of a crash.

Attribution arithmetic: every instruction executes once per device per
step (loop bodies more often — their repeats still sum into the same
instruction bucket), so dividing an instruction's summed trace duration
by ``steps x n_devices`` yields its mean per-device per-step seconds.
Per-device op execution is serial, so ``compute + comm <= wall`` and
``idle`` is the (clamped) residual; the raw residual is kept on the
profile so over-attribution is visible, never silently absorbed.

Everything is opt-in: nothing here runs unless a caller invokes
:func:`profile_step` (or the ``Trainer.profile`` /
``ServingEngine.profile`` fronts), and the profiled function pays the
profiler's own overhead only for the measured steps.
"""
from __future__ import annotations

import contextlib
import dataclasses
import glob
import gzip
import json
import os
import re
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from pipegoose_tpu.telemetry.derived import (
    DCI_AXES,
    dci_bytes_per_s_for,
    ici_bytes_per_s_for,
    peak_flops_for,
)
from pipegoose_tpu.telemetry.doctor import (
    CollectiveInfo,
    estimated_wire_bytes,
    hlo_instruction_names,
    parse_collective_schedule,
)

# trace-event names that are HLO collectives, including the async
# "-start"/"-done" halves real TPU schedules split them into
_COLLECTIVE_PREFIXES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)

_HLO_MODULE_RE = re.compile(r"^HloModule\s+([^\s,]+)", re.MULTILINE)



def _is_collective_name(name: str) -> bool:
    return name.startswith(_COLLECTIVE_PREFIXES)


def _base_collective_name(name: str) -> str:
    """Strip the async suffix: ``all-gather-start.1`` and
    ``all-gather-done.1`` both attribute to the schedule's
    ``all-gather-start.1``-or-plain row by its stem."""
    return re.sub(r"-(start|done)(?=\.|$)", "", name)


@dataclasses.dataclass
class StepProfile:
    """Measured device-time attribution of one compiled step.

    ``compute_s`` / ``comm_s`` / ``idle_s`` are mean per-device
    per-step seconds and sum to ``wall_step_s`` (the fenced host wall
    time per step) up to ``residual_s`` — the raw un-clamped residual,
    negative exactly when attribution over-counted. ``comm_by_axes``
    buckets the collective time by the mesh axes each instruction's
    replica groups span (``"?"`` = unattributed). ``collectives`` keeps
    the per-instruction rows (name, op, axes, seconds, schedule bytes)
    — the op-for-op join against the doctor's schedule the acceptance
    tests pin. ``source`` is ``"device_trace"`` when op events were
    found, ``"host_clock"`` for the wall-time-only fallback.
    """

    steps: int
    n_devices: int
    wall_step_s: float
    compute_s: float
    comm_s: float
    idle_s: float
    residual_s: float
    comm_by_axes: Dict[str, float]
    collectives: List[Dict[str, Any]]
    source: str
    device_kind: str
    module_name: str = ""
    # distinct HLO instructions of the compiled module — the dispatch-
    # cost driver the calibrated planner model (planner/cost.py) fits
    # its per-instruction overhead term against
    hlo_instructions: Optional[int] = None
    flops_per_device: Optional[float] = None
    mfu: Optional[float] = None
    # axes-bucket -> measured fraction of the fabric's peak bandwidth
    # (estimated wire bytes / measured bucket seconds / peak B/s)
    fabric_utilization: Dict[str, float] = dataclasses.field(
        default_factory=dict
    )
    top_ops: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    wall_steps_s: List[float] = dataclasses.field(default_factory=list)

    # -- views -------------------------------------------------------------

    @property
    def attributed_s(self) -> float:
        return self.compute_s + self.comm_s + self.idle_s

    @property
    def compute_fraction(self) -> float:
        return self.compute_s / self.wall_step_s if self.wall_step_s > 0 else 0.0

    @property
    def comm_fraction(self) -> float:
        return self.comm_s / self.wall_step_s if self.wall_step_s > 0 else 0.0

    @property
    def idle_fraction(self) -> float:
        return self.idle_s / self.wall_step_s if self.wall_step_s > 0 else 0.0

    def components(self) -> Dict[str, float]:
        """Flat component dict — the perf sentinel's comparison unit:
        ``{"compute_s", "idle_s", "comm[<axes>]_s"...}``."""
        out = {"compute_s": self.compute_s, "idle_s": self.idle_s}
        for axes, t in self.comm_by_axes.items():
            out[f"comm[{axes}]_s"] = t
        return out

    # -- serialization -----------------------------------------------------

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["compute_fraction"] = self.compute_fraction
        d["comm_fraction"] = self.comm_fraction
        d["idle_fraction"] = self.idle_fraction
        return d

    @classmethod
    def from_json(cls, d: dict) -> "StepProfile":
        # forward compat: pick known keys only (doctor/report convention)
        return cls(
            steps=int(d["steps"]),
            n_devices=int(d["n_devices"]),
            wall_step_s=float(d["wall_step_s"]),
            compute_s=float(d["compute_s"]),
            comm_s=float(d["comm_s"]),
            idle_s=float(d["idle_s"]),
            residual_s=float(d.get("residual_s", 0.0)),
            comm_by_axes={str(k): float(v)
                          for k, v in (d.get("comm_by_axes") or {}).items()},
            collectives=[dict(c) for c in d.get("collectives", [])],
            source=str(d.get("source", "device_trace")),
            device_kind=str(d.get("device_kind", "?")),
            module_name=str(d.get("module_name", "")),
            hlo_instructions=(None if d.get("hlo_instructions") is None
                              else int(d["hlo_instructions"])),
            flops_per_device=(None if d.get("flops_per_device") is None
                              else float(d["flops_per_device"])),
            mfu=(None if d.get("mfu") is None else float(d["mfu"])),
            fabric_utilization={
                str(k): float(v)
                for k, v in (d.get("fabric_utilization") or {}).items()
            },
            top_ops=[dict(t) for t in d.get("top_ops", [])],
            wall_steps_s=[float(x) for x in d.get("wall_steps_s", [])],
        )

    def format_table(self, max_ops: int = 8) -> str:
        from pipegoose_tpu.telemetry.doctor import _align

        def ms(x: float) -> str:
            return f"{x * 1e3:.3f}ms"

        lines = [
            f"step profile ({self.source}): {self.steps} step(s) x "
            f"{self.n_devices} device(s), wall {ms(self.wall_step_s)}/step",
            "",
        ]
        rows = [("component", "per-step", "fraction")]
        rows.append(("compute", ms(self.compute_s),
                     f"{self.compute_fraction:6.1%}"))
        for axes, t in sorted(self.comm_by_axes.items()):
            frac = t / self.wall_step_s if self.wall_step_s > 0 else 0.0
            rows.append((f"comm[{axes}]", ms(t), f"{frac:6.1%}"))
        rows.append(("idle", ms(self.idle_s), f"{self.idle_fraction:6.1%}"))
        lines += _align(rows)
        if self.mfu is not None:
            lines += ["", f"measured MFU {self.mfu:.4f} "
                          f"({self.device_kind})"]
        for axes, u in sorted(self.fabric_utilization.items()):
            lines.append(f"fabric[{axes}] utilization {u:.1%}")
        if self.collectives:
            lines += ["", "collectives (measured vs schedule):"]
            lines += _align([("name", "op", "axes", "per-step", "bytes")] + [
                (c["name"] or "?", c["op"],
                 ",".join(c["axes"]) if c.get("axes") else "?",
                 ms(float(c["seconds"])), str(c.get("bytes", 0)))
                for c in self.collectives
            ])
        if self.top_ops:
            lines += ["", "largest compute ops:"]
            lines += _align([("name", "per-step")] + [
                (t["name"], ms(float(t["seconds"])))
                for t in self.top_ops[:max_ops]
            ])
        if self.residual_s < 0:
            lines += ["", f"WARNING: attribution exceeds wall by "
                          f"{ms(-self.residual_s)} (concurrent thunks)"]
        return "\n".join(lines)


def set_profile_gauges(profile: StepProfile, registry: Any = None) -> None:
    """Land the profile's headline fractions as gauges next to MFU:
    ``perf.compute_fraction`` / ``perf.comm_fraction`` /
    ``perf.idle_fraction`` (+ ``perf.measured_mfu`` when modeled)."""
    from pipegoose_tpu.telemetry.registry import get_registry

    reg = registry if registry is not None else get_registry()
    reg.gauge(
        "perf.compute_fraction",
        help="measured compute share of the fenced step wall time",
    ).set(float(profile.compute_fraction))
    reg.gauge(
        "perf.comm_fraction",
        help="measured collective share of the fenced step wall time",
    ).set(float(profile.comm_fraction))
    reg.gauge(
        "perf.idle_fraction",
        help="measured idle share of the fenced step wall time",
    ).set(float(profile.idle_fraction))
    if profile.mfu is not None:
        reg.gauge(
            "perf.measured_mfu",
            help="XLA cost-analysis FLOPs over measured step wall x peak",
        ).set(float(profile.mfu))


# -- trace parsing ---------------------------------------------------------


def find_trace_file(logdir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under a ``jax.profiler.trace`` logdir
    (the profiler writes ``plugins/profile/<run>/<host>.trace.json.gz``)."""
    paths = glob.glob(
        os.path.join(logdir, "plugins", "profile", "*", "*.trace.json.gz")
    ) + glob.glob(os.path.join(logdir, "*.trace.json.gz"))
    # the perfetto conversion of the same run is not the event stream
    paths = [p for p in paths
             if not os.path.basename(p).startswith("perfetto")]
    return max(paths, key=os.path.getmtime) if paths else None


def load_trace_events(path: str) -> List[dict]:
    """The ``traceEvents`` list of a (gzipped) Chrome-trace JSON."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    return data["traceEvents"] if isinstance(data, dict) else data


def op_events(
    events: Sequence[dict],
    module_name: str,
    instruction_names: Optional[set] = None,
) -> List[dict]:
    """Complete ("X") events that are HLO op executions of
    ``module_name``: primary match on ``args.hlo_module`` (what the TSL
    profiler stamps on op events); fallback — for traces whose op
    events carry no args — on the event name being one of the module's
    instruction names."""
    primary = [
        e for e in events
        if e.get("ph") == "X"
        and isinstance(e.get("args"), dict)
        and e["args"].get("hlo_module") == module_name
    ]
    if primary or not instruction_names:
        return primary
    return [
        e for e in events
        if e.get("ph") == "X" and not e.get("args")
        and e.get("name") in instruction_names
    ]


def attribute_op_times(
    events: Sequence[dict],
    steps: int,
    n_devices: int,
    schedule: Sequence[CollectiveInfo] = (),
) -> Dict[str, Any]:
    """Aggregate op events into per-device per-step seconds.

    Returns ``{"compute_s", "comm_s", "comm_by_axes", "collectives",
    "top_ops", "per_op"}`` where every seconds value is
    ``sum(dur) / (steps * n_devices)``. Collective events join the
    doctor ``schedule`` by HLO instruction name (async start/done halves
    by stem) to inherit mesh axes + payload bytes; unmatched collectives
    land in the ``"?"`` bucket with ``bytes=0``.
    """
    totals: Dict[str, float] = {}
    for e in events:
        name = e.get("name")
        if not name:
            continue
        op = (e.get("args") or {}).get("hlo_op") or name
        totals[op] = totals.get(op, 0.0) + float(e.get("dur", 0.0)) * 1e-6
    denom = max(steps, 1) * max(n_devices, 1)
    per_op = {k: v / denom for k, v in totals.items()}

    by_name: Dict[str, CollectiveInfo] = {}
    for c in schedule:
        if c.name:
            by_name[c.name] = c
    compute_s = 0.0
    comm_by_axes: Dict[str, float] = {}
    collectives: List[Dict[str, Any]] = []
    top_ops: List[Dict[str, Any]] = []
    for name, secs in per_op.items():
        if not _is_collective_name(name):
            compute_s += secs
            top_ops.append({"name": name, "seconds": secs})
            continue
        info = by_name.get(name) or by_name.get(_base_collective_name(name))
        axes = tuple(info.mesh_axes) if info is not None and info.mesh_axes \
            else None
        key = "+".join(axes) if axes else "?"
        comm_by_axes[key] = comm_by_axes.get(key, 0.0) + secs
        collectives.append({
            "name": name,
            "op": (info.op if info is not None
                   else _base_collective_name(name).rsplit(".", 1)[0]),
            "axes": list(axes) if axes else None,
            "seconds": secs,
            "bytes": int(info.bytes) if info is not None else 0,
            "intentional": (bool(info.intentional)
                            if info is not None else None),
        })
    top_ops.sort(key=lambda t: -t["seconds"])
    collectives.sort(key=lambda c: -c["seconds"])
    return {
        "compute_s": compute_s,
        "comm_s": sum(comm_by_axes.values()),
        "comm_by_axes": comm_by_axes,
        "collectives": collectives,
        "top_ops": top_ops[:16],
        "per_op": per_op,
    }


# -- the profiler ----------------------------------------------------------


@contextlib.contextmanager
def _trace_session(logdir: str, create_perfetto_trace: bool = False):
    """A profiler session with the PYTHON tracer disabled.

    ``jax.profiler.trace`` defaults to ``python_tracer_level=1``, which
    wraps every Python call in a TraceMe — measured ~25x dispatch
    inflation on the CPU smoke, enough to invert the step-time ranking
    being profiled. The XLA op events this module consumes come from
    the host/device tracers, so the Python tracer is pure observer
    effect here. Falls back to plain ``jax.profiler.trace`` when the
    session API is unavailable (it is on the container's jax 0.4.37).
    """
    try:
        from jax._src.lib import xla_client

        opts = xla_client.profiler.ProfileOptions()
        opts.python_tracer_level = 0
        # level 1 keeps the XLA op events (the payload here) at about
        # half the per-event recording overhead of the default 2
        opts.host_tracer_level = 1
        sess = xla_client.profiler.ProfilerSession(opts)
    except Exception:  # noqa: BLE001 - private API; degrade gracefully
        with jax.profiler.trace(logdir,
                                create_perfetto_trace=create_perfetto_trace):
            yield
        return
    try:
        yield
    finally:
        sess.export(sess.stop(), str(logdir))
        if create_perfetto_trace:
            try:
                from jax._src.profiler import _write_perfetto_trace_file

                _write_perfetto_trace_file(logdir)
            except Exception:  # noqa: BLE001 - the conversion is a
                pass           # convenience; the parsed trace exists


def _mesh_axes_of(compiled: Any, mesh: Any) -> Dict[str, int]:
    if mesh is None:
        from jax.sharding import NamedSharding

        try:
            leaves = (
                list(jax.tree_util.tree_leaves(compiled.input_shardings[0]))
                + list(jax.tree_util.tree_leaves(compiled.output_shardings))
            )
        except Exception:  # noqa: BLE001 - shardings are advisory
            leaves = []
        for s in leaves:
            if isinstance(s, NamedSharding):
                mesh = s.mesh
                break
    if mesh is None:
        return {}
    return {str(k): int(v) for k, v in dict(mesh.shape).items()}


def profile_step(
    fn: Callable,
    *args: Any,
    steps: int = 3,
    warmup: int = 2,
    update_args: Optional[Callable] = None,
    mesh: Any = None,
    device_kind: Optional[str] = None,
    trace_dir: Optional[str] = None,
    registry: Any = None,
) -> StepProfile:
    """Run the real compiled step under the XLA profiler and return its
    measured :class:`StepProfile`.

    ``fn`` may be jitted (donation settings kept) or a plain callable
    (wrapped in ``jax.jit``). ``args`` are REAL arrays — unlike the
    mesh doctor, the step EXECUTES (``warmup + steps`` times: warmup
    outside the trace so compile/cache effects never pollute the
    measured events; the default ``warmup=2`` matters — the FIRST call
    compiles and the SECOND settles donated-buffer layouts, measured at
    ~50x a steady step on CPU, so a 1-warmup profile would bake that
    one-off into every component). ``update_args(out, args) -> args`` threads one
    step's outputs into the next call — REQUIRED when the step donates
    inputs (the hybrid train step donates params/opt state; the paged
    decode step donates its KV pages), otherwise the second call would
    touch deleted buffers. Each measured step is individually fenced
    (``block_until_ready``) and host-timed; the fenced mean is the
    profile's wall denominator.

    ``trace_dir``: keep the profiler artifact there (TensorBoard /
    ui.perfetto.dev viewable — ``create_perfetto_trace=True`` also
    writes the perfetto conversion); default is a temp dir parsed and
    discarded. Fractions land as ``perf.*`` gauges on ``registry``
    (default: the global one; disabled registries cost one branch).
    """
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if warmup < 0:
        raise ValueError(f"warmup must be >= 0, got {warmup}")
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)

    # ONE AOT lower+compile for the compile-time side: module name,
    # instruction set, the collective schedule (axes + bytes), FLOPs
    lowered = jfn.lower(*args)
    compiled = lowered.compile()
    try:
        hlo = compiled.as_text()
    except Exception:  # noqa: BLE001 - backends without HLO text export
        hlo = ""
    m = _HLO_MODULE_RE.search(hlo)
    module_name = m.group(1) if m else ""
    # the SAME counting rule the doctor/planner side uses — the
    # calibration fit joins the two counts
    instruction_names = hlo_instruction_names(hlo)
    mesh_axes = _mesh_axes_of(compiled, mesh)
    n_devices = int(np.prod(list(mesh_axes.values()))) if mesh_axes else 1
    schedule = parse_collective_schedule(hlo, mesh_axes)
    cost_flops = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        f = dict(ca or {}).get("flops")
        cost_flops = float(f) if f is not None else None
    except Exception:  # noqa: BLE001 - cost analysis is advisory
        pass

    if device_kind is None:
        dev = jax.devices()[0]
        device_kind = getattr(dev, "device_kind", dev.platform)

    def one(step_args):
        out = jfn(*step_args)
        jax.block_until_ready(out)
        return out, (update_args(out, step_args) if update_args is not None
                     else step_args)

    cur = tuple(args)
    for _ in range(warmup):
        _, cur = one(cur)

    logdir = trace_dir
    tmp = None
    if logdir is None:
        tmp = tempfile.TemporaryDirectory(prefix="pipegoose_xprof_")
        logdir = tmp.name
    walls: List[float] = []
    try:
        # perfetto conversion only when the caller keeps the artifact —
        # a parsed-and-discarded temp dir doesn't need the copy
        with _trace_session(logdir,
                            create_perfetto_trace=trace_dir is not None):
            for _ in range(steps):
                t0 = time.perf_counter()
                _, cur = one(cur)
                walls.append(time.perf_counter() - t0)
        trace_path = find_trace_file(logdir)
        events = load_trace_events(trace_path) if trace_path else []
    finally:
        if tmp is not None:
            tmp.cleanup()

    wall_step_s = float(sum(walls) / len(walls))
    ops = op_events(events, module_name, instruction_names)
    if ops:
        att = attribute_op_times(ops, steps, n_devices, schedule)
        compute_s = att["compute_s"]
        comm_s = att["comm_s"]
        comm_by_axes = att["comm_by_axes"]
        collectives = att["collectives"]
        top_ops = att["top_ops"]
        source = "device_trace"
    else:
        # host-clock fallback: no op events in the trace (backend
        # without op-level profiling) — the fenced wall IS the only
        # measurement; attribute it to compute, loudly labelled
        compute_s, comm_s = wall_step_s, 0.0
        comm_by_axes, collectives, top_ops = {}, [], []
        source = "host_clock"
    residual_s = wall_step_s - compute_s - comm_s
    idle_s = max(residual_s, 0.0)

    flops_per_device = cost_flops
    mfu = None
    if flops_per_device is not None and wall_step_s > 0:
        mfu = flops_per_device / wall_step_s / peak_flops_for(device_kind)

    # measured fabric utilization: estimated wire bytes of each axes
    # bucket over its measured seconds, vs the fabric's peak B/s
    wire_by_key: Dict[str, int] = {}
    for c in schedule:
        if not c.mesh_axes:
            continue
        key = "+".join(c.mesh_axes)
        wire_by_key[key] = (wire_by_key.get(key, 0)
                            + estimated_wire_bytes(c, mesh_axes))
    fabric_utilization: Dict[str, float] = {}
    for key, secs in comm_by_axes.items():
        nbytes = wire_by_key.get(key)
        if not nbytes or secs <= 0:
            continue
        peak_bw = (dci_bytes_per_s_for(device_kind)
                   if any(ax in DCI_AXES for ax in key.split("+"))
                   else ici_bytes_per_s_for(device_kind))
        fabric_utilization[key] = (nbytes / secs) / peak_bw

    profile = StepProfile(
        steps=steps, n_devices=n_devices, wall_step_s=wall_step_s,
        compute_s=compute_s, comm_s=comm_s, idle_s=idle_s,
        residual_s=residual_s, comm_by_axes=comm_by_axes,
        collectives=collectives, source=source, device_kind=str(device_kind),
        module_name=module_name,
        hlo_instructions=len(instruction_names) or None,
        flops_per_device=flops_per_device,
        mfu=mfu, fabric_utilization=fabric_utilization,
        top_ops=top_ops, wall_steps_s=[float(w) for w in walls],
    )
    set_profile_gauges(profile, registry=registry)
    return profile
