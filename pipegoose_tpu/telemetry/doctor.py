"""Mesh doctor: compile-time sharding & memory inspection of pjit /
shard_map programs, with CI regression guards.

The runtime telemetry layer (registry/spans/derived, health, flight
recorder) measures what a step DID; this module inspects what the
partitioner COMPILED — the artifact Megatron-LM and Alpa-style systems
treat as first-class and the reference stack never exposes. A silently
replicated weight or a GSPMD-inserted all-gather on the hot path shows
up here as a named table row at compile time, not as a mysteriously
slow (or OOMing) step on hardware. Three views, all from ONE
``jax.jit(fn).lower(*args).compile()``:

- :class:`ShardingReport` — the ACTUAL sharding of every input leaf
  (params, optimizer state, batch, KV pages ...) and output buffer from
  ``compiled.input_shardings`` / ``output_shardings``, diffed against
  the INTENDED ``PartitionSpec`` trees (``parallel/auto.py`` /
  ``parallel/hybrid.py``), with the parameter's module path on every
  flag; plus the per-collective schedule the compiler actually emitted
  (bytes, mesh axes recovered from replica groups, source op), split
  into *intentional* traffic (an HLO collective whose metadata names a
  user-level jax collective primitive — psum, pmean, all_gather,
  psum_scatter, ppermute, all_to_all) and *resharding* traffic (GSPMD
  inserted it; no collective primitive in the metadata).
- :class:`MemoryReport` — a per-device HBM budget: bytes per argument
  group (params / opt state / batch / ...), outputs, XLA's own
  temp/peak numbers from ``compiled.memory_analysis()`` where the
  backend reports them (shape-walk fallback otherwise), and the
  largest buffers ranked — an OOM becomes a table, not a crash.
- Guards — :func:`assert_no_resharding` /
  :func:`assert_fully_sharded` / :func:`assert_matches_intended` raise
  :class:`ShardingRegressionError` with the offending rows, so tier-1
  tests pin a step's partitioning plan and a future PR that breaks a
  PartitionSpec fails at compile time on a host-device mesh, not in a
  TPU bench.

Reports serialize (``to_json``/``from_json``), pretty-print
(``format_table``), and land as telemetry gauges
(``doctor.replicated_bytes``, ``doctor.resharding_bytes``,
``doctor.hbm_peak_bytes`` — :func:`set_doctor_gauges`) next to MFU.
Entry points: :func:`diagnose` (any jitted/plain callable),
``Trainer.doctor()``, ``ServingEngine.doctor()``, the
``scripts/mesh_doctor.py`` CLI, and bench.py's ``BENCH_DOCTOR_JSON``
artifact. See docs/observability.md ("Mesh doctor").
"""
from __future__ import annotations

import dataclasses
import itertools
import json
import re
from fnmatch import fnmatch
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from pipegoose_tpu.telemetry.derived import iter_collectives

# jax collective primitives a user writes explicitly (inside shard_map
# or via lax.*): an HLO collective whose metadata op_name ends in one of
# these is the user's own traffic, anything else was inserted by the
# partitioner (resharding / partial-sum reduction of a sharded matmul).
INTENTIONAL_PRIMITIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_gather_invariant",
    "all_to_all", "ppermute", "pshuffle", "psum_scatter", "reduce_scatter",
})

_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_INSTR_NAME_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([A-Za-z0-9_.-]+)\s*=", re.MULTILINE
)
_RG_IOTA_RE = re.compile(
    r"replica_groups=\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)
_RG_EXPLICIT_RE = re.compile(r"replica_groups=\{(\{[0-9,{} ]*\})\}")
_STP_RE = re.compile(r"source_target_pairs=\{(\{[0-9,{} ]*\})\}")


# -- dataclasses -----------------------------------------------------------


@dataclasses.dataclass
class BufferInfo:
    """One input/output leaf of the compiled program."""

    path: str                 # e.g. "params/transformer/h_0/attn/qkv/w"
    shape: Tuple[int, ...]
    dtype: str
    actual: str               # actual sharding spec (str(PartitionSpec))
    intended: Optional[str]   # intended spec string, None = no intent given
    global_bytes: int
    per_device_bytes: int
    replicated: bool          # fully replicated across a >1-device mesh
    role: str = "input"       # "input" | "donated input" | "output"
    flags: List[str] = dataclasses.field(default_factory=list)
    # flags: "mismatch" (intended != actual), "replicated_large"
    # (intended sharded, actual replicated), "unsharded_large" (large
    # and replicated with no/replicated intent — likely a missing spec)


@dataclasses.dataclass
class CollectiveInfo:
    """One collective instruction of the compiled program."""

    op: str                           # "all-gather", "all-reduce", ...
    bytes: int                        # output-payload bytes (wire proxy)
    mesh_axes: Optional[Tuple[str, ...]]  # axes the groups span, if resolvable
    source: str                       # last metadata op_name component, "" if none
    intentional: bool                 # user collective primitive vs GSPMD-inserted
    # HLO instruction name ("all-reduce.2") — the key the measured
    # profiler attribution (telemetry/xprof.py) joins trace events on,
    # so a profiled collective's device time lands on THIS schedule row.
    # "" on reports from artifacts written before the field existed.
    name: str = ""


@dataclasses.dataclass
class ShardingReport:
    """Actual-vs-intended shardings + the emitted collective schedule."""

    mesh_axes: Dict[str, int]
    n_devices: int
    buffers: List[BufferInfo]
    collectives: List[CollectiveInfo]

    @property
    def replicated_bytes(self) -> int:
        """Per-device bytes pinned by fully replicated buffers (inputs
        only — outputs usually alias donated inputs)."""
        return sum(b.per_device_bytes for b in self.buffers
                   if b.replicated and b.role != "output")

    @property
    def resharding_bytes(self) -> int:
        return sum(c.bytes for c in self.collectives if not c.intentional)

    @property
    def intentional_bytes(self) -> int:
        return sum(c.bytes for c in self.collectives if c.intentional)

    @property
    def resharding_collectives(self) -> List[CollectiveInfo]:
        return [c for c in self.collectives if not c.intentional]

    def mismatches(self) -> List[BufferInfo]:
        return [b for b in self.buffers if "mismatch" in b.flags]

    def flagged(self) -> List[BufferInfo]:
        return [b for b in self.buffers if b.flags]

    def to_json(self) -> dict:
        return {
            "mesh_axes": dict(self.mesh_axes),
            "n_devices": self.n_devices,
            "buffers": [dataclasses.asdict(b) for b in self.buffers],
            "collectives": [dataclasses.asdict(c) for c in self.collectives],
            "replicated_bytes": self.replicated_bytes,
            "resharding_bytes": self.resharding_bytes,
            "intentional_bytes": self.intentional_bytes,
        }

    @classmethod
    def from_json(cls, d: dict) -> "ShardingReport":
        return cls(
            mesh_axes=dict(d["mesh_axes"]),
            n_devices=int(d["n_devices"]),
            buffers=[BufferInfo(
                path=b["path"], shape=tuple(b["shape"]), dtype=b["dtype"],
                actual=b["actual"], intended=b.get("intended"),
                global_bytes=int(b["global_bytes"]),
                per_device_bytes=int(b["per_device_bytes"]),
                replicated=bool(b["replicated"]),
                role=b.get("role", "input"), flags=list(b.get("flags", [])),
            ) for b in d["buffers"]],
            collectives=[CollectiveInfo(
                op=c["op"], bytes=int(c["bytes"]),
                mesh_axes=tuple(c["mesh_axes"]) if c.get("mesh_axes") else None,
                source=c.get("source", ""),
                intentional=bool(c["intentional"]),
                name=c.get("name", ""),
            ) for c in d["collectives"]],
        )

    def format_table(self, max_rows: int = 32) -> str:
        mesh = " ".join(f"{k}={v}" for k, v in self.mesh_axes.items()) or "-"
        lines = [f"mesh: {mesh} ({self.n_devices} devices)", "", "buffers:"]
        # flagged rows always shown, then the largest of the rest
        flagged = self.flagged()
        rest = sorted((b for b in self.buffers if not b.flags),
                      key=lambda b: -b.global_bytes)
        rows = flagged + rest[:max(0, max_rows - len(flagged))]
        header = ("path", "shape", "dtype", "intended", "actual",
                  "global", "per-dev", "flags")
        table = [header] + [
            (b.path, "x".join(map(str, b.shape)) or "()", b.dtype,
             b.intended if b.intended is not None else "-", b.actual,
             _fmt_bytes(b.global_bytes), _fmt_bytes(b.per_device_bytes),
             ",".join(b.flags) or ("replicated" if b.replicated else "-"))
            for b in rows
        ]
        lines += _align(table)
        hidden = len(self.buffers) - len(rows)
        if hidden > 0:
            lines.append(f"  ... {hidden} more unflagged buffer(s)")
        lines += ["", "collectives:"]
        if self.collectives:
            ctable = [("op", "bytes", "axes", "source", "class")] + [
                (c.op, _fmt_bytes(c.bytes),
                 ",".join(c.mesh_axes) if c.mesh_axes else "?",
                 c.source or "-",
                 "intentional" if c.intentional else "RESHARDING")
                for c in self.collectives
            ]
            lines += _align(ctable)
        else:
            lines.append("  (none)")
        lines += ["", (
            f"replicated={_fmt_bytes(self.replicated_bytes)}/dev  "
            f"intentional-comm={_fmt_bytes(self.intentional_bytes)}  "
            f"resharding-comm={_fmt_bytes(self.resharding_bytes)}  "
            f"mismatches={len(self.mismatches())}"
        )]
        return "\n".join(lines)


@dataclasses.dataclass
class MemoryReport:
    """Per-device HBM budget of one compiled program."""

    groups: Dict[str, int]        # arg-group label -> per-device bytes
    output_bytes: int             # per-device
    temp_bytes: Optional[int]     # XLA temp (activations/workspace), per-device
    peak_bytes: int               # per-device peak estimate
    source: str                   # "memory_analysis" | "shape_walk"
    hbm_limit: Optional[int]      # device bytes_limit where the backend reports it
    top: List[dict]               # largest buffers: {path, per_device_bytes, role}
    # arg-group label -> {dtype string -> per-device bytes}: the dtype
    # split of each group, so a quantized serving engine's weight and
    # KV-page drop reads straight off /debug/doctor and
    # BENCH_DOCTOR_JSON (None on reports from older artifacts)
    by_dtype: Optional[Dict[str, Dict[str, int]]] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "MemoryReport":
        by_dtype = d.get("by_dtype")
        return cls(
            groups=dict(d["groups"]), output_bytes=int(d["output_bytes"]),
            temp_bytes=(None if d.get("temp_bytes") is None
                        else int(d["temp_bytes"])),
            peak_bytes=int(d["peak_bytes"]), source=d["source"],
            hbm_limit=(None if d.get("hbm_limit") is None
                       else int(d["hbm_limit"])),
            top=[dict(t) for t in d.get("top", [])],
            by_dtype=(None if by_dtype is None else {
                str(g): {str(k): int(v) for k, v in dd.items()}
                for g, dd in by_dtype.items()
            }),
        )

    def format_table(self) -> str:
        rows = [("group", "per-device", "of peak")]
        denom = max(self.peak_bytes, 1)
        for k, v in self.groups.items():
            label = k
            if self.by_dtype and len(self.by_dtype.get(k, {})) > 0:
                label = k + " (" + " + ".join(
                    f"{dt}:{_fmt_bytes(b)}"
                    for dt, b in sorted(self.by_dtype[k].items())
                ) + ")"
            rows.append((label, _fmt_bytes(v), f"{v / denom:6.1%}"))
        rows.append(("outputs", _fmt_bytes(self.output_bytes),
                     f"{self.output_bytes / denom:6.1%}"))
        if self.temp_bytes is not None:
            rows.append(("temp (XLA)", _fmt_bytes(self.temp_bytes),
                         f"{self.temp_bytes / denom:6.1%}"))
        lines = [f"memory budget per device ({self.source}):"]
        lines += _align(rows)
        peak = f"peak ~= {_fmt_bytes(self.peak_bytes)}/dev"
        if self.hbm_limit:
            peak += (f"  (HBM limit {_fmt_bytes(self.hbm_limit)}, "
                     f"{self.peak_bytes / self.hbm_limit:.1%})")
        lines += ["", peak, "", "largest buffers:"]
        lines += _align([("path", "per-dev", "role")] + [
            (t["path"], _fmt_bytes(t["per_device_bytes"]), t["role"])
            for t in self.top
        ])
        return "\n".join(lines)


@dataclasses.dataclass
class DoctorReport:
    """The full mesh-doctor result for one compiled program."""

    sharding: ShardingReport
    memory: MemoryReport
    # XLA cost-analysis FLOPs of the compiled (per-device, SPMD)
    # program — the planner's compute-time numerator. None where the
    # backend reports no cost analysis, and on reports deserialized
    # from artifacts written before the field existed.
    cost_flops: Optional[float] = None
    # distinct HLO instructions of the compiled module — the static
    # driver of per-step dispatch cost (a calibrated planner cost model
    # prices host/thunk dispatch per instruction; telemetry/xprof.py
    # measures the same count from its own HLO parse). None on older
    # artifacts and backends without HLO text export.
    hlo_instructions: Optional[int] = None
    # free-form program annotations the producer wants in the artifact
    # (e.g. the serving engine's chosen paged-attention tile geometry).
    # JSON-serializable values only. None on older artifacts.
    extras: Optional[dict] = None

    def to_json(self) -> dict:
        d = {"sharding": self.sharding.to_json(),
             "memory": self.memory.to_json(),
             "cost_flops": self.cost_flops,
             "hlo_instructions": self.hlo_instructions}
        if self.extras is not None:
            d["extras"] = self.extras
        return d

    @classmethod
    def from_json(cls, d: dict) -> "DoctorReport":
        # forward compat: pick known keys only — a plan/doctor artifact
        # written by a NEWER version (extra fields at any level) must
        # still load here, e.g. in the CLI's --check mode
        return cls(sharding=ShardingReport.from_json(d["sharding"]),
                   memory=MemoryReport.from_json(d["memory"]),
                   cost_flops=(None if d.get("cost_flops") is None
                               else float(d["cost_flops"])),
                   hlo_instructions=(
                       None if d.get("hlo_instructions") is None
                       else int(d["hlo_instructions"])),
                   extras=d.get("extras"))

    def format_table(self, max_rows: int = 32) -> str:
        return (self.sharding.format_table(max_rows=max_rows)
                + "\n\n" + self.memory.format_table())


# -- formatting helpers ----------------------------------------------------


def _fmt_bytes(n: int) -> str:
    f = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(f) < 1024 or unit == "GiB":
            return f"{f:.1f}{unit}" if unit != "B" else f"{int(f)}B"
        f /= 1024
    return f"{int(n)}B"


def _align(rows: Sequence[Tuple[str, ...]]) -> List[str]:
    widths = [max(len(str(r[i])) for r in rows) for i in range(len(rows[0]))]
    return ["  " + "  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip()
            for r in rows]


def _path_str(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


# -- spec normalization / sharding introspection ---------------------------


def _norm_spec(spec: Optional[P]) -> tuple:
    """Canonical comparable form of a PartitionSpec: single-name tuples
    unwrapped, trailing None entries stripped (``P(None, 'tensor')`` ==
    ``P(None, ('tensor',))``, ``P('data')`` == ``P('data', None)``)."""
    if spec is None:
        return ()
    entries: list = []
    for e in tuple(spec):
        if isinstance(e, (tuple, list)):
            e = tuple(e)
            if len(e) == 1:
                e = e[0]
        entries.append(e)
    while entries and entries[-1] is None:
        entries.pop()
    return tuple(entries)


def _spec_str(spec: Optional[P]) -> str:
    if spec is None:
        return "?"
    return "P(" + ", ".join(
        repr(e) if not isinstance(e, (tuple, list)) else repr(tuple(e))
        for e in _norm_spec(spec)
    ) + ")"


def _gspmd_to_spec(sharding, mesh) -> Optional[P]:
    """PartitionSpec of a compiled-program GSPMDSharding on ``mesh``
    (jit-of-shard_map programs report their input shardings in GSPMD
    form, not as NamedShardings). Best effort — None when the tiling
    doesn't decompose over the mesh."""
    try:
        from jax._src.sharding_impls import parse_flatten_op_sharding

        hlo = getattr(sharding, "_hlo_sharding", None)
        if hlo is None:
            hlo = sharding._op_sharding
        parsed = parse_flatten_op_sharding(hlo, mesh)
        return parsed[0].get_partition_spec() if parsed else None
    except Exception:  # noqa: BLE001 - private API; degrade to repr
        return None


def _sharding_info(sharding, shape, mesh=None) -> Tuple[str, Optional[P], int]:
    """(spec string, PartitionSpec or None, per-device nbytes-divisor).

    Returns the shard-count divisor instead of bytes so callers can
    apply it to the leaf's own itemsize."""
    if sharding is None:
        return "?", None, 1
    spec = getattr(sharding, "spec", None)
    if spec is None and mesh is not None:
        spec = _gspmd_to_spec(sharding, mesh)
    try:
        shard_shape = sharding.shard_shape(tuple(shape))
        denom = max(1, int(np.prod(shape)) // max(1, int(np.prod(shard_shape))))
    except Exception:  # noqa: BLE001 - uneven shapes / exotic shardings
        denom = 1
    if spec is not None:
        return _spec_str(spec), spec, denom
    name = type(sharding).__name__
    if name == "SingleDeviceSharding":
        return "single-device", None, 1
    return name, None, denom


def _equivalent(sharding, mesh, spec: P, ndim: int) -> bool:
    """Whether a compiled sharding is layout-equivalent to the intended
    PartitionSpec (catches specs that normalize differently but place
    bytes identically). False on any API failure — the spec-string
    comparison then governs."""
    try:
        return bool(sharding.is_equivalent_to(NamedSharding(mesh, spec), ndim))
    except Exception:  # noqa: BLE001
        return False


# -- collective schedule parsing -------------------------------------------


def _parse_groups(line: str) -> Optional[List[List[int]]]:
    """Device-id groups of one collective line: explicit
    ``replica_groups={{0,1},{2,3}}``, iota ``[4,2]<=[8]`` (optionally
    ``T(perm)``), or ``source_target_pairs`` (connected components of
    the permutation graph)."""
    m = _RG_EXPLICIT_RE.search(line)
    if m:
        # whitespace-tolerant: pretty-printed dumps write "{0,1}, {2,3}"
        return [
            [int(x) for x in re.findall(r"\d+", g)]
            for g in re.split(r"\}\s*,\s*\{", m.group(1).strip("{}"))
        ]
    m = _RG_IOTA_RE.search(line)
    if m:
        gshape = [int(x) for x in m.group(1).split(",")]
        dims = [int(x) for x in m.group(2).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(3):
            perm = [int(x) for x in m.group(3).split(",")]
            ids = ids.transpose(perm)
        return [list(map(int, row)) for row in ids.reshape(gshape)]
    m = _STP_RE.search(line)
    if m:
        pairs = [
            tuple(int(x) for x in re.findall(r"\d+", g))
            for g in re.split(r"\}\s*,\s*\{", m.group(1).strip("{}"))
        ]
        if not pairs or any(len(p) != 2 for p in pairs):
            return None
        # union-find over permutation edges: each connected component is
        # the device set the permute cycles within (= its "group")
        parent: Dict[int, int] = {}

        def find(a: int) -> int:
            parent.setdefault(a, a)
            while parent[a] != a:
                parent[a] = parent[parent[a]]
                a = parent[a]
            return a

        for a, b in pairs:
            parent[find(a)] = find(b)
        comps: Dict[int, List[int]] = {}
        for a in parent:
            comps.setdefault(find(a), []).append(a)
        return [sorted(v) for v in comps.values()]
    return None


def _groups_to_axes(
    groups: Optional[List[List[int]]], mesh_axes: Dict[str, int]
) -> Optional[Tuple[str, ...]]:
    """Smallest mesh-axis subset whose induced device partition matches
    ``groups``. Device ids are logical positions in the mesh's flat
    device order (row-major over the axis sizes), which is how
    jit-on-a-mesh numbers replica groups."""
    if not groups or not mesh_axes:
        return None
    names = list(mesh_axes)
    sizes = [mesh_axes[n] for n in names]
    n = int(np.prod(sizes))
    if max(max(g) for g in groups) >= n:
        return None
    target = {frozenset(g) for g in groups}
    coords = np.stack(np.unravel_index(np.arange(n), sizes), axis=1)
    for r in range(1, len(names) + 1):  # smallest subset wins
        for sub in itertools.combinations(range(len(names)), r):
            keep = [a for a in range(len(names)) if a not in sub]
            part: Dict[tuple, set] = {}
            for i in range(n):
                part.setdefault(tuple(coords[i, keep]), set()).add(i)
            if {frozenset(v) for v in part.values()} == target:
                return tuple(names[a] for a in sub)
    return None


def hlo_instruction_names(hlo_text: str) -> set:
    """Distinct HLO instruction names of a module's text — the join
    key between the compiled schedule and profiler trace op events
    (telemetry/xprof.py), and the static dispatch-cost driver
    (``DoctorReport.hlo_instructions``). ONE definition: the profiler
    and the planner must count with the same rule."""
    return set(_INSTR_NAME_RE.findall(hlo_text))


def _source_primitive(line: str) -> str:
    m = _OP_NAME_RE.search(line)
    if not m:
        return ""
    tail = m.group(1).split("/")[-1]
    return tail.split("[")[0].strip()


def parse_collective_schedule(
    hlo_text: str, mesh_axes: Optional[Dict[str, int]] = None
) -> List[CollectiveInfo]:
    """Per-instruction collective table of an HLO module: op, payload
    bytes, the mesh axes its replica groups span (when resolvable
    against ``mesh_axes``), the source jax primitive from the metadata,
    and the intentional/resharding classification."""
    out = []
    for c in iter_collectives(hlo_text):
        src = _source_primitive(c["line"])
        try:  # axes are advisory — a malformed group never aborts the run
            axes = _groups_to_axes(_parse_groups(c["line"]), mesh_axes or {})
        except (ValueError, IndexError):
            axes = None
        nm = _INSTR_NAME_RE.match(c["line"])
        out.append(CollectiveInfo(
            op=c["op"],
            bytes=c["bytes"],
            mesh_axes=axes,
            source=src,
            intentional=src in INTENTIONAL_PRIMITIVES,
            name=nm.group(1) if nm else "",
        ))
    return out


# -- intended-spec alignment -----------------------------------------------


def _intended_by_path(args: tuple, intended: Optional[tuple]) -> Dict[str, P]:
    """{leaf path -> intended PartitionSpec} for the args tuple.

    ``intended`` aligns positionally with ``args``; each entry is None
    (no intent), a single PartitionSpec (broadcast over every leaf of
    that arg), or a pytree of PartitionSpecs structurally matching the
    arg (leaf paths are matched individually, so a partial tree simply
    leaves the unmatched leaves un-diffed)."""
    out: Dict[str, P] = {}
    if intended is None:
        return out
    for i, spec_i in enumerate(intended):
        if spec_i is None:
            continue
        if isinstance(spec_i, P):
            for path, _ in jax.tree_util.tree_leaves_with_path(args[i]):
                out[f"{i}/{_path_str(path)}".rstrip("/")] = spec_i
            continue
        for path, leaf in jax.tree_util.tree_leaves_with_path(
            spec_i, is_leaf=lambda x: isinstance(x, P)
        ):
            if isinstance(leaf, P):
                out[f"{i}/{_path_str(path)}".rstrip("/")] = leaf
    return out


# -- the inspector ---------------------------------------------------------


def diagnose(
    fn: Any,
    *args: Any,
    intended: Optional[tuple] = None,
    labels: Optional[Sequence[str]] = None,
    mesh: Any = None,
    large_bytes: int = 1 << 20,
) -> DoctorReport:
    """Lower+compile ``fn`` at these arg shapes (ShapeDtypeStructs are
    fine — nothing executes) and inspect the compiled partitioning plan.

    ``fn`` may be a jitted function (its donation/sharding settings are
    kept) or a plain callable (wrapped in ``jax.jit``). ``intended``
    aligns with ``args`` (see :func:`_intended_by_path`); ``labels``
    names each positional arg in report paths (default ``arg0``...).
    ``large_bytes`` is the threshold above which a replicated buffer is
    flagged as a problem rather than noise."""
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    lowered = jfn.lower(*args)
    compiled = lowered.compile()

    # one entry per positional arg, each a PYTREE of shardings (bare
    # arrays give a flat tuple, container args give containers): flatten
    # the whole structure — sharding objects are pytree leaves
    in_shardings = jax.tree_util.tree_leaves(compiled.input_shardings[0])
    out_sh_leaves = jax.tree_util.tree_leaves(compiled.output_shardings)
    arg_leaves = jax.tree_util.tree_leaves_with_path(args)
    labels = list(labels) if labels is not None else [
        f"arg{i}" for i in range(len(args))
    ]

    # donated flags, leaf-aligned with args (best effort across versions)
    donated: List[bool] = []
    try:
        donated = [bool(getattr(a, "donated", False))
                   for a in jax.tree_util.tree_leaves(
                       lowered.args_info,
                       is_leaf=lambda x: hasattr(x, "donated"))]
    except Exception:  # noqa: BLE001
        donated = []
    if len(donated) != len(arg_leaves):
        donated = [False] * len(arg_leaves)

    # mesh: explicit > first NamedSharding seen (outputs included —
    # jit-of-shard_map reports GSPMD input shardings but Named outputs)
    if mesh is None:
        for s in list(in_shardings) + list(out_sh_leaves):
            if isinstance(s, NamedSharding):
                mesh = s.mesh
                break
    mesh_axes = (
        {str(k): int(v) for k, v in dict(mesh.shape).items()}
        if mesh is not None else {}
    )
    n_devices = int(np.prod(list(mesh_axes.values()))) if mesh_axes else 1

    intent = _intended_by_path(args, intended)
    aligned = len(in_shardings) == len(arg_leaves)

    def _leaf_path(i, path) -> str:
        if not aligned:
            return f"input[{i}]"
        first = path[0]
        idx = getattr(first, "idx", None)
        prefix = labels[idx] if idx is not None and idx < len(labels) else str(idx)
        rest = _path_str(path[1:])
        return f"{prefix}/{rest}" if rest else prefix

    buffers: List[BufferInfo] = []
    for i, (path, leaf) in enumerate(arg_leaves):
        sharding = in_shardings[i] if i < len(in_shardings) else None
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        gbytes = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
        actual_str, actual_spec, denom = _sharding_info(sharding, shape, mesh)
        pbytes = gbytes // max(denom, 1)
        replicated = n_devices > 1 and (
            bool(getattr(sharding, "is_fully_replicated", denom == 1))
            if sharding is not None else denom == 1
        )
        spec_want = None
        if aligned and hasattr(path[0], "idx"):
            key = f"{path[0].idx}/{_path_str(path[1:])}".rstrip("/")
            spec_want = intent.get(key)
        flags: List[str] = []
        if spec_want is not None and sharding is not None:
            differs = (actual_spec is None
                       or _norm_spec(spec_want) != _norm_spec(actual_spec))
            if differs and (mesh is None or not _equivalent(
                    sharding, mesh, spec_want, len(shape))):
                flags.append("mismatch")
        if replicated and gbytes >= large_bytes:
            if spec_want is not None and _norm_spec(spec_want) != ():
                flags.append("replicated_large")
            else:
                flags.append("unsharded_large")
        buffers.append(BufferInfo(
            path=_leaf_path(i, path), shape=shape, dtype=str(dtype),
            actual=actual_str,
            intended=_spec_str(spec_want) if spec_want is not None else None,
            global_bytes=gbytes, per_device_bytes=pbytes,
            replicated=replicated,
            role="donated input" if donated[i] else "input",
            flags=flags,
        ))

    # outputs: shardings from the compiled object; avals from the
    # lowering (out_info), falling back to a re-trace only when the jax
    # version lacks it — diagnose stays ONE trace+compile
    out_bytes_per_device = 0
    try:
        out_avals = getattr(lowered, "out_info", None)
        if out_avals is None:
            out_avals = jax.eval_shape(jfn, *args)
        out_leaves = jax.tree_util.tree_leaves_with_path(out_avals)
        if len(out_sh_leaves) == len(out_leaves):
            for (path, leaf), sharding in zip(out_leaves, out_sh_leaves):
                shape = tuple(leaf.shape)
                dtype = np.dtype(leaf.dtype)
                gbytes = (int(np.prod(shape)) * dtype.itemsize
                          if shape else dtype.itemsize)
                actual_str, _, denom = _sharding_info(sharding, shape, mesh)
                pbytes = gbytes // max(denom, 1)
                out_bytes_per_device += pbytes
                p = _path_str(path)
                buffers.append(BufferInfo(
                    path=f"out/{p}" if p else "out", shape=shape,
                    dtype=str(dtype), actual=actual_str, intended=None,
                    global_bytes=gbytes, per_device_bytes=pbytes,
                    replicated=n_devices > 1 and denom == 1,
                    role="output", flags=[],
                ))
    except Exception:  # noqa: BLE001 - outputs are advisory
        pass

    # collective schedule from the compiled HLO
    try:
        hlo = compiled.as_text()
    except Exception:  # noqa: BLE001 - backends without HLO text export
        hlo = ""
    collectives = parse_collective_schedule(hlo, mesh_axes)

    sharding_report = ShardingReport(
        mesh_axes=mesh_axes, n_devices=n_devices,
        buffers=buffers, collectives=collectives,
    )

    # -- memory budget -----------------------------------------------------
    groups: Dict[str, int] = {}
    by_dtype: Dict[str, Dict[str, int]] = {}
    for b in buffers:
        if b.role == "output":
            continue
        label = b.path.split("/")[0]
        groups[label] = groups.get(label, 0) + b.per_device_bytes
        dd = by_dtype.setdefault(label, {})
        dd[b.dtype] = dd.get(b.dtype, 0) + b.per_device_bytes
    temp = peak = None
    source = "shape_walk"
    try:
        ma = compiled.memory_analysis()
        if ma is not None and getattr(ma, "temp_size_in_bytes", None) is not None:
            temp = int(ma.temp_size_in_bytes)
            # argument + output + temp - alias is XLA's own budget view;
            # aliased (donated) outputs don't double-count
            peak = int(ma.argument_size_in_bytes + ma.output_size_in_bytes
                       + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
            source = "memory_analysis"
    except Exception:  # noqa: BLE001
        pass
    if peak is None:
        donated_bytes = sum(b.per_device_bytes for b in buffers
                            if b.role == "donated input")
        peak = (sum(groups.values()) + out_bytes_per_device - donated_bytes)
        peak = max(peak, sum(groups.values()))
    hbm_limit = None
    try:
        from pipegoose_tpu.utils.profiler import device_memory_stats

        dev = (mesh.devices.reshape(-1)[0] if mesh is not None
               else jax.devices()[0])
        lim = device_memory_stats(dev).get("bytes_limit")
        hbm_limit = int(lim) if lim else None
    except Exception:  # noqa: BLE001
        pass
    top = [
        {"path": b.path, "per_device_bytes": b.per_device_bytes, "role": b.role}
        for b in sorted(buffers, key=lambda b: -b.per_device_bytes)[:10]
    ]
    memory_report = MemoryReport(
        groups=groups, output_bytes=out_bytes_per_device, temp_bytes=temp,
        peak_bytes=int(peak), source=source, hbm_limit=hbm_limit, top=top,
        by_dtype=by_dtype,
    )
    cost_flops = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        f = dict(ca or {}).get("flops")
        cost_flops = float(f) if f is not None else None
    except Exception:  # noqa: BLE001 - cost analysis is advisory
        pass
    n_instr = len(hlo_instruction_names(hlo)) if hlo else None
    return DoctorReport(sharding=sharding_report, memory=memory_report,
                        cost_flops=cost_flops,
                        hlo_instructions=n_instr or None)


# -- wire-byte estimation --------------------------------------------------


def estimated_wire_bytes(
    collective: CollectiveInfo, mesh_axes: Dict[str, int]
) -> int:
    """Per-device TRANSMITTED bytes of one collective, normalized across
    the ops' differing payload conventions (``CollectiveInfo.bytes`` is
    the instruction's OUTPUT bytes: a reduce-scatter reports its shard
    while an all-to-all reports the full array, so raw payloads cannot
    be compared across op kinds). Ring-algorithm estimates over the
    group size ``g`` spanned by the collective's mesh axes:

    - ``all-gather``: output is the full array; each device sends its
      shard ``g-1`` times interleaved -> ``(g-1)/g x bytes``.
    - ``reduce-scatter``: output is the shard; each device forwards a
      shard per hop for ``g-1`` hops -> ``(g-1) x bytes``.
    - ``all-reduce``: reduce-scatter + all-gather ->
      ``2(g-1)/g x bytes`` of the full-array output.
    - ``all-to-all``: each device keeps 1/g of the (full-array) output
      -> ``(g-1)/g x bytes``.
    - ``collective-permute``: one hop, ``bytes``.

    The comm-engine tests use this to compare the fp32 reduce-scatter
    gradient phase against its quantized all-to-all replacement on
    equal footing (docs/comm.md)."""
    g = 1
    for ax in collective.mesh_axes or ():
        g *= int(mesh_axes.get(ax, 1))
    if g <= 1:
        return 0
    b = collective.bytes
    op = collective.op
    if op == "all-gather":
        return b * (g - 1) // g
    if op == "reduce-scatter":
        return b * (g - 1)
    if op == "all-reduce":
        return 2 * b * (g - 1) // g
    if op == "all-to-all":
        return b * (g - 1) // g
    return b  # collective-permute and friends: one hop of the payload


def wire_bytes_by_axes(report: Any) -> Dict[Tuple[str, ...], int]:
    """{mesh-axes tuple -> estimated per-device wire bytes} over a
    report's collective schedule — the planner's comm-time numerator,
    grouped by the fabric each axis group rides (ICI vs DCI). A
    collective whose replica groups resolved to no axis subset lands
    under the empty tuple ``()`` at its one-hop payload bytes
    (``estimated_wire_bytes`` needs a group size and would report 0),
    so unattributed traffic stays visible, never silently dropped."""
    sr = _sharding_of(report)
    out: Dict[Tuple[str, ...], int] = {}
    for c in sr.collectives:
        if c.mesh_axes:
            key = tuple(c.mesh_axes)
            nbytes = estimated_wire_bytes(c, sr.mesh_axes)
        else:
            key, nbytes = (), c.bytes
        out[key] = out.get(key, 0) + nbytes
    return out


def wire_bytes_by_op(
    report: Any, axes: Optional[Tuple[str, ...]] = None
) -> Dict[str, int]:
    """{op -> estimated per-device wire bytes} over a report's
    collective schedule, optionally restricted to collectives spanning
    exactly ``axes`` — e.g. ``wire_bytes_by_op(rep, ("data",))`` is the
    gradient/optimizer traffic of a hybrid step."""
    sr = _sharding_of(report)
    out: Dict[str, int] = {}
    for c in sr.collectives:
        if axes is not None and c.mesh_axes != tuple(axes):
            continue
        out[c.op] = out.get(c.op, 0) + estimated_wire_bytes(c, sr.mesh_axes)
    return out


# -- telemetry gauges ------------------------------------------------------


def set_doctor_gauges(report: Any, registry: Any = None) -> None:
    """Land the report's headline numbers as gauges next to MFU:
    ``doctor.replicated_bytes``, ``doctor.resharding_bytes``,
    ``doctor.intentional_bytes``, ``doctor.hbm_peak_bytes``."""
    from pipegoose_tpu.telemetry.registry import get_registry

    reg = registry if registry is not None else get_registry()
    sr = getattr(report, "sharding", report)
    reg.gauge("doctor.replicated_bytes").set(float(sr.replicated_bytes))
    reg.gauge("doctor.resharding_bytes").set(float(sr.resharding_bytes))
    reg.gauge("doctor.intentional_bytes").set(float(sr.intentional_bytes))
    mem = getattr(report, "memory", None)
    if mem is not None:
        reg.gauge("doctor.hbm_peak_bytes").set(float(mem.peak_bytes))


# -- regression guards -----------------------------------------------------


class ShardingRegressionError(AssertionError):
    """A compiled program's partitioning plan violates a doctor guard."""


def _sharding_of(report: Any) -> ShardingReport:
    return getattr(report, "sharding", report)


def assert_no_resharding(report: Any, allow: Sequence[str] = ()) -> None:
    """Fail if GSPMD inserted any collective the user didn't write.

    ``allow`` is a list of fnmatch patterns matched against the
    collective's op name (``all-gather``), its source primitive
    (``dot_general``), and ``op:source`` — e.g.
    ``allow=["all-reduce:dot_general"]`` tolerates the partial-sum
    reductions of sharded matmuls while still pinning gathers."""
    sr = _sharding_of(report)
    bad = [
        c for c in sr.resharding_collectives
        if not any(
            fnmatch(c.op, pat) or fnmatch(c.source or "", pat)
            or fnmatch(f"{c.op}:{c.source}", pat)
            for pat in allow
        )
    ]
    if bad:
        rows = "\n".join(
            f"  {c.op}  {_fmt_bytes(c.bytes)}  "
            f"axes={','.join(c.mesh_axes) if c.mesh_axes else '?'}  "
            f"source={c.source or '-'}"
            for c in bad
        )
        raise ShardingRegressionError(
            f"{len(bad)} unintended (partitioner-inserted) collective(s) "
            f"in the compiled program — a PartitionSpec no longer lines up "
            f"with the dataflow:\n{rows}"
        )


def assert_fully_sharded(
    report: Any, min_bytes: int = 1 << 20, allow: Sequence[str] = ()
) -> None:
    """Fail if any input buffer of at least ``min_bytes`` is fully
    replicated across a multi-device mesh. ``allow`` holds fnmatch
    patterns over buffer paths (e.g. ``["params/*/ln*", "batch*"]``)."""
    sr = _sharding_of(report)
    bad = [
        b for b in sr.buffers
        if b.role != "output" and b.replicated and b.global_bytes >= min_bytes
        and not any(fnmatch(b.path, pat) for pat in allow)
    ]
    if bad:
        rows = "\n".join(
            f"  {b.path}  {'x'.join(map(str, b.shape))}  "
            f"{_fmt_bytes(b.global_bytes)} replicated "
            f"(intended {b.intended or '-'}, actual {b.actual})"
            for b in bad
        )
        raise ShardingRegressionError(
            f"{len(bad)} buffer(s) >= {_fmt_bytes(min_bytes)} are fully "
            f"replicated across {sr.n_devices} devices:\n{rows}"
        )


def assert_matches_intended(report: Any, allow: Sequence[str] = ()) -> None:
    """Fail if any buffer's actual sharding differs from its intended
    PartitionSpec. ``allow``: fnmatch patterns over buffer paths."""
    sr = _sharding_of(report)
    bad = [b for b in sr.mismatches()
           if not any(fnmatch(b.path, pat) for pat in allow)]
    if bad:
        rows = "\n".join(
            f"  {b.path}: intended {b.intended} != actual {b.actual}"
            for b in bad
        )
        raise ShardingRegressionError(
            f"{len(bad)} sharding mismatch(es) between intended "
            f"PartitionSpecs and the compiled program:\n{rows}"
        )


def _json_default(o: Any):
    if hasattr(o, "to_json"):
        return o.to_json()
    raise TypeError(f"not JSON-serializable: {type(o)}")


def report_json_dumps(report: Any, **kwargs: Any) -> str:
    """``json.dumps`` for reports and dicts containing them."""
    return json.dumps(report, default=_json_default, **kwargs)
