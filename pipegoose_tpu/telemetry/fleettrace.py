"""Fleet-wide distributed request tracing (ISSUE 17).

PR 8's ``RequestTracer`` stops at the replica boundary: each engine
holds a uid-keyed timeline fragment, and a request that crosses
replicas — control-plane dispatch (PR 11), drain migration, crash
salvage (PR 14), disagg prefill->decode handoff (PR 12), kv-tier peer
pull (PR 15) — leaves one half-timeline per replica that nothing can
safely join (uids are replica-local, and the salvage path deliberately
REUSES them). So a fleet p99 TTFT breach cannot answer "which hop ate
the time, on which replica".

``FleetTracer`` closes the gap:

- ``ControlPlane.submit`` mints a monotonic ``trace_id`` onto the
  ``Request`` (``on_ingress``) — the one identity that survives every
  re-submission, because the same Request OBJECT flows through every
  hop.
- The plane marks each causal hand-over on the trace as it happens,
  in order: ``ingress`` (entered the tenant ledger), ``pass`` (first
  dispatch pass saw it), ``pop`` (DRR batch popped it), ``route``
  (router picked a replica), ``dispatch`` (replica scheduler accepted
  it — the mark's time IS the replica fragment's ``t_submit``, read
  back from the tracer rather than re-sampled, so the two domains
  share one float), ``leave`` (drain migration or crash salvage pulled
  it back off a replica — the fragment is SEALED at that instant), and
  terminally ``done`` / ``shed`` / ``lost``.
- Stitching is telescoping: consecutive marks bound plane-side hops
  (``ingress_s``, ``ledger_s``, ``route_s``, ``dispatch_s``,
  ``salvage_s``), and each dispatch->leave/done interval is covered by
  that leg's replica components, which PR 8's contract makes sum to
  exactly the interval. Everything shares ONE clock (the plane passes
  its ``now`` to every engine's ``start_run``, which re-points every
  tracer), so plane hops + per-replica attributions == fleet e2e to
  1e-6 by construction — including the crash-salvage and
  resubmit-from-prompt paths (property-swept in
  tests/serving/test_fleet_trace.py).

On top of the stitched store: ``fleet.attrib.{ingress,ledger,route,
dispatch,replica,salvage}_seconds`` histograms; a :class:`TailSampler`
retaining the top-K slowest completed traces per objective (ttft, e2e)
so the ``slo_burn`` and ``replica_failure`` black boxes embed EXEMPLAR
traces naming the dominant hop instead of bare ratios;
:func:`fleet_trace_events` (a merged Perfetto export — one process per
replica plus a plane hop track, flow arrows binding dispatch->admit,
handoff->transfer->admit, and pull source->destination); and the
``/debug/trace?uid=`` / ``/debug/tail`` OpsServer endpoints.

Host-side only — nothing here runs under jit. Disabled cost on the
plane's hot path is one attribute read + branch per hook site (the
plane holds ``None`` unless a tracer was passed).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from pipegoose_tpu.telemetry.registry import MetricsRegistry, get_registry

#: plane-side hop taxonomy (additive with the replica components):
#: ``ingress_s``  submitted into the tenant ledger -> first dispatch
#:                pass (the plane had not yet looked)
#: ``ledger_s``   first pass -> DRR pop (tenant fair-share wait)
#: ``route_s``    pop -> routing decision (includes requeue_front
#:                retries when no replica could admit)
#: ``dispatch_s`` routing decision -> replica scheduler accept
#: ``salvage_s``  left a replica (drain migration or crash salvage)
#:                -> re-routed (the re-dispatch gap)
PLANE_HOPS = ("ingress_s", "ledger_s", "route_s", "dispatch_s",
              "salvage_s")

_MARK_TO_HOP = {
    "ingress": "ingress_s",
    "pass": "ledger_s",
    "pop": "route_s",
    "route": "dispatch_s",
    "leave": "salvage_s",
}

#: tail objectives the sampler keys on (None values are skipped — a
#: shed request has no TTFT and must not pollute the tail)
OBJECTIVES = ("ttft", "e2e")


class _Trace:
    """One request's fleet-level record: the ordered plane-side mark
    list plus one leg per replica visit (sealed fragments ride on the
    legs)."""

    __slots__ = ("trace_id", "tenant", "t0", "marks", "legs", "uid",
                 "t_done", "e2e_s", "ttft_s", "finish_reason", "lost")

    def __init__(self, trace_id: int, t0: float,
                 tenant: Optional[str]) -> None:
        self.trace_id = trace_id
        self.tenant = tenant
        self.t0 = t0
        self.marks: List[tuple] = [("ingress", t0, None)]
        self.legs: List[Dict[str, Any]] = []
        self.uid: Optional[int] = None    # final replica-side uid
        self.t_done: Optional[float] = None
        self.e2e_s: Optional[float] = None
        self.ttft_s: Optional[float] = None
        self.finish_reason: Optional[str] = None
        self.lost = False

    def hops(self) -> Dict[str, float]:
        """Plane-side hop durations from the telescoping mark walk.
        Each dispatch->next-mark interval belongs to the replica
        fragment (its components sum to exactly that interval), so it
        is deliberately NOT booked here."""
        out = {h: 0.0 for h in PLANE_HOPS}
        for (kind, t, _arg), (_nk, nt, _na) in zip(self.marks,
                                                   self.marks[1:]):
            hop = _MARK_TO_HOP.get(kind)
            if hop is not None:
                out[hop] += max(nt - t, 0.0)
        return out

    def replica_s(self) -> float:
        return sum(sum(leg["components"].values()) for leg in self.legs
                   if leg.get("components"))

    def dominant(self) -> tuple:
        """(label, seconds) of the single largest hop — plane hops by
        name, replica components as ``<replica>:<component>`` — the
        exemplar's one-line verdict."""
        best, best_s = "ingress_s", 0.0
        for hop, s in self.hops().items():
            if s > best_s:
                best, best_s = hop, s
        for leg in self.legs:
            for comp, s in (leg.get("components") or {}).items():
                if s > best_s:
                    best, best_s = f"{leg['replica']}:{comp}", s
        return best, best_s

    def attribution(self) -> Dict[str, Any]:
        """JSON-able stitched record (the ``/debug/trace`` row)."""
        hops = self.hops()
        rep_s = self.replica_s()
        dom, dom_s = self.dominant()
        total = sum(hops.values()) + rep_s
        return {
            "trace_id": self.trace_id,
            "uid": self.uid,
            "tenant": self.tenant,
            "t0": self.t0,
            "t_done": self.t_done,
            "e2e_s": self.e2e_s,
            "ttft_s": self.ttft_s,
            "finish_reason": self.finish_reason,
            "lost": self.lost,
            "hops": hops,
            "replica_s": rep_s,
            "stitched_total_s": total,
            "legs": [
                {
                    "replica": leg["replica"],
                    "uid": leg.get("uid"),
                    "t_route": leg.get("t_route"),
                    "t_dispatch": leg.get("t_dispatch"),
                    "t_leave": leg.get("t_leave"),
                    "leave_reason": leg.get("leave_reason"),
                    "components": dict(leg.get("components") or {}),
                }
                for leg in self.legs
            ],
            "dominant_hop": dom,
            "dominant_s": dom_s,
            "dominant_share": (dom_s / total if total > 0 else 0.0),
        }


class TailSampler:
    """Top-K slowest completed fleet traces per objective. Bounded and
    cheap: insertion keeps a small sorted list per objective, so the
    black-box embed is O(K) regardless of traffic."""

    def __init__(self, k: int = 8):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.k = int(k)
        self._tails: Dict[str, List[tuple]] = {o: [] for o in OBJECTIVES}

    def offer(self, trace: "_Trace") -> None:
        for obj in OBJECTIVES:
            value = trace.ttft_s if obj == "ttft" else trace.e2e_s
            if value is None:
                continue
            tail = self._tails[obj]
            tail.append((float(value), trace))
            tail.sort(key=lambda pair: -pair[0])
            del tail[self.k:]

    def top(self, objective: str, n: Optional[int] = None) -> List[tuple]:
        if objective not in self._tails:
            raise ValueError(
                f"unknown objective {objective!r} (have {OBJECTIVES})"
            )
        tail = self._tails[objective]
        return tail[: (len(tail) if n is None else n)]

    def payload(self, top_n: Optional[int] = None) -> Dict[str, Any]:
        return {
            obj: [
                {"value_s": v, **tr.attribution()}
                for v, tr in self.top(obj, top_n)
            ]
            for obj in OBJECTIVES
        }


class FleetTracer:
    """Cross-replica trace stitcher (module docstring). The control
    plane drives the ``on_*`` hooks single-threaded from its run loop;
    the lock exists for the ops-server read path.

    ``registry``: the ``fleet.attrib.*`` histograms land here (default
    the global registry). ``keep_completed`` bounds the stitched-trace
    history the debug endpoints read; ``tail_k`` sizes the per-
    objective tail sampler.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 keep_completed: int = 256, tail_k: int = 8,
                 clock: Callable[[], float] = time.perf_counter):
        if keep_completed < 1:
            raise ValueError(
                f"keep_completed must be >= 1, got {keep_completed}"
            )
        self.registry = registry if registry is not None else get_registry()
        self.clock = clock
        self.tail = TailSampler(tail_k)
        self.active: Dict[int, _Trace] = {}
        self.completed: deque = deque(maxlen=int(keep_completed))
        self.tracers: Dict[str, Any] = {}     # replica name -> RequestTracer
        self._next_trace_id = 1
        self._uid_to_trace: Dict[int, int] = {}   # last dispatch wins
        self._awaiting_pass: set = set()
        self._wall_offset = time.time() - clock()
        self._lock = threading.Lock()
        reg = self.registry
        self._h_ingress = reg.histogram("fleet.attrib.ingress_seconds")
        self._h_ledger = reg.histogram("fleet.attrib.ledger_seconds")
        self._h_route = reg.histogram("fleet.attrib.route_seconds")
        self._h_dispatch = reg.histogram("fleet.attrib.dispatch_seconds")
        self._h_replica = reg.histogram("fleet.attrib.replica_seconds")
        self._h_salvage = reg.histogram("fleet.attrib.salvage_seconds")
        self._c_traces = reg.counter("fleet.attrib.traces_total")
        self._c_legs = reg.counter("fleet.attrib.legs_total")
        self._c_lost = reg.counter("fleet.attrib.lost_total")

    # -- plumbing ----------------------------------------------------------

    def set_clock(self, clock: Callable[[], float]) -> None:
        """Re-point at the plane's run clock (one time domain fleet-
        wide) and re-anchor the Perfetto wall offset."""
        if clock is self.clock:
            return
        self.clock = clock
        self._wall_offset = time.time() - clock()

    @property
    def wall_offset(self) -> float:
        return self._wall_offset

    def register_replica(self, name: str, tracer: Any) -> None:
        """Bind a replica's ``RequestTracer`` — dispatch marks read the
        fragment's ``t_submit`` from it and leave marks seal fragments
        out of it."""
        with self._lock:
            self.tracers[name] = tracer

    def reset(self) -> None:
        """Drop every stitched trace (active, completed ring, tail,
        uid index) but keep replica registrations and the trace-id
        sequence — the bench's traced arm resets between the compile
        warmup and the measured replay so warmup traces never land in
        the reported attribution."""
        with self._lock:
            self.active.clear()
            self.completed.clear()
            self.tail = TailSampler(self.tail.k)
            self._uid_to_trace.clear()
            self._awaiting_pass.clear()

    # -- plane hooks (ControlPlane drives these, in causal order) ----------

    def on_ingress(self, req: Any, t: float) -> int:
        """Mint the trace at the fleet front door. ``t`` must be the
        same float the plane stamps into ``req.t_submit`` — the trace's
        t0 IS the user-visible clock start."""
        with self._lock:
            trace_id = self._next_trace_id
            self._next_trace_id += 1
            req.trace_id = trace_id
            self.active[trace_id] = _Trace(
                trace_id, t, getattr(req, "tenant", None)
            )
            self._awaiting_pass.add(trace_id)
            return trace_id

    def on_dispatch_pass(self, t: float) -> None:
        """Top of a dispatch pass: every trace not yet popped closes
        its ingress hop here (first pass wins)."""
        with self._lock:
            for trace_id in self._awaiting_pass:
                trace = self.active.get(trace_id)
                if trace is not None:
                    trace.marks.append(("pass", t, None))
            self._awaiting_pass.clear()

    def on_ledger_pop(self, req: Any, t: float) -> None:
        """DRR batch popped the request off its tenant FIFO (first pop
        wins — a requeue_front retry books as route_s, the wait it
        actually is)."""
        trace = self.active.get(getattr(req, "trace_id", None))
        if trace is None:
            return
        with self._lock:
            if not any(kind == "pop" for kind, _t, _a in trace.marks):
                trace.marks.append(("pop", t, None))

    def on_routed(self, req: Any, t: float, replica: str) -> None:
        """Router picked a replica: opens a new leg (and, after a
        leave, closes the salvage hop)."""
        trace = self.active.get(getattr(req, "trace_id", None))
        if trace is None:
            return
        with self._lock:
            trace.marks.append(("route", t, replica))
            trace.legs.append({"replica": replica, "t_route": t})
            self._c_legs.inc()

    def on_dispatched(self, req: Any, replica: str) -> None:
        """Replica scheduler accepted the request: close the dispatch
        hop at the FRAGMENT's ``t_submit`` (read back from the replica
        tracer, never re-sampled — the mark and the fragment share one
        float, which is what makes the stitched sum exact)."""
        trace = self.active.get(getattr(req, "trace_id", None))
        if trace is None:
            return
        with self._lock:
            tracer = self.tracers.get(replica)
            t = None
            if tracer is not None:
                tl = tracer.in_flight.get(
                    (trace.trace_id, req.uid)
                )
                if tl is not None:
                    t = tl.t_submit
            if t is None and trace.legs:         # untraced replica
                t = trace.legs[-1]["t_route"]
            trace.marks.append(("dispatch", t, replica))
            if trace.legs and trace.legs[-1]["replica"] == replica:
                trace.legs[-1]["uid"] = req.uid
                trace.legs[-1]["t_dispatch"] = t
            trace.uid = req.uid
            self._uid_to_trace[req.uid] = trace.trace_id

    def on_leave(self, req: Any, replica: str, t: float,
                 reason: str) -> None:
        """The request left ``replica`` without finishing (drain
        migration or crash salvage): SEAL the fragment at ``t`` — its
        open phase (stall after a preempt, queue after a withdraw, or
        whatever a degraded harvest left) closes into its component, so
        the leg's components sum to exactly t_leave - t_dispatch."""
        trace = self.active.get(getattr(req, "trace_id", None))
        if trace is None:
            return
        with self._lock:
            components = None
            tracer = self.tracers.get(replica)
            if tracer is not None:
                tl = tracer.in_flight.pop(
                    (trace.trace_id, req.uid), None
                )
                if tl is not None:
                    tl.transition(None, t)
                    components = dict(tl.components)
                    for leg in reversed(trace.legs):
                        if (leg["replica"] == replica
                                and leg.get("components") is None):
                            leg["timeline"] = tl
                            break
            for leg in reversed(trace.legs):
                if (leg["replica"] == replica
                        and leg.get("components") is None):
                    leg["components"] = components or {}
                    leg["t_leave"] = t
                    leg["leave_reason"] = reason
                    break
            trace.marks.append(("leave", t, reason))

    def _final_fragment(self, trace: "_Trace") -> Optional[Any]:
        """The finishing leg's completed timeline, from its replica
        tracer's completed ring (``on_done``/``on_shed`` moved it there
        during the tick that finished the request)."""
        if not trace.legs:
            return None
        leg = trace.legs[-1]
        tracer = self.tracers.get(leg["replica"])
        if tracer is None:
            return None
        tl = tracer.in_flight.get((trace.trace_id, leg.get("uid")))
        if tl is not None:
            return tl
        for tl in reversed(tracer.completed):
            if (getattr(tl, "trace_id", None) == trace.trace_id
                    and tl.uid == leg.get("uid")):
                return tl
        return None

    def on_finished(self, req: Any, out: Any) -> None:
        """Terminal stitch: attach the final fragment, walk the marks
        into hops, observe the fleet histograms, offer the trace to the
        tail sampler."""
        with self._lock:
            trace = self.active.pop(getattr(req, "trace_id", None), None)
            if trace is None:
                return
            self._awaiting_pass.discard(trace.trace_id)
            tl = self._final_fragment(trace)
            if tl is not None and trace.legs:
                leg = trace.legs[-1]
                if leg.get("components") is None:
                    leg["components"] = dict(tl.components)
                    leg["timeline"] = tl
            t_done = getattr(tl, "t_done", None)
            if t_done is None:
                t_done = getattr(req, "t_done", None)
            if t_done is None:                # no fragment, no stamp
                t_done = self.clock()
            trace.t_done = t_done
            trace.marks.append(("done", t_done, None))
            trace.finish_reason = (getattr(out, "finish_reason", None)
                                   or getattr(req, "finish_reason", None))
            trace.e2e_s = getattr(out, "e2e_latency_s", None)
            if trace.e2e_s is None:
                trace.e2e_s = t_done - trace.t0
            trace.ttft_s = getattr(out, "ttft_s", None)
            self.completed.append(trace)
            if trace.finish_reason != "shed":
                self.tail.offer(trace)
            if len(self._uid_to_trace) > 8 * (self.completed.maxlen or 1):
                # bounded debug index: keep only uids whose trace is
                # still reachable (active, completed ring, or tail)
                live = {t.trace_id for t in self.active.values()}
                live.update(t.trace_id for t in self.completed)
                self._uid_to_trace = {
                    u: tid for u, tid in self._uid_to_trace.items()
                    if tid in live
                }
            hops = trace.hops()
        self._h_ingress.observe(hops["ingress_s"])
        self._h_ledger.observe(hops["ledger_s"])
        self._h_route.observe(hops["route_s"])
        self._h_dispatch.observe(hops["dispatch_s"])
        self._h_salvage.observe(hops["salvage_s"])
        self._h_replica.observe(trace.replica_s())
        self._c_traces.inc()

    def on_plane_shed(self, req: Any, t: float) -> None:
        """Ledger-level shed (never dispatched): the trace finalizes
        with its whole life in plane hops; the tail sampler never sees
        it (a shed has no serving latency to exemplify)."""
        with self._lock:
            trace = self.active.pop(getattr(req, "trace_id", None), None)
            if trace is None:
                return
            self._awaiting_pass.discard(trace.trace_id)
            trace.marks.append(("shed", t, None))
            trace.t_done = t
            trace.finish_reason = "shed"
            trace.e2e_s = t - trace.t0
            self.completed.append(trace)
        self._c_traces.inc()

    def on_lost(self, req: Any, t: float) -> None:
        """Salvage could not recover the request (the degraded path's
        terminal failure): the trace completes flagged ``lost`` so the
        black box can still show where it had gotten to."""
        with self._lock:
            trace = self.active.pop(getattr(req, "trace_id", None), None)
            if trace is None:
                return
            self._awaiting_pass.discard(trace.trace_id)
            trace.marks.append(("lost", t, None))
            trace.t_done = t
            trace.lost = True
            self.completed.append(trace)
        self._c_lost.inc()

    # -- views -------------------------------------------------------------

    def trace_json(self, uid: Optional[int] = None,
                   trace_id: Optional[int] = None) -> Optional[Dict]:
        """One stitched trace by uid (any leg's) or trace_id — the
        ``/debug/trace`` payload; None when unknown."""
        with self._lock:
            if trace_id is None and uid is not None:
                trace_id = self._uid_to_trace.get(uid)
                if trace_id is None:
                    for trace in list(self.completed) + list(
                            self.active.values()):
                        if any(leg.get("uid") == uid
                               for leg in trace.legs):
                            trace_id = trace.trace_id
                            break
            if trace_id is None:
                return None
            trace = self.active.get(trace_id)
            if trace is None:
                for done in reversed(self.completed):
                    if done.trace_id == trace_id:
                        trace = done
                        break
            return trace.attribution() if trace is not None else None

    def tail_payload(self, top_n: Optional[int] = None) -> Dict[str, Any]:
        """Top-K slowest stitched traces per objective (the
        ``/debug/tail`` payload)."""
        with self._lock:
            return self.tail.payload(top_n)

    def exemplar(self, objective: str = "e2e") -> Optional[Dict[str, Any]]:
        """THE exemplar for a black box: the single slowest completed
        trace on ``objective``, its dominant hop named up front."""
        with self._lock:
            top = self.tail.top(objective, 1)
            if not top:
                return None
            value, trace = top[0]
            row = trace.attribution()
            return {
                "objective": objective,
                "value_s": value,
                "dominant_hop": row["dominant_hop"],
                "dominant_s": row["dominant_s"],
                "dominant_share": row["dominant_share"],
                "trace": row,
            }

    def blackbox_payload(self, top_n: int = 3) -> Dict[str, Any]:
        """The flight-recorder embed: every still-active trace (a stuck
        dump must name where each in-flight request IS) plus the tail
        exemplars."""
        with self._lock:
            return {
                "active": [t.attribution() for t in self.active.values()],
                "tail": self.tail.payload(top_n),
            }

    def summary_payload(self, top_n: int = 3) -> Dict[str, Any]:
        """Per-hop p50/p99 over the completed ring + top-N exemplars
        per objective — the ``bench_fleet_trace.json`` block."""
        with self._lock:
            done = [t for t in self.completed if not t.lost]
            rows = [(t.hops(), t.replica_s()) for t in done]
            tail = self.tail.payload(top_n)
        per_hop: Dict[str, Dict[str, float]] = {}
        for hop in PLANE_HOPS + ("replica_s",):
            values = sorted(
                (h[hop] if hop != "replica_s" else rep)
                for h, rep in rows
            )
            if values:
                per_hop[hop] = {
                    "p50": values[int(0.50 * (len(values) - 1))],
                    "p99": values[int(0.99 * (len(values) - 1))],
                    "mean": sum(values) / len(values),
                }
            else:
                per_hop[hop] = {"p50": 0.0, "p99": 0.0, "mean": 0.0}
        return {
            "traces": len(rows),
            "per_hop": per_hop,
            "tail_exemplars": tail,
        }


# -- merged Perfetto export -------------------------------------------------


def fleet_trace_events(fleet: FleetTracer) -> List[dict]:
    """Render the whole fleet as one Perfetto trace: a plane process
    (one track of plane-side hop slices per trace), one process per
    registered replica (their full per-slot timelines, via
    :func:`request_trace_events` at disjoint pids), and flow arrows
    binding each dispatch slice to the fragment it started
    (dispatch->admit), each handoff's transfer_start->transfer_done,
    and each kv-tier pull's hinted source to its destination import."""
    from pipegoose_tpu.telemetry.chrometrace import (
        PID_PLANE,
        REPLICA_PID_BASE,
    )
    from pipegoose_tpu.telemetry.reqtrace import request_trace_events

    off = fleet.wall_offset
    hops_tid = 1

    def us(t: float) -> float:
        return (t + off) * 1e6

    events: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": PID_PLANE,
         "args": {"name": "control plane (fleet hops)"}},
        {"name": "thread_name", "ph": "M", "pid": PID_PLANE,
         "tid": hops_tid, "args": {"name": "plane hops"}},
    ]
    with fleet._lock:
        replica_pids = {name: REPLICA_PID_BASE + i
                        for i, name in enumerate(sorted(fleet.tracers))}
        traces = list(fleet.completed) + list(fleet.active.values())
        tracers = dict(fleet.tracers)
    _HOP_LABEL = {
        "ingress": "ingress", "pass": "ledger", "pop": "route",
        "route": "dispatch", "dispatch": "replica", "leave": "salvage",
    }
    flow_id = 0
    for trace in traces:
        tid = trace.trace_id
        leg_i = 0
        for (kind, t, arg), (_nk, nt, _na) in zip(trace.marks,
                                                  trace.marks[1:]):
            label = _HOP_LABEL.get(kind)
            if label is None or t is None or nt is None:
                continue
            events.append({
                "name": f"trace{tid} {label}",
                "cat": f"fleet.{label}", "ph": "X", "ts": us(t),
                "dur": max(nt - t, 0.0) * 1e6, "pid": PID_PLANE,
                "tid": hops_tid,
                "args": {"trace_id": tid, "replica": arg}
                if isinstance(arg, str) else {"trace_id": tid},
            })
            if kind == "dispatch" and isinstance(arg, str):
                # dispatch -> admit flow arrow into the replica process
                pid_to = replica_pids.get(arg)
                leg = (trace.legs[leg_i]
                       if leg_i < len(trace.legs) else None)
                leg_i += 1
                if pid_to is None:
                    continue
                flow_id += 1
                common = {"cat": "fleet.flow",
                          "name": f"trace{tid} dispatch",
                          "id": flow_id}
                events.append({**common, "ph": "s", "pid": PID_PLANE,
                               "tid": hops_tid, "ts": us(t)})
                events.append({**common, "ph": "f", "bp": "e",
                               "pid": pid_to, "tid": 1_000,
                               "ts": us(t)})
                if leg is not None:
                    tl = leg.get("timeline")
                    if tl is None:
                        tl = _completed_fragment(tracers.get(arg),
                                                 tid, leg.get("uid"))
                    if tl is not None:
                        events.extend(_fragment_flows(
                            tl, tid, pid_to, replica_pids, us,
                            start_id=flow_id * 1_000))
    for name, pid in replica_pids.items():
        tracer = tracers[name]
        events.extend(request_trace_events(
            tracer, pid=pid, process_name=f"replica {name}"
        ))
    return events


def _completed_fragment(tracer, trace_id, uid):
    if tracer is None:
        return None
    for tl in reversed(tracer.completed):
        if getattr(tl, "trace_id", None) == trace_id and tl.uid == uid:
            return tl
    return None


def _fragment_flows(tl, trace_id, pid, replica_pids, us, *,
                    start_id: int) -> List[dict]:
    """Flow arrows INSIDE one replica fragment: disagg/pull
    transfer_start -> transfer_done (handoff->transfer->admit), and
    pull_hint's named peer -> the destination's import completion
    (pull source -> destination)."""
    events: List[dict] = []
    t_start = None
    hint_peer = None
    t_hint = None
    fid = start_id
    for ev in tl.events:
        kind = ev.get("kind")
        if kind == "transfer_start":
            t_start = ev["t"]
        elif kind == "pull_hint":
            hint_peer, t_hint = ev.get("peer"), ev["t"]
        elif kind in ("transfer_done", "restore_done"):
            t = ev["t"]
            if t_start is not None and kind == "transfer_done":
                fid += 1
                common = {"cat": "fleet.flow",
                          "name": f"trace{trace_id} transfer",
                          "id": fid}
                events.append({**common, "ph": "s", "pid": pid,
                               "tid": 2_000, "ts": us(t_start)})
                events.append({**common, "ph": "f", "bp": "e",
                               "pid": pid, "tid": 2_000, "ts": us(t)})
                t_start = None
            if hint_peer is not None:
                pid_src = replica_pids.get(hint_peer)
                if pid_src is not None:
                    fid += 1
                    common = {"cat": "fleet.flow",
                              "name": f"trace{trace_id} pull "
                                      f"{hint_peer}",
                              "id": fid}
                    events.append({**common, "ph": "s", "pid": pid_src,
                                   "tid": 1_000, "ts": us(t_hint)})
                    events.append({**common, "ph": "f", "bp": "e",
                                   "pid": pid, "tid": 2_000,
                                   "ts": us(t)})
                hint_peer = None
    return events
