"""TelemetryCallback: per-step trainer metrics into the registry.

The trainer-side instrumentation lives in a callback (not the fit loop)
so the cost profile is opt-in: the loop itself only carries disabled-
registry spans. Adding this callback turns on:

- ``train.step_seconds`` histogram + ``train.tokens_per_s`` gauge per
  step (tokens from ``trainer.tokens_per_step``, same source the
  ``LossLoggerCallback`` uses);
- ``train.tokens_total`` / ``train.steps_total`` counters;
- ``train.mfu`` gauge — from an explicit ``flops_per_step`` or, with
  ``auto_cost=True``, a ONE-TIME lower+compile cost probe of the
  trainer's jitted step (``telemetry.derived.compiled_step_stats``: XLA
  flops + per-collective comm bytes). The probe compiles a second
  executable, so it is off by default — enable it for small models or
  pass ``flops_per_step`` measured offline for big ones. XLA reports
  the PER-DEVICE SPMD program's flops, and the peak table is per chip,
  so the resulting MFU is per-device (the number bench.py quotes);
- ``train.comm_bytes_per_step`` gauge from the same probe;
- ``train.hbm_utilization`` gauge every ``hbm_every`` steps (0 = off;
  CPU backends report no memory stats and the gauge stays unset);
- a ``"train.step"`` JSONL event every ``every`` steps.

**Timing semantics.** The trainer deliberately never blocks on the loss
(async dispatch); with ``fence=False`` (default) a step's measured wall
time is dispatch-to-dispatch, which in steady state equals device step
time (the dispatch queue backpressures) but mis-attributes the first
few steps. ``fence=True`` blocks on the loss every step — exact
per-step times, at the cost of draining the pipeline each step.
"""
from __future__ import annotations

import time
from typing import Any, Optional, Union

import jax

from pipegoose_tpu.telemetry import derived
from pipegoose_tpu.telemetry.exporters import (
    JSONLExporter,
    PrometheusTextfileExporter,
)
from pipegoose_tpu.telemetry.registry import MetricsRegistry, get_registry
from pipegoose_tpu.trainer.callback import Callback


class TelemetryCallback(Callback):
    order = 5  # after recovery (-10) / default (0) callbacks

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        jsonl: Union[str, JSONLExporter, None] = None,
        prom: Union[str, PrometheusTextfileExporter, None] = None,
        every: int = 1,
        flops_per_step: Optional[float] = None,
        auto_cost: bool = False,
        hbm_every: int = 0,
        fence: bool = False,
        device_kind: Optional[str] = None,
    ):
        self.registry = registry
        self.every = max(int(every), 1)
        self.flops_per_step = flops_per_step
        self.auto_cost = auto_cost
        self.hbm_every = int(hbm_every)
        self.fence = fence
        self.device_kind = device_kind
        self._jsonl = jsonl
        self._prom = prom
        self._t0: Optional[float] = None
        self._peak: Optional[float] = None
        self._cost_probed = flops_per_step is not None
        self._comm_bytes: Optional[int] = None

    # -- lifecycle ---------------------------------------------------------

    def on_fit_start(self, trainer: Any) -> None:
        reg = self.registry or get_registry()
        self.registry = reg
        reg.enable()  # adding the callback IS the opt-in
        if isinstance(self._jsonl, str):
            self._jsonl = JSONLExporter(self._jsonl, registry=reg)
        elif self._jsonl is not None:
            reg.attach(self._jsonl)
        if isinstance(self._prom, str):
            self._prom = PrometheusTextfileExporter(self._prom)
        if self._peak is None:
            self._peak = derived.peak_flops_for(self.device_kind)
        reg.event("train.fit_start")

    def on_step_start(self, trainer: Any, step: int) -> None:
        self._t0 = time.perf_counter()

    def on_step_end(self, trainer: Any, step: int, loss: Any) -> None:
        if self._t0 is None:
            return
        if self.fence:
            jax.block_until_ready(loss)
        dt = time.perf_counter() - self._t0
        reg = self.registry
        reg.histogram("train.step_seconds").observe(dt)
        reg.counter("train.steps_total").inc()
        tokens = getattr(trainer, "tokens_per_step", 0)
        tps = derived.tokens_per_second(tokens, dt)
        if tokens:
            reg.counter("train.tokens_total").inc(tokens)
            reg.gauge("train.tokens_per_s").set(tps)
        if not self._cost_probed and self.auto_cost:
            self._probe_cost(trainer)
        step_mfu = None
        if self.flops_per_step:
            step_mfu = derived.mfu(self.flops_per_step, dt, peak=self._peak)
            reg.gauge("train.mfu").set(step_mfu)
        if self.hbm_every and step % self.hbm_every == 0:
            hbm = derived.hbm_utilization()
            if "utilization" in hbm:
                reg.gauge("train.hbm_utilization").set(hbm["utilization"])
            if "bytes_in_use" in hbm:
                reg.gauge("train.hbm_bytes_in_use").set(hbm["bytes_in_use"])
        if step % self.every == 0:
            ev = {"step": step, "dur_s": dt, "tokens_per_s": tps}
            if step_mfu is not None:
                ev["mfu"] = step_mfu
            reg.event("train.step", **ev)

    def on_fit_end(self, trainer: Any) -> None:
        reg = self.registry
        if reg is None:
            return
        reg.event("train.fit_end")
        if isinstance(self._jsonl, JSONLExporter):
            self._jsonl.export_snapshot(reg)
        if isinstance(self._prom, PrometheusTextfileExporter):
            self._prom.write(reg)

    # -- cost probe --------------------------------------------------------

    def _probe_cost(self, trainer: Any) -> None:
        """One lower+compile of the trainer's jitted step at the live
        arg shapes -> flops + comm bytes. Failure (exotic step fn, no
        batch seen yet) downgrades to 'no MFU gauge', never breaks the
        fit loop."""
        self._cost_probed = True  # one attempt, success or not
        batch = getattr(trainer, "last_batch", None)
        step_fn = getattr(trainer, "_step_fn", None)
        if batch is None or step_fn is None:
            return
        try:
            args = (trainer.params, trainer.opt_state, batch)
            if getattr(trainer, "with_rng", False):
                args = args + (jax.random.PRNGKey(0),)
            stats = derived.compiled_step_stats(step_fn, *args)
        except Exception:  # noqa: BLE001
            return
        if stats["flops"]:
            self.flops_per_step = stats["flops"]
            self.registry.gauge("train.flops_per_step").set(stats["flops"])
        self._comm_bytes = stats["comm_bytes"]
        self.registry.gauge("train.comm_bytes_per_step").set(
            stats["comm_bytes"]
        )
        self.registry.event("train.cost_probe", **{
            "flops": stats["flops"],
            "bytes_accessed": stats["bytes_accessed"],
            "comm_bytes": stats["comm_bytes"],
            "comm_by_op": stats["comm_by_op"],
        })
