"""Perfetto / Chrome ``trace_event`` timeline export.

The PR-2 span stream (telemetry/spans.py) is a flat JSONL of named
durations; this module renders it as the Trace Event JSON that
https://ui.perfetto.dev and chrome://tracing open natively — every
``span`` event becomes a complete ("ph": "X") slice on its thread's
row, so a ``trainer.fit`` run reads as an actual timeline (data pulls
interleaved with step dispatches, serving prefills vs. decode steps)
instead of quantile tables.

Because the pipeline schedule is *compiled into* the program (one
``lax.scan`` clock per ``GPipeScheduler`` cycle — nn/pipeline_parallel/
pipeline.py), its per-stage activity cannot be host-traced; instead
:func:`pipeline_trace_events` renders the scheduler's deterministic
clock timetable as one row per stage (the torchgpipe-style
microbatch/clock diagram), and :func:`register_pipeline_gauges` derives
the **bubble fraction** — the idle share of the stage-clock grid that
upper-bounds pipeline efficiency — as a gauge next to the PR-2 MFU
gauge, with the measured ``span.train.step.seconds`` turning the
fraction into lost seconds.

Format notes (the subset Perfetto accepts strictly): timestamps and
durations are MICROSECONDS; ``pid``/``tid`` are ints, named via
``"M"``-phase ``process_name``/``thread_name`` metadata events; the
file is one JSON object ``{"traceEvents": [...]}``.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

from pipegoose_tpu.telemetry.registry import MetricsRegistry, get_registry
from pipegoose_tpu.utils.procindex import RankFilter as _RankFilter

# fixed pid per row family so multiple writers agree
PID_HOST = 1        # host-side spans (trainer/serving/decode driver)
PID_PIPELINE = 2    # theoretical pipeline clock timeline
PID_REQUESTS = 3    # per-request serving timelines (telemetry/reqtrace.py)
PID_FLEET = 4       # control-plane router decisions (one track per replica)
PID_PLANE = 5       # control-plane hop slices (telemetry/fleettrace.py)
PID_MEMORY = 6      # memory-ledger counter tracks (telemetry/memledger.py)
PID_GOODPUT = 7     # goodput state bands + incidents (telemetry/goodput.py)
# multi-replica request timelines get one process EACH, allocated from
# here up (the first tracer keeps PID_REQUESTS for backward compat)
REPLICA_PID_BASE = 10


def span_events_to_trace(
    events: Iterable[dict], *, pid: int = PID_HOST
) -> List[dict]:
    """``"span"`` event dicts (JSONL schema: ``ts`` = exit wall-clock
    seconds, ``dur_s``) -> complete trace events. Non-span events pass
    through as instant events so step markers stay visible."""
    out: List[dict] = []
    for ev in events:
        kind = ev.get("kind")
        extra = {
            k: v for k, v in ev.items()
            if k not in ("kind", "span", "ts", "dur_s", "tid")
        }
        if kind == "span":
            dur = float(ev.get("dur_s", 0.0))
            end = float(ev.get("ts", 0.0))
            out.append({
                "name": ev.get("span", "?"),
                "cat": "span",
                "ph": "X",
                "ts": (end - dur) * 1e6,
                "dur": dur * 1e6,
                "pid": pid,
                "tid": int(ev.get("tid", 0)),
                "args": extra,
            })
        elif kind is not None:
            out.append({
                "name": str(kind),
                "cat": "event",
                "ph": "i",
                "s": "t",  # thread-scoped instant
                "ts": float(ev.get("ts", 0.0)) * 1e6,
                "pid": pid,
                "tid": int(ev.get("tid", 0)),
                "args": extra,
            })
    return out


def pipeline_trace_events(
    scheduler: Any,
    *,
    clock_s: float = 1e-3,
    t0_s: float = 0.0,
    include_backward: bool = True,
    pid: int = PID_PIPELINE,
) -> List[dict]:
    """Render a ``GPipeScheduler`` (or subclass) clock timetable as one
    trace row PER PIPELINE STAGE: task (m, p) becomes an ``F{m}`` slice
    at clock ``m + p`` on stage p's row, backwards follow as ``B{m}``
    after the forward clocks — the microbatch/clock diagram torchgpipe
    §3.2.1 draws, loadable next to the measured spans. ``clock_s`` is
    the nominal seconds per clock (pure visualization scale).

    A ``OneFOneBScheduler`` renders from its ACTUAL interleaved
    timetable (``tables()``): F and B slices of different microbatches
    share the steady-state clocks instead of the GPipe two-phase
    layout."""
    events: List[dict] = [
        {
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": "pipeline (theoretical clock timeline)"},
        }
    ]
    for p in range(scheduler.n_partitions):
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": p,
            "args": {"name": f"stage {p}"},
        })

    def emit_slice(label, m, p, clock):
        events.append({
            "name": f"{label}{m}",
            "cat": f"pipeline.{'forward' if label == 'F' else 'backward'}",
            "ph": "X",
            "ts": (t0_s + clock * clock_s) * 1e6,
            "dur": clock_s * 1e6,
            "pid": pid,
            "tid": p,
            "args": {"microbatch": m, "stage": p, "clock": clock},
        })

    from pipegoose_tpu.nn.pipeline_parallel.scheduler import OneFOneBScheduler

    if isinstance(scheduler, OneFOneBScheduler):
        fwd, bwd, _, n_clock = scheduler.tables()
        for c in range(n_clock):
            for p in range(scheduler.n_partitions):
                if fwd[c][p] >= 0:
                    emit_slice("F", int(fwd[c][p]), p, c)
                if include_backward and bwd[c][p] >= 0:
                    emit_slice("B", int(bwd[c][p]), p, c)
        return events

    def emit(tasks_by_clock, label, clock_offset):
        for c, tasks in enumerate(tasks_by_clock):
            for t in tasks:
                events.append({
                    "name": f"{label}{t.microbatch_idx}",
                    "cat": f"pipeline.{'forward' if label == 'F' else 'backward'}",
                    "ph": "X",
                    "ts": (t0_s + (clock_offset + c) * clock_s) * 1e6,
                    "dur": clock_s * 1e6,
                    "pid": pid,
                    "tid": t.partition_idx,
                    "args": {
                        "microbatch": t.microbatch_idx,
                        "stage": t.partition_idx,
                        "clock": clock_offset + c,
                    },
                })

    emit(scheduler.get_forward_schedules(), "F", 0)
    if include_backward:
        emit(
            scheduler.get_backward_schedules(), "B",
            scheduler.total_forward_clocks,
        )
    return events


def register_pipeline_gauges(
    scheduler: Any,
    registry: Optional[MetricsRegistry] = None,
    step_seconds: Optional[float] = None,
) -> float:
    """Set ``pipeline.bubble_fraction`` (theoretical idle share of the
    scheduler's own clock timeline — ``(P-1)/(M+P-1)`` for GPipe, the
    measured-timetable share for ``OneFOneBScheduler``) alongside the
    PR-2 ``train.mfu`` gauge; with a measured step time (e.g. the
    ``span.train.step.seconds`` p50) also ``pipeline.bubble_seconds`` —
    the wall-clock that fraction costs per step. Returns the fraction."""
    reg = registry if registry is not None else get_registry()
    frac = scheduler.bubble_fraction
    reg.gauge(
        "pipeline.bubble_fraction",
        help="theoretical pipeline idle fraction of the scheduler's "
             "clock timetable",
    ).set(frac)
    reg.gauge("pipeline.n_microbatches").set(float(scheduler.n_microbatches))
    reg.gauge("pipeline.n_partitions").set(float(scheduler.n_partitions))
    if step_seconds is not None:
        reg.gauge(
            "pipeline.bubble_seconds",
            help="measured step seconds x theoretical bubble fraction",
        ).set(frac * step_seconds)
    return frac


def router_trace_events(decisions: Iterable[dict], *,
                        pid: int = PID_FLEET,
                        wall_offset: float = 0.0) -> List[dict]:
    """Render a control-plane router's decision log
    (``Router.decisions`` — serving/control_plane/router.py) as
    Perfetto rows: ONE TRACK PER REPLICA, an instant marker per routing
    decision carrying the tenant, the matched cached-prefix tokens, and
    the candidate count — loadable next to the per-slot request
    timelines, so "why did this request land here" sits one track above
    "what happened to it". ``wall_offset`` aligns the decisions' clock
    domain with the span rows (pass the owning tracer's
    ``wall_offset`` when combining)."""
    decisions = list(decisions)
    replicas: List[str] = []
    for d in decisions:
        if d["replica"] not in replicas:
            replicas.append(d["replica"])
    events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid,
        "args": {"name": "serving fleet (router decisions)"},
    }]
    for tid, name in enumerate(replicas):
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": name},
        })
    for d in decisions:
        tenant = d.get("tenant") or "default"
        events.append({
            "name": f"route {tenant}"
                    + (f" +{d['matched_tokens']}tok"
                       if d.get("matched_tokens") else ""),
            "cat": "router.decision", "ph": "i", "s": "t",
            "ts": (d["t"] + wall_offset) * 1e6,
            "pid": pid, "tid": replicas.index(d["replica"]),
            "args": {k: v for k, v in d.items() if k != "t"},
        })
    return events


def memory_trace_events(ledger: Any, *,
                        pid: int = PID_MEMORY,
                        wall_offset: float = 0.0) -> List[dict]:
    """Render a ``MemoryLedger``'s per-tick occupancy samples
    (telemetry/memledger.py) as Perfetto COUNTER tracks: one stacked
    ``kv bytes`` counter with the per-owner-class split (request /
    staged / cow / cached / reserved / free), plus scalar tracks for
    fragmentation, the steps-to-exhaustion forecast, and — when a host
    tier is bound — host-DRAM resident bytes. Loadable next to the
    request timelines, so "who owned the pool when this request
    queued" is one track group away. Samples without a wall clock
    (``t is None`` — replay without a ``now``) fall back to 1ms per
    engine tick."""
    samples = list(ledger.samples)
    bpp = int(getattr(ledger, "bytes_per_page", 1))
    events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid,
        "args": {"name": "serving memory (ledger counter tracks)"},
    }]
    for s in samples:
        t = s.get("t")
        ts = ((t + wall_offset) * 1e6 if t is not None
              else (wall_offset * 1e6 + s.get("step", 0) * 1e3))
        events.append({
            "name": "kv bytes", "cat": "memory", "ph": "C",
            "ts": ts, "pid": pid,
            "args": {
                "request": s.get("request", 0) * bpp,
                "staged": s.get("staged", 0) * bpp,
                "cow": s.get("cow", 0) * bpp,
                "cached": s.get("cached", 0) * bpp,
                "reserved": s.get("reserved_unmaterialized", 0) * bpp,
                "free": s.get("free", 0) * bpp,
            },
        })
        events.append({
            "name": "fragmentation", "cat": "memory", "ph": "C",
            "ts": ts, "pid": pid,
            "args": {"fragmentation": s.get("fragmentation", 0.0)},
        })
        ste = s.get("steps_to_exhaustion")
        if ste is not None:
            events.append({
                "name": "steps_to_exhaustion", "cat": "memory",
                "ph": "C", "ts": ts, "pid": pid,
                "args": {"steps": ste},
            })
        if "host_tier_bytes" in s:
            events.append({
                "name": "host tier bytes", "cat": "memory", "ph": "C",
                "ts": ts, "pid": pid,
                "args": {"resident": s["host_tier_bytes"]},
            })
    return events


def goodput_trace_events(ledger: Any, *,
                         pid: int = PID_GOODPUT,
                         wall_offset: float = 0.0) -> List[dict]:
    """Render a ``GoodputLedger``'s per-replica state bands
    (telemetry/goodput.py) as Perfetto rows: ONE TRACK PER REPLICA, one
    colored slice per class episode (the color keys off the slice name,
    so productive / stall / failed_quarantine bands read apart at a
    glance), plus an instant marker at every incident's detection
    (named by kind, args carrying MTTR + capacity-gap integral).
    Loadable next to the request timelines and router decisions, so
    "the fleet lost this replica HERE" lines up with the requests that
    ate the latency. ``wall_offset`` aligns the clock domain with the
    span rows (pass the owning tracer's ``wall_offset``)."""
    events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid,
        "args": {"name": "fleet goodput (state bands + incidents)"},
    }]
    names = sorted(ledger.replicas)
    for tid, name in enumerate(names):
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": name},
        })
        acct = ledger.replicas[name]
        for ep in acct.episodes:
            dur = max(ep["t1"] - ep["t0"], 0.0)
            events.append({
                "name": ep["class"], "cat": "goodput.state", "ph": "X",
                "ts": (ep["t0"] + wall_offset) * 1e6, "dur": dur * 1e6,
                "pid": pid, "tid": tid,
                "args": {"state": ep["state"], "ticks": ep["ticks"],
                         "tick0": ep["tick0"], "tick1": ep["tick1"]},
            })
    for inc in ledger.incidents:
        tid = names.index(inc.replica) if inc.replica in names else 0
        events.append({
            "name": f"incident {inc.kind}",
            "cat": "goodput.incident", "ph": "i", "s": "t",
            "ts": (inc.t_detected + wall_offset) * 1e6,
            "pid": pid, "tid": tid,
            "args": {
                "id": inc.id,
                "reason": inc.reason,
                "detection_latency_ticks": inc.detection_latency_ticks,
                "mttr_s": inc.mttr_s,
                "resolved_by": inc.resolved_by,
                "capacity_gap_integral_s": round(
                    inc.capacity_gap_integral_s, 9),
            },
        })
    return events


class ChromeTraceExporter:
    """Registry sink accumulating span/step events as trace events;
    ``write()`` emits one Perfetto-loadable JSON file atomically.

    Same conventions as ``JSONLExporter``: callable (the sink
    protocol), attaches itself when constructed with ``registry=``,
    rank-0 filtered file writes. Events are buffered in memory (one
    small dict per span — bound a long run with ``max_events``, which
    keeps the NEWEST events) and annotated with the capturing thread so
    serving-engine and trainer rows separate naturally. Rows beyond the
    live capture (the pipeline clock timeline) are added with
    :meth:`add_events` / :meth:`add_pipeline_timeline`."""

    def __init__(
        self,
        path: str,
        registry: Optional[MetricsRegistry] = None,
        rank: Optional[int] = 0,
        max_events: int = 100_000,
    ):
        self.path = path
        self._rank_ok = _RankFilter(rank)
        self._lock = threading.Lock()
        # deque(maxlen): O(1) append-with-drop — a list would memmove
        # the whole buffer per event once the cap is hit, on the
        # instrumented hot path of exactly the longest runs
        self.max_events = int(max_events)
        self._events: deque = deque(maxlen=self.max_events)
        self._extra: List[dict] = []        # pre-rendered trace events
        self._tids: Dict[int, int] = {}     # thread ident -> compact tid
        # tracer identity -> pid for add_request_timelines: the FIRST
        # tracer keeps the historical PID_REQUESTS; every further
        # tracer (a second replica sharing this exporter) gets its own
        # process from REPLICA_PID_BASE up, so multi-replica exports
        # never interleave slot tracks on one pid
        self._request_pids: Dict[int, int] = {}
        self._dropped = 0
        self._registry = registry
        if registry is not None:
            registry.attach(self)

    def __call__(self, event: dict) -> None:
        # rank-filter at CAPTURE, not just at write: non-emitting ranks
        # must not spend memory/copies buffering events they will never
        # render (JSONLExporter drops per-event the same way)
        if not self._rank_ok():
            return
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.setdefault(ident, len(self._tids))
            ev = dict(event)
            ev["tid"] = tid
            if len(self._events) == self.max_events:
                self._dropped += 1  # deque drops the oldest on append
            self._events.append(ev)

    def add_events(self, trace_events: Iterable[dict]) -> None:
        with self._lock:
            self._extra.extend(trace_events)

    def add_pipeline_timeline(self, scheduler: Any, **kwargs: Any) -> None:
        """Attach a ``GPipeScheduler`` clock timeline's rows (see
        :func:`pipeline_trace_events`)."""
        self.add_events(pipeline_trace_events(scheduler, **kwargs))

    def add_request_timelines(self, tracer: Any, **kwargs: Any) -> None:
        """Attach a ``RequestTracer``'s per-slot request timelines (see
        ``telemetry.reqtrace.request_trace_events``) as their own
        process group next to the host spans and pipeline rows. Each
        DISTINCT tracer gets its own pid (named after ``tracer.name``
        when set), so a fleet's replicas render as separate processes
        instead of interleaving slot tracks; pass ``pid=`` to pin one
        explicitly."""
        from pipegoose_tpu.telemetry.reqtrace import request_trace_events

        if "pid" not in kwargs:
            with self._lock:
                pid = self._request_pids.get(id(tracer))
                if pid is None:
                    pid = (PID_REQUESTS if not self._request_pids
                           else REPLICA_PID_BASE
                           + len(self._request_pids) - 1)
                    self._request_pids[id(tracer)] = pid
            kwargs["pid"] = pid
        self.add_events(request_trace_events(tracer, **kwargs))

    def add_fleet_trace(self, fleet: Any) -> None:
        """Attach a ``FleetTracer``'s merged cross-replica export (see
        ``telemetry.fleettrace.fleet_trace_events``): the plane hop
        track plus one process per registered replica with flow arrows
        binding dispatch->admit, handoff transfers, and peer pulls."""
        from pipegoose_tpu.telemetry.fleettrace import fleet_trace_events

        self.add_events(fleet_trace_events(fleet))

    def add_router_decisions(self, decisions: Iterable[dict],
                             **kwargs: Any) -> None:
        """Attach a control-plane router's decision log (see
        :func:`router_trace_events`) — one track per replica in the
        fleet process group."""
        self.add_events(router_trace_events(decisions, **kwargs))

    def add_goodput(self, ledger: Any, **kwargs: Any) -> None:
        """Attach a ``GoodputLedger``'s per-replica state bands and
        incident markers (see :func:`goodput_trace_events`)."""
        self.add_events(goodput_trace_events(ledger, **kwargs))

    def write(self, path: Optional[str] = None) -> Optional[str]:
        """Render and atomically write the trace JSON; returns the path
        (None when rank-filtered out)."""
        if not self._rank_ok():
            return None
        path = path or self.path
        with self._lock:
            events = list(self._events)
            extra = list(self._extra)
            tids = dict(self._tids)
            dropped = self._dropped
        trace: List[dict] = [
            {
                "name": "process_name", "ph": "M", "pid": PID_HOST,
                "args": {"name": "pipegoose_tpu host spans"},
            }
        ]
        for ident, tid in tids.items():
            trace.append({
                "name": "thread_name", "ph": "M", "pid": PID_HOST,
                "tid": tid, "args": {"name": f"thread {ident}"},
            })
        trace.extend(span_events_to_trace(events))
        trace.extend(extra)
        payload = {
            "traceEvents": trace,
            "displayTimeUnit": "ms",
            "otherData": {
                "exporter": "pipegoose_tpu.telemetry.chrometrace",
                "created_ts": time.time(),
                "dropped_events": dropped,
            },
        }
        from pipegoose_tpu.telemetry.exporters import (
            atomic_write_text,
            safe_json_dumps,
        )

        atomic_write_text(path, safe_json_dumps(payload), suffix=".trace.tmp")
        return path

    def close(self) -> None:
        if self._registry is not None:
            self._registry.detach(self)
            self._registry = None

    def __enter__(self) -> "ChromeTraceExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def trace_from_jsonl(jsonl_path: str, trace_path: str) -> str:
    """Offline conversion: a ``JSONLExporter`` stream (e.g. a run's
    ``telemetry.jsonl`` artifact) -> Perfetto trace JSON. Snapshot
    lines are skipped; malformed lines are ignored (a truncated last
    line from a killed run must not block the post-mortem)."""
    events: List[dict] = []
    with open(jsonl_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            if ev.get("kind") == "snapshot":
                continue
            events.append(ev)
    exp = ChromeTraceExporter(trace_path, rank=None)
    for ev in events:
        exp(ev)
    out = exp.write()
    assert out is not None  # rank=None never filters
    return out
