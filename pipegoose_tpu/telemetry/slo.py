"""Declarative SLOs with multi-window burn-rate alerting.

A dashboard full of histograms still leaves the operator to decide
"is this bad *enough* to act?". This module makes that decision
declarative: an :class:`SLOTarget` names a registry metric, a
per-observation objective (e.g. TTFT <= 250 ms), and the fraction of
observations that must meet it (e.g. 99%); the :class:`SLOMonitor`
evaluates every target over a FAST and a SLOW trailing window and
alerts on the **error-budget burn rate** — the SRE-workbook recipe:

    burn = (bad fraction over the window) / (1 - target)

A burn of 1.0 spends the budget exactly at the sustainable rate;
``burn_threshold`` (default 2.0) pages when the budget burns faster.
Requiring BOTH windows to breach gives the classic multi-window
behavior: the fast window catches a fresh regression quickly, the slow
window keeps a brief blip from paging, and recovery resets the fast
window first.

Mechanics: registry histograms are CUMULATIVE (bucket counts since
process start), so the monitor keeps a bounded ring of
``(t, bad, total)`` samples per target — one appended per
:meth:`~SLOMonitor.evaluate` — and windowed rates are deltas against
the newest sample at least ``window`` old. "Bad" for a latency target
is conservative: an observation is good only when it lands in a bucket
whose upper bound is <= the objective, so an objective between bucket
bounds over-counts bad, never under-counts. ``kind="ratio"`` targets
two counters instead (bad / total — e.g. an error rate).

When a target starts breaching, the monitor raises a STRUCTURED
``slo_burn`` trigger through the PR 3 flight-recorder path
(``FlightRecorder.fire_trigger``): an atomic black-box dump whose ring
holds the last N steps *before* the burn, once per breach episode.
Burn rates are also exported as ``slo.<name>.burn_fast`` /
``burn_slow`` gauges and the overall state feeds the ops endpoint's
``/healthz`` (telemetry/opsserver.py).

Host-side only; evaluation is pull-driven (the ops endpoint evaluates
on ``/healthz``, tests call :meth:`~SLOMonitor.evaluate` directly), so
there is no background thread to leak and a disabled registry costs
nothing.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from pipegoose_tpu.telemetry.registry import (
    Counter,
    Histogram,
    MetricsRegistry,
    get_registry,
)


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """One declarative objective over a registry metric.

    ``kind="latency"``: ``metric`` is a histogram; an observation is
    good when <= ``objective`` (seconds). ``kind="ratio"``:
    ``bad_metric``/``total_metric`` are counters (objective unused).
    ``target`` is the required good fraction (0.99 = 1% error budget).
    """

    name: str
    metric: str = ""
    objective: float = 0.0
    target: float = 0.99
    kind: str = "latency"          # "latency" | "ratio"
    bad_metric: Optional[str] = None
    total_metric: Optional[str] = None

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"SLO {self.name!r}: target must be in (0, 1), "
                f"got {self.target}"
            )
        if self.kind == "latency":
            if not self.metric:
                raise ValueError(f"SLO {self.name!r}: latency kind needs "
                                 f"a histogram metric name")
        elif self.kind == "ratio":
            if not (self.bad_metric and self.total_metric):
                raise ValueError(f"SLO {self.name!r}: ratio kind needs "
                                 f"bad_metric and total_metric")
        else:
            raise ValueError(
                f"SLO {self.name!r}: unknown kind {self.kind!r} "
                f"(expected 'latency' or 'ratio')"
            )


class _TargetState:
    __slots__ = ("samples", "breaching", "alerts", "last")

    def __init__(self, history: int):
        self.samples: deque = deque(maxlen=history)  # (t, bad, total)
        self.breaching = False
        self.alerts = 0
        self.last: Dict[str, Any] = {}


class SLOMonitor:
    """Evaluate :class:`SLOTarget` burn rates over fast+slow windows.

    ``recorder``: optional ``telemetry.FlightRecorder`` — a breach
    transition fires a structured ``slo_burn`` trigger (black-box dump)
    through it. ``exemplars``: optional zero-arg callable (typically
    ``FleetTracer.exemplar``) whose result is embedded in the trigger
    details as ``exemplar`` — the slowest stitched fleet trace naming
    the dominant hop behind the burn. ``clock`` is injectable for tests
    (defaults to ``time.monotonic``; only deltas are used).
    """

    def __init__(
        self,
        targets: Sequence[SLOTarget],
        registry: Optional[MetricsRegistry] = None,
        *,
        fast_window_s: float = 60.0,
        slow_window_s: float = 600.0,
        burn_threshold: float = 2.0,
        recorder: Optional[Any] = None,
        clock: Callable[[], float] = time.monotonic,
        history: int = 1024,
        exemplars: Optional[Callable[[], Any]] = None,
    ):
        if not targets:
            raise ValueError("SLOMonitor needs at least one target")
        if fast_window_s <= 0 or slow_window_s <= fast_window_s:
            raise ValueError(
                f"windows must satisfy 0 < fast ({fast_window_s}) < slow "
                f"({slow_window_s})"
            )
        if burn_threshold <= 0:
            raise ValueError(
                f"burn_threshold must be > 0, got {burn_threshold}"
            )
        names = [t.name for t in targets]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO target names: {names}")
        self.targets = list(targets)
        self.registry = registry if registry is not None else get_registry()
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.recorder = recorder
        self.clock = clock
        # zero-arg provider of a tail exemplar (typically
        # FleetTracer.exemplar): a breach black box then NAMES the
        # slowest stitched fleet trace and its dominant hop, so the
        # page says "replica_1:stall_s" instead of just "e2e burning"
        self.exemplars = exemplars
        self._state = {t.name: _TargetState(history) for t in self.targets}
        self._evals = 0

    # -- cumulative (bad, total) reads -------------------------------------

    def _read(self, target: SLOTarget) -> Tuple[float, float]:
        metrics = self.registry.metrics()
        if target.kind == "ratio":
            bad = metrics.get(target.bad_metric)
            tot = metrics.get(target.total_metric)
            bad_v = bad.value if isinstance(bad, Counter) else 0.0
            tot_v = tot.value if isinstance(tot, Counter) else 0.0
            return float(bad_v), float(tot_v)
        h = metrics.get(target.metric)
        if not isinstance(h, Histogram):
            return 0.0, 0.0  # metric not observed yet: no data, no burn
        with h._lock:  # consistent counts vs a concurrent observe()
            counts = list(h._counts)
            total = h._count
        good = sum(
            c for b, c in zip(h.buckets, counts) if b <= target.objective
        )
        return float(total - good), float(total)

    # -- evaluation --------------------------------------------------------

    @staticmethod
    def _window_rate(samples, now: float, window: float,
                     bad: float, total: float) -> Tuple[float, float]:
        """Bad fraction + event count over ``[now - window, now]``:
        delta of the cumulative (bad, total) vs the newest sample at
        least ``window`` old (falling back to the oldest sample when
        history is shorter than the window).

        The fallback means a monitor younger than ``slow_window_s``
        computes its slow rate over whatever history exists, so fast
        and slow agree and a sustained burn right after startup CAN
        page before a full slow window has elapsed. That is deliberate:
        startup is when serving stalls are most likely, and the
        acceptance contract is "503 within one evaluation of the data
        showing the burn" — full multi-window blip suppression kicks in
        once history spans the slow window."""
        base_bad = base_total = None
        for t, b, n in samples:          # oldest -> newest
            if t <= now - window:
                base_bad, base_total = b, n
            else:
                break
        if base_bad is None:
            if not samples:
                return 0.0, 0.0
            t, base_bad, base_total = samples[0]
        d_total = total - base_total
        d_bad = bad - base_bad
        if d_total <= 0:
            return 0.0, 0.0
        return d_bad / d_total, d_total

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation pass: sample every target, compute fast/slow
        burn rates, fire/clear breach state, export gauges. Returns the
        status dict (also available via :meth:`status`)."""
        if now is None:
            now = self.clock()
        self._evals += 1
        reg = self.registry
        out: Dict[str, Any] = {"ok": True, "targets": {}}
        for target in self.targets:
            st = self._state[target.name]
            bad, total = self._read(target)
            rate_fast, n_fast = self._window_rate(
                st.samples, now, self.fast_window_s, bad, total
            )
            rate_slow, n_slow = self._window_rate(
                st.samples, now, self.slow_window_s, bad, total
            )
            st.samples.append((now, bad, total))
            budget = 1.0 - target.target
            burn_fast = rate_fast / budget
            burn_slow = rate_slow / budget
            breaching = (
                n_fast > 0
                and burn_fast >= self.burn_threshold
                and burn_slow >= self.burn_threshold
            )
            if breaching and not st.breaching:
                st.alerts += 1
                reg.counter("slo.alerts_total").inc()
                if self.recorder is not None:
                    exemplar = None
                    if self.exemplars is not None:
                        try:
                            exemplar = self.exemplars()
                        except Exception:  # noqa: BLE001 - an exemplar
                            pass  # provider bug must not eat the page
                    self.recorder.fire_trigger(
                        "slo_burn",
                        f"SLO {target.name!r} burning at "
                        f"{burn_fast:.2f}x budget (fast "
                        f"{self.fast_window_s:.0f}s) and "
                        f"{burn_slow:.2f}x (slow "
                        f"{self.slow_window_s:.0f}s), threshold "
                        f"{self.burn_threshold}x",
                        self._evals,
                        details={
                            "target": dataclasses.asdict(target),
                            "burn_fast": burn_fast,
                            "burn_slow": burn_slow,
                            "bad_fraction_fast": rate_fast,
                            "events_fast": n_fast,
                            "exemplar": exemplar,
                        },
                    )
            st.breaching = breaching
            st.last = {
                "kind": target.kind,
                "metric": target.metric or target.bad_metric,
                "objective": target.objective,
                "target": target.target,
                "burn_fast": burn_fast,
                "burn_slow": burn_slow,
                "bad_fraction_fast": rate_fast,
                "events_fast": n_fast,
                "events_slow": n_slow,
                "cumulative_bad": bad,
                "cumulative_total": total,
                "breaching": breaching,
                "alerts": st.alerts,
            }
            reg.gauge(f"slo.{target.name}.burn_fast").set(burn_fast)
            reg.gauge(f"slo.{target.name}.burn_slow").set(burn_slow)
            out["targets"][target.name] = st.last
            if breaching:
                out["ok"] = False
        reg.gauge("slo.breaching").set(
            float(sum(1 for s in self._state.values() if s.breaching))
        )
        return out

    def status(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Evaluate now and return the status dict — the pull-driven
        entry point ``/healthz`` uses, so a blown burn rate is visible
        within one evaluation of the data showing it."""
        return self.evaluate(now)

    @property
    def breaching(self) -> List[str]:
        return sorted(
            name for name, st in self._state.items() if st.breaching
        )


def default_serving_slos(
    *,
    ttft_p: float = 0.95,
    ttft_objective_s: float = 0.5,
    decode_gap_objective_s: float = 0.25,
    decode_gap_p: float = 0.99,
    shed_target: float = 0.95,
) -> List[SLOTarget]:
    """A reasonable starting set over the engine's existing metrics:
    TTFT, the inter-decode-step gap (the stall smell the watchdog
    catches only at full livelock), and the deadline-shed fraction.
    Shedding is the DESIGNED degraded mode — ``/healthz`` stays 200
    while it happens — so the shed target is what turns "degraded" into
    "page someone": with ``shed_target=0.95``, sustained shedding of
    more than 5% of submitted requests burns the budget and breaches."""
    return [
        SLOTarget(name="ttft", metric="serving.ttft_seconds",
                  objective=ttft_objective_s, target=ttft_p),
        SLOTarget(name="decode_gap", metric="serving.decode_gap_seconds",
                  objective=decode_gap_objective_s, target=decode_gap_p),
        SLOTarget(name="shed_fraction", kind="ratio",
                  bad_metric="serving.shed_total",
                  total_metric="serving.requests_total",
                  target=shed_target),
    ]
