"""Derived gauges: MFU, tokens/s, HBM occupancy, per-step comm bytes.

The GSPMD / Mesh-TensorFlow lineage (arxiv 2105.04663, 1811.02084)
treats the COMPILER's cost model as the ground truth for utilization on
TPU: XLA already knows the per-step FLOPs and every collective it
emitted. This module turns those into operator-facing numbers:

- ``mfu``: achieved model-FLOPs utilization from ``compiled_cost``
  FLOPs (utils/profiler.py) against the per-device peak-FLOPs table;
- ``compiled_step_stats``: ONE lower+compile yielding flops, bytes
  accessed, AND per-collective communication bytes parsed from the
  compiled HLO (all-reduce / all-gather / reduce-scatter / all-to-all /
  collective-permute output shapes);
- ``hbm_utilization``: live HBM occupancy from ``device_memory_stats``
  (empty off-TPU — CPU devices report no memory stats).

``PEAK_FLOPS`` is the single source of truth for per-chip peak bf16
FLOP/s — bench.py imports it from here rather than keeping its own
copy.
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, Optional

import jax

from pipegoose_tpu.utils.profiler import compiled_cost, device_memory_stats

# per-chip peak bf16 FLOP/s (the MFU denominator; docs/observability.md
# documents the sources). "cpu" is a nominal placeholder so CPU smoke
# runs produce a finite, clearly-not-real number.
PEAK_FLOPS: Dict[str, float] = {
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,  # v6e (Trillium)
    "v6e": 918e12,
    "v4": 275e12,
    "cpu": 1e12,
}


def peak_flops_for(device_kind: Optional[str] = None) -> float:
    """Peak FLOP/s for a device-kind string (substring match, like
    bench.py always did); defaults to the first visible device."""
    if device_kind is None:
        dev = jax.devices()[0]
        device_kind = getattr(dev, "device_kind", dev.platform)
    kind = device_kind.lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return 1e12


def mfu(flops_per_step: float, step_seconds: float,
        device_kind: Optional[str] = None, peak: Optional[float] = None,
        n_devices: int = 1) -> float:
    """Achieved / peak FLOP/s. ``flops_per_step`` is the WHOLE step's
    model FLOPs (e.g. XLA's cost analysis of the jitted step);
    ``n_devices`` divides the peak pool it ran against."""
    if step_seconds <= 0:
        return 0.0
    peak = peak if peak is not None else peak_flops_for(device_kind)
    return flops_per_step / step_seconds / (peak * max(n_devices, 1))


def tokens_per_second(tokens: float, seconds: float) -> float:
    return tokens / seconds if seconds > 0 else 0.0


def hbm_utilization(device: Optional[Any] = None) -> dict:
    """{"bytes_in_use", "bytes_limit", "utilization"} from the device's
    live memory stats; {} where the backend reports none (CPU)."""
    stats = device_memory_stats(device)
    used = stats.get("bytes_in_use")
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    if used is None:
        return {}
    out = {"bytes_in_use": int(used)}
    if limit:
        out["bytes_limit"] = int(limit)
        out["utilization"] = used / limit
    return out


# -- communication accounting from compiled HLO ---------------------------

_ITEMSIZE = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                "collective-permute")

# "f32[8,128]" with optional layout suffix "{1,0}"
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def _shape_bytes_list(shape_part: str) -> list:
    out = []
    for dtype, dims in _SHAPE_RE.findall(shape_part):
        size = _ITEMSIZE.get(dtype)
        if size is None:
            continue  # token/opaque types carry no payload
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append(n * size)
    return out


def _shape_bytes(shape_part: str) -> int:
    return sum(_shape_bytes_list(shape_part))


def _async_start_bytes(shape_part: str) -> int:
    """Output payload of an async ``-start`` result tuple, whose shape
    is ``(operand..., output..., [context scalars])``: strip trailing
    scalar contexts (<= 8 bytes, e.g. the u32[] slots of
    collective-permute-start), then take the SECOND half — the output
    buffers. Correct for asymmetric collectives too (all-gather output
    > input, reduce-scatter output < input), where halving the summed
    tuple would miscount."""
    shapes = _shape_bytes_list(shape_part)
    while len(shapes) > 2 and shapes[-1] <= 8:
        shapes.pop()
    if len(shapes) < 2:
        return sum(shapes)  # unexpected non-tuple form: count as-is
    return sum(shapes[len(shapes) // 2:])


def collective_bytes(hlo_text: str) -> Dict[str, int]:
    """Per-collective output bytes summed over an HLO module's text:
    {"all-reduce": N, ..., "total": M}. Output-shape bytes are the
    standard proxy for wire traffic (exact for all-reduce/all-gather
    payloads; a ring all-reduce moves ~2x on the wire — this counts the
    logical payload, the per-algorithm constant is the reader's)."""
    out = {k: 0 for k in _COLLECTIVES}
    for line in hlo_text.splitlines():
        for op in _COLLECTIVES:
            # "%name = f32[8,16]{1,0} all-reduce(..." — shape(s) sit
            # between '=' and the op name; skip the "-done" async half
            # (its result duplicates the "-start" tuple's output)
            m = re.search(rf"=\s*(.*?)\s{op}(-start)?\(", line)
            if m:
                # async "-start" results are (operand..., output...)
                # tuples: count only the output half
                nbytes = (_async_start_bytes(m.group(1)) if m.group(2)
                          else _shape_bytes(m.group(1)))
                out[op] += nbytes
                break
    out["total"] = sum(out[k] for k in _COLLECTIVES)
    return out


def compiled_step_stats(fn: Callable, *args, **kwargs) -> dict:
    """ONE lower+compile of ``jit(fn)`` at these arg shapes, returning
    {"flops", "bytes_accessed", "comm_bytes", "comm_by_op"} — the
    compiler-ground-truth inputs to the MFU and comms gauges."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = dict(ca or {})
    try:
        comm = collective_bytes(compiled.as_text())
    except Exception:  # noqa: BLE001 - backends without HLO text export
        comm = {"total": 0}
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "comm_bytes": int(comm.get("total", 0)),
        "comm_by_op": {k: v for k, v in comm.items()
                       if k != "total" and v},
    }


def step_flops(fn: Callable, *args, **kwargs) -> float:
    """XLA-reported FLOPs of one call of ``jit(fn)`` (compiled_cost
    sugar for the common MFU input)."""
    return float(compiled_cost(fn, *args, **kwargs).get("flops", 0.0))
