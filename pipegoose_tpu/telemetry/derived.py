"""Derived gauges: MFU, tokens/s, HBM occupancy, per-step comm bytes.

The GSPMD / Mesh-TensorFlow lineage (arxiv 2105.04663, 1811.02084)
treats the COMPILER's cost model as the ground truth for utilization on
TPU: XLA already knows the per-step FLOPs and every collective it
emitted. This module turns those into operator-facing numbers:

- ``mfu``: achieved model-FLOPs utilization from ``compiled_cost``
  FLOPs (utils/profiler.py) against the per-device peak-FLOPs table;
- ``compiled_step_stats``: ONE lower+compile yielding flops, bytes
  accessed, AND per-collective communication bytes parsed from the
  compiled HLO (all-reduce / all-gather / reduce-scatter / all-to-all /
  collective-permute output shapes);
- ``hbm_utilization``: live HBM occupancy from ``device_memory_stats``
  (empty off-TPU — CPU devices report no memory stats).

``PEAK_FLOPS`` is the single source of truth for per-chip peak bf16
FLOP/s — bench.py imports it from here rather than keeping its own
copy.
"""
from __future__ import annotations

import re
import warnings
from typing import Any, Callable, Dict, Optional

import jax

from pipegoose_tpu.utils.profiler import compiled_cost, device_memory_stats

# per-chip peak bf16 FLOP/s (the MFU denominator; docs/observability.md
# documents the sources). "cpu" is a nominal placeholder so CPU smoke
# runs produce a finite, clearly-not-real number.
PEAK_FLOPS: Dict[str, float] = {
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,  # v6e (Trillium)
    "v6e": 918e12,
    "v4": 275e12,
    "cpu": 1e12,
}

# Peer tables to PEAK_FLOPS: per-chip interconnect bandwidth and HBM
# capacity — the denominators of the parallelism planner's static cost
# model (pipegoose_tpu/planner/). ICI is the intra-slice fabric every
# mesh axis rides by default; DCI is the data-center network a
# cross-slice axis (e.g. the DiLoCo outer loop) pays instead. Aggregate
# per-chip numbers from the public TPU system specs (ICI Gbps -> B/s);
# "cpu" rows are nominal placeholders so fake-device planning yields
# finite, clearly-not-real times with the same code path.
PEAK_ICI_BYTES: Dict[str, float] = {
    "v5 lite": 200e9,   # v5e: 1600 Gbps aggregate
    "v5e": 200e9,
    "v5p": 600e9,       # 4800 Gbps
    "v6 lite": 448e9,   # v6e: 3584 Gbps
    "v6e": 448e9,
    "v4": 300e9,        # 2400 Gbps
    "cpu": 10e9,
}

PEAK_DCI_BYTES: Dict[str, float] = {
    "v5 lite": 25e9,
    "v5e": 25e9,
    "v5p": 25e9,
    "v6 lite": 25e9,
    "v6e": 25e9,
    "v4": 25e9,
    "cpu": 1e9,
}

HBM_BYTES: Dict[str, float] = {
    "v5 lite": 16 * 1024**3,
    "v5e": 16 * 1024**3,
    "v5p": 95 * 1024**3,
    "v6 lite": 32 * 1024**3,
    "v6e": 32 * 1024**3,
    "v4": 32 * 1024**3,
    "cpu": 16 * 1024**3,
}

# Per-chip HBM BANDWIDTH (B/s, public system specs) — the denominator
# of the serving decode-layout cost model (planner/serving.py): a
# batch-1-per-slot decode step is memory-bound, so its floor is
# (resident weights + KV read) / this number. The quantized-inference
# win is exactly a smaller numerator here.
HBM_BW_BYTES: Dict[str, float] = {
    "v5 lite": 819e9,
    "v5e": 819e9,
    "v5p": 2765e9,
    "v6 lite": 1640e9,
    "v6e": 1640e9,
    "v4": 1228e9,
    "cpu": 50e9,
}

# mesh axes that ride the data-center network instead of ICI — the ONE
# definition both the planner cost model (CostModel.dci_axes default)
# and the measured fabric-utilization attribution (telemetry/xprof.py)
# key their bandwidth choice on. "diloco" is the cross-slice outer
# loop (optim/diloco.py).
DCI_AXES: tuple = ("diloco",)


# documented fallbacks for device kinds absent from the spec tables —
# finite, clearly-not-real numbers (the "cpu" placeholder philosophy)
# so an unknown chip plans/meters with the same code path instead of
# dividing by zero. The lookup WARNS when it falls back: a silent
# default would let a typo'd --device-kind quietly score every layout
# against the wrong machine.
DEFAULT_PEAK_FLOPS = 1e12
DEFAULT_ICI_BYTES = 10e9
DEFAULT_DCI_BYTES = 1e9
DEFAULT_HBM_BYTES = 16 * 1024**3
DEFAULT_HBM_BW_BYTES = 100e9


def _kind_lookup(table: Dict[str, float], device_kind: Optional[str],
                 default: float, table_name: str = "") -> float:
    if device_kind is None:
        dev = jax.devices()[0]
        device_kind = getattr(dev, "device_kind", dev.platform)
    kind = device_kind.lower()
    for k, v in table.items():
        if k in kind:
            return v
    warnings.warn(
        f"unknown device kind {device_kind!r}: no {table_name or 'spec-table'}"
        f" entry matches — falling back to the documented default "
        f"{default:g} (plans/meters against this kind are placeholders, "
        f"not hardware numbers)",
        stacklevel=3,
    )
    return default


def peak_flops_for(device_kind: Optional[str] = None) -> float:
    """Peak FLOP/s for a device-kind string (substring match, like
    bench.py always did); defaults to the first visible device. Unknown
    kinds fall back LOUDLY (UserWarning) to ``DEFAULT_PEAK_FLOPS``."""
    return _kind_lookup(PEAK_FLOPS, device_kind, DEFAULT_PEAK_FLOPS,
                        "PEAK_FLOPS")


def ici_bytes_per_s_for(device_kind: Optional[str] = None) -> float:
    """Per-chip intra-slice interconnect bandwidth (B/s) for a
    device-kind string; defaults to the first visible device. Unknown
    kinds fall back LOUDLY to ``DEFAULT_ICI_BYTES``."""
    return _kind_lookup(PEAK_ICI_BYTES, device_kind, DEFAULT_ICI_BYTES,
                        "PEAK_ICI_BYTES")


def dci_bytes_per_s_for(device_kind: Optional[str] = None) -> float:
    """Per-chip cross-slice (data-center network) bandwidth (B/s).
    Unknown kinds fall back LOUDLY to ``DEFAULT_DCI_BYTES``."""
    return _kind_lookup(PEAK_DCI_BYTES, device_kind, DEFAULT_DCI_BYTES,
                        "PEAK_DCI_BYTES")


def hbm_bytes_for(device_kind: Optional[str] = None) -> float:
    """Per-chip HBM capacity (bytes) from the spec table — the planner's
    feasibility budget where the backend reports no live ``bytes_limit``
    (fake CPU devices report none). Unknown kinds fall back LOUDLY to
    ``DEFAULT_HBM_BYTES``."""
    return _kind_lookup(HBM_BYTES, device_kind, DEFAULT_HBM_BYTES,
                        "HBM_BYTES")


def hbm_bw_bytes_per_s_for(device_kind: Optional[str] = None) -> float:
    """Per-chip HBM bandwidth (B/s) — the memory-bound decode cost
    model's denominator (planner/serving.py). Unknown kinds fall back
    LOUDLY to ``DEFAULT_HBM_BW_BYTES``."""
    return _kind_lookup(HBM_BW_BYTES, device_kind, DEFAULT_HBM_BW_BYTES,
                        "HBM_BW_BYTES")


def mfu(flops_per_step: float, step_seconds: float,
        device_kind: Optional[str] = None, peak: Optional[float] = None,
        n_devices: int = 1) -> float:
    """Achieved / peak FLOP/s. ``flops_per_step`` is the WHOLE step's
    model FLOPs (e.g. XLA's cost analysis of the jitted step);
    ``n_devices`` divides the peak pool it ran against."""
    if step_seconds <= 0:
        return 0.0
    peak = peak if peak is not None else peak_flops_for(device_kind)
    return flops_per_step / step_seconds / (peak * max(n_devices, 1))


def tokens_per_second(tokens: float, seconds: float) -> float:
    return tokens / seconds if seconds > 0 else 0.0


def hbm_utilization(device: Optional[Any] = None) -> dict:
    """{"bytes_in_use", "bytes_limit", "utilization"} from the device's
    live memory stats; {} where the backend reports none (CPU)."""
    stats = device_memory_stats(device)
    used = stats.get("bytes_in_use")
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    if used is None:
        return {}
    out = {"bytes_in_use": int(used)}
    if limit:
        out["bytes_limit"] = int(limit)
        out["utilization"] = used / limit
    return out


# -- communication accounting from compiled HLO ---------------------------

_ITEMSIZE = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                "collective-permute")

# "f32[8,128]" with optional layout suffix "{1,0}"
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

_RESULT_RE = {
    # "%name = f32[8,16]{1,0} all-reduce(..." — shape(s) sit between '='
    # and the op name; a "-done" suffix never matches (its result
    # duplicates the "-start" tuple's output and must not count twice)
    op: re.compile(rf"=\s*(.*?)\s{op}(-start)?\(") for op in _COLLECTIVES
}


def _atom_bytes(dtype: str, dims: str) -> Optional[int]:
    size = _ITEMSIZE.get(dtype)
    if size is None:
        return None  # token/opaque types carry no payload
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * size


def _shape_bytes_list(shape_part: str) -> list:
    out = []
    for dtype, dims in _SHAPE_RE.findall(shape_part):
        b = _atom_bytes(dtype, dims)
        if b is not None:
            out.append(b)
    return out


def _shape_bytes(shape_part: str) -> int:
    return sum(_shape_bytes_list(shape_part))


def _split_top_level(shape_part: str) -> list:
    """Split a result-shape string into its TOP-LEVEL tuple elements,
    respecting nesting: ``"((f32[8], u8[2]), (f32[2]), u32[])"`` ->
    ``["(f32[8], u8[2])", "(f32[2])", "u32[]"]``. A non-tuple shape
    comes back as a single element. Layout braces (``{1,0}``) carry no
    parens, so only ``(``/``)`` depth matters."""
    s = shape_part.strip()
    if not s.startswith("("):
        return [s]
    body = s[1:s.rfind(")")] if ")" in s else s[1:]
    # dims ("[2,8]") and layouts ("{1,0}") hold commas too — only a
    # comma at depth 0 across ALL bracket kinds separates tuple elements
    elems, depth, start = [], 0, 0
    for i, ch in enumerate(body):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "," and depth == 0:
            elems.append(body[start:i].strip())
            start = i + 1
    tail = body[start:].strip()
    if tail:
        elems.append(tail)
    return elems


def _async_start_bytes(shape_part: str) -> int:
    """Output payload of an async ``-start`` result tuple.

    Two printed forms exist:

    - nested (variadic): ``((operands...), (outputs...), [contexts])``
      — the LAST nested tuple is the output buffer set; sum exactly it.
    - flat: ``(operand..., output..., [context scalars])`` — strip the
      trailing scalar contexts (<= 8 bytes, e.g. the u32[] slots of
      collective-permute-start), then take the SECOND half — the output
      buffers. Correct for asymmetric collectives too (all-gather
      output > input, reduce-scatter output < input), where halving the
      summed tuple would miscount.
    """
    elems = _split_top_level(shape_part)
    nested = [e for e in elems if e.startswith("(")]
    if nested:
        return _shape_bytes(nested[-1])
    shapes = _shape_bytes_list(shape_part)
    while len(shapes) > 2 and shapes[-1] <= 8:
        shapes.pop()
    if len(shapes) < 2:
        return sum(shapes)  # unexpected non-tuple form: count as-is
    return sum(shapes[len(shapes) // 2:])


def _sync_bytes(shape_part: str) -> int:
    """Payload of a SYNC collective result: every top-level element is
    an output buffer (tuple-shaped variadic reduce-scatter /
    collective-permute included) EXCEPT trailing scalar context slots,
    which some permute forms keep even in the sync printing."""
    elems = _split_top_level(shape_part)
    sizes = [_shape_bytes(e) for e in elems]
    while len(sizes) > 1 and sizes[-1] <= 8 and elems[-1].startswith("u32"):
        sizes.pop()
        elems.pop()
    return sum(sizes)


def iter_collectives(hlo_text: str):
    """Yield one ``{"op", "bytes", "start", "line"}`` dict per
    collective instruction in an HLO module's text (async ``-done``
    halves skipped). The line-level form telemetry/doctor.py builds its
    schedule table on; ``collective_bytes`` is the aggregate view."""
    for line in hlo_text.splitlines():
        for op in _COLLECTIVES:
            m = _RESULT_RE[op].search(line)
            if m:
                start = bool(m.group(2))
                nbytes = (_async_start_bytes(m.group(1)) if start
                          else _sync_bytes(m.group(1)))
                yield {"op": op, "bytes": nbytes, "start": start,
                       "line": line}
                break


def collective_bytes(hlo_text: str) -> Dict[str, int]:
    """Per-collective output bytes summed over an HLO module's text:
    {"all-reduce": N, ..., "total": M}. Output-shape bytes are the
    standard proxy for wire traffic (exact for all-reduce/all-gather
    payloads; a ring all-reduce moves ~2x on the wire — this counts the
    logical payload, the per-algorithm constant is the reader's)."""
    out = {k: 0 for k in _COLLECTIVES}
    for c in iter_collectives(hlo_text):
        out[c["op"]] += c["bytes"]
    out["total"] = sum(out[k] for k in _COLLECTIVES)
    return out


def compiled_step_stats(fn: Callable, *args, **kwargs) -> dict:
    """ONE lower+compile of ``jit(fn)`` at these arg shapes, returning
    {"flops", "bytes_accessed", "comm_bytes", "comm_by_op"} — the
    compiler-ground-truth inputs to the MFU and comms gauges."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = dict(ca or {})
    try:
        comm = collective_bytes(compiled.as_text())
    except Exception:  # noqa: BLE001 - backends without HLO text export
        comm = {"total": 0}
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "comm_bytes": int(comm.get("total", 0)),
        "comm_by_op": {k: v for k, v in comm.items()
                       if k != "total" and v},
    }


def step_flops(fn: Callable, *args, **kwargs) -> float:
    """XLA-reported FLOPs of one call of ``jit(fn)`` (compiled_cost
    sugar for the common MFU input)."""
    return float(compiled_cost(fn, *args, **kwargs).get("flops", 0.0))
