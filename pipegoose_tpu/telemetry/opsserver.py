"""Live ops endpoint: /metrics, /healthz, /debug/requests, /debug/doctor.

Every exporter so far writes FILES (JSONL, Prometheus textfile, trace
JSON, black boxes) — fine for post-mortems, useless for "is the serving
engine healthy RIGHT NOW?". :class:`OpsServer` is a stdlib-only
(``http.server``; the container's jax 0.4.37 image gets no new deps)
background HTTP endpoint over the same telemetry objects:

- ``GET /metrics``        Prometheus text exposition rendered live from
                          the registry — byte-identical to what
                          ``PrometheusTextfileExporter`` would write
                          for the same snapshot, so one scrape config
                          covers both transports.
- ``GET /healthz``        200 when healthy, 503 when degraded, with a
                          JSON body naming WHY: an un-consumed flight-
                          recorder trigger (decode stall, nonfinite,
                          slo_burn, ...) and/or a breaching SLO target
                          (the monitor is evaluated on every probe, so
                          a blown burn rate flips the probe within one
                          evaluation of the data showing it).
- ``GET /debug/requests`` in-flight + recent request timelines from the
                          ``RequestTracer`` as JSON — "which request is
                          stuck and where is its latency going".
- ``GET /debug/doctor``   the last mesh-doctor ``DoctorReport`` as JSON
                          (the compiled program's sharding plan).
- ``GET /debug/profile``  the last measured ``StepProfile``
                          (telemetry/xprof.py) as JSON — where the
                          step's device time went: compute vs per-axis
                          collectives vs idle, measured MFU.
- ``GET /debug/plan``     the last planner ``PlanReport``
                          (pipegoose_tpu/planner/) as JSON — the ranked
                          layout space, scores, prune reasons
                          (``planner.last_plan_report`` is the natural
                          provider).
- ``GET /debug/fleet``    the control plane's live fleet status
                          (serving/control_plane/): per-replica state +
                          load, router stats, per-tenant fair-share
                          ledger, autoscaler audit log.
- ``GET /debug/memory``   the live memory ledger (telemetry/
                          memledger.py) as JSON — per-owner-class byte
                          account, conservation verdict, leak-audit
                          findings, steps-to-exhaustion forecast.
- ``GET /debug/trace``    one stitched cross-replica fleet trace from
                          the ``FleetTracer`` (telemetry/fleettrace.py)
                          selected by ``?trace_id=`` or ``?uid=`` —
                          plane hops + per-replica legs + dominant-hop
                          attribution for ONE request.
- ``GET /debug/tail``     the fleet tail sampler: the slowest completed
                          fleet traces per objective (ttft, e2e), each
                          with its dominant hop — "where is the p99
                          actually going, which replica, which phase".

Operational posture: rank-0-filtered (non-zero ranks never bind a
socket — same ``RankFilter`` convention as the file exporters),
``port=0`` binds an ephemeral port (tests and multi-tenant hosts),
handlers snapshot shared state under the server lock and serialize
with ``safe_json_dumps`` (non-finite floats land as strings, like
every other telemetry artifact). The server runs on daemon threads and
is explicitly ``stop()``-able; nothing starts unless the caller
constructs one, so the engine's default hot path is untouched.
"""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs

from pipegoose_tpu.telemetry.registry import MetricsRegistry, get_registry
from pipegoose_tpu.utils.procindex import RankFilter as _RankFilter

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# /debug endpoints that serve an attached report/provider verbatim:
# path -> (OpsServer attribute, 404-message label). One branch serves
# all of them; adding the next debug surface is one row here plus a
# constructor knob.
_PROVIDER_ENDPOINTS = {
    "/debug/doctor": ("_doctor", "doctor report"),
    "/debug/profile": ("_profile", "step profile"),
    "/debug/plan": ("_plan", "plan report"),
    "/debug/fleet": ("_fleet", "fleet status provider"),
    "/debug/memory": ("_memory", "memory ledger"),
    "/debug/goodput": ("_goodput", "goodput ledger"),
}


class OpsServer:
    """Background ops HTTP endpoint (see module docstring).

    ``slo``: optional ``telemetry.slo.SLOMonitor`` (evaluated per
    ``/healthz`` probe). ``recorder``: optional ``FlightRecorder`` —
    a pending (un-consumed) trigger marks the process degraded.
    ``tracer``: optional ``RequestTracer`` behind ``/debug/requests``.
    ``doctor``: a ``DoctorReport`` or a zero-arg callable returning one
    (e.g. ``lambda: engine.last_doctor_report``).
    ``profile``: a ``StepProfile`` or a zero-arg callable returning one
    (e.g. ``lambda: engine.last_step_profile``) behind
    ``/debug/profile``.
    ``plan``: a ``PlanReport`` or a zero-arg callable returning one
    (e.g. ``planner.last_plan_report``) behind ``/debug/plan``.
    ``fleet``: a JSON-able dict or a zero-arg callable returning one
    (e.g. ``control_plane.fleet_status``) behind ``/debug/fleet`` —
    per-replica state + load, router stats, per-tenant shares, the
    autoscaler audit log.
    ``memory``: a JSON-able dict or a zero-arg callable returning one
    (e.g. ``engine.memledger.report``) behind ``/debug/memory`` — the
    live memory ledger's per-owner-class byte account, conservation
    verdict, leak-audit findings, and steps-to-exhaustion forecast.
    ``goodput``: a JSON-able dict or a zero-arg callable returning one
    (e.g. ``plane.goodput.report``) behind ``/debug/goodput`` — the
    fleet goodput ledger's wall-clock attribution, conservation
    verdict, and incident log (MTTR, capacity-gap, SLO burn).
    ``fleettrace``: optional ``telemetry.fleettrace.FleetTracer``
    behind ``/debug/trace`` (one stitched trace by ``?trace_id=`` /
    ``?uid=``) and ``/debug/tail`` (slowest-trace exemplars).
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        rank: Optional[int] = 0,
        slo: Optional[Any] = None,
        recorder: Optional[Any] = None,
        tracer: Optional[Any] = None,
        doctor: Optional[Any] = None,
        profile: Optional[Any] = None,
        plan: Optional[Any] = None,
        fleet: Optional[Any] = None,
        fleettrace: Optional[Any] = None,
        memory: Optional[Any] = None,
        goodput: Optional[Any] = None,
    ):
        self.registry = registry if registry is not None else get_registry()
        self.host = host
        self._requested_port = int(port)
        self._rank_ok = _RankFilter(rank)
        self.slo = slo
        self.recorder = recorder
        self.tracer = tracer
        self._doctor = doctor
        self._profile = profile
        self._plan = plan
        self._fleet = fleet
        self._memory = memory
        self._goodput = goodput
        self.fleettrace = fleettrace
        self._lock = threading.Lock()
        # SLOMonitor mutates per-target state on evaluate(), so
        # concurrent /healthz probes must serialize — but on its OWN
        # lock: a breach transition fires a flight-recorder black-box
        # dump (disk write) mid-evaluation, and holding the server lock
        # through that would stall a concurrent /metrics scrape exactly
        # when the system is degraded.
        self._slo_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- wiring ------------------------------------------------------------

    def _resolve_provider(self, attr: str) -> Optional[Any]:
        """ONE provider-or-value resolution for every /debug endpoint:
        a zero-arg callable (that isn't itself a report object) is
        invoked per request, anything else is served as-is; a raising
        provider resolves to None (404, never a 500 storm)."""
        with self._lock:
            p = getattr(self, attr)
        if callable(p) and not hasattr(p, "to_json"):
            try:
                return p()
            except Exception:  # noqa: BLE001 - provider failure != 500 storm
                return None
        return p

    def set_doctor_report(self, report: Any) -> None:
        """Attach (or replace) the report behind ``/debug/doctor``."""
        with self._lock:
            self._doctor = report

    def set_profile(self, profile: Any) -> None:
        """Attach (or replace) the provider behind ``/debug/profile``."""
        with self._lock:
            self._profile = profile

    def set_plan(self, plan: Any) -> None:
        """Attach (or replace) the provider behind ``/debug/plan``."""
        with self._lock:
            self._plan = plan

    def set_fleet(self, fleet: Any) -> None:
        """Attach (or replace) the provider behind ``/debug/fleet``."""
        with self._lock:
            self._fleet = fleet

    def set_memory(self, memory: Any) -> None:
        """Attach (or replace) the provider behind ``/debug/memory``."""
        with self._lock:
            self._memory = memory

    def set_goodput(self, goodput: Any) -> None:
        """Attach (or replace) the provider behind ``/debug/goodput``
        — a ``GoodputLedger.report``-shaped dict or a callable
        returning one (``lambda: ledger.report()`` stays live)."""
        with self._lock:
            self._goodput = goodput

    def set_fleettrace(self, fleettrace: Any) -> None:
        """Attach (or replace) the ``FleetTracer`` behind
        ``/debug/trace`` and ``/debug/tail``."""
        with self._lock:
            self.fleettrace = fleettrace

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> Optional[str]:
        """Bind + serve on a daemon thread; returns the base URL, or
        None when rank-filtered out (non-zero ranks are no-ops so the
        same construction code runs on every process)."""
        if not self._rank_ok():
            return None
        if self._httpd is not None:
            return self.url
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="pipegoose-ops-server",
            daemon=True,
        )
        self._thread.start()
        return self.url

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._httpd = None
        self._thread = None

    @property
    def port(self) -> Optional[int]:
        return None if self._httpd is None else self._httpd.server_address[1]

    @property
    def url(self) -> Optional[str]:
        p = self.port
        return None if p is None else f"http://{self.host}:{p}"

    def __enter__(self) -> "OpsServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- endpoint payloads (snapshot-under-lock) ---------------------------

    def render_metrics(self) -> str:
        with self._lock:
            return self.registry.to_prometheus()

    def health(self) -> Tuple[int, Dict[str, Any]]:
        """(status_code, body) for ``/healthz``: 200 iff no pending
        flight-recorder trigger and no breaching SLO target."""
        problems = []
        trig = getattr(self.recorder, "last_trigger", None)
        if trig is not None:
            problems.append({
                "kind": "flight_recorder_trigger",
                "name": trig.name,
                "reason": trig.reason,
                "step": trig.step,
                "dump_path": trig.dump_path,
            })
        slo_status = None
        if self.slo is not None:
            with self._slo_lock:
                slo_status = self.slo.status()
            if not slo_status.get("ok", True):
                for name, t in slo_status.get("targets", {}).items():
                    if t.get("breaching"):
                        problems.append({
                            "kind": "slo_burn",
                            "name": name,
                            "burn_fast": t.get("burn_fast"),
                            "burn_slow": t.get("burn_slow"),
                        })
        body: Dict[str, Any] = {
            "ok": not problems,
            "problems": problems,
        }
        if slo_status is not None:
            body["slo"] = slo_status
        if self.tracer is not None:
            # the tracer guards its own state; len() needs no ops lock
            body["requests_in_flight"] = len(self.tracer.in_flight)
        return (200 if not problems else 503), body

    def debug_requests(self) -> Optional[Dict[str, Any]]:
        if self.tracer is None:
            return None
        with self._lock:
            return self.tracer.snapshot()

    def debug_trace(self, query: Dict[str, str]) -> Tuple[int, Any]:
        """(status_code, body) for ``/debug/trace?trace_id=``/``?uid=``:
        one stitched cross-replica trace. trace_id is the fleet-stable
        key; uid resolves through the tracer's dispatch index (uids are
        replica-local, so the MOST RECENT dispatch wins a reused uid)."""
        ft = self.fleettrace
        if ft is None:
            return 404, {"error": "no fleet tracer attached"}
        try:
            uid = int(query["uid"]) if "uid" in query else None
            trace_id = (int(query["trace_id"])
                        if "trace_id" in query else None)
        except ValueError:
            return 400, {"error": "uid/trace_id must be integers"}
        if uid is None and trace_id is None:
            return 400, {"error": "pass ?trace_id=N or ?uid=N"}
        payload = ft.trace_json(uid=uid, trace_id=trace_id)
        if payload is None:
            return 404, {"error": "no trace for "
                         f"trace_id={trace_id} uid={uid}"}
        return 200, payload

    def debug_tail(self) -> Tuple[int, Any]:
        """(status_code, body) for ``/debug/tail``: the slowest
        completed fleet traces per objective with dominant hops."""
        ft = self.fleettrace
        if ft is None:
            return 404, {"error": "no fleet tracer attached"}
        return 200, ft.tail_payload()


def _make_handler(ops: OpsServer):
    """Handler class closed over the server object (BaseHTTPRequestHandler
    is instantiated per connection by ThreadingHTTPServer)."""
    from pipegoose_tpu.telemetry.exporters import safe_json_dumps

    class _OpsHandler(BaseHTTPRequestHandler):
        server_version = "pipegoose-ops/1"

        def log_message(self, fmt: str, *args: Any) -> None:
            pass  # probes must not spam the serving process's stderr

        def _send(self, code: int, body: bytes, content_type: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, payload: Any) -> None:
            self._send(code, (safe_json_dumps(payload, indent=1) + "\n")
                       .encode(), "application/json")

        def do_GET(self) -> None:  # noqa: N802 - http.server API
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            try:
                if path == "/metrics":
                    self._send(200, ops.render_metrics().encode(),
                               PROM_CONTENT_TYPE)
                elif path == "/healthz":
                    code, body = ops.health()
                    self._send_json(code, body)
                elif path == "/debug/requests":
                    payload = ops.debug_requests()
                    if payload is None:
                        self._send_json(404, {"error": "no request tracer "
                                              "attached"})
                    else:
                        self._send_json(200, payload)
                elif path == "/debug/trace":
                    parts = self.path.split("?", 1)
                    query = {
                        k: v[-1]
                        for k, v in parse_qs(parts[1]).items()
                    } if len(parts) == 2 else {}
                    code, payload = ops.debug_trace(query)
                    self._send_json(code, payload)
                elif path == "/debug/tail":
                    code, payload = ops.debug_tail()
                    self._send_json(code, payload)
                elif path in _PROVIDER_ENDPOINTS:
                    attr, label = _PROVIDER_ENDPOINTS[path]
                    report = ops._resolve_provider(attr)
                    if report is None:
                        self._send_json(404, {"error": f"no {label} "
                                              "attached"})
                    else:
                        payload = (report.to_json()
                                   if hasattr(report, "to_json") else report)
                        self._send_json(200, payload)
                elif path == "/":
                    self._send_json(200, {
                        "endpoints": ["/metrics", "/healthz",
                                      "/debug/requests", "/debug/doctor",
                                      "/debug/profile", "/debug/plan",
                                      "/debug/fleet", "/debug/memory",
                                      "/debug/goodput",
                                      "/debug/trace", "/debug/tail"],
                    })
                else:
                    self._send_json(404, {"error": f"unknown path {path!r}"})
            except (BrokenPipeError, ConnectionResetError):
                pass  # probe hung up mid-response: not our problem
            except Exception as e:  # noqa: BLE001 - a handler bug must
                # surface as a 500 on THIS probe, not kill the thread pool
                try:
                    self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
                except Exception:  # noqa: BLE001
                    pass

    return _OpsHandler


def parse_prometheus_text(text: str) -> Dict[str, float]:
    """Minimal parser for the text exposition format: sample lines ->
    {name_with_labels: value}. Raises ValueError on a malformed line —
    what the CI smoke and tests use to assert ``/metrics`` parses."""
    out: Dict[str, float] = {}
    for i, line in enumerate(text.splitlines()):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(" ", 1)
        if len(parts) != 2:
            raise ValueError(f"line {i + 1}: not '<name> <value>': {line!r}")
        name, value = parts
        key = name.split("{", 1)[0]
        if not key or not (key[0].isalpha() or key[0] == "_"):
            raise ValueError(f"line {i + 1}: bad metric name {name!r}")
        out[name] = float(value)  # ValueError on a non-numeric sample
    return out
