"""In-graph training-health statistics.

The reference pipegoose's only divergence signal is the host-synced
loss (trainer/trainer.py stub + SURVEY.md §5: no health checks of any
kind); in this port the whole optimizer step is ONE compiled SPMD
program, so by the time a NaN loss reaches the host there is no record
of *which* module's gradients exploded or whether the optimizer update
itself overflowed. This module computes that record INSIDE the compiled
step — a fused reduction over the grad/param/update trees — so the
diagnosis costs one extra all-reduce tree instead of a post-hoc host
sweep over materialized gradients (which the donated-buffer train step
couldn't even provide).

``health_stats`` is called from ``make_hybrid_train_step`` behind the
``with_health`` flag (parallel/hybrid.py): off, the step program is
byte-identical to the unflagged one (zero recompiles, zero overhead —
pinned by tests/telemetry/test_health.py's HLO guard); on, the step
returns one extra small pytree of replicated f32 scalars:

- ``grad_norm`` — global L2 norm of the (data-axis-meaned) gradient;
- ``grad_norm_per_module`` — the same, split by TOP-LEVEL param group
  (``embed`` / ``blocks`` / ``ln_f`` ...), which is what lets a flight
  recorder dump name the offending module instead of "somewhere";
- ``update_max_abs`` / ``update_norm`` — the applied optimizer update
  (``new_params - params``), catching overflowed Adam moments that a
  pre-update loss canary misses (the CheckpointCallback guard's blind
  spot, trainer/callback.py);
- ``param_norm`` and ``update_ratio`` (``||update|| / ||param||``) —
  the classic lr-sanity ratio (~1e-3 healthy, ~1 means the step is
  rewriting the network);
- ``nonfinite_grad_leaves`` / ``nonfinite_update_leaves`` — count of
  param leaves containing any non-finite value (for a leaf sharded
  over a mesh axis each bad SHARD counts once, so the number can
  exceed the leaf count — it is a severity signal whose load-bearing
  property is ``> 0``).

Sharding correctness: inside ``shard_map`` every leaf is a local
shard. For leaves *sharded* over a mesh axis the local partial sums
add up across that axis; for leaves *replicated* over an axis the
copies are identical and must be counted once. Both cases fold into a
single ``psum`` over ALL mesh axes by pre-dividing each leaf's partial
by the total size of the axes it is replicated over — so the whole
stats tree costs exactly one fused psum (sums + flag counts) plus one
pmax (maxima) beyond the grad-mean tree.
"""
from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


# the one spec-axis-membership helper lives in parallel/hybrid.py; by
# the time this module loads (via the telemetry package __init__,
# whose callback import already pulled trainer -> hybrid) it is
# initialized, while the reverse import direction would cycle
from pipegoose_tpu.parallel.hybrid import spec_mentions as _spec_mentions


def _key_name(k: Any) -> str:
    """Pretty name of one tree_flatten_with_path key entry."""
    for attr in ("key", "idx", "name"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def module_of(path: Tuple[Any, ...]) -> str:
    """Top-level param-group name of a tree path ('' for a bare leaf)."""
    return _key_name(path[0]) if path else ""


def _replication_factor(spec: P, axes: Sequence[str]) -> Any:
    """Product of mesh-axis sizes this leaf is REPLICATED over (static
    python int under shard_map: compat's axis_size const-folds)."""
    n = 1
    for ax in axes:
        if not _spec_mentions(spec, ax):
            n *= lax.axis_size(ax)
    return n


def health_stats(
    grads: Any,
    params: Any,
    new_params: Any,
    param_specs: Any,
    *,
    axes: Sequence[str] = (),
    mean_axes: Sequence[str] = (),
    eps: float = 1e-12,
) -> Dict[str, Any]:
    """Fused health reduction over one step's grad/param/update trees.

    ``axes``: ALL mesh axis names bound by the surrounding shard_map
    (empty = single-device / outside shard_map: no collectives emitted,
    the same arithmetic runs locally — how the equivalence tests use
    it). ``mean_axes``: axes over which replicated-param grads are
    still PARTIAL per rank (the data axis before the optimizer's
    reduce-scatter); those leaves get a ``pmean`` first — the "one
    extra all-reduce tree" the with_health flag buys.

    Returns a flat dict of f32 scalars (plus the per-module sub-dict),
    replicated across the mesh — ``out_specs=P()`` downstream.
    """
    axes = tuple(axes)
    mean_axes = tuple(mean_axes)
    spec_leaves = jax.tree_util.tree_leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P)
    )
    g_paths = jax.tree_util.tree_flatten_with_path(grads)[0]
    p_leaves = jax.tree_util.tree_leaves(params)
    q_leaves = jax.tree_util.tree_leaves(new_params)
    if not (len(spec_leaves) == len(g_paths) == len(p_leaves) == len(q_leaves)):
        raise ValueError(
            f"tree mismatch: {len(g_paths)} grad leaves, {len(p_leaves)} "
            f"param leaves, {len(q_leaves)} updated leaves, "
            f"{len(spec_leaves)} specs"
        )

    modules = sorted({module_of(path) for path, _ in g_paths})
    mod_sq = {m: jnp.float32(0.0) for m in modules}
    g_sq = u_sq = p_sq = jnp.float32(0.0)
    g_bad = u_bad = jnp.float32(0.0)
    u_mx = jnp.float32(0.0)

    for (path, g), p, q, spec in zip(g_paths, p_leaves, q_leaves, spec_leaves):
        # replicated-over-data grads are per-rank partials: mean them so
        # the norm below is the norm of the TRUE (optimizer-seen) grad
        for ax in mean_axes:
            if not _spec_mentions(spec, ax):
                g = lax.pmean(g, ax)
        repl = _replication_factor(spec, axes)
        g32 = g.astype(jnp.float32)
        u32 = (q.astype(jnp.float32) - p.astype(jnp.float32))
        p32 = p.astype(jnp.float32)

        g_sq += jnp.sum(jnp.square(g32)) / repl
        mod_sq[module_of(path)] += jnp.sum(jnp.square(g32)) / repl
        u_sq += jnp.sum(jnp.square(u32)) / repl
        p_sq += jnp.sum(jnp.square(p32)) / repl
        # per-leaf-shard flags (any nonfinite element) summed into
        # counts; replicated copies are de-duplicated by the same
        # divide-then-psum as the sq-sums
        g_bad += jnp.any(~jnp.isfinite(g32)).astype(jnp.float32) / repl
        u_bad += jnp.any(~jnp.isfinite(u32)).astype(jnp.float32) / repl
        u_mx = jnp.maximum(u_mx, jnp.max(jnp.abs(u32)))

    if axes:
        # ONE fused psum for every additive stat (sums AND the flag
        # counts — the leaf flags were divided by their replication
        # factor, so the all-axes psum restores exact 0/1-per-leaf
        # counts), one pmax for maxima. NaN caveat: a nonfinite shard
        # makes its sq-sum nonfinite — exactly the signal we want
        # propagated — while the *_bad flags use any(~isfinite), which
        # never yields NaN itself.
        stacked = lax.psum(
            jnp.stack(
                [g_sq, u_sq, p_sq, g_bad, u_bad]
                + [mod_sq[m] for m in modules]
            ),
            axes,
        )
        u_mx = lax.pmax(u_mx, axes)
        g_sq, u_sq, p_sq, g_bad, u_bad = (
            stacked[0], stacked[1], stacked[2], stacked[3], stacked[4]
        )
        mod_sq = {m: stacked[5 + i] for i, m in enumerate(modules)}

    g_norm = jnp.sqrt(g_sq)
    u_norm = jnp.sqrt(u_sq)
    p_norm = jnp.sqrt(p_sq)
    # rounding in the flag psums: counts are integral by construction
    g_bad = jnp.round(g_bad)
    u_bad = jnp.round(u_bad)
    return {
        "grad_norm": g_norm,
        "grad_norm_per_module": {m: jnp.sqrt(mod_sq[m]) for m in modules},
        "nonfinite_grad_leaves": g_bad,
        "nonfinite_update_leaves": u_bad,
        "update_max_abs": u_mx,
        "update_norm": u_norm,
        "param_norm": p_norm,
        "update_ratio": u_norm / (p_norm + eps),
    }


def host_health(health: Any) -> Any:
    """Device health pytree -> plain nested dict of python floats (one
    blocking fetch; the flight recorder's record format)."""
    if health is None:
        return None
    return jax.tree_util.tree_map(float, health)
