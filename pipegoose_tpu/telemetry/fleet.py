"""Fleet-level metric aggregation: N replica registries, one view.

Every serving replica owns its own ``MetricsRegistry`` (its engine's
counters/histograms must not interleave with a neighbor's), but SLO
verdicts and dashboards want the FLEET: "p95 TTFT across all
replicas", "total shed fraction". :class:`FleetRegistry` is a real
``MetricsRegistry`` whose :meth:`metrics` view overlays its own
metrics on a merge of every member registry:

- **Counters** sum (``serving.shed_total`` fleet-wide is the sum of
  the replicas').
- **Histograms** merge exactly: per-bucket counts, total count, and
  sum add — the merged bucket distribution IS the distribution of the
  union of observations, so a burn rate computed on the merged
  histogram equals one computed on a single registry that saw every
  observation (pinned by test, including multi-window blip
  suppression). Reservoirs concatenate member-order then truncate to
  the cap — quantiles over the merged reservoir are approximate the
  same way any reservoir's are. Merging requires identical bucket
  boundaries; mismatched buckets raise.
- **Gauges** sum, because the fleet gauges that matter are capacities
  (free pages, queue depth); intensive gauges (ratios, occupancies)
  should be read per member. Documented sharp edge, not a bug trap:
  the per-replica values stay available in each member registry.

The fleet registry's OWN metrics win name collisions — that is where
an ``SLOMonitor`` over the fleet writes its ``slo.*`` gauges and
alert counters without them being re-merged from members.

Host-side only; merging snapshots member state under each metric's own
lock, so a concurrent engine tick never torn-reads.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from pipegoose_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)


def merge_counters(name: str, counters: List[Counter]) -> Counter:
    out = Counter(name, help=counters[0].help if counters else "")
    total = 0.0
    for c in counters:
        with c._lock:
            total += c._value
    out._value = total
    return out


def merge_gauges(name: str, gauges: List[Gauge]) -> Gauge:
    out = Gauge(name, help=gauges[0].help if gauges else "")
    vals = []
    for g in gauges:
        with g._lock:
            v = g._value
        if v == v:             # skip NaN (never-set members)
            vals.append(v)
    out._value = sum(vals) if vals else float("nan")
    return out


def merge_histograms(name: str, hists: List[Histogram]) -> Histogram:
    """Exact bucket/count/sum merge (module docstring). The merged
    object is a real ``Histogram`` — everything that reads bucket
    counts (Prometheus export, ``SLOMonitor._read``) works on it
    unchanged."""
    buckets = hists[0].buckets
    for h in hists[1:]:
        if h.buckets != buckets:
            raise ValueError(
                f"histogram {name!r}: cannot merge mismatched buckets "
                f"{h.buckets} vs {buckets}"
            )
    out = Histogram(name, help=hists[0].help, buckets=buckets)
    counts = [0] * (len(buckets) + 1)
    total = 0
    sum_ = 0.0
    lo, hi = float("inf"), float("-inf")
    reservoir: List[float] = []
    for h in hists:
        with h._lock:
            h_counts = list(h._counts)
            h_count, h_sum = h._count, h._sum
            h_min, h_max = h._min, h._max
            h_res = list(h._reservoir)
        for i, c in enumerate(h_counts):
            counts[i] += c
        total += h_count
        sum_ += h_sum
        lo, hi = min(lo, h_min), max(hi, h_max)
        reservoir.extend(h_res)
    out._counts = counts
    out._count = total
    out._sum = sum_
    out._min = lo
    out._max = hi
    out._reservoir = reservoir[:out._cap]
    return out


def merge_metrics(members: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge the ``metrics()`` dicts of several registries into fresh
    metric objects (same-name metrics must share a type)."""
    by_name: Dict[str, List[Any]] = {}
    for metrics in members:
        for name, m in metrics.items():
            by_name.setdefault(name, []).append(m)
    out: Dict[str, Any] = {}
    for name, ms in by_name.items():
        kinds = {type(m) for m in ms}
        if len(kinds) != 1:
            raise TypeError(
                f"metric {name!r} has conflicting types across members: "
                f"{sorted(k.__name__ for k in kinds)}"
            )
        if isinstance(ms[0], Counter):
            out[name] = merge_counters(name, ms)
        elif isinstance(ms[0], Gauge):
            out[name] = merge_gauges(name, ms)
        elif isinstance(ms[0], Histogram):
            out[name] = merge_histograms(name, ms)
        else:  # unknown metric kind: pass the first through untouched
            out[name] = ms[0]
    return out


class FleetRegistry(MetricsRegistry):
    """A ``MetricsRegistry`` whose read view merges member registries
    (module docstring). Writes (``counter()``/``gauge()``/
    ``histogram()`` handles, ``event()``) land on the fleet registry
    itself — e.g. the fleet ``SLOMonitor``'s gauges — and overlay the
    merged member metrics on name collision."""

    def __init__(self, members: Optional[List[Tuple[str, MetricsRegistry]]]
                 = None, enabled: bool = True):
        super().__init__(enabled=enabled)
        self._members: List[Tuple[str, MetricsRegistry]] = []
        for name, reg in members or []:
            self.add_member(name, reg)

    def add_member(self, name: str, registry: MetricsRegistry) -> None:
        if any(n == name for n, _ in self._members):
            raise ValueError(f"fleet member {name!r} already registered")
        self._members.append((name, registry))

    def remove_member(self, name: str) -> None:
        before = len(self._members)
        self._members = [(n, r) for n, r in self._members if n != name]
        if len(self._members) == before:
            raise ValueError(f"no fleet member named {name!r}")

    @property
    def member_names(self) -> List[str]:
        return [n for n, _ in self._members]

    def metrics(self) -> Dict[str, Any]:
        merged = merge_metrics(
            [reg.metrics() for _, reg in self._members]
        )
        merged.update(super().metrics())   # own metrics win collisions
        return merged

    def member_snapshots(self) -> Dict[str, dict]:
        """Per-member plain-dict snapshots (the /debug/fleet per-replica
        breakdown next to the merged view)."""
        return {name: reg.snapshot() for name, reg in self._members}
