"""Shared autoregressive decode driver.

One compiled prefill + scan-decode loop, parameterized by a model's
``forward_cached(params, ids, cache, start, config) -> (logits, cache)``
— used by both BLOOM (models/generate.py) and Mixtral
(models/mixtral.py) so EOS semantics, sampling, and jit caching cannot
drift between model families.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

# bounded: a long-lived process generating from many prompt lengths /
# temperatures would otherwise retain every compiled program pair
_JIT_CACHE: dict = {}
_JIT_CACHE_MAX = 32

_MASKS: dict = {}


def vocab_mask_for(config):
    """Memoized padded-vocab logits mask, keyed on valid size: None when
    the config has no ``valid_vocab_size``. The closure participates in
    the decode driver's jit-cache key, so it must be a stable object.
    pad_for_tp zero-rows give padded slots logit 0.0 exactly — they must
    never win a decode step."""
    valid = getattr(config, "valid_vocab_size", None)
    if valid is None:
        return None
    if valid not in _MASKS:
        from pipegoose_tpu.nn.tensor_parallel.layers import mask_padded_vocab

        def mask(logits, _valid=valid):
            return mask_padded_vocab(logits, None, _valid)

        _MASKS[valid] = mask
    return _MASKS[valid]


def autoregressive_generate(
    forward_cached: Callable,
    init_cache: Callable,
    params,
    input_ids: jax.Array,
    config,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_token_id: Optional[int] = None,
    logits_mask: Optional[Callable] = None,
) -> jax.Array:
    """Greedy (temperature=0) or sampled decoding with a KV cache.

    - ``eos_token_id``: finished sequences emit eos from then on (HF
      generate's pad-with-eos behavior);
    - ``logits_mask(logits) -> logits``: e.g. padded-vocab masking;
    - compiled programs cached per (model fwd, config, prompt len, exact
      temperature, eos) — params stay runtime arguments.
    """
    if max_new_tokens <= 0:
        return input_ids
    b, s = input_ids.shape
    cache = init_cache(config, b, s + max_new_tokens)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    eos = -1 if eos_token_id is None else int(eos_token_id)

    key = (forward_cached, config, s, float(temperature), eos, logits_mask)
    if key not in _JIT_CACHE:

        def pick(logits, k):
            if logits_mask is not None:
                logits = logits_mask(logits)
            if temperature <= 0.0:
                return jnp.argmax(logits, axis=-1)
            return jax.random.categorical(k, logits / temperature, axis=-1)

        @jax.jit
        def prefill(params, ids, cache, k):
            logits, cache = forward_cached(params, ids, cache, 0, config)
            return pick(logits, k), cache

        @jax.jit
        def decode_all(params, first, cache, keys):
            def step(carry, k):
                tok, done, cache, pos = carry
                logits, cache = forward_cached(params, tok[:, None], cache, pos, config)
                nxt = pick(logits, k)
                nxt = jnp.where(done, eos, nxt)
                done = done | (nxt == eos)
                return (nxt, done, cache, pos + 1), nxt

            init = (first, first == eos, cache, jnp.asarray(s))
            _, toks = lax.scan(step, init, keys)
            return toks

        while len(_JIT_CACHE) >= _JIT_CACHE_MAX:
            _JIT_CACHE.pop(next(iter(_JIT_CACHE)))  # evict least-recent
        _JIT_CACHE[key] = (prefill, decode_all)
    else:
        _JIT_CACHE[key] = _JIT_CACHE.pop(key)  # LRU refresh on hit
    prefill, decode_all = _JIT_CACHE[key]

    first, cache = prefill(params, input_ids, cache, rng)
    if max_new_tokens == 1:
        return jnp.concatenate([input_ids, first[:, None]], axis=1)
    keys = jax.random.split(jax.random.fold_in(rng, 1), max_new_tokens - 1)
    rest = decode_all(params, first, cache, keys)
    out = jnp.concatenate([first[:, None], rest.T], axis=1)
    return jnp.concatenate([input_ids, out], axis=1)
