"""Shared autoregressive decode driver.

One compiled prefill + scan-decode loop, parameterized by a model's
``forward_cached(params, ids, cache, start, config) -> (logits, cache)``
— used by both BLOOM (models/generate.py) and Mixtral
(models/mixtral.py) so EOS semantics, sampling, and jit caching cannot
drift between model families.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from pipegoose_tpu.telemetry.spans import span

# bounded: a long-lived process generating from many prompt lengths /
# temperatures would otherwise retain every compiled program pair
_JIT_CACHE: dict = {}
_JIT_CACHE_MAX = 32

_MASKS: dict = {}


def vocab_mask_for(config):
    """Memoized padded-vocab logits mask, keyed on valid size: None when
    the config has no ``valid_vocab_size``. The closure participates in
    the decode driver's jit-cache key, so it must be a stable object.
    pad_for_tp zero-rows give padded slots logit 0.0 exactly — they must
    never win a decode step."""
    valid = getattr(config, "valid_vocab_size", None)
    if valid is None:
        return None
    if valid not in _MASKS:
        from pipegoose_tpu.nn.tensor_parallel.layers import mask_padded_vocab

        def mask(logits, _valid=valid):
            return mask_padded_vocab(logits, None, _valid)

        _MASKS[valid] = mask
    return _MASKS[valid]


def greedy_token(logits: jax.Array, logits_mask: Optional[Callable] = None
                 ) -> jax.Array:
    """Single-device greedy pick: optional padded-vocab mask, then argmax
    — the temperature-0 branch of :func:`autoregressive_generate`'s pick,
    shared with the serving engine's slot-batched decode step
    (serving/engine.py) so the two paths cannot drift."""
    if logits_mask is not None:
        logits = logits_mask(logits)
    return jnp.argmax(logits, axis=-1)


def autoregressive_generate(
    forward_cached: Callable,
    init_cache: Callable,
    params,
    input_ids: jax.Array,
    config,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_token_id: Optional[int] = None,
    logits_mask: Optional[Callable] = None,
    extras=None,
) -> jax.Array:
    """Greedy (temperature=0) or sampled decoding with a KV cache.

    - ``eos_token_id``: finished sequences emit eos from then on (HF
      generate's pad-with-eos behavior);
    - ``logits_mask(logits) -> logits``: e.g. padded-vocab masking;
    - ``extras``: optional pytree of RUNTIME side inputs forwarded to
      ``forward_cached(..., extras=extras)`` — e.g. the extended
      attention mask for ragged/left-padded prompts. A runtime argument,
      NOT baked into the compiled program: new masks don't recompile;
    - compiled programs cached per (model fwd, config, prompt len, exact
      temperature, eos) — params stay runtime arguments.
    """
    if max_new_tokens <= 0:
        return input_ids
    b, s = input_ids.shape
    cache = init_cache(config, b, s + max_new_tokens)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    eos = -1 if eos_token_id is None else int(eos_token_id)

    key = (
        forward_cached, config, s, float(temperature), eos, logits_mask,
        extras is not None,
    )
    if key not in _JIT_CACHE:

        def pick(logits, k):
            if temperature <= 0.0:
                return greedy_token(logits, logits_mask)
            if logits_mask is not None:
                logits = logits_mask(logits)
            return jax.random.categorical(k, logits / temperature, axis=-1)

        def fwd(params, ids, cache, pos, extras):
            if extras is None:
                return forward_cached(params, ids, cache, pos, config)
            return forward_cached(params, ids, cache, pos, config, extras=extras)

        @jax.jit
        def prefill(params, ids, cache, k, extras):
            logits, cache = fwd(params, ids, cache, 0, extras)
            return pick(logits, k), cache

        @jax.jit
        def decode_all(params, first, cache, keys, extras):
            def step(carry, k):
                tok, done, cache, pos = carry
                logits, cache = fwd(params, tok[:, None], cache, pos, extras)
                nxt = pick(logits, k)
                nxt = jnp.where(done, eos, nxt)
                done = done | (nxt == eos)
                return (nxt, done, cache, pos + 1), nxt

            init = (first, first == eos, cache, jnp.asarray(s))
            _, toks = lax.scan(step, init, keys)
            return toks

        while len(_JIT_CACHE) >= _JIT_CACHE_MAX:
            _JIT_CACHE.pop(next(iter(_JIT_CACHE)))  # evict least-recent
        _JIT_CACHE[key] = (prefill, decode_all)
    else:
        _JIT_CACHE[key] = _JIT_CACHE.pop(key)  # LRU refresh on hit
    prefill, decode_all = _JIT_CACHE[key]

    # spans are no-ops unless telemetry is enabled; fencing then pins the
    # prefill/decode device work to the right span (telemetry/spans.py)
    with span("generate.prefill", attrs={"prompt_len": s, "batch": b}) as sp:
        first, cache = prefill(params, input_ids, cache, rng, extras)
        sp.fence(first)
    if max_new_tokens == 1:
        return jnp.concatenate([input_ids, first[:, None]], axis=1)
    keys = jax.random.split(jax.random.fold_in(rng, 1), max_new_tokens - 1)
    with span("generate.decode",
              attrs={"new_tokens": max_new_tokens, "batch": b}) as sp:
        rest = decode_all(params, first, cache, keys, extras)
        sp.fence(rest)
    out = jnp.concatenate([first[:, None], rest.T], axis=1)
    return jnp.concatenate([input_ids, out], axis=1)


def global_greedy_pick(logits_local: jax.Array, tp_axis: str,
                       valid_size: Optional[int] = None) -> jax.Array:
    """Greedy argmax over a VOCAB-SHARDED logits row (B, V/tp): each
    shard takes its local argmax, an all_gather compares shard maxima,
    and the winner's local index is offset to the global id. Padded
    vocab slots (>= valid_size) are masked by their GLOBAL column."""
    b, vloc = logits_local.shape
    logits_local = logits_local.astype(jnp.float32)
    rank = lax.axis_index(tp_axis)
    if valid_size is not None:
        gcol = rank * vloc + jnp.arange(vloc)
        logits_local = jnp.where(gcol[None, :] < valid_size, logits_local, -1e30)
    local_idx = jnp.argmax(logits_local, axis=-1)  # (B,)
    local_max = jnp.max(logits_local, axis=-1)
    all_max = lax.all_gather(local_max, tp_axis)  # (tp, B)
    all_idx = lax.all_gather(local_idx, tp_axis)
    best = jnp.argmax(all_max, axis=0)  # (B,) winning shard per row
    widx = jnp.take_along_axis(all_idx, best[None, :], axis=0)[0]
    return best * vloc + widx


def autoregressive_generate_sharded(
    forward_cached: Callable,
    init_cache: Callable,
    params,
    input_ids: jax.Array,
    config,
    max_new_tokens: int,
    mesh,
    param_specs,
    tp_axis: str = "tensor",
    eos_token_id: Optional[int] = None,
    extras=None,
) -> jax.Array:
    """TENSOR-PARALLEL greedy decoding: the whole generation (prefill +
    scanned decode) runs as one shard_map program over ``mesh`` with
    vocab/head-sharded weights and a per-shard KV cache of nh/tp heads
    — distributed inference, which the reference cannot do at all (its
    re-classed modules break HF ``generate``).

    ``forward_cached(params, ids, cache, start, config, tp_axis)`` must
    return LOCAL vocab-shard logits (the model's TP decode path);
    ``init_cache(config, b, max_len, tp)`` the local-head cache;
    ``extras`` an optional replicated side-input pytree forwarded to
    ``forward_cached`` (ragged-prompt masks). Greedy only: sampling
    under a sharded vocab needs a global categorical — use the
    single-device path for temperature > 0.
    """
    from jax.sharding import PartitionSpec as P

    from pipegoose_tpu.distributed.compat import shard_map

    if max_new_tokens <= 0:
        return input_ids
    b, s = input_ids.shape
    tp = mesh.shape[tp_axis]
    eos = -1 if eos_token_id is None else int(eos_token_id)
    valid = getattr(config, "valid_vocab_size", None)

    def fwd(params, ids, cache, pos, extras):
        if extras is None:
            return forward_cached(params, ids, cache, pos, config, tp_axis)
        return forward_cached(
            params, ids, cache, pos, config, tp_axis, extras=extras
        )

    def body(params, ids, extras):
        cache = init_cache(config, b, s + max_new_tokens, tp)
        logits, cache = fwd(params, ids, cache, 0, extras)
        first = global_greedy_pick(logits, tp_axis, valid)

        def step(carry, _):
            tok, done, cache, pos = carry
            logits, cache = fwd(params, tok[:, None], cache, pos, extras)
            nxt = global_greedy_pick(logits, tp_axis, valid)
            nxt = jnp.where(done, eos, nxt)
            done = done | (nxt == eos)
            return (nxt, done, cache, pos + 1), nxt

        init = (first, first == eos, cache, jnp.asarray(s))
        _, rest = lax.scan(step, init, None, length=max_new_tokens - 1)
        return jnp.concatenate([first[:, None], rest.T], axis=1)

    extras_specs = jax.tree_util.tree_map(lambda _: P(), extras)
    fn = jax.jit(
        shard_map(
            body, mesh=mesh,
            in_specs=(param_specs, P(), extras_specs),
            out_specs=P(),
            check_vma=False,
        )
    )
    # prefill + decode fuse into ONE shard_map program here, so a single
    # span covers the whole sharded generation
    with span("generate.sharded",
              attrs={"prompt_len": s, "new_tokens": max_new_tokens,
                     "batch": b, "tp": tp}) as sp:
        out = fn(params, input_ids, extras)
        sp.fence(out)
    return jnp.concatenate([input_ids, out], axis=1)
