"""Mixtral: sparse-MoE transformer (RMSNorm, RoPE, GQA, SwiGLU experts).

Second model family, targeting BASELINE.json config 5 (Mixtral-8x7B 4D
TP x PP x DP x EP + DiLoCo). The reference supports only BLOOM
(README.md:19); this model is built on the same framework primitives —
stacked-layer scan, TP layer functions, static-shape MoE dispatch — so
every parallel form (TP/DP/EP/ZeRO/PP-ready stacked layout) applies.

Semantics match HF ``modeling_mixtral`` for checkpoint parity:
- RMSNorm (no bias, f32 stats), rotate-half RoPE (theta from config),
  GQA via kv-head repetition, scaling = head_dim**-0.5;
- SwiGLU experts: w2(silu(w1(x)) * w3(x)); router = softmax over f32
  logits -> top-k -> renormalize (HF MixtralSparseMoeBlock:112-118) —
  exactly our TopKRouter with normalize_gates=True and ample capacity.
Parity is tested against HF in tests/models/test_mixtral.py.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pipegoose_tpu.nn.expert_parallel.experts import moe_layer
from pipegoose_tpu.nn.expert_parallel.loss import ExpertLoss
from pipegoose_tpu.nn.expert_parallel.routers import SwitchNoisePolicy, TopKRouter
from pipegoose_tpu.nn.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_cross_entropy,
    vocab_parallel_embedding,
)

NEG_INF = -1e9


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 8
    num_experts: int = 8
    top_k: int = 2
    rope_theta: float = 1e6
    rms_eps: float = 1e-5
    initializer_range: float = 0.02
    router_jitter: float = 0.0
    aux_loss_weight: float = 0.001  # HF MixtralConfig router_aux_loss_coef default
    z_loss_weight: float = 0.0
    # None -> no-drop capacity (= num_experts/top_k, i.e. C = n_tokens):
    # HF's MixtralSparseMoeBlock never drops, so checkpoint parity needs
    # this; set a real factor (e.g. 1.25-2.0) for capacity-bound training
    capacity_factor: Optional[float] = None
    dtype: Any = jnp.float32
    remat: bool = False
    # fused Pallas flash attention (ops/flash_attention.py): applied
    # after RoPE, zero ALiBi slopes, padding via the kernel's kv_neg
    # bias input; GQA served natively (grouped K/V index maps, no head
    # repetition)
    use_flash: bool = False
    # fused Pallas CE (ops/fused_ce.py): no logits buffer in HBM
    fused_ce: bool = False
    # set when the embedding/head was padded for TP divisibility: the
    # true vocab size; padded logit slots are masked out of CE + decode
    valid_vocab_size: Optional[int] = None
    # Mistral-style sliding-window attention: each query attends keys
    # within `sliding_window` positions behind it (None = full causal;
    # HF Mixtral-8x7B configs disable it)
    sliding_window: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.n_head

    @classmethod
    def mixtral_8x7b(cls, **kw) -> "MixtralConfig":
        return cls(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                   n_layer=32, n_head=32, n_kv_head=8, **kw)

    def router(self) -> TopKRouter:
        noise = SwitchNoisePolicy(self.router_jitter) if self.router_jitter else None
        cf = (
            self.capacity_factor
            if self.capacity_factor is not None
            else self.num_experts / self.top_k  # C = n_tokens: no drops
        )
        return TopKRouter(
            num_experts=self.num_experts,
            top_k=self.top_k,
            capacity_factor=cf,
            noise=noise,
            normalize_gates=True,
        )


# -- init ------------------------------------------------------------------

def init_params(config: MixtralConfig, key: jax.Array) -> dict:
    h, v, L = config.hidden_size, config.vocab_size, config.n_layer
    hd, nh, nkv = config.head_dim, config.n_head, config.n_kv_head
    f, E = config.intermediate_size, config.num_experts
    std, dt = config.initializer_range, config.dtype
    ks = jax.random.split(key, 10)

    def dense(k, shape):
        return (jax.random.normal(k, shape) * std).astype(dt)

    def rms_stack():
        return {"scale": jnp.ones((L, h), dt)}

    return {
        "embed": {"weight": dense(ks[0], (v, h))},
        "blocks": {
            "ln_1": rms_stack(),
            "attn": {
                "q": {"kernel": dense(ks[1], (L, h, nh * hd))},
                "k": {"kernel": dense(ks[2], (L, h, nkv * hd))},
                "v": {"kernel": dense(ks[3], (L, h, nkv * hd))},
                "o": {"kernel": dense(ks[4], (L, nh * hd, h))},
            },
            "ln_2": rms_stack(),
            "router": {"gate": {"kernel": dense(ks[5], (L, h, E))}},
            "moe": {
                "w1": {"kernel": dense(ks[6], (L, E, h, f))},  # gate proj
                "w3": {"kernel": dense(ks[7], (L, E, h, f))},  # up proj
                "w2": {"kernel": dense(ks[8], (L, E, f, h))},  # down proj
            },
        },
        "ln_f": {"scale": jnp.ones(h, dt)},
        "lm_head": {"kernel": dense(ks[9], (h, v))},
    }


# -- ops -------------------------------------------------------------------

def rms_norm(params: dict, x: jax.Array, eps: float) -> jax.Array:
    dt = x.dtype
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt((xf * xf).mean(-1, keepdims=True) + eps)
    return (y * params["scale"]).astype(dt)


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """HF ``rope_scaling`` semantics (transformers modeling_rope_utils):
    ``linear`` divides positions by ``factor``; ``dynamic`` is NTK theta
    rescaling past the original context; ``llama3`` is the per-frequency
    interpolation of Llama-3.1+ checkpoints. Frozen dataclass (not the
    raw HF dict) so configs stay hashable for jit static args."""

    rope_type: str  # "linear" | "dynamic" | "llama3"
    factor: float = 1.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192

    @classmethod
    def from_hf(cls, d, default_original_max: int = 8192) -> Optional["RopeScaling"]:
        if d is None:
            return None
        rope_type = d.get("rope_type", d.get("type", "default"))
        if rope_type == "default":
            return None
        if rope_type not in ("linear", "dynamic", "llama3"):
            raise NotImplementedError(
                f"rope_scaling type {rope_type!r} not supported "
                "(linear, dynamic, llama3 are)"
            )
        return cls(
            rope_type=rope_type,
            factor=float(d.get("factor", 1.0)),
            low_freq_factor=float(d.get("low_freq_factor", 1.0)),
            high_freq_factor=float(d.get("high_freq_factor", 4.0)),
            original_max_position_embeddings=int(
                d.get("original_max_position_embeddings", default_original_max)
            ),
        )


def _scaled_inv_freq(inv: jax.Array, seq: int, head_dim: int, theta: float,
                     scaling: RopeScaling) -> jax.Array:
    """Apply one RopeScaling variant to the base inverse frequencies."""
    if scaling.rope_type == "linear":
        return inv / scaling.factor
    if scaling.rope_type == "dynamic":
        orig = scaling.original_max_position_embeddings
        if seq <= orig:  # static shape — resolved at trace time
            return inv
        theta = theta * (
            (scaling.factor * seq / orig) - (scaling.factor - 1)
        ) ** (head_dim / (head_dim - 2))
        return 1.0 / (
            theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
        )
    if scaling.rope_type == "llama3":
        orig = scaling.original_max_position_embeddings
        low_wl = orig / scaling.low_freq_factor
        high_wl = orig / scaling.high_freq_factor
        wavelen = 2.0 * jnp.pi / inv
        inv_lo = jnp.where(wavelen > low_wl, inv / scaling.factor, inv)
        smooth = (orig / wavelen - scaling.low_freq_factor) / (
            scaling.high_freq_factor - scaling.low_freq_factor
        )
        smoothed = (1.0 - smooth) * inv / scaling.factor + smooth * inv
        mid = (wavelen >= high_wl) & (wavelen <= low_wl)
        return jnp.where(mid, smoothed, inv_lo)
    raise NotImplementedError(scaling.rope_type)


def rope_cos_sin(seq: int, head_dim: int, theta: float,
                 scaling: Optional[RopeScaling] = None):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if scaling is not None:
        inv = _scaled_inv_freq(inv, seq, head_dim, theta, scaling)
    t = jnp.arange(seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)  # (S, hd/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # (S, hd)
    return jnp.cos(emb), jnp.sin(emb)


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(q, k, cos, sin):
    """q,k: (B, S, h, hd); cos/sin: (S, hd)."""
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    return q * cos + _rotate_half(q) * sin, k * cos + _rotate_half(k) * sin


def causal_mask_bias(
    attention_mask: jax.Array, window: Optional[int] = None
) -> jax.Array:
    """Combined causal + padding (+ optional sliding window) additive
    bias (B, 1, S, S) — shared by the Mixtral and Llama families
    (absolute positions; RoPE models carry no ALiBi term)."""
    s = attention_mask.shape[-1]
    keep = jnp.tril(jnp.ones((s, s), bool))
    if window is not None:
        pos = jnp.arange(s)
        keep = keep & (pos[:, None] - pos[None, :] < window)
    keep = keep[None, None] & (attention_mask[:, None, None, :] > 0)
    return jnp.where(keep, 0.0, NEG_INF).astype(jnp.float32)


def rope_attention_bias(attention_mask: jax.Array, config) -> dict:
    """Bias inputs in the form the configured attention path consumes
    (shared by Mixtral and Llama): flash gets the O(S) per-key validity
    bias ``kv_neg`` (the causal mask lives inside the kernel); the
    standard path gets the dense (B, 1, S, S) ``mask_bias``."""
    if config.use_flash:
        from pipegoose_tpu.ops.flash_attention import mask_to_kv_bias

        # the sliding window (if any) is applied inside the kernel
        return {"kv_neg": mask_to_kv_bias(attention_mask)[1]}
    return {"mask_bias": causal_mask_bias(
        attention_mask, getattr(config, "sliding_window", None)
    )}


def _swiglu_experts(moe_params: dict, x: jax.Array, tp_axis: Optional[str]) -> jax.Array:
    """(E_local, C, H) -> (E_local, C, H): w2(silu(w1 x) * w3 x), with the
    FFN dim Megatron-sharded over tensor (w1/w3 column, w2 row+reduce)."""
    from pipegoose_tpu.distributed.functional import (
        copy_to_tensor_group,
        reduce_from_tensor_group,
    )

    if tp_axis is not None:
        # f-operator (see expert_mlp): completes the input cotangent's
        # psum across tensor ranks in backward
        x = copy_to_tensor_group(x, tp_axis)
    g = jnp.einsum("ech,ehf->ecf", x, moe_params["w1"]["kernel"],
                   preferred_element_type=jnp.float32).astype(x.dtype)
    u = jnp.einsum("ech,ehf->ecf", x, moe_params["w3"]["kernel"],
                   preferred_element_type=jnp.float32).astype(x.dtype)
    h = jax.nn.silu(g) * u
    out = jnp.einsum("ecf,efh->ech", h, moe_params["w2"]["kernel"],
                     preferred_element_type=jnp.float32).astype(x.dtype)
    if tp_axis is not None:
        out = reduce_from_tensor_group(out, tp_axis)
    return out


def _attention(blk, x, cos, sin, bias, config, tp_axis):
    """RoPE + GQA attention; ``bias`` is the dict from
    :func:`rope_attention_bias` (dense mask_bias OR flash kv_neg)."""
    b, s, _ = x.shape
    hd = config.head_dim
    tp = jax.lax.axis_size(tp_axis) if tp_axis else 1
    if config.n_head % tp or config.n_kv_head % tp:
        raise ValueError(
            f"n_head={config.n_head}/n_kv_head={config.n_kv_head} must divide "
            f"tensor axis size {tp}"
        )
    nh_l, nkv_l = config.n_head // tp, config.n_kv_head // tp
    groups = nh_l // nkv_l

    q = column_parallel_linear(blk["q"], x, tp_axis).reshape(b, s, nh_l, hd)
    k = column_parallel_linear(blk["k"], x, tp_axis).reshape(b, s, nkv_l, hd)
    v = column_parallel_linear(blk["v"], x, tp_axis).reshape(b, s, nkv_l, hd)
    q, k = apply_rope(q, k, cos, sin)

    if config.use_flash:
        from pipegoose_tpu.ops.flash_attention import flash_attention

        # native GQA: the kernel reads the nkv-wide K/V via grouped
        # index maps — no head repetition, g x less KV traffic
        ctx = flash_attention(
            q, k, v, alibi_slopes=None,  # RoPE: no ALiBi term
            kv_neg=bias["kv_neg"], causal=True,
            window=getattr(config, "sliding_window", None),
        )
        ctx = ctx.astype(x.dtype).reshape(b, s, nh_l * hd)
        return row_parallel_linear(blk["o"], ctx, tp_axis)

    # GQA: repeat kv heads for the dense einsum path
    k = jnp.repeat(k, groups, axis=2)
    v = jnp.repeat(v, groups, axis=2)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * (hd**-0.5) + bias["mask_bias"]
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v, preferred_element_type=jnp.float32)
    ctx = ctx.astype(x.dtype).reshape(b, s, nh_l * hd)
    return row_parallel_linear(blk["o"], ctx, tp_axis)


def _block(blk, x, cos, sin, bias, key, config, tp_axis, ep_axis, train):
    h = rms_norm(blk["ln_1"], x, config.rms_eps)
    x = x + _attention(blk["attn"], h, cos, sin, bias, config, tp_axis)
    h = rms_norm(blk["ln_2"], x, config.rms_eps)

    router = config.router()
    flat = h.reshape(-1, h.shape[-1])
    routing = router(blk["router"], flat, key=key, train=train)
    y = moe_layer(
        blk["moe"], h, routing, axis_name=ep_axis,
        tp_axis=tp_axis, act=None, mlp_fn=_swiglu_experts,
    )
    return x + y, routing.aux_loss, routing.z_loss


def forward_hidden(
    params, input_ids, attention_mask, config,
    tp_axis=None, ep_axis=None, rng=None, train=False,
):
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), jnp.int32)
    x = vocab_parallel_embedding(params["embed"], input_ids, tp_axis).astype(config.dtype)

    cos, sin = rope_cos_sin(s, config.head_dim, config.rope_theta)
    bias = rope_attention_bias(attention_mask, config)

    if rng is None:
        if train and config.router_jitter:
            raise ValueError("train=True with router jitter needs an explicit rng")
        rng = jax.random.PRNGKey(0)
    layer_keys = jax.random.split(rng, config.n_layer)

    def scan_fn(carry, blk_and_key):
        blk, key = blk_and_key
        out, aux, z = _block(
            blk, carry, cos, sin, bias, key, config, tp_axis, ep_axis, train
        )
        return out, (aux, z)

    step = jax.checkpoint(scan_fn) if config.remat else scan_fn
    x, (aux, z) = jax.lax.scan(step, x, (params["blocks"], layer_keys))
    return rms_norm(params["ln_f"], x, config.rms_eps), aux, z


def forward(params, input_ids, attention_mask, config,
            tp_axis=None, ep_axis=None, rng=None, train=False):
    """Logits (B, S, V/tp) — lm_head is column-parallel over tensor."""
    hidden, aux, z = forward_hidden(
        params, input_ids, attention_mask, config, tp_axis, ep_axis, rng, train
    )
    return column_parallel_linear(params["lm_head"], hidden, tp_axis), aux, z


def loss_fn(params, input_ids, attention_mask, labels, config,
            tp_axis=None, ep_axis=None, rng=None, train=True):
    if config.fused_ce:
        # fused Pallas CE on the (H, V/tp) column head in its native
        # layout (ops/fused_ce.py, weight_layout="hv") — no logits
        # buffer; the f-operator psum lives in the kernel's VJP
        from pipegoose_tpu.ops.fused_ce import fused_ce_shifted_loss

        hidden, aux, z = forward_hidden(
            params, input_ids, attention_mask, config, tp_axis, ep_axis,
            rng, train,
        )
        task = fused_ce_shifted_loss(
            hidden, params["lm_head"]["kernel"], labels, attention_mask,
            tp_axis, config.valid_vocab_size, weight_layout="hv",
        )
        return ExpertLoss(config.aux_loss_weight, config.z_loss_weight)(
            task, aux.mean(), z.mean()
        )
    logits, aux, z = forward(
        params, input_ids, attention_mask, config, tp_axis, ep_axis, rng, train
    )
    per_tok = vocab_parallel_cross_entropy(
        logits[:, :-1], labels[:, 1:], tp_axis, valid_size=config.valid_vocab_size
    )
    if attention_mask is not None:
        w = attention_mask[:, 1:].astype(per_tok.dtype)
        task = (per_tok * w).sum() / jnp.maximum(w.sum(), 1)
    else:
        task = per_tok.mean()
    # HF computes ONE load-balancing loss over all layers' gates jointly
    # (~O(1) when balanced); our scan yields per-layer losses, so take the
    # layer MEAN to keep router_aux_loss_coef on HF's scale
    return ExpertLoss(config.aux_loss_weight, config.z_loss_weight)(
        task, aux.mean(), z.mean()
    )


def _pp_prologue(
    input_ids, attention_mask, labels, config, n_microbatches, pipe_axis, rng,
    train, stage_layer_counts=None,
):
    """Shared pipeline setup for the GPipe and 1F1B Mixtral losses:
    validates the stage split, derives THIS stage's slice of the same
    L-layer router keys the dense path uses, splits microbatches, and
    builds the RoPE tables + per-microbatch attention bias (M-leading,
    ready as gpipe/1F1B side inputs).

    ``stage_layer_counts``: UNEVEN stages — the keys for stage p's live
    slots are ``layer_keys[offset_p : offset_p + n_p]`` (layer ORDER as
    in ``repartition_blocks``), padded to L_max; pad-slot keys are
    zeros and never reach a router (the masked scan skips the block)."""
    from pipegoose_tpu.nn.pipeline_parallel import microbatch as mb

    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), jnp.int32)

    P_pipe = jax.lax.axis_size(pipe_axis)
    L = config.n_layer
    stage = jax.lax.axis_index(pipe_axis)

    if rng is None:
        if train and config.router_jitter:
            raise ValueError("train=True with router jitter needs an explicit rng")
        rng = jax.random.PRNGKey(0)
    layer_keys = jax.random.split(rng, L)  # (L, 2) — same keys as dense

    from pipegoose_tpu.nn.pipeline_parallel.partitioner import stage_n_valid

    n_valid = None
    if stage_layer_counts is not None:
        n_valid = stage_n_valid(stage_layer_counts, L, pipe_axis)  # validates
        counts_np = np.asarray(stage_layer_counts, np.int64)
        L_max = int(counts_np.max())
        offsets = jnp.asarray(
            np.concatenate([[0], np.cumsum(counts_np)[:-1]]), jnp.int32
        )
        keys_padded = jnp.concatenate(
            [layer_keys, jnp.zeros((L_max,) + layer_keys.shape[1:], layer_keys.dtype)]
        )
        local_keys = jax.lax.dynamic_slice_in_dim(
            keys_padded, offsets[stage], L_max, 0
        )
    else:
        if L % P_pipe:
            raise ValueError(
                f"n_layer={L} must be divisible by the pipe axis size {P_pipe}"
            )
        L_local = L // P_pipe
        local_keys = jax.lax.dynamic_slice_in_dim(
            layer_keys, stage * L_local, L_local, 0
        )

    mbs = mb.split(
        {"ids": input_ids, "mask": attention_mask, "labels": labels}, n_microbatches
    )
    cos, sin = rope_cos_sin(s, config.head_dim, config.rope_theta)
    side = {"bias": jax.vmap(lambda m: rope_attention_bias(m, config))(mbs["mask"])}
    return attention_mask, mbs, cos, sin, local_keys, L, side, n_valid


def _stage_scan(blocks, keys, h, bias, cos, sin, config, tp_axis, ep_axis,
                train, n_valid=None):
    """Scan this stage's local layer slice; returns (h, aux (L_local,),
    z (L_local,)). Shared by the GPipe and 1F1B stage functions.

    ``n_valid`` (runtime scalar): UNEVEN stages — slots >= n_valid are
    pad layers, genuinely skipped by ``lax.cond`` (zero aux/z, h passes
    through). Collective-safe for the same reason as
    ``masked_stage_scan``: the predicate varies only over the pipe
    axis, so all tensor/expert peers of a stage take the same branch."""

    def live(blk, key, hh):
        out, aux, z = _block(
            blk, hh, cos, sin, bias, key, config, tp_axis, ep_axis, train
        )
        return out, (aux.astype(jnp.float32), z.astype(jnp.float32))

    if n_valid is None:
        def scan_fn(carry, blk_key):
            blk, key = blk_key
            return live(blk, key, carry)

        h, (aux, z) = jax.lax.scan(scan_fn, h, (blocks, keys))
        return h, aux, z

    L_max = jax.tree_util.tree_leaves(blocks)[0].shape[0]

    def scan_fn(carry, xs):
        blk, key, i = xs
        return jax.lax.cond(
            i < n_valid,
            lambda hh: live(blk, key, hh),
            lambda hh: (hh, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))),
            carry,
        )

    h, (aux, z) = jax.lax.scan(scan_fn, h, (blocks, keys, jnp.arange(L_max)))
    return h, aux, z


def loss_fn_pp(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: MixtralConfig,
    n_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    ep_axis: Optional[str] = None,
    rng: Optional[jax.Array] = None,
    train: bool = True,
    stage_layer_counts=None,
) -> jax.Array:
    """Pipeline-parallel Mixtral loss: the 4D TP x PP x DP x EP
    composition (BASELINE config 5 shape; the reference's group layout
    supports it at parallel_context.py:173-198 but never demonstrates it
    end-to-end).

    ``stage_layer_counts``: UNEVEN stages exactly as in
    ``bloom.loss_fn_pp`` — ``params["blocks"]`` must carry the padded
    ``repartition_blocks`` layout; router keys follow the same layer
    order (see ``_pp_prologue``).

    Structure mirrors bloom.loss_fn_pp (vectorized embed -> compiled
    GPipe over the pipe-sharded block stack -> vectorized head) plus the
    MoE-specific parts:
    - per-stage router aux/z losses ride gpipe's ``with_aux``
      accumulator (valid microbatches only) and are combined across the
      pipe axis with an identity-backward psum — each rank's router
      gradients stay local;
    - per-layer router RNG: every rank derives the full L-layer key
      array from ``rng`` and slices its own stage's rows, so routing
      matches the dense path exactly regardless of pp size;
    - aux/z are averaged over layers x microbatches, keeping
      ``router_aux_loss_coef`` on HF's scale (dense ``loss_fn`` takes
      the layer mean; with M=1 the two coincide exactly).
    """
    from pipegoose_tpu.distributed.functional import reduce_from_tensor_group
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import gpipe, last_stage_value

    M = n_microbatches
    attention_mask, mbs, cos, sin, local_keys, L, side, n_valid = _pp_prologue(
        input_ids, attention_mask, labels, config, M, pipe_axis, rng, train,
        stage_layer_counts,
    )

    h0 = jax.vmap(
        lambda ids: vocab_parallel_embedding(params["embed"], ids, tp_axis).astype(
            config.dtype
        )
    )(mbs["ids"])

    def stage_fn(blocks_and_keys, h, side):
        blocks, keys = blocks_and_keys
        h, aux, z = _stage_scan(
            blocks, keys, h, side["bias"], cos, sin, config, tp_axis, ep_axis,
            train, n_valid,
        )
        return h, (aux.sum(), z.sum())

    outs, (aux_sum, z_sum) = gpipe(
        stage_fn,
        (params["blocks"], local_keys),
        h0,
        side_inputs=side,
        axis_name=pipe_axis,
        remat=config.remat,
        with_aux=True,
    )

    def head_one(h, mask, labels):
        h = rms_norm(params["ln_f"], h, config.rms_eps)
        if config.fused_ce:
            from pipegoose_tpu.ops.fused_ce import fused_ce_shifted_sums

            return fused_ce_shifted_sums(
                h, params["lm_head"]["kernel"], labels, mask, tp_axis,
                config.valid_vocab_size, weight_layout="hv",
            )
        logits = column_parallel_linear(params["lm_head"], h, tp_axis)
        per_tok = vocab_parallel_cross_entropy(
            logits[:, :-1], labels[:, 1:], tp_axis, valid_size=config.valid_vocab_size
        )
        w = mask[:, 1:].astype(per_tok.dtype)
        return (per_tok * w).sum(), w.sum()

    tot, cnt = jax.vmap(head_one)(outs, mbs["mask"], mbs["labels"])
    task = last_stage_value(tot.sum() / jnp.maximum(cnt.sum(), 1), pipe_axis)

    # identity-backward psum over pipe: forward-replicated totals, local
    # gradients per rank (the psum-transpose hazard)
    aux_mean = reduce_from_tensor_group(aux_sum, pipe_axis) / (L * M)
    z_mean = reduce_from_tensor_group(z_sum, pipe_axis) / (L * M)
    return ExpertLoss(config.aux_loss_weight, config.z_loss_weight)(
        task, aux_mean, z_mean
    )


def specs(params: dict, tp_axis: str = "tensor", ep_axis: str = "expert") -> dict:
    """4D PartitionSpecs: attention q/k/v column + o row over tensor,
    experts over expert with FFN over tensor, lm_head column, embedding
    vocab-sharded; stacked n_layer dim free for the pipe axis."""
    from jax.sharding import PartitionSpec as P

    from pipegoose_tpu.nn.parallel import spec_tree

    t, e = tp_axis, ep_axis

    def spec_fn(path, x):
        if "attn/q" in path or "attn/k" in path or "attn/v" in path:
            return P(None, None, t)
        if "attn/o" in path:
            return P(None, t, None)
        if "moe/w1" in path or "moe/w3" in path:
            return P(None, e, None, t)
        if "moe/w2" in path:
            return P(None, e, t, None)
        if "router" in path:
            return P()
        if "embed/weight" in path:
            return P(t, None)
        if "lm_head" in path:
            return P(None, t)
        return P()

    return spec_tree(params, spec_fn)


def loss_fn_1f1b(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: MixtralConfig,
    n_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    ep_axis: Optional[str] = None,
    rng: Optional[jax.Array] = None,
    train: bool = True,
    stage_layer_counts=None,
) -> jax.Array:
    """Mixtral pipeline loss on the 1F1B runtime: same value/gradients
    as :func:`loss_fn_pp` with O(stages) activation memory. Router aux/z
    losses ride ``one_f_one_b``'s ``with_aux`` channel: each stage's
    pre-weighted aux scalar seeds its OWN backward, so router gradients
    never cross stages, and the per-rank loss sums combine with one
    psum over the pipe axis. ``stage_layer_counts``: UNEVEN stages as in
    :func:`loss_fn_pp`."""
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import (
        manual_grads_loss,
        one_f_one_b,
    )

    M = n_microbatches
    attention_mask, mbs, cos, sin, local_keys, L, side, n_valid = _pp_prologue(
        input_ids, attention_mask, labels, config, M, pipe_axis, rng, train,
        stage_layer_counts,
    )
    side = {**side, "labels": mbs["labels"], "mask": mbs["mask"]}
    inv_count = 1.0 / jnp.maximum(attention_mask[:, 1:].sum().astype(jnp.float32), 1)

    def stage_fn(blocks, h, side):
        # local_keys is closed over (constant for AD): integer key
        # arrays must not enter the differentiated stage_params pytree
        h, aux, z = _stage_scan(
            blocks, local_keys, h, side["bias"], cos, sin,
            config, tp_axis, ep_axis, train, n_valid,
        )
        aux_scalar = (
            config.aux_loss_weight * aux.sum() + config.z_loss_weight * z.sum()
        ) / (L * M)
        return h, aux_scalar.astype(jnp.float32)

    def head_fn(hp, h, side):
        h = rms_norm(hp["ln_f"], h, config.rms_eps)
        if config.fused_ce:
            from pipegoose_tpu.ops.fused_ce import fused_ce_shifted_sums

            tot, _ = fused_ce_shifted_sums(
                h, hp["lm_head"]["kernel"], side["labels"], side["mask"],
                tp_axis, config.valid_vocab_size, weight_layout="hv",
            )
            return (tot * inv_count).astype(jnp.float32)
        logits = column_parallel_linear(hp["lm_head"], h, tp_axis)
        per_tok = vocab_parallel_cross_entropy(
            logits[:, :-1], side["labels"][:, 1:], tp_axis,
            valid_size=config.valid_vocab_size,
        )
        w = side["mask"][:, 1:].astype(per_tok.dtype)
        return ((per_tok * w).sum() * inv_count).astype(jnp.float32)

    def run(params):
        h0, embed_vjp = jax.vjp(
            lambda ep: jax.vmap(
                lambda ids: vocab_parallel_embedding(ep, ids, tp_axis).astype(
                    config.dtype
                )
            )(mbs["ids"]),
            params["embed"],
        )
        head_params = {"ln_f": params["ln_f"], "lm_head": params["lm_head"]}
        loss_local, dh0, d_blocks, d_head = one_f_one_b(
            stage_fn, params["blocks"], head_fn, head_params,
            h0, side, pipe_axis, with_aux=True,
        )
        (d_embed,) = embed_vjp(dh0)
        # every rank's aux rode its local loss sum; the task part lives
        # on the last rank — one psum combines both
        loss = jax.lax.psum(loss_local, pipe_axis)
        grads = {
            "embed": d_embed,
            "blocks": d_blocks,
            "ln_f": d_head["ln_f"],
            "lm_head": d_head["lm_head"],
        }
        return loss, grads

    return manual_grads_loss(run, params)


def upcycle_from_llama(
    llama_params: dict,
    llama_config,
    num_experts: int,
    top_k: int = 2,
    key: Optional[jax.Array] = None,
    jitter: float = 0.0,
    **config_overrides,
):
    """Sparse-upcycle a dense Llama into a Mixtral-style MoE: every
    expert starts as a copy of the dense SwiGLU MLP (gate/up/down map
    exactly onto w1/w3/w2), plus a fresh router gate.

    This is the "turn this model into MoE" capability beyond the
    framework's own BLOOM (the reference's Experts wraps arbitrary HF
    MLP modules, experts.py:55-68; its ExpertParallel swaps dense MLPs
    for expert copies, expert_parallel.py:53-80). With ``jitter=0`` the
    upcycled model's FORWARD equals the dense Llama exactly — identical
    experts and normalized top-k gates make routing irrelevant — which
    the test pins; ``jitter`` perturbs experts so they diverge in
    training.

    Returns (MixtralConfig, params) ready for every Mixtral parallel
    form (TP/EP/PP/ZeRO, generation).
    """
    cfg = MixtralConfig(
        vocab_size=llama_config.vocab_size,
        hidden_size=llama_config.hidden_size,
        intermediate_size=llama_config.intermediate_size,
        n_layer=llama_config.n_layer,
        n_head=llama_config.n_head,
        n_kv_head=llama_config.n_kv_head,
        rope_theta=llama_config.rope_theta,
        rms_eps=llama_config.rms_eps,
        num_experts=num_experts,
        top_k=top_k,
        dtype=llama_config.dtype,
        remat=llama_config.remat,
        use_flash=llama_config.use_flash,
        valid_vocab_size=llama_config.valid_vocab_size,
        **config_overrides,
    )
    if key is None:
        key = jax.random.PRNGKey(0)
    kj, kr = jax.random.split(key)

    blocks = dict(llama_params["blocks"])
    mlp = blocks.pop("mlp")
    E = num_experts

    def tile(x):
        return jnp.broadcast_to(x[:, None], (x.shape[0], E) + x.shape[1:])

    moe = {
        "w1": {"kernel": tile(mlp["gate"]["kernel"])},
        "w3": {"kernel": tile(mlp["up"]["kernel"])},
        "w2": {"kernel": tile(mlp["down"]["kernel"])},
    }
    if jitter:
        leaves, treedef = jax.tree_util.tree_flatten(moe)
        keys = jax.random.split(kj, len(leaves))
        leaves = [
            x * (1 + jitter * jax.random.normal(k, x.shape, x.dtype))
            for x, k in zip(leaves, keys)
        ]
        moe = jax.tree_util.tree_unflatten(treedef, leaves)
    blocks["moe"] = moe
    blocks["router"] = {
        "gate": {
            "kernel": (
                jax.random.normal(kr, (cfg.n_layer, cfg.hidden_size, E)) * 0.02
            ).astype(cfg.dtype)
        }
    }

    lm_head = llama_params.get("lm_head")
    if lm_head is None:  # tied checkpoint: materialize the head
        lm_head = {"kernel": llama_params["embed"]["weight"].T}
    params = {
        "embed": llama_params["embed"],
        "blocks": blocks,
        "ln_f": llama_params["ln_f"],
        "lm_head": lm_head,
    }
    return cfg, params


def pp_specs(
    params: dict,
    tp_axis: str = "tensor",
    ep_axis: str = "expert",
    pipe_axis: str = "pipe",
) -> dict:
    """4D specs with the stacked n_layer dim of blocks sharded over the
    pipe axis (stage assignment as a PartitionSpec, like bloom.pp_specs)."""
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import pipe_stage_specs

    sp = specs(params, tp_axis, ep_axis)
    sp["blocks"] = pipe_stage_specs(sp["blocks"], pipe_axis)
    return sp


# -- sequence-parallel composition ------------------------------------------

def _attention_sp(blk, x, config, tp_axis, sp_axis, pad_mask_local,
                  variant: str = "ring"):
    """RoPE/GQA attention with the sequence sharded over ``sp_axis``,
    heads over ``tp_axis``. RoPE is applied at GLOBAL positions — each
    rank slices the full cos/sin tables at its chunk offset
    (rope_scaling honored via the shared rope_cos_sin) — BEFORE any
    head exchange, since RoPE travels with tokens, not heads.

    ``variant="ring"``: K/V rotate over the sp ring. GQA is NATIVE on
    both ring paths: the nkv-headed K/V ride the ring — the flash chunk
    kernels read them via grouped index maps, the dense-math ring
    (sliding-window configs, or use_flash=False) via a grouped einsum.
    Hop bytes shrink by g either way.

    ``variant="ulysses"``: two all_to_alls re-shard seq -> heads so each
    device runs FULL-sequence attention on nh_l/sp query heads and
    nkv_l/sp kv heads (the grouped-head mapping stays consistent because
    nh_l = g * nkv_l splits uniformly); needs both head counts divisible
    by the sp size — use ring otherwise (it has no such constraint).

    Shared by Mixtral and Llama (llama.loss_fn_sp imports this)."""
    from pipegoose_tpu.nn.sequence_parallel.ring_attention import (
        make_causal_alibi_bias_fn,
        ring_attention,
        ring_flash_attention,
    )

    if variant not in ("ring", "ulysses"):
        raise ValueError(f"unknown SP variant {variant!r} (ring, ulysses)")
    b, s_local, _ = x.shape
    hd = config.head_dim
    tp = jax.lax.axis_size(tp_axis) if tp_axis else 1
    nh_l, nkv_l = config.n_head // tp, config.n_kv_head // tp

    q = column_parallel_linear(blk["q"], x, tp_axis).reshape(b, s_local, nh_l, hd)
    k = column_parallel_linear(blk["k"], x, tp_axis).reshape(b, s_local, nkv_l, hd)
    v = column_parallel_linear(blk["v"], x, tp_axis).reshape(b, s_local, nkv_l, hd)

    sp = jax.lax.axis_size(sp_axis) if sp_axis else 1
    rank = jax.lax.axis_index(sp_axis) if sp_axis else 0
    cos_f, sin_f = rope_cos_sin(
        sp * s_local, hd, config.rope_theta,
        getattr(config, "rope_scaling", None),
    )
    cos = jax.lax.dynamic_slice_in_dim(cos_f, rank * s_local, s_local, 0)
    sin = jax.lax.dynamic_slice_in_dim(sin_f, rank * s_local, s_local, 0)
    q, k = apply_rope(q, k, cos, sin)

    window = getattr(config, "sliding_window", None)
    if variant == "ulysses":
        from pipegoose_tpu.nn.sequence_parallel.ulysses import (
            ulysses_causal_attention,
        )

        ctx = ulysses_causal_attention(
            q, k, v, sp_axis, pad_mask_local,
            window=window, use_flash=config.use_flash,
        )
    elif config.use_flash and window is None:
        # native GQA: nkv-headed K/V ride the ring
        ctx = ring_flash_attention(
            q, k, v, sp_axis, alibi_slopes=None, kv_side=pad_mask_local
        )
    else:
        # no ALiBi term (RoPE carries position in q/k); window is a
        # value-based position mask in the shared block bias
        bias_fn = make_causal_alibi_bias_fn(s_local, sp_axis, window=window)
        ctx = ring_attention(q, k, v, sp_axis, bias_fn, kv_side=pad_mask_local)
    ctx = ctx.astype(x.dtype).reshape(b, s_local, nh_l * hd)
    return row_parallel_linear(blk["o"], ctx, tp_axis)


def _sp_block(blk, x, key, config, tp_axis, ep_axis, sp_axis,
              pad_mask_local, train, variant="ring"):
    h = rms_norm(blk["ln_1"], x, config.rms_eps)
    x = x + _attention_sp(
        blk["attn"], h, config, tp_axis, sp_axis, pad_mask_local, variant
    )
    h = rms_norm(blk["ln_2"], x, config.rms_eps)

    router = config.router()
    flat = h.reshape(-1, h.shape[-1])
    routing = router(blk["router"], flat, key=key, train=train)
    y = moe_layer(
        blk["moe"], h, routing, axis_name=ep_axis,
        tp_axis=tp_axis, act=None, mlp_fn=_swiglu_experts,
    )
    return x + y, routing.aux_loss, routing.z_loss


def loss_fn_sp(
    params: dict,
    input_ids: jax.Array,  # (B, S_local) — sequence sharded over sp_axis
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: MixtralConfig,
    tp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
    sp_axis: str = "seq",
    rng=None,
    train: bool = True,
    variant: str = "ring",
) -> jax.Array:
    """Sequence-parallel Mixtral loss: ring (or, with
    ``variant="ulysses"``, all_to_all head-exchange) attention over
    ``sp_axis`` with RoPE at global positions; MoE routing/dispatch
    stays on each rank's local tokens (composes with ``ep_axis``
    all_to_all as usual).
    This is the long-context path for the RoPE/GQA families — the ring
    machinery previously served only BLOOM (VERDICT r2 weak #4).

    Loss terms: the task CE uses the cross-chunk target shift
    (nn/sequence_parallel/targets.py); z-loss is a per-token mean, so the
    rank average IS the dense value (equal chunks); the router aux loss
    is nonlinear in the token split — the rank average is the standard
    Megatron-style approximation (zero-weight it for strict equivalence
    tests, same policy as loss_fn_pp with M>1).

    Grad sync for replicated params: ``grad_sync_axes=(("seq","sum"),)``.
    """
    from pipegoose_tpu.distributed.functional import reduce_from_tensor_group
    from pipegoose_tpu.nn.sequence_parallel.targets import sp_shifted_targets

    b, s_local = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s_local), jnp.int32)

    x = vocab_parallel_embedding(params["embed"], input_ids, tp_axis).astype(
        config.dtype
    )
    if rng is None:
        if train and config.router_jitter:
            raise ValueError("train=True with router jitter needs an explicit rng")
        rng = jax.random.PRNGKey(0)
    layer_keys = jax.random.split(rng, config.n_layer)

    def scan_fn(carry, blk_key):
        blk, key = blk_key
        out, aux, z = _sp_block(
            blk, carry, key, config, tp_axis, ep_axis, sp_axis,
            attention_mask, train, variant,
        )
        return out, (aux, z)

    step = jax.checkpoint(scan_fn) if config.remat else scan_fn
    x, (aux, z) = jax.lax.scan(step, x, (params["blocks"], layer_keys))

    x = rms_norm(params["ln_f"], x, config.rms_eps)
    shifted_labels, shifted_w = sp_shifted_targets(
        labels, attention_mask, sp_axis
    )
    if config.fused_ce:
        from pipegoose_tpu.ops.fused_ce import fused_ce_masked_sums

        tot, cnt = fused_ce_masked_sums(
            x, params["lm_head"]["kernel"], shifted_labels, shifted_w,
            tp_axis, config.valid_vocab_size, weight_layout="hv",
        )
    else:
        logits = column_parallel_linear(params["lm_head"], x, tp_axis)
        per_tok = vocab_parallel_cross_entropy(
            logits, shifted_labels, tp_axis,
            valid_size=config.valid_vocab_size,
        )
        w = shifted_w.astype(per_tok.dtype)
        tot, cnt = (per_tok * w).sum(), w.sum()
    count = jax.lax.psum(cnt, sp_axis)
    # identity-backward combines: values become global means, gradients
    # stay local (summed later by grad_sync_axes)
    task = reduce_from_tensor_group(
        tot / jnp.maximum(count, 1), sp_axis
    )
    sp = jax.lax.axis_size(sp_axis)
    aux_t = reduce_from_tensor_group(aux.mean() / sp, sp_axis)
    z_t = reduce_from_tensor_group(z.mean() / sp, sp_axis)
    return ExpertLoss(config.aux_loss_weight, config.z_loss_weight)(
        task, aux_t, z_t
    )


def loss_fn_pp_sp(
    params: dict,
    input_ids: jax.Array,  # (B, S_local) — sequence sharded over sp_axis
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: MixtralConfig,
    n_microbatches: int,
    tp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    sp_axis: str = "seq",
    rng=None,
    train: bool = True,
) -> jax.Array:
    """Pipeline x sequence parallel Mixtral: ring attention (RoPE at
    global positions) runs INSIDE compiled GPipe stages, with MoE
    routing on each rank's local tokens — the long-context + deep-model
    composition for the RoPE/GQA/MoE family (bloom.loss_fn_pp_sp is the
    ALiBi analog). All sp peers of a stage advance in lockstep (uniform
    SPMD), so the ring's ppermutes and the pipeline's ppermutes compose
    without any scheduling interaction.

    Loss terms follow loss_fn_sp: cross-chunk target shift; z is exact
    (per-token mean over equal chunks); aux is the Megatron-style rank/
    microbatch average — zero-weight it for strict equivalence tests.

    Grad sync: ``grad_sync_axes=(("pipe","sum"), ("seq","sum"))`` (+
    ``("expert","mean")`` when expert-data replicas carry different
    tokens)."""
    from pipegoose_tpu.distributed.functional import reduce_from_tensor_group
    from pipegoose_tpu.nn.pipeline_parallel import microbatch as mb
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import gpipe, last_stage_value
    from pipegoose_tpu.nn.sequence_parallel.targets import sp_shifted_targets

    M = n_microbatches
    b, s_local = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s_local), jnp.int32)

    P_pipe = jax.lax.axis_size(pipe_axis)
    L = config.n_layer
    if L % P_pipe:
        raise ValueError(
            f"n_layer={L} must be divisible by the pipe axis size {P_pipe}"
        )
    L_local = L // P_pipe
    stage = jax.lax.axis_index(pipe_axis)
    if rng is None:
        if train and config.router_jitter:
            raise ValueError("train=True with router jitter needs an explicit rng")
        rng = jax.random.PRNGKey(0)
    layer_keys = jax.random.split(rng, L)
    local_keys = jax.lax.dynamic_slice_in_dim(layer_keys, stage * L_local, L_local, 0)

    mbs = mb.split(
        {"ids": input_ids, "mask": attention_mask, "labels": labels}, M
    )
    h0 = jax.vmap(
        lambda ids: vocab_parallel_embedding(params["embed"], ids, tp_axis).astype(
            config.dtype
        )
    )(mbs["ids"])
    side = {"mask": mbs["mask"]}

    def stage_fn(blocks_and_keys, h, side):
        blocks, keys = blocks_and_keys

        def scan_fn(carry, blk_key):
            blk, key = blk_key
            out, aux, z = _sp_block(
                blk, carry, key, config, tp_axis, ep_axis, sp_axis,
                side["mask"], train,
            )
            return out, (aux, z)

        h, (aux, z) = jax.lax.scan(scan_fn, h, (blocks, keys))
        return h, (aux.sum(), z.sum())

    outs, (aux_sum, z_sum) = gpipe(
        stage_fn,
        (params["blocks"], local_keys),
        h0,
        side_inputs=side,
        axis_name=pipe_axis,
        remat=config.remat,
        with_aux=True,
    )

    def head_one(h, mask_mb, labels_mb):
        h = rms_norm(params["ln_f"], h, config.rms_eps)
        sl, sw = sp_shifted_targets(labels_mb, mask_mb, sp_axis)
        if config.fused_ce:
            from pipegoose_tpu.ops.fused_ce import fused_ce_masked_sums

            return fused_ce_masked_sums(
                h, params["lm_head"]["kernel"], sl, sw, tp_axis,
                config.valid_vocab_size, weight_layout="hv",
            )
        logits = column_parallel_linear(params["lm_head"], h, tp_axis)
        per_tok = vocab_parallel_cross_entropy(
            logits, sl, tp_axis, valid_size=config.valid_vocab_size
        )
        w = sw.astype(per_tok.dtype)
        return (per_tok * w).sum(), w.sum()

    tot, cnt = jax.vmap(head_one)(outs, mbs["mask"], mbs["labels"])
    count = jax.lax.psum(cnt.sum(), sp_axis)
    task_local = reduce_from_tensor_group(
        tot.sum() / jnp.maximum(count, 1), sp_axis
    )
    task = last_stage_value(task_local, pipe_axis)

    sp = jax.lax.axis_size(sp_axis)
    aux_mean = reduce_from_tensor_group(
        reduce_from_tensor_group(aux_sum, pipe_axis), sp_axis
    ) / (L * M * sp)
    z_mean = reduce_from_tensor_group(
        reduce_from_tensor_group(z_sum, pipe_axis), sp_axis
    ) / (L * M * sp)
    return ExpertLoss(config.aux_loss_weight, config.z_loss_weight)(
        task, aux_mean, z_mean
    )


# -- generation (KV cache) ---------------------------------------------------

def init_cache(config: MixtralConfig, batch: int, max_len: int) -> dict:
    L, nkv, hd = config.n_layer, config.n_kv_head, config.head_dim
    shape = (L, batch, max_len, nkv, hd)
    return {"k": jnp.zeros(shape, config.dtype), "v": jnp.zeros(shape, config.dtype)}


def _attn_cached(blk, x, k_cache, v_cache, start, cos_full, sin_full, config):
    """S new tokens against cache[:start]+selves (GQA, RoPE at absolute
    positions). Returns (out, k_cache, v_cache)."""
    b, s, _ = x.shape
    hd = config.head_dim
    nh, nkv = config.n_head, config.n_kv_head
    groups = nh // nkv
    max_len = k_cache.shape[1]

    q = column_parallel_linear(blk["q"], x, None).reshape(b, s, nh, hd)
    k = column_parallel_linear(blk["k"], x, None).reshape(b, s, nkv, hd)
    v = column_parallel_linear(blk["v"], x, None).reshape(b, s, nkv, hd)

    cos = jax.lax.dynamic_slice_in_dim(cos_full, start, s, 0)
    sin = jax.lax.dynamic_slice_in_dim(sin_full, start, s, 0)
    q, k = apply_rope(q, k, cos, sin)

    k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype), (0, start, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype), (0, start, 0, 0))

    key_pos = jnp.arange(max_len)
    q_pos = start + jnp.arange(s)
    keep = key_pos[None, :] <= q_pos[:, None]
    window = getattr(config, "sliding_window", None)  # shared with Llama decode
    if window is not None:
        keep = keep & (q_pos[:, None] - key_pos[None, :] < window)
    bias = jnp.where(keep[None, None, None], 0.0, NEG_INF)  # (1,1,1,S,max_len)

    # grouped einsum against the nkv-wide cache: no group-repeated K/V
    # copies in the decode hot loop (GQA's whole point)
    qg = q.reshape(b, s, nkv, groups, hd)
    scores = jnp.einsum("bqkgd,bmkd->bkgqm", qg, k_cache,
                        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(scores * (hd**-0.5) + bias, axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bkgqm,bmkd->bqkgd", probs, v_cache,
                     preferred_element_type=jnp.float32)
    ctx = ctx.astype(x.dtype).reshape(b, s, nh * hd)
    return row_parallel_linear(blk["o"], ctx, None), k_cache, v_cache


def forward_cached(params, ids, cache, start, config):
    """(logits at last position, new cache); deterministic routing
    (no-drop capacity, no jitter — inference)."""
    x = vocab_parallel_embedding(params["embed"], ids, None).astype(config.dtype)
    max_len = cache["k"].shape[2]
    cos_full, sin_full = rope_cos_sin(max_len, config.head_dim, config.rope_theta)

    def scan_fn(carry, blk_and_cache):
        h = carry
        blk, kc, vc = blk_and_cache
        ln1 = rms_norm(blk["ln_1"], h, config.rms_eps)
        attn, kc, vc = _attn_cached(
            blk["attn"], ln1, kc, vc, start, cos_full, sin_full, config
        )
        h = h + attn
        ln2 = rms_norm(blk["ln_2"], h, config.rms_eps)
        router = config.router()
        flat = ln2.reshape(-1, ln2.shape[-1])
        routing = router(blk["router"], flat, train=False)
        h = h + moe_layer(blk["moe"], ln2, routing, axis_name=None,
                          tp_axis=None, mlp_fn=_swiglu_experts)
        return h, (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(scan_fn, x, (params["blocks"], cache["k"], cache["v"]))
    x = rms_norm(params["ln_f"], x, config.rms_eps)
    logits = column_parallel_linear(params["lm_head"], x[:, -1:], None)[:, 0]
    return logits, {"k": k_new, "v": v_new}


def generate(
    params: dict,
    input_ids: jax.Array,
    config: MixtralConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng=None,
    eos_token_id=None,
) -> jax.Array:
    """Greedy/sampled decoding with a GQA KV cache — shared decode
    driver (models/_decode.py), same EOS semantics as BLOOM's generate."""
    from pipegoose_tpu.models._decode import autoregressive_generate, vocab_mask_for

    return autoregressive_generate(
        forward_cached, init_cache, params, input_ids, config,
        max_new_tokens, temperature, rng, eos_token_id,
        logits_mask=vocab_mask_for(config),
    )
