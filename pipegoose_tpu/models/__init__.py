from pipegoose_tpu.models import bloom
from pipegoose_tpu.models.bloom import BloomConfig

__all__ = ["bloom", "BloomConfig"]
