from pipegoose_tpu.models import bloom, bloom_moe, mixtral
from pipegoose_tpu.models.bloom import BloomConfig
from pipegoose_tpu.models.bloom_moe import BloomMoEConfig
from pipegoose_tpu.models.mixtral import MixtralConfig

__all__ = ["bloom", "bloom_moe", "mixtral", "BloomConfig", "BloomMoEConfig", "MixtralConfig"]
