from pipegoose_tpu.models import bloom, bloom_moe, llama, mixtral
from pipegoose_tpu.models.bloom import BloomConfig
from pipegoose_tpu.models.bloom_moe import BloomMoEConfig
from pipegoose_tpu.models.convert import from_hf
from pipegoose_tpu.models.llama import LlamaConfig
from pipegoose_tpu.models.mixtral import MixtralConfig

__all__ = [
    "bloom", "bloom_moe", "llama", "mixtral", "from_hf",
    "BloomConfig", "BloomMoEConfig", "LlamaConfig", "MixtralConfig",
]
