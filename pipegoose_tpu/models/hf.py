"""HuggingFace checkpoint interop.

The reference consumes HF torch models directly and mutates them
(tensor_parallel.py:27-42); here HF weights are *converted once* into the
framework's stacked-pytree layout. Torch is only imported inside these
functions — the training path never touches it.

Each family is a declarative RULES table executed by the generic
converter (models/convert.py) — the checkpoint-side half of the policy
registry the reference keeps in its per-model ``__MAPPING__`` tables
(reference nn/tensor_parallel/parallel_mapping.py:16-52). Three
families are registered: bloom, mixtral, llama.

Layout notes:
- torch Linear stores (out, in); JAX kernels are (in, out) -> transpose.
- per-layer tensors are stacked on a leading n_layer axis (models/bloom.py).
- the fused qkv keeps HF's [n_head, 3, head_dim] output layout, so
  head-contiguous TP slicing stays correct.
"""
from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from pipegoose_tpu.models.bloom import BloomConfig
from pipegoose_tpu.models.convert import (
    params_from_state_dict,
    register_family,
    state_dict_from_params,
)

# -- BLOOM ------------------------------------------------------------------

BLOOM_RULES = [
    {"path": "embed/weight", "hf": "word_embeddings.weight"},
    {"path": "embed_ln/scale", "hf": "word_embeddings_layernorm.weight"},
    {"path": "embed_ln/bias", "hf": "word_embeddings_layernorm.bias"},
    {"path": "blocks/ln_1/scale", "hf": "h.{l}.input_layernorm.weight"},
    {"path": "blocks/ln_1/bias", "hf": "h.{l}.input_layernorm.bias"},
    {"path": "blocks/attn/qkv/kernel",
     "hf": "h.{l}.self_attention.query_key_value.weight", "transpose": True},
    {"path": "blocks/attn/qkv/bias",
     "hf": "h.{l}.self_attention.query_key_value.bias"},
    {"path": "blocks/attn/out/kernel",
     "hf": "h.{l}.self_attention.dense.weight", "transpose": True},
    {"path": "blocks/attn/out/bias", "hf": "h.{l}.self_attention.dense.bias"},
    {"path": "blocks/ln_2/scale", "hf": "h.{l}.post_attention_layernorm.weight"},
    {"path": "blocks/ln_2/bias", "hf": "h.{l}.post_attention_layernorm.bias"},
    {"path": "blocks/mlp/up/kernel",
     "hf": "h.{l}.mlp.dense_h_to_4h.weight", "transpose": True},
    {"path": "blocks/mlp/up/bias", "hf": "h.{l}.mlp.dense_h_to_4h.bias"},
    {"path": "blocks/mlp/down/kernel",
     "hf": "h.{l}.mlp.dense_4h_to_h.weight", "transpose": True},
    {"path": "blocks/mlp/down/bias", "hf": "h.{l}.mlp.dense_4h_to_h.bias"},
    {"path": "ln_f/scale", "hf": "ln_f.weight"},
    {"path": "ln_f/bias", "hf": "ln_f.bias"},
]


def bloom_config_from_hf(hf_config, **overrides) -> BloomConfig:
    if getattr(hf_config, "apply_residual_connection_post_layernorm", False):
        raise NotImplementedError(
            "apply_residual_connection_post_layernorm=True checkpoints are "
            "not supported (bloom._block uses the standard pre-LN residual)"
        )
    return BloomConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        layer_norm_epsilon=hf_config.layer_norm_epsilon,
        initializer_range=hf_config.initializer_range,
        **overrides,
    )


def bloom_params_from_hf(model: Any, dtype=jnp.float32) -> tuple[BloomConfig, dict]:
    """Convert an HF ``BloomForCausalLM`` (or ``BloomModel``) to the
    stacked params pytree. The lm_head is tied to the embedding in BLOOM,
    so only the embedding table is stored (reference LMHeadParallelizer
    tied-weight handling, parallelizer.py:205-211)."""
    sd = dict(model.state_dict())
    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    cfg = bloom_config_from_hf(model.config, dtype=dtype)
    params = params_from_state_dict(
        sd, BLOOM_RULES, cfg.n_layer, dtype=dtype, prefix=prefix
    )
    return cfg, params


def bloom_params_to_hf_state_dict(params: dict) -> dict:
    """Inverse conversion, for exporting back to HF format (numpy arrays
    keyed by HF names; caller wraps in torch tensors if needed)."""
    out = state_dict_from_params(params, BLOOM_RULES, prefix="transformer.")
    out["lm_head.weight"] = out["transformer.word_embeddings.weight"]
    return out


# -- Mixtral ----------------------------------------------------------------

MIXTRAL_RULES = [
    {"path": "embed/weight", "hf": "model.embed_tokens.weight"},
    {"path": "blocks/ln_1/scale", "hf": "model.layers.{l}.input_layernorm.weight"},
    {"path": "blocks/attn/q/kernel",
     "hf": "model.layers.{l}.self_attn.q_proj.weight", "transpose": True},
    {"path": "blocks/attn/k/kernel",
     "hf": "model.layers.{l}.self_attn.k_proj.weight", "transpose": True},
    {"path": "blocks/attn/v/kernel",
     "hf": "model.layers.{l}.self_attn.v_proj.weight", "transpose": True},
    {"path": "blocks/attn/o/kernel",
     "hf": "model.layers.{l}.self_attn.o_proj.weight", "transpose": True},
    {"path": "blocks/ln_2/scale",
     "hf": "model.layers.{l}.post_attention_layernorm.weight"},
    {"path": "blocks/router/gate/kernel",
     "hf": "model.layers.{l}.block_sparse_moe.gate.weight", "transpose": True},
    {"path": "blocks/moe/w1/kernel",
     "hf": "model.layers.{l}.block_sparse_moe.experts.{e}.w1.weight",
     "transpose": True},
    {"path": "blocks/moe/w3/kernel",
     "hf": "model.layers.{l}.block_sparse_moe.experts.{e}.w3.weight",
     "transpose": True},
    {"path": "blocks/moe/w2/kernel",
     "hf": "model.layers.{l}.block_sparse_moe.experts.{e}.w2.weight",
     "transpose": True},
    {"path": "ln_f/scale", "hf": "model.norm.weight"},
    {"path": "lm_head/kernel", "hf": "lm_head.weight", "transpose": True},
]


def mixtral_config_from_hf(hf_config, **overrides):
    from pipegoose_tpu.models.mixtral import MixtralConfig

    # normalize falsy/non-positive windows to disabled (HF treats 0/None
    # as no sliding window)
    window = getattr(hf_config, "sliding_window", None)
    return MixtralConfig(
        sliding_window=window if window and window > 0 else None,
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        n_layer=hf_config.num_hidden_layers,
        n_head=hf_config.num_attention_heads,
        n_kv_head=hf_config.num_key_value_heads,
        num_experts=hf_config.num_local_experts,
        top_k=hf_config.num_experts_per_tok,
        rope_theta=hf_config.rope_theta,
        rms_eps=hf_config.rms_norm_eps,
        router_jitter=getattr(hf_config, "router_jitter_noise", 0.0) or 0.0,
        # 0.001 is MixtralConfig's documented router_aux_loss_coef default
        aux_loss_weight=getattr(hf_config, "router_aux_loss_coef", 0.001),
        **overrides,
    )


def mixtral_params_from_hf(model: Any, dtype=jnp.float32) -> tuple:
    """Convert HF ``MixtralForCausalLM`` to the stacked pytree (experts
    gathered into (L, E, in, out) stacks)."""
    cfg = mixtral_config_from_hf(model.config, dtype=dtype)
    params = params_from_state_dict(
        dict(model.state_dict()), MIXTRAL_RULES, cfg.n_layer,
        n_experts=cfg.num_experts, dtype=dtype,
    )
    return cfg, params


# -- Llama ------------------------------------------------------------------

LLAMA_RULES = [
    {"path": "embed/weight", "hf": "model.embed_tokens.weight"},
    {"path": "blocks/ln_1/scale", "hf": "model.layers.{l}.input_layernorm.weight"},
    {"path": "blocks/attn/q/kernel",
     "hf": "model.layers.{l}.self_attn.q_proj.weight", "transpose": True},
    {"path": "blocks/attn/k/kernel",
     "hf": "model.layers.{l}.self_attn.k_proj.weight", "transpose": True},
    {"path": "blocks/attn/v/kernel",
     "hf": "model.layers.{l}.self_attn.v_proj.weight", "transpose": True},
    {"path": "blocks/attn/o/kernel",
     "hf": "model.layers.{l}.self_attn.o_proj.weight", "transpose": True},
    {"path": "blocks/ln_2/scale",
     "hf": "model.layers.{l}.post_attention_layernorm.weight"},
    {"path": "blocks/mlp/gate/kernel",
     "hf": "model.layers.{l}.mlp.gate_proj.weight", "transpose": True},
    {"path": "blocks/mlp/up/kernel",
     "hf": "model.layers.{l}.mlp.up_proj.weight", "transpose": True},
    {"path": "blocks/mlp/down/kernel",
     "hf": "model.layers.{l}.mlp.down_proj.weight", "transpose": True},
    {"path": "ln_f/scale", "hf": "model.norm.weight"},
    {"path": "lm_head/kernel", "hf": "lm_head.weight", "transpose": True,
     "optional": True},  # absent on tied checkpoints
]


def llama_config_from_hf(hf_config, **overrides):
    from pipegoose_tpu.models.llama import LlamaConfig

    from pipegoose_tpu.models.mixtral import RopeScaling

    rope_scaling = RopeScaling.from_hf(
        getattr(hf_config, "rope_scaling", None),
        # HF 'dynamic' checkpoints omit original_max_position_embeddings
        # and rescale relative to the model's max_position_embeddings
        default_original_max=getattr(hf_config, "max_position_embeddings", 8192),
    )
    if getattr(hf_config, "attention_bias", False):
        raise NotImplementedError("attention_bias=True checkpoints not supported")
    derived_hd = hf_config.hidden_size // hf_config.num_attention_heads
    if getattr(hf_config, "head_dim", None) not in (None, derived_hd):
        raise NotImplementedError(
            f"explicit head_dim={hf_config.head_dim} != "
            f"hidden_size/num_attention_heads={derived_hd} not supported"
        )
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        n_layer=hf_config.num_hidden_layers,
        n_head=hf_config.num_attention_heads,
        n_kv_head=hf_config.num_key_value_heads,
        rope_theta=getattr(hf_config, "rope_theta", 1e4),
        rope_scaling=rope_scaling,
        rms_eps=hf_config.rms_norm_eps,
        tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        **overrides,
    )


def llama_params_from_hf(model: Any, dtype=jnp.float32) -> tuple:
    """Convert HF ``LlamaForCausalLM`` to the stacked pytree."""
    cfg = llama_config_from_hf(model.config, dtype=dtype)
    params = params_from_state_dict(
        dict(model.state_dict()), LLAMA_RULES, cfg.n_layer, dtype=dtype
    )
    if cfg.tie_word_embeddings:
        params.pop("lm_head", None)
    return cfg, params


# -- ALBERT (encoder family) -------------------------------------------------

_ALBERT_L = "albert.encoder.albert_layer_groups.0.albert_layers.0."

ALBERT_RULES = [
    {"path": "embed/word/weight", "hf": "albert.embeddings.word_embeddings.weight"},
    {"path": "embed/pos", "hf": "albert.embeddings.position_embeddings.weight"},
    {"path": "embed/type", "hf": "albert.embeddings.token_type_embeddings.weight"},
    {"path": "embed/ln/scale", "hf": "albert.embeddings.LayerNorm.weight"},
    {"path": "embed/ln/bias", "hf": "albert.embeddings.LayerNorm.bias"},
    {"path": "map_in/kernel",
     "hf": "albert.encoder.embedding_hidden_mapping_in.weight", "transpose": True},
    {"path": "map_in/bias", "hf": "albert.encoder.embedding_hidden_mapping_in.bias"},
    # ONE shared layer (cross-layer parameter sharing): group 0, layer 0
    {"path": "layer/attn/q/kernel", "hf": _ALBERT_L + "attention.query.weight",
     "transpose": True},
    {"path": "layer/attn/q/bias", "hf": _ALBERT_L + "attention.query.bias"},
    {"path": "layer/attn/k/kernel", "hf": _ALBERT_L + "attention.key.weight",
     "transpose": True},
    {"path": "layer/attn/k/bias", "hf": _ALBERT_L + "attention.key.bias"},
    {"path": "layer/attn/v/kernel", "hf": _ALBERT_L + "attention.value.weight",
     "transpose": True},
    {"path": "layer/attn/v/bias", "hf": _ALBERT_L + "attention.value.bias"},
    {"path": "layer/attn/dense/kernel", "hf": _ALBERT_L + "attention.dense.weight",
     "transpose": True},
    {"path": "layer/attn/dense/bias", "hf": _ALBERT_L + "attention.dense.bias"},
    {"path": "layer/attn/ln/scale", "hf": _ALBERT_L + "attention.LayerNorm.weight"},
    {"path": "layer/attn/ln/bias", "hf": _ALBERT_L + "attention.LayerNorm.bias"},
    {"path": "layer/ffn/up/kernel", "hf": _ALBERT_L + "ffn.weight",
     "transpose": True},
    {"path": "layer/ffn/up/bias", "hf": _ALBERT_L + "ffn.bias"},
    {"path": "layer/ffn/down/kernel", "hf": _ALBERT_L + "ffn_output.weight",
     "transpose": True},
    {"path": "layer/ffn/down/bias", "hf": _ALBERT_L + "ffn_output.bias"},
    {"path": "layer/ffn/ln/scale",
     "hf": _ALBERT_L + "full_layer_layer_norm.weight"},
    {"path": "layer/ffn/ln/bias", "hf": _ALBERT_L + "full_layer_layer_norm.bias"},
    # MLM head; the decoder weight is TIED to the word embedding
    {"path": "mlm/dense/kernel", "hf": "predictions.dense.weight",
     "transpose": True},
    {"path": "mlm/dense/bias", "hf": "predictions.dense.bias"},
    {"path": "mlm/ln/scale", "hf": "predictions.LayerNorm.weight"},
    {"path": "mlm/ln/bias", "hf": "predictions.LayerNorm.bias"},
    {"path": "mlm/bias", "hf": "predictions.bias"},
]


def albert_config_from_hf(hf_config, **overrides):
    from pipegoose_tpu.models.albert import AlbertConfig

    if getattr(hf_config, "num_hidden_groups", 1) != 1 or getattr(
        hf_config, "inner_group_num", 1
    ) != 1:
        raise NotImplementedError(
            "albert with num_hidden_groups/inner_group_num != 1 not supported "
            "(the standard released configs use 1 group x 1 inner layer)"
        )
    act = getattr(hf_config, "hidden_act", "gelu_new")
    if act != "gelu_new":
        raise NotImplementedError(
            f"albert hidden_act={act!r} not supported (models/albert.py "
            "applies gelu_new, the released albert-v1/v2 activation)"
        )
    return AlbertConfig(
        vocab_size=hf_config.vocab_size,
        embedding_size=hf_config.embedding_size,
        hidden_size=hf_config.hidden_size,
        n_layer=hf_config.num_hidden_layers,
        n_head=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        max_position_embeddings=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        layer_norm_eps=hf_config.layer_norm_eps,
        initializer_range=hf_config.initializer_range,
        **overrides,
    )


def albert_params_from_hf(model: Any, dtype=jnp.float32) -> tuple:
    """Convert an HF ``AlbertForMaskedLM`` to the shared-layer pytree
    (reference albert TP mapping, parallel_mapping.py:33-52)."""
    sd = dict(model.state_dict())
    cfg = albert_config_from_hf(model.config, dtype=dtype)
    params = params_from_state_dict(sd, ALBERT_RULES, cfg.n_layer, dtype=dtype)
    return cfg, params


# -- family registry --------------------------------------------------------

def _load_bloom(model, dtype):
    from pipegoose_tpu.models import bloom as module

    cfg, params = bloom_params_from_hf(model, dtype)
    return cfg, params, module


def _load_mixtral(model, dtype):
    from pipegoose_tpu.models import mixtral as module

    cfg, params = mixtral_params_from_hf(model, dtype)
    return cfg, params, module


def _load_llama(model, dtype):
    from pipegoose_tpu.models import llama as module

    cfg, params = llama_params_from_hf(model, dtype)
    return cfg, params, module


def _load_albert(model, dtype):
    from pipegoose_tpu.models import albert as module

    cfg, params = albert_params_from_hf(model, dtype)
    return cfg, params, module


register_family("bloom", _load_bloom)
register_family("mixtral", _load_mixtral)
register_family("llama", _load_llama)
register_family("albert", _load_albert)

__all__ = [
    "bloom_config_from_hf", "bloom_params_from_hf", "bloom_params_to_hf_state_dict",
    "mixtral_config_from_hf", "mixtral_params_from_hf",
    "llama_config_from_hf", "llama_params_from_hf",
    "BLOOM_RULES", "MIXTRAL_RULES", "LLAMA_RULES",
]
