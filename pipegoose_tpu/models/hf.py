"""HuggingFace checkpoint interop.

The reference consumes HF torch models directly and mutates them
(tensor_parallel.py:27-42); here HF weights are *converted once* into the
framework's stacked-pytree layout. Torch is only imported inside these
functions — the training path never touches it.

Layout notes:
- torch Linear stores (out, in); JAX kernels are (in, out) -> transpose.
- per-layer tensors are stacked on a leading n_layer axis (models/bloom.py).
- the fused qkv keeps HF's [n_head, 3, head_dim] output layout, so
  head-contiguous TP slicing stays correct.
"""
from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from pipegoose_tpu.models.bloom import BloomConfig


def _t(x) -> np.ndarray:
    x = x.detach().cpu()
    if str(x.dtype) == "torch.bfloat16":  # torch bf16 has no .numpy()
        x = x.float()
    return np.asarray(x.numpy())


def bloom_config_from_hf(hf_config, **overrides) -> BloomConfig:
    if getattr(hf_config, "apply_residual_connection_post_layernorm", False):
        raise NotImplementedError(
            "apply_residual_connection_post_layernorm=True checkpoints are "
            "not supported (bloom._block uses the standard pre-LN residual)"
        )
    return BloomConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        layer_norm_epsilon=hf_config.layer_norm_epsilon,
        initializer_range=hf_config.initializer_range,
        **overrides,
    )


def bloom_params_from_hf(model: Any, dtype=jnp.float32) -> tuple[BloomConfig, dict]:
    """Convert an HF ``BloomForCausalLM`` (or ``BloomModel``) to the
    stacked params pytree. The lm_head is tied to the embedding in BLOOM,
    so only the embedding table is stored (reference LMHeadParallelizer
    tied-weight handling, parallelizer.py:205-211)."""
    sd = {k: v for k, v in model.state_dict().items()}
    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    cfg = bloom_config_from_hf(model.config, dtype=dtype)
    L = cfg.n_layer

    def get(name):
        return _t(sd[prefix + name])

    def stack(fmt, transpose=False):
        mats = [get(fmt.format(i)) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return jnp.asarray(np.stack(mats), dtype=dtype)

    params = {
        "embed": {"weight": jnp.asarray(get("word_embeddings.weight"), dtype=dtype)},
        "embed_ln": {
            "scale": jnp.asarray(get("word_embeddings_layernorm.weight"), dtype=dtype),
            "bias": jnp.asarray(get("word_embeddings_layernorm.bias"), dtype=dtype),
        },
        "blocks": {
            "ln_1": {
                "scale": stack("h.{}.input_layernorm.weight"),
                "bias": stack("h.{}.input_layernorm.bias"),
            },
            "attn": {
                "qkv": {
                    "kernel": stack("h.{}.self_attention.query_key_value.weight", transpose=True),
                    "bias": stack("h.{}.self_attention.query_key_value.bias"),
                },
                "out": {
                    "kernel": stack("h.{}.self_attention.dense.weight", transpose=True),
                    "bias": stack("h.{}.self_attention.dense.bias"),
                },
            },
            "ln_2": {
                "scale": stack("h.{}.post_attention_layernorm.weight"),
                "bias": stack("h.{}.post_attention_layernorm.bias"),
            },
            "mlp": {
                "up": {
                    "kernel": stack("h.{}.mlp.dense_h_to_4h.weight", transpose=True),
                    "bias": stack("h.{}.mlp.dense_h_to_4h.bias"),
                },
                "down": {
                    "kernel": stack("h.{}.mlp.dense_4h_to_h.weight", transpose=True),
                    "bias": stack("h.{}.mlp.dense_4h_to_h.bias"),
                },
            },
        },
        "ln_f": {
            "scale": jnp.asarray(get("ln_f.weight"), dtype=dtype),
            "bias": jnp.asarray(get("ln_f.bias"), dtype=dtype),
        },
    }
    return cfg, params


def bloom_params_to_hf_state_dict(params: dict) -> dict:
    """Inverse conversion, for exporting back to HF format (numpy arrays
    keyed by HF names; caller wraps in torch tensors if needed)."""
    out = {}
    out["transformer.word_embeddings.weight"] = np.asarray(params["embed"]["weight"])
    out["transformer.word_embeddings_layernorm.weight"] = np.asarray(
        params["embed_ln"]["scale"]
    )
    out["transformer.word_embeddings_layernorm.bias"] = np.asarray(
        params["embed_ln"]["bias"]
    )
    blocks = params["blocks"]
    L = np.asarray(blocks["ln_1"]["scale"]).shape[0]
    for i in range(L):
        p = f"transformer.h.{i}."
        out[p + "input_layernorm.weight"] = np.asarray(blocks["ln_1"]["scale"][i])
        out[p + "input_layernorm.bias"] = np.asarray(blocks["ln_1"]["bias"][i])
        out[p + "self_attention.query_key_value.weight"] = np.asarray(
            blocks["attn"]["qkv"]["kernel"][i]
        ).T
        out[p + "self_attention.query_key_value.bias"] = np.asarray(
            blocks["attn"]["qkv"]["bias"][i]
        )
        out[p + "self_attention.dense.weight"] = np.asarray(
            blocks["attn"]["out"]["kernel"][i]
        ).T
        out[p + "self_attention.dense.bias"] = np.asarray(blocks["attn"]["out"]["bias"][i])
        out[p + "post_attention_layernorm.weight"] = np.asarray(blocks["ln_2"]["scale"][i])
        out[p + "post_attention_layernorm.bias"] = np.asarray(blocks["ln_2"]["bias"][i])
        out[p + "mlp.dense_h_to_4h.weight"] = np.asarray(blocks["mlp"]["up"]["kernel"][i]).T
        out[p + "mlp.dense_h_to_4h.bias"] = np.asarray(blocks["mlp"]["up"]["bias"][i])
        out[p + "mlp.dense_4h_to_h.weight"] = np.asarray(
            blocks["mlp"]["down"]["kernel"][i]
        ).T
        out[p + "mlp.dense_4h_to_h.bias"] = np.asarray(blocks["mlp"]["down"]["bias"][i])
    out["transformer.ln_f.weight"] = np.asarray(params["ln_f"]["scale"])
    out["transformer.ln_f.bias"] = np.asarray(params["ln_f"]["bias"])
    out["lm_head.weight"] = out["transformer.word_embeddings.weight"]
    return out


# -- Mixtral ----------------------------------------------------------------

def mixtral_config_from_hf(hf_config, **overrides):
    from pipegoose_tpu.models.mixtral import MixtralConfig

    if getattr(hf_config, "sliding_window", None):
        raise NotImplementedError("sliding-window attention not supported yet")
    return MixtralConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        n_layer=hf_config.num_hidden_layers,
        n_head=hf_config.num_attention_heads,
        n_kv_head=hf_config.num_key_value_heads,
        num_experts=hf_config.num_local_experts,
        top_k=hf_config.num_experts_per_tok,
        rope_theta=hf_config.rope_theta,
        rms_eps=hf_config.rms_norm_eps,
        router_jitter=getattr(hf_config, "router_jitter_noise", 0.0) or 0.0,
        aux_loss_weight=getattr(hf_config, "router_aux_loss_coef", 0.02),
        **overrides,
    )


def mixtral_params_from_hf(model: Any, dtype=jnp.float32) -> tuple:
    """Convert HF ``MixtralForCausalLM`` to the stacked pytree (experts
    gathered into (L, E, in, out) stacks)."""
    sd = model.state_dict()
    cfg = mixtral_config_from_hf(model.config, dtype=dtype)
    L, E = cfg.n_layer, cfg.num_experts

    def get(name):
        return _t(sd[name])

    def stack(fmt, transpose=True):
        mats = [get(fmt.format(i)) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return jnp.asarray(np.stack(mats), dtype=dtype)

    def stack_experts(fmt):
        # (L, E, in, out), torch stores (out, in)
        return jnp.asarray(
            np.stack(
                [np.stack([get(fmt.format(i, e)).T for e in range(E)]) for i in range(L)]
            ),
            dtype=dtype,
        )

    pre = "model."
    params = {
        "embed": {"weight": jnp.asarray(get(pre + "embed_tokens.weight"), dtype=dtype)},
        "blocks": {
            "ln_1": {"scale": stack(pre + "layers.{}.input_layernorm.weight", transpose=False)},
            "attn": {
                "q": {"kernel": stack(pre + "layers.{}.self_attn.q_proj.weight")},
                "k": {"kernel": stack(pre + "layers.{}.self_attn.k_proj.weight")},
                "v": {"kernel": stack(pre + "layers.{}.self_attn.v_proj.weight")},
                "o": {"kernel": stack(pre + "layers.{}.self_attn.o_proj.weight")},
            },
            "ln_2": {
                "scale": stack(pre + "layers.{}.post_attention_layernorm.weight", transpose=False)
            },
            "router": {
                "gate": {"kernel": stack(pre + "layers.{}.block_sparse_moe.gate.weight")}
            },
            "moe": {
                "w1": {"kernel": stack_experts(pre + "layers.{}.block_sparse_moe.experts.{}.w1.weight")},
                "w3": {"kernel": stack_experts(pre + "layers.{}.block_sparse_moe.experts.{}.w3.weight")},
                "w2": {"kernel": stack_experts(pre + "layers.{}.block_sparse_moe.experts.{}.w2.weight")},
            },
        },
        "ln_f": {"scale": jnp.asarray(get(pre + "norm.weight"), dtype=dtype)},
        "lm_head": {"kernel": jnp.asarray(get("lm_head.weight").T, dtype=dtype)},
    }
    return cfg, params
