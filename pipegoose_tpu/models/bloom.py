"""BLOOM, TPU-native.

The reference wraps HuggingFace's torch ``BloomForCausalLM`` and rewrites
its modules in place (pipegoose/nn/tensor_parallel/tensor_parallel.py:18-82);
BLOOM is its supported model family (reference README.md:19). Here BLOOM
is implemented from scratch in pure JAX, designed for the MXU and for
4D sharding:

- per-layer params are STACKED on a leading ``n_layer`` dim and the
  forward scans over them (``lax.scan`` + optional ``jax.checkpoint``):
  one compiled block regardless of depth, and pipeline stages slice the
  leading dim instead of torch.fx graph surgery
  (vs reference partitioner.py:29-219).
- attention/MLP use the tensor-parallel layer functions, so the same
  code runs single-device (``tp_axis=None``) or inside ``shard_map``
  with head- and vocab-sharded params.
- matmuls accumulate in fp32 (``preferred_element_type``), activations
  can be bf16; softmax and layernorm stats are always fp32.

Semantics match HF ``modeling_bloom`` (gelu-tanh MLP, fused qkv in
[n_head, 3, head_dim] layout, alibi from mask positions, fp32 softmax,
pre-LN residuals with ``apply_residual_connection_post_layernorm=False``)
so HF checkpoints load exactly; parity is tested against the torch
implementation in tests/models/test_bloom.py.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from pipegoose_tpu.nn.parallel_mapping import (
    Column,
    ParallelMapping,
    Row,
    Vocab,
)
from pipegoose_tpu.nn.tensor_parallel.layers import (
    column_parallel_linear,
    layer_norm,
    row_parallel_linear,
    vocab_parallel_cross_entropy,
    vocab_parallel_embedding,
)

NEG_INF = -1e9


@dataclasses.dataclass(frozen=True)
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 64
    n_layer: int = 2
    n_head: int = 8
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    # dtype of activations/params at run time; f32 for parity tests,
    # bf16 for TPU throughput
    dtype: Any = jnp.float32
    # rematerialize each block's activations in backward (HBM for FLOPs)
    remat: bool = False
    # selective-remat policy under remat=True: None saves nothing (full
    # remat); "dots" saves matmul outputs except batch-dim ones
    # (jax.checkpoint_policies.dots_with_no_batch_dims_saveable);
    # "attn" saves only the per-block attention outputs
    # (checkpoint_name "attn_out", present on every attention variant)
    # so backward never re-runs attention — between full remat (slow,
    # tiny HBM) and no remat (fast, 2x HBM)
    remat_policy: Optional[str] = None
    # fused Pallas flash attention (ops/flash_attention.py): causal+alibi,
    # padding masks supported via the kernel's kv_pos/kv_neg bias inputs
    use_flash: bool = False
    # set when the embedding was padded for TP divisibility (pad_for_tp):
    # the true vocab size; padded logit slots are masked out of the CE
    valid_vocab_size: Optional[int] = None
    # chunk the loss over the sequence so the (B, S, V) fp32 logits
    # buffer (8 GB at bench shapes) never materializes — backward
    # rematerializes per chunk (nn/tensor_parallel/layers.py:
    # chunked_ce_sums). None = plain full-logits path.
    ce_chunks: Optional[int] = None
    # fused Pallas CE (ops/fused_ce.py): the logits buffer never exists
    # in HBM at all, forward or backward, with no chunk recompute —
    # strictly dominates ce_chunks when the kernel is available; takes
    # precedence over it
    fused_ce: bool = False
    # ring collective-matmul overlap (nn/tensor_parallel/overlap.py):
    # the dense/hybrid train path keeps activations TOKEN-SHARDED over
    # the tensor axis between blocks and decomposes the column gather /
    # row reduce into ppermute steps interleaved with partial matmuls,
    # so TP comm hides behind compute (and activations shrink by 1/tp).
    # Training-path flag: generate/serving and the PP/SP compositions
    # ignore it. Requires seq % tp == 0.
    overlap_tp: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.n_head

    @classmethod
    def bloom_560m(cls, **kw) -> "BloomConfig":
        return cls(vocab_size=250880, hidden_size=1024, n_layer=24, n_head=16, **kw)


def _remat_wrap(fn, config):
    """``jax.checkpoint`` honoring ``config.remat_policy`` (caller gates
    on ``config.remat``)."""
    policy = getattr(config, "remat_policy", None)
    if policy == "dots":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    if policy == "attn":
        # save only the attention outputs (checkpoint_name "attn_out",
        # set on every _attention/_attention_sp branch): backward
        # recomputes the cheap elementwise/matmul parts but never
        # re-runs attention — for ~(B,S,H) x n_layer extra HBM
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.save_only_these_names("attn_out")
        )
    return jax.checkpoint(fn)


# -- init ------------------------------------------------------------------

def init_params(config: BloomConfig, key: jax.Array) -> dict:
    """Random init matching HF's scheme (normal(0, initializer_range) for
    dense/embedding, zeros bias, ones/zeros layernorm)."""
    h, v, L = config.hidden_size, config.vocab_size, config.n_layer
    std = config.initializer_range
    dt = config.dtype
    ks = jax.random.split(key, 6)

    def dense(k, shape):
        return (jax.random.normal(k, shape) * std).astype(dt)

    def ln():
        return {"scale": jnp.ones(h, dt), "bias": jnp.zeros(h, dt)}

    def ln_stack():
        return {"scale": jnp.ones((L, h), dt), "bias": jnp.zeros((L, h), dt)}

    return {
        "embed": {"weight": dense(ks[0], (v, h))},
        "embed_ln": ln(),
        "blocks": {
            "ln_1": ln_stack(),
            "attn": {
                "qkv": {
                    "kernel": dense(ks[1], (L, h, 3 * h)),
                    "bias": jnp.zeros((L, 3 * h), dt),
                },
                "out": {
                    "kernel": dense(ks[2], (L, h, h)),
                    "bias": jnp.zeros((L, h), dt),
                },
            },
            "ln_2": ln_stack(),
            "mlp": {
                "up": {
                    "kernel": dense(ks[3], (L, h, 4 * h)),
                    "bias": jnp.zeros((L, 4 * h), dt),
                },
                "down": {
                    "kernel": dense(ks[4], (L, 4 * h, h)),
                    "bias": jnp.zeros((L, h), dt),
                },
            },
        },
        "ln_f": {"scale": jnp.ones(h, dt), "bias": jnp.zeros(h, dt)},
    }


# -- alibi -----------------------------------------------------------------

def alibi_slopes(n_head: int) -> np.ndarray:
    """Per-head slopes from the ALiBi paper's geometric recipe (matches
    HF build_alibi_tensor's closest-power-of-2 construction)."""
    closest = 2 ** math.floor(math.log2(n_head))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = [base ** i for i in range(1, closest + 1)]
    if closest != n_head:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        n_extra = min(closest, n_head - closest)
        slopes += [extra_base ** i for i in range(1, 2 * n_extra, 2)]
    return np.asarray(slopes, dtype=np.float32)


def build_alibi(attention_mask: jax.Array, n_head: int) -> jax.Array:
    """(B, n_head, 1, S) bias: slope * key position, where position is the
    mask-aware index ``(cumsum(mask)-1)*mask``. Constant per query row, so
    softmax translation-invariance makes it equivalent to relative bias
    under the causal mask."""
    slopes = jnp.asarray(alibi_slopes(n_head))
    pos = (jnp.cumsum(attention_mask, axis=-1) - 1) * attention_mask  # (B,S)
    return slopes[None, :, None, None] * pos[:, None, None, :].astype(jnp.float32)


def bloom_gelu(x: jax.Array) -> jax.Array:
    """Megatron-style tanh gelu. Deliberately uses HF's truncated constant
    0.79788456 (not jax.nn.gelu's full-precision sqrt(2/pi)) so logits
    match HF bit-for-bit in the parity tests."""
    return x * 0.5 * (1.0 + jnp.tanh(0.79788456 * x * (1.0 + 0.044715 * x * x)))


# -- forward ---------------------------------------------------------------

def _local_heads(config: BloomConfig, tp: int) -> int:
    if config.n_head % tp != 0:
        raise ValueError(
            f"n_head={config.n_head} must be divisible by the tensor axis "
            f"size {tp} (whole heads per shard)"
        )
    return config.n_head // tp


def _mlp(
    blk: dict, x: jax.Array, config: BloomConfig, tp_axis, overlap: bool = False
) -> jax.Array:
    """ln_2 -> column up -> gelu -> row down (single source for the
    dense, pipeline, and sequence-parallel block paths).

    ``overlap``: ``x`` is this rank's token chunk; the up-projection
    ring-gathers tokens while it projects and the down-projection
    ring-reduces while it projects (nn/tensor_parallel/overlap.py), so
    the block maps token shard -> token shard with the comm hidden.
    ``ln_2`` then sees only local tokens, so its params route through
    the f-operator for exact full-sequence grads."""
    ln2_p = blk["ln_2"]
    if overlap:
        from pipegoose_tpu.nn.tensor_parallel.overlap import replicated_for_overlap

        ln2_p = replicated_for_overlap(ln2_p, tp_axis)
    ln2 = layer_norm(ln2_p, x, config.layer_norm_epsilon)
    h = column_parallel_linear(blk["mlp"]["up"], ln2, tp_axis, overlap=overlap)
    return row_parallel_linear(
        blk["mlp"]["down"], bloom_gelu(h), tp_axis, overlap=overlap
    )


def _attention(
    blk: dict,
    x: jax.Array,
    bias: dict,
    config: BloomConfig,
    tp_axis: Optional[str],
    overlap: bool = False,
) -> jax.Array:
    """Self-attention with heads sharded over ``tp_axis``. qkv is
    column-parallel, the output projection row-parallel — the Megatron
    pattern the reference applies by module surgery
    (tensor_parallel/parallel_mapping.py:23-31). ``bias`` is the dict
    from :func:`attention_bias`.

    ``overlap``: ``x`` is this rank's token chunk; the qkv projection
    ring-gathers the sequence while it projects (attention itself needs
    every key anyway), the attention core runs full-sequence exactly as
    the monolithic path, and the output projection ring-reduce-scatters
    back to the token chunk."""
    b = x.shape[0]
    hd = config.head_dim
    tp = jax.lax.axis_size(tp_axis) if tp_axis else 1
    local_heads = _local_heads(config, tp)

    fused = column_parallel_linear(
        blk["qkv"], x, tp_axis, overlap=overlap
    )  # (B,S,3H/tp) — full-token either way
    s = fused.shape[1]
    fused = fused.reshape(b, s, local_heads, 3, hd)
    q, k, v = fused[..., 0, :], fused[..., 1, :], fused[..., 2, :]

    if config.use_flash:
        # fused kernel path: alibi from static slopes; causal + padding
        # masks applied inside the kernel via kv_pos/kv_neg
        from pipegoose_tpu.ops.flash_attention import flash_attention

        slopes = jnp.asarray(alibi_slopes(config.n_head))
        if tp_axis:
            h0 = jax.lax.axis_index(tp_axis) * local_heads
            slopes = jax.lax.dynamic_slice_in_dim(slopes, h0, local_heads, 0)
        ctx = flash_attention(
            q, k, v, slopes,
            kv_pos=bias["kv_pos"], kv_neg=bias["kv_neg"], causal=True,
        )
        # zero pad-query rows (see the XLA branch below: every attention
        # path defines pad-query context as zero)
        ctx = ctx * bias["qmask"][:, :, None, None].astype(ctx.dtype)
        ctx = checkpoint_name(ctx, "attn_out")  # for remat_policy="attn"
        ctx = ctx.astype(x.dtype).reshape(b, s, local_heads * hd)
        return row_parallel_linear(blk["out"], ctx, tp_axis, overlap=overlap)

    # local head slice of the alibi bias
    alibi = bias["alibi"]
    if tp_axis:
        h0 = jax.lax.axis_index(tp_axis) * local_heads
        alibi = jax.lax.dynamic_slice_in_dim(alibi, h0, local_heads, axis=1)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * (1.0 / math.sqrt(hd)) + alibi + bias["mask_bias"]
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v, preferred_element_type=jnp.float32)
    # fully-masked query rows (pad queries under LEFT padding attend
    # nothing): softmax of an all-NEG_INF row is an accidental uniform
    # average over keys. Define the context as ZERO there instead — the
    # flash kernel's natural value — so the XLA, flash, ring, and
    # Ulysses paths agree bit-for-bit. No loss-carrying position is
    # affected under the reference's right-padded protocol (a valid
    # target implies a valid query there).
    ctx = ctx * bias["qmask"][:, :, None, None].astype(ctx.dtype)
    ctx = checkpoint_name(ctx, "attn_out")
    ctx = ctx.astype(x.dtype).reshape(b, s, local_heads * hd)
    return row_parallel_linear(blk["out"], ctx, tp_axis, overlap=overlap)


def _block(
    blk: dict,
    x: jax.Array,
    bias: dict,
    config: BloomConfig,
    tp_axis: Optional[str],
    overlap: bool = False,
) -> jax.Array:
    """One transformer block, HF BloomBlock ordering (pre-LN, residual
    from the un-normalized stream).

    ``overlap``: the ring collective-matmul path — ``x`` is this rank's
    token chunk of the residual stream; the dense/hybrid forward sets
    it from ``config.overlap_tp``, the PP/SP compositions keep the
    monolithic path (their stream is already sharded differently)."""
    eps = config.layer_norm_epsilon
    ln1_p = blk["ln_1"]
    if overlap:
        from pipegoose_tpu.nn.tensor_parallel.overlap import replicated_for_overlap

        ln1_p = replicated_for_overlap(ln1_p, tp_axis)
    ln1 = layer_norm(ln1_p, x, eps)
    x = x + _attention(blk["attn"], ln1, bias, config, tp_axis, overlap=overlap)
    return x + _mlp(blk, x, config, tp_axis, overlap=overlap)


def embed_tokens(
    params: dict, input_ids: jax.Array, config: BloomConfig, tp_axis: Optional[str]
) -> jax.Array:
    """Embedding lookup + embedding layernorm (single source for the
    plain and pipeline forward paths)."""
    x = vocab_parallel_embedding(params["embed"], input_ids, tp_axis)
    x = x.astype(config.dtype)
    return layer_norm(params["embed_ln"], x, config.layer_norm_epsilon)


def attention_bias(attention_mask: jax.Array, config: BloomConfig) -> dict:
    """Attention bias inputs, in the form the configured attention path
    consumes (single source for the plain, pipeline, and 1F1B paths):
    - flash (``config.use_flash``): O(S) per-key mask-aware ALiBi
      position ``kv_pos`` and validity bias ``kv_neg`` — the dense
      (B, 1, S, S) tensors are never materialized;
    - standard: per-head ``alibi`` plus the dense causal/padding
      ``mask_bias``."""
    if config.use_flash:
        from pipegoose_tpu.ops.flash_attention import mask_to_kv_bias

        kv_pos, kv_neg = mask_to_kv_bias(attention_mask)
        return {"kv_pos": kv_pos, "kv_neg": kv_neg, "qmask": attention_mask}

    s = attention_mask.shape[-1]
    alibi = build_alibi(attention_mask, config.n_head)
    causal = jnp.tril(jnp.ones((s, s), dtype=bool))
    keep = causal[None, None] & (attention_mask[:, None, None, :] > 0)
    return {
        "alibi": alibi,
        "mask_bias": jnp.where(keep, 0.0, NEG_INF).astype(jnp.float32),
        "qmask": attention_mask,
    }


def forward_hidden(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    config: BloomConfig,
    tp_axis: Optional[str] = None,
) -> jax.Array:
    """Embedding -> scanned blocks -> final LN. Returns (B, S, H).

    With ``config.overlap_tp`` (and a tensor axis) the residual stream
    between blocks is TOKEN-SHARDED over ``tp_axis``: one f/g scatter
    after the (replicated) embedding, ring collective-matmuls inside
    every block, one f/g gather before the final LN — the hidden the
    caller sees is identical (fp32 allclose) to the monolithic path."""
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), dtype=jnp.int32)

    x = embed_tokens(params, input_ids, config, tp_axis)
    bias = attention_bias(attention_mask, config)

    overlap = bool(getattr(config, "overlap_tp", False)) and tp_axis is not None
    if overlap:
        from pipegoose_tpu.distributed.functional import scatter_to_tensor_group

        tp = jax.lax.axis_size(tp_axis)
        if s % tp:
            raise ValueError(
                f"overlap_tp: sequence length {s} must be divisible by "
                f"the tensor axis size {tp} (token chunks ride the ring)"
            )
        x = scatter_to_tensor_group(x, tp_axis, dim=1)

    block = partial(_block, config=config, tp_axis=tp_axis, overlap=overlap)
    if config.remat:
        block = _remat_wrap(block, config)

    def scan_fn(carry, blk):
        return block(blk, carry, bias), None

    x, _ = jax.lax.scan(scan_fn, x, params["blocks"])
    if overlap:
        from pipegoose_tpu.distributed.functional import gather_from_tensor_group

        x = gather_from_tensor_group(x, tp_axis, dim=1)
    return layer_norm(params["ln_f"], x, config.layer_norm_epsilon)


def logits_fn(
    params: dict,
    hidden: jax.Array,
    tp_axis: Optional[str] = None,
) -> jax.Array:
    """LM head tied to the (vocab-sharded) embedding: local logits are the
    local vocab shard — exactly what vocab_parallel_cross_entropy expects.
    Mirrors the reference's tied LMHead handling (parallelizer.py:205-211).

    The f-operator (copy_to_tensor_group) on ``hidden`` is load-bearing:
    in backward, each rank's hidden cotangent is only the partial sum over
    its local vocab shard, and the f-operator's all-reduce completes it —
    without it every gradient upstream of the LM head is wrong under TP."""
    from pipegoose_tpu.distributed.functional import copy_to_tensor_group

    if tp_axis:
        hidden = copy_to_tensor_group(hidden, tp_axis)
    w = params["embed"]["weight"]  # (V/tp, H) under TP
    out = jnp.einsum("bsh,vh->bsv", hidden, w, preferred_element_type=jnp.float32)
    return out


def forward(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    config: BloomConfig,
    tp_axis: Optional[str] = None,
) -> jax.Array:
    """Full causal-LM forward -> local-vocab-shard logits (B, S, V/tp)."""
    hidden = forward_hidden(params, input_ids, attention_mask, config, tp_axis)
    return logits_fn(params, hidden, tp_axis)


def loss_fn(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: BloomConfig,
    tp_axis: Optional[str] = None,
) -> jax.Array:
    """Next-token cross entropy (shift-by-one), masked by attention_mask,
    vocab-parallel over ``tp_axis``. With ``config.ce_chunks`` the loss
    is computed chunk-by-chunk over the sequence (the full logits buffer
    never exists — see chunked_ce_sums)."""
    if config.fused_ce:
        from pipegoose_tpu.ops.fused_ce import fused_ce_shifted_loss

        # final-LN output -> kernel; the tied embedding is the LM head
        # (logits_fn without the materialized einsum)
        hidden = forward_hidden(params, input_ids, attention_mask, config, tp_axis)
        return fused_ce_shifted_loss(
            hidden, params["embed"]["weight"], labels, attention_mask,
            tp_axis, config.valid_vocab_size,
        )
    if config.ce_chunks:
        from pipegoose_tpu.nn.tensor_parallel.layers import chunked_ce_sums

        hidden = forward_hidden(params, input_ids, attention_mask, config, tp_axis)
        w = (
            attention_mask[:, 1:]
            if attention_mask is not None
            else jnp.ones_like(labels[:, 1:])
        ).astype(jnp.float32)
        tot, cnt = chunked_ce_sums(
            hidden[:, :-1], labels[:, 1:], w,
            lambda h: logits_fn(params, h, tp_axis),
            tp_axis, config.valid_vocab_size, config.ce_chunks,
        )
        return tot / jnp.maximum(cnt, 1)
    logits = forward(params, input_ids, attention_mask, config, tp_axis)
    shift_logits = logits[:, :-1]
    shift_labels = labels[:, 1:]
    per_tok = vocab_parallel_cross_entropy(
        shift_logits, shift_labels, tp_axis, valid_size=config.valid_vocab_size
    )
    if attention_mask is not None:
        w = attention_mask[:, 1:].astype(per_tok.dtype)
        return (per_tok * w).sum() / jnp.maximum(w.sum(), 1)
    return per_tok.mean()


# -- TP policy -------------------------------------------------------------

def pad_for_tp(params: dict, config: BloomConfig, tp: int):
    """Pad the (tied) embedding so vocab divides the tensor axis —
    returns (params, config) with ``valid_vocab_size`` recording the true
    vocab so the CE masks padded slots (reference
    EmbeddingParallelizer._resize_vocab_size semantics,
    parallelizer.py:125-141, plus the loss masking it lacked)."""
    import dataclasses as _dc

    from pipegoose_tpu.nn.tensor_parallel.tensor_parallel import pad_vocab

    v = params["embed"]["weight"].shape[0]
    padded = pad_vocab(params["embed"]["weight"], tp)
    if padded.shape[0] == v:
        return params, config
    params = dict(params)
    params["embed"] = {"weight": padded}
    config = _dc.replace(
        config, vocab_size=padded.shape[0], valid_vocab_size=config.valid_vocab_size or v
    )
    return params, config


def tp_mapping(axis: str = "tensor") -> ParallelMapping:
    """Partition policy for the BLOOM params tree — the analog of the
    reference's per-model __MAPPING__ table
    (tensor_parallel/parallel_mapping.py:16-52): qkv/up column, out/down
    row, embedding vocab-sharded (head-contiguous qkv layout keeps whole
    heads per shard; requires n_head % tp == 0)."""
    return ParallelMapping(
        [
            (r"blocks/attn/qkv", Column(axis)),
            (r"blocks/attn/out", Row(axis)),
            (r"blocks/mlp/up", Column(axis)),
            (r"blocks/mlp/down", Row(axis)),
            (r"embed/weight", Vocab(axis)),
        ]
    )


def tp_specs(params: dict, axis: str = "tensor") -> dict:
    """PartitionSpec pytree for the stacked-layer params layout. The
    stacked leading n_layer dim shifts every kernel spec right by one."""
    from jax.sharding import PartitionSpec as P

    from pipegoose_tpu.nn.parallel import spec_tree

    mapping = tp_mapping(axis)

    def spec_fn(path, x):
        if "blocks" in path:
            base = mapping.spec_for(path, x.ndim - 1)
            return P(None, *base)
        return mapping.spec_for(path, x.ndim)

    return spec_tree(params, spec_fn)


# -- pipeline-parallel composition ------------------------------------------

def loss_fn_pp(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: BloomConfig,
    n_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    stage_layer_counts=None,
) -> jax.Array:
    """Pipeline-parallel loss: embed (vectorized over all microbatches on
    every rank — replicated compute off the critical path), GPipe over
    the pipe-sharded block stack, then vectorized LN/LM-head/CE, with the
    scalar combined from the last stage.

    Replaces the reference's PipelineEngine.run + scheduled backward
    (pipeline_engine.py:60-134, _job/creator.py:182-277) with one
    differentiable program.

    ``stage_layer_counts`` (len-P ints): UNEVEN stages — ``params`` must
    carry the padded block layout from ``repartition_blocks`` (driven by
    the cost-DP ``partition_costs``); each stage runs only its own live
    layers (lax.cond skip — see nn/pipeline_parallel/partitioner.py).
    The analog of the reference's cost-balanced partitioning incl. its
    embedding/head exclusions (reference partitioner.py:73-144).
    """
    from pipegoose_tpu.nn.pipeline_parallel import microbatch as mb
    from pipegoose_tpu.nn.pipeline_parallel.partitioner import (
        masked_stage_scan,
        stage_n_valid,
    )
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import gpipe, last_stage_value

    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), dtype=jnp.int32)

    mbs = mb.split(
        {"ids": input_ids, "mask": attention_mask, "labels": labels}, n_microbatches
    )

    # pipeline-entry activations for ALL microbatches (vmapped embed);
    # shared helpers keep PP/non-PP loss parity by construction
    h0 = jax.vmap(lambda ids: embed_tokens(params, ids, config, tp_axis))(mbs["ids"])

    # per-microbatch side inputs: alibi + combined mask bias
    side = jax.vmap(lambda m: attention_bias(m, config))(mbs["mask"])

    # with a selective remat_policy, checkpoint PER BLOCK (the policy's
    # named values live inside _block) instead of letting gpipe wrap the
    # whole stage — same semantics as the dense/1F1B paths
    def block_call(blk, hh, side):
        return _block(blk, hh, side, config, tp_axis)

    if config.remat and getattr(config, "remat_policy", None):
        block_call = _remat_wrap(block_call, config)
        gpipe_remat = False
    else:
        gpipe_remat = config.remat

    if stage_layer_counts is not None:
        n_valid = stage_n_valid(stage_layer_counts, config.n_layer, pipe_axis)

        def stage_fn(blocks, h, side):
            return masked_stage_scan(
                lambda blk, hh: block_call(blk, hh, side), blocks, h, n_valid
            )
    else:
        def stage_fn(blocks, h, side):
            def scan_fn(carry, blk):
                return block_call(blk, carry, side), None

            h, _ = jax.lax.scan(scan_fn, h, blocks)
            return h

    outs = gpipe(
        stage_fn,
        params["blocks"],
        h0,
        side_inputs=side,
        axis_name=pipe_axis,
        remat=gpipe_remat,
    )  # (M, mb, S, H), valid on last stage

    # vectorized head over all microbatches
    def head_one(h, ids, mask, labels):
        h = layer_norm(params["ln_f"], h, config.layer_norm_epsilon)
        if config.fused_ce:
            # the LAST stage's per-microbatch logits buffer is the PP
            # step's largest tensor — the fused kernel never builds it
            from pipegoose_tpu.ops.fused_ce import fused_ce_shifted_sums

            return fused_ce_shifted_sums(
                h, params["embed"]["weight"], labels, mask, tp_axis,
                config.valid_vocab_size,
            )
        logits = logits_fn(params, h, tp_axis)
        per_tok = vocab_parallel_cross_entropy(
            logits[:, :-1], labels[:, 1:], tp_axis, valid_size=config.valid_vocab_size
        )
        w = mask[:, 1:].astype(per_tok.dtype)
        return (per_tok * w).sum(), w.sum()

    tot, cnt = jax.vmap(head_one)(outs, mbs["ids"], mbs["mask"], mbs["labels"])
    loss_local = tot.sum() / jnp.maximum(cnt.sum(), 1)
    return last_stage_value(loss_local, pipe_axis)


def loss_fn_1f1b(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: BloomConfig,
    n_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    stage_layer_counts=None,
) -> jax.Array:
    """Pipeline-parallel loss with the 1F1B (PipeDream-flush) runtime:
    same semantics as :func:`loss_fn_pp` (identical loss value and
    gradients) but peak activation memory bounded by the STAGE count
    instead of the microbatch count — each microbatch's backward starts
    as soon as its forward clears the last stage
    (nn/pipeline_parallel/pipeline.py:one_f_one_b).

    ``stage_layer_counts``: UNEVEN stages exactly as in :func:`loss_fn_pp`
    — ``params["blocks"]`` must carry the padded ``repartition_blocks``
    layout; pad slots are lax.cond-skipped in both the forward and the
    rematerialized backward of each stage.

    Implemented as a ``jax.custom_vjp`` whose forward runs the fused
    forward+backward pipeline and stashes the parameter gradients as
    residuals, so ``jax.value_and_grad(loss_fn_1f1b)`` plugs into
    ``make_hybrid_train_step`` unchanged (grad_sync_axes=("pipe","sum")
    completes the replicated embed/ln_f grads across stages, exactly as
    for loss_fn_pp)."""
    from functools import partial as _partial

    from pipegoose_tpu.nn.pipeline_parallel import microbatch as mb
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import (
        manual_grads_loss,
        one_f_one_b,
    )

    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), dtype=jnp.int32)

    mbs = mb.split(
        {"ids": input_ids, "mask": attention_mask, "labels": labels}, n_microbatches
    )
    side = jax.vmap(lambda m: attention_bias(m, config))(mbs["mask"])
    side = {**side, "labels": mbs["labels"], "mask": mbs["mask"]}

    # per-microbatch head losses are pre-normalized by the LOCAL total
    # token count so their plain sum equals loss_fn_pp's tot/cnt
    inv_count = 1.0 / jnp.maximum(attention_mask[:, 1:].sum().astype(jnp.float32), 1)

    block = _partial(_block, config=config, tp_axis=tp_axis)
    if config.remat:
        block = _remat_wrap(block, config)

    if stage_layer_counts is not None:
        from pipegoose_tpu.nn.pipeline_parallel.partitioner import (
            masked_stage_scan,
            stage_n_valid,
        )

        n_valid = stage_n_valid(stage_layer_counts, config.n_layer, pipe_axis)

        def stage_fn(blocks, h, side):
            return masked_stage_scan(
                lambda blk, hh: block(blk, hh, side), blocks, h, n_valid
            )
    else:
        def stage_fn(blocks, h, side):
            def scan_fn(carry, blk):
                return block(blk, carry, side), None

            h, _ = jax.lax.scan(scan_fn, h, blocks)
            return h

    def head_fn(hp, h, side):
        h = layer_norm(hp["ln_f"], h, config.layer_norm_epsilon)
        if config.fused_ce:
            from pipegoose_tpu.ops.fused_ce import fused_ce_shifted_sums

            tot, _ = fused_ce_shifted_sums(
                h, hp["embed"]["weight"], side["labels"], side["mask"],
                tp_axis, config.valid_vocab_size,
            )
            return (tot * inv_count).astype(jnp.float32)
        logits = logits_fn({"embed": hp["embed"]}, h, tp_axis)
        per_tok = vocab_parallel_cross_entropy(
            logits[:, :-1], side["labels"][:, 1:], tp_axis,
            valid_size=config.valid_vocab_size,
        )
        w = side["mask"][:, 1:].astype(per_tok.dtype)
        return ((per_tok * w).sum() * inv_count).astype(jnp.float32)

    def run(params):
        embed_params = {"embed": params["embed"], "embed_ln": params["embed_ln"]}
        h0, embed_vjp = jax.vjp(
            lambda ep: jax.vmap(
                lambda ids: embed_tokens(ep, ids, config, tp_axis)
            )(mbs["ids"]),
            embed_params,
        )
        head_params = {"ln_f": params["ln_f"], "embed": params["embed"]}
        loss_local, dh0, d_blocks, d_head = one_f_one_b(
            stage_fn, params["blocks"], head_fn, head_params, h0, side, pipe_axis
        )
        (d_embed,) = embed_vjp(dh0)
        P = jax.lax.axis_size(pipe_axis)
        is_last = jax.lax.axis_index(pipe_axis) == P - 1
        loss = jax.lax.psum(jnp.where(is_last, loss_local, 0.0), pipe_axis)
        grads = {
            "embed": {
                "weight": d_embed["embed"]["weight"] + d_head["embed"]["weight"]
            },
            "embed_ln": d_embed["embed_ln"],
            "blocks": d_blocks,
            "ln_f": d_head["ln_f"],
        }
        return loss, grads

    return manual_grads_loss(run, params)


def pp_specs(params: dict, tp_axis: str = "tensor", pipe_axis: str = "pipe") -> dict:
    """tp_specs with the stacked n_layer dim of blocks sharded over the
    pipe axis — stage assignment as a PartitionSpec (vs the reference's
    torch.fx partitioner, partitioner.py:29-219)."""
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import pipe_stage_specs

    specs = tp_specs(params, tp_axis)
    specs["blocks"] = pipe_stage_specs(specs["blocks"], pipe_axis)
    return specs


# -- sequence-parallel composition ------------------------------------------

def _sp_alibi_pos(pad_mask_local: jax.Array, sp_axis: str) -> jax.Array:
    """GLOBAL mask-aware ALiBi key positions for this sequence chunk:
    BLOOM's ``(cumsum(mask)-1)*mask`` over the FULL sequence (HF
    build_alibi_tensor semantics — matches :func:`build_alibi`), under
    sequence sharding. One tiny all_gather of per-chunk mask counts
    gives every rank the global prefix for its chunk; for unpadded or
    right-padded batches the result equals plain global positions, for
    LEFT-padded batches it is what HF computes and plain positions are
    not. Compute ONCE per step and thread through the blocks."""
    m = pad_mask_local.astype(jnp.float32)
    counts = jax.lax.all_gather(m.sum(-1), sp_axis)  # (sp, B)
    sp = jax.lax.axis_size(sp_axis)
    rank = jax.lax.axis_index(sp_axis)
    prefix = jnp.where(
        jnp.arange(sp)[:, None] < rank, counts, 0.0
    ).sum(0)  # (B,) non-pad tokens on earlier chunks
    return (prefix[:, None] + jnp.cumsum(m, axis=-1) - 1.0) * m


def _attention_sp(
    blk: dict,
    x: jax.Array,  # (B, S_local, H)
    config: BloomConfig,
    tp_axis: Optional[str],
    sp_axis: str,
    pad_mask_local: jax.Array,  # (B, S_local)
    variant: str = "ring",
    alibi_pos: Optional[jax.Array] = None,  # (B, S_local) global positions
) -> jax.Array:
    """BLOOM attention with the sequence sharded over ``sp_axis`` and
    heads over ``tp_axis``. ALiBi positions come from ``alibi_pos``
    (mask-aware global positions, HF semantics under ANY padding —
    _sp_alibi_pos); when None, plain global key positions are used,
    identical for unpadded or right-padded batches.

    ``variant``:
    - ``"ring"``: K/V blocks rotate over the sp ring (flash chunk
      kernels when config.use_flash) — O(S_local^2) score working set,
      comm = K+V once around, best for very long sequences;
    - ``"ulysses"``: two all_to_all ops re-shard seq -> heads so each
      device runs FULL-sequence attention on local_heads/sp heads
      (flash kernel inside when config.use_flash), then one all_to_all
      restores sequence sharding — 4 collectives/layer, best when
      heads >= sp and the ring's per-hop latency dominates.
    Both are exact; gradient flows through the collectives' AD."""
    from pipegoose_tpu.nn.sequence_parallel.ring_attention import (
        make_causal_alibi_bias_fn,
        ring_attention,
        ring_flash_attention,
    )

    if variant not in ("ring", "ulysses"):
        raise ValueError(f"unknown SP variant {variant!r} (ring, ulysses)")
    b, s_local, _ = x.shape
    hd = config.head_dim
    tp = jax.lax.axis_size(tp_axis) if tp_axis else 1
    local_heads = _local_heads(config, tp)

    fused = column_parallel_linear(blk["qkv"], x, tp_axis)
    fused = fused.reshape(b, s_local, local_heads, 3, hd)
    q, k, v = fused[..., 0, :], fused[..., 1, :], fused[..., 2, :]

    slopes = jnp.asarray(alibi_slopes(config.n_head))
    if tp_axis:
        h0 = jax.lax.axis_index(tp_axis) * local_heads
        slopes = jax.lax.dynamic_slice_in_dim(slopes, h0, local_heads, 0)

    if variant == "ulysses":
        from pipegoose_tpu.nn.sequence_parallel.ulysses import (
            ulysses_causal_attention,
        )

        # per-head slopes follow the heads through the all_to_all —
        # device r serves the sp_rank-th subset (sliced inside)
        ctx = ulysses_causal_attention(
            q, k, v, sp_axis, pad_mask_local,
            alibi_slopes=slopes, use_flash=config.use_flash,
            alibi_pos_local=alibi_pos,
        )
    elif config.use_flash:
        # fused chunk kernel per ring step — no (S_local, S_local) score
        # materialization in the forward
        ctx = ring_flash_attention(
            q, k, v, sp_axis, alibi_slopes=slopes, kv_side=pad_mask_local,
            alibi_pos=alibi_pos,
        )
    else:
        bias_fn = make_causal_alibi_bias_fn(s_local, sp_axis, alibi_slopes=slopes)
        side = (
            (pad_mask_local, alibi_pos)
            if alibi_pos is not None else pad_mask_local
        )
        ctx = ring_attention(q, k, v, sp_axis, bias_fn, kv_side=side)
    # pad-query context is ZERO in every attention path (see _attention)
    ctx = ctx * pad_mask_local[:, :, None, None].astype(ctx.dtype)
    ctx = checkpoint_name(ctx, "attn_out")
    ctx = ctx.astype(x.dtype).reshape(b, s_local, local_heads * hd)
    return row_parallel_linear(blk["out"], ctx, tp_axis)


def loss_fn_sp(
    params: dict,
    input_ids: jax.Array,  # (B, S_local) — sequence sharded over sp_axis
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: BloomConfig,
    tp_axis: Optional[str] = None,
    sp_axis: str = "seq",
    variant: str = "ring",
) -> jax.Array:
    """Sequence-parallel causal-LM loss: every activation tensor lives
    sequence-sharded; attention is the ring (or Ulysses all_to_all with
    ``variant="ulysses"`` — see _attention_sp); the next-token target at
    each chunk boundary arrives by one ppermute of the label chunk.
    Gradients of (seq-replicated) params are partial per rank — sum them
    over ``sp_axis`` (grad_sync_axes=(("seq","sum"),))."""
    from pipegoose_tpu.distributed.functional import reduce_from_tensor_group

    b, s_local = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s_local), dtype=jnp.int32)

    x = embed_tokens(params, input_ids, config, tp_axis)
    # global mask-aware ALiBi positions, once per step (HF semantics
    # under any padding — left-padded batches included)
    apos = _sp_alibi_pos(attention_mask, sp_axis)

    def scan_fn(carry, blk):
        return _sp_block(
            blk, carry, config, tp_axis, sp_axis, attention_mask, variant,
            alibi_pos=apos,
        ), None

    step = _remat_wrap(scan_fn, config) if config.remat else scan_fn
    x, _ = jax.lax.scan(step, x, params["blocks"])

    total, w_sum = _sp_head_sums(
        params, x, attention_mask, labels, config, tp_axis, sp_axis
    )
    count = jax.lax.psum(w_sum, sp_axis)
    # identity-backward combine: each rank's grads stay local and are
    # psum'd over sp by the train step
    return reduce_from_tensor_group(total / jnp.maximum(count, 1), sp_axis)


def _sp_block(blk, h, config, tp_axis, sp_axis, pad_mask_local,
              variant: str = "ring", alibi_pos=None):
    """One transformer block on sequence-sharded activations (shared by
    the plain SP and the PP x SP compositions)."""
    ln1 = layer_norm(blk["ln_1"], h, config.layer_norm_epsilon)
    attn_blk = {"qkv": blk["attn"]["qkv"], "out": blk["attn"]["out"]}
    h = h + _attention_sp(
        attn_blk, ln1, config, tp_axis, sp_axis, pad_mask_local, variant,
        alibi_pos=alibi_pos,
    )
    return h + _mlp(blk, h, config, tp_axis)


def _sp_head_sums(params, x, attention_mask, labels, config, tp_axis, sp_axis):
    """Final LN -> logits -> SP-shifted CE sums. Returns the LOCAL
    (weighted-loss sum, weight sum) for this sequence shard.

    Global shift-by-one on a sharded sequence: see
    nn/sequence_parallel/targets.py (shared by all families)."""
    from pipegoose_tpu.nn.sequence_parallel.targets import sp_shifted_targets

    x = layer_norm(params["ln_f"], x, config.layer_norm_epsilon)
    shifted_labels, shifted_w = sp_shifted_targets(
        labels, attention_mask, sp_axis
    )
    if config.fused_ce:
        # the local (B, S_local, V) fp32 logits buffer is the tensor
        # that explodes at exactly the long-context shapes SP serves —
        # the fused kernel never materializes it
        from pipegoose_tpu.ops.fused_ce import fused_ce_masked_sums

        return fused_ce_masked_sums(
            x, params["embed"]["weight"], shifted_labels, shifted_w,
            tp_axis, config.valid_vocab_size,
        )
    logits = logits_fn(params, x, tp_axis)  # (B, S_local, V/tp)
    per_tok = vocab_parallel_cross_entropy(
        logits, shifted_labels, tp_axis, valid_size=config.valid_vocab_size
    )
    w = shifted_w.astype(per_tok.dtype)
    return (per_tok * w).sum(), w.sum()


def loss_fn_pp_sp(
    params: dict,
    input_ids: jax.Array,  # (B, S_local) — sequence sharded over sp_axis
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: BloomConfig,
    n_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    sp_axis: str = "seq",
) -> jax.Array:
    """Pipeline x sequence parallel composition: sequence-sharded
    activations flow through the compiled GPipe schedule, with ring
    attention running over the ``seq`` axis INSIDE each pipeline stage
    (all sp peers of a stage advance in lockstep — uniform SPMD). This
    is the long-context + deep-model shape neither axis covers alone.

    Gradient sync for the hybrid step: ``grad_sync_axes=(("pipe","sum"),
    ("seq","sum"))`` — replicated params get partial grads from both the
    stage split and the sequence split."""
    from pipegoose_tpu.distributed.functional import reduce_from_tensor_group
    from pipegoose_tpu.nn.pipeline_parallel import microbatch as mb
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import gpipe, last_stage_value

    b, s_local = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s_local), dtype=jnp.int32)

    mbs = mb.split(
        {"ids": input_ids, "mask": attention_mask, "labels": labels}, n_microbatches
    )
    h0 = jax.vmap(lambda ids: embed_tokens(params, ids, config, tp_axis))(mbs["ids"])
    # mask-aware global ALiBi positions per microbatch (HF semantics
    # under any padding), computed once and fed as a pipeline side input
    apos = jax.vmap(lambda m: _sp_alibi_pos(m, sp_axis))(mbs["mask"])
    side = {"mask": mbs["mask"], "apos": apos}

    def stage_fn(blocks, h, side):
        def scan_fn(carry, blk):
            return _sp_block(
                blk, carry, config, tp_axis, sp_axis, side["mask"],
                alibi_pos=side["apos"],
            ), None

        h, _ = jax.lax.scan(scan_fn, h, blocks)
        return h

    outs = gpipe(
        stage_fn, params["blocks"], h0, side_inputs=side,
        axis_name=pipe_axis, remat=config.remat,
    )

    tot, cnt = jax.vmap(
        lambda h, m, l: _sp_head_sums(params, h, m, l, config, tp_axis, sp_axis)
    )(outs, mbs["mask"], mbs["labels"])
    count = jax.lax.psum(cnt.sum(), sp_axis)
    loss_local = reduce_from_tensor_group(
        tot.sum() / jnp.maximum(count, 1), sp_axis
    )
    return last_stage_value(loss_local, pipe_axis)
