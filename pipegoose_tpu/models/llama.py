"""Llama: dense decoder family (RMSNorm, RoPE, GQA, SwiGLU MLP).

Third model family. The reference's policy registry carries exactly two
architectures (bloom + albert, reference
nn/tensor_parallel/parallel_mapping.py:16-52); this framework's
equivalent registry (models/convert.py RULES tables) gains the Llama
decoder line (Llama 2/3, TinyLlama, and any llama-type HF checkpoint).

Built on the same primitives as Mixtral — the attention stack (RoPE,
GQA, column/row TP projections) is literally Mixtral's; only the MLP
differs (dense SwiGLU instead of routed experts), so every parallel
form (TP/DP/PP/ZeRO, stacked-layer scan, KV-cache generation) applies.
Semantics match HF ``modeling_llama`` for checkpoint parity (tested in
tests/models/test_llama.py).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from pipegoose_tpu.models.mixtral import (
    RopeScaling,
    _attention,
    rms_norm,
    rope_attention_bias,
    rope_cos_sin,
)
from pipegoose_tpu.nn.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_cross_entropy,
    vocab_parallel_embedding,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 32
    rope_theta: float = 1e4
    # HF rope_scaling (linear / dynamic / llama3) — None = plain RoPE
    rope_scaling: Optional["RopeScaling"] = None
    rms_eps: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    dtype: Any = jnp.float32
    remat: bool = False
    # fused Pallas flash attention after RoPE; GQA served natively by
    # the kernel's grouped K/V index maps (no head repetition)
    use_flash: bool = False
    # fused Pallas CE (ops/fused_ce.py): no logits buffer in HBM
    fused_ce: bool = False
    valid_vocab_size: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.n_head

    @classmethod
    def llama2_7b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            n_layer=32, n_head=32, n_kv_head=8, rope_theta=5e5, **kw,
        )


# -- init ------------------------------------------------------------------

def init_params(config: LlamaConfig, key: jax.Array) -> dict:
    h, v, L = config.hidden_size, config.vocab_size, config.n_layer
    hd, nh, nkv = config.head_dim, config.n_head, config.n_kv_head
    f = config.intermediate_size
    std, dt = config.initializer_range, config.dtype
    ks = jax.random.split(key, 9)

    def dense(k, shape):
        return (jax.random.normal(k, shape) * std).astype(dt)

    def rms_stack():
        return {"scale": jnp.ones((L, h), dt)}

    params = {
        "embed": {"weight": dense(ks[0], (v, h))},
        "blocks": {
            "ln_1": rms_stack(),
            "attn": {
                "q": {"kernel": dense(ks[1], (L, h, nh * hd))},
                "k": {"kernel": dense(ks[2], (L, h, nkv * hd))},
                "v": {"kernel": dense(ks[3], (L, h, nkv * hd))},
                "o": {"kernel": dense(ks[4], (L, nh * hd, h))},
            },
            "ln_2": rms_stack(),
            "mlp": {
                "gate": {"kernel": dense(ks[5], (L, h, f))},
                "up": {"kernel": dense(ks[6], (L, h, f))},
                "down": {"kernel": dense(ks[7], (L, f, h))},
            },
        },
        "ln_f": {"scale": jnp.ones(h, dt)},
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = {"kernel": dense(ks[8], (h, v))}
    return params


# -- forward ---------------------------------------------------------------

def _mlp(blk: dict, x: jax.Array, tp_axis: Optional[str]) -> jax.Array:
    """SwiGLU: down(silu(gate x) * up x), gate/up column, down row."""
    g = column_parallel_linear(blk["gate"], x, tp_axis)
    u = column_parallel_linear(blk["up"], x, tp_axis)
    return row_parallel_linear(blk["down"], jax.nn.silu(g) * u, tp_axis)


def _block(blk, x, cos, sin, bias, config, tp_axis):
    h = rms_norm(blk["ln_1"], x, config.rms_eps)
    x = x + _attention(blk["attn"], h, cos, sin, bias, config, tp_axis)
    h = rms_norm(blk["ln_2"], x, config.rms_eps)
    return x + _mlp(blk["mlp"], h, tp_axis)


def forward_hidden(
    params, input_ids, attention_mask, config, tp_axis: Optional[str] = None
):
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), jnp.int32)
    x = vocab_parallel_embedding(params["embed"], input_ids, tp_axis).astype(
        config.dtype
    )
    cos, sin = rope_cos_sin(
        s, config.head_dim, config.rope_theta, config.rope_scaling
    )
    bias = rope_attention_bias(attention_mask, config)

    block = partial(_block, config=config, tp_axis=tp_axis)
    if config.remat:
        block = jax.checkpoint(block)

    def scan_fn(carry, blk):
        return block(blk, carry, cos, sin, bias), None

    x, _ = jax.lax.scan(scan_fn, x, params["blocks"])
    return rms_norm(params["ln_f"], x, config.rms_eps)


def logits_fn(params, hidden, config, tp_axis: Optional[str] = None):
    """lm_head column-parallel; tied checkpoints reuse the (vocab-
    sharded) embedding like BLOOM (reference parallelizer.py:205-211)."""
    if config.tie_word_embeddings:
        from pipegoose_tpu.distributed.functional import copy_to_tensor_group

        if tp_axis:
            hidden = copy_to_tensor_group(hidden, tp_axis)
        w = params["embed"]["weight"]  # (V/tp, H) under TP
        return jnp.einsum(
            "bsh,vh->bsv", hidden, w, preferred_element_type=jnp.float32
        )
    return column_parallel_linear(params["lm_head"], hidden, tp_axis)


def forward(params, input_ids, attention_mask, config, tp_axis=None):
    hidden = forward_hidden(params, input_ids, attention_mask, config, tp_axis)
    return logits_fn(params, hidden, config, tp_axis)


def _head_weight_layout(params, config):
    """(weight, fused-CE layout) of the LM head in its native form:
    tied = the (V/tp, H) vocab-sharded embedding, untied = the
    (H, V/tp) column-parallel kernel."""
    return (
        (params["embed"]["weight"], "vh")
        if config.tie_word_embeddings
        else (params["lm_head"]["kernel"], "hv")
    )


def loss_fn(params, input_ids, attention_mask, labels, config, tp_axis=None):
    if config.fused_ce:
        # fused Pallas CE: loss straight from (hidden, head weight) in
        # its NATIVE layout — tied = (V/tp, H) embedding, untied =
        # (H, V/tp) column head — no logits buffer, no transpose copy
        # (ops/fused_ce.py; the f-operator psum lives in its VJP)
        from pipegoose_tpu.ops.fused_ce import fused_ce_shifted_loss

        hidden = forward_hidden(
            params, input_ids, attention_mask, config, tp_axis
        )
        weight, layout = _head_weight_layout(params, config)
        return fused_ce_shifted_loss(
            hidden, weight, labels, attention_mask, tp_axis,
            config.valid_vocab_size, weight_layout=layout,
        )
    logits = forward(params, input_ids, attention_mask, config, tp_axis)
    per_tok = vocab_parallel_cross_entropy(
        logits[:, :-1], labels[:, 1:], tp_axis, valid_size=config.valid_vocab_size
    )
    if attention_mask is not None:
        w = attention_mask[:, 1:].astype(per_tok.dtype)
        return (per_tok * w).sum() / jnp.maximum(w.sum(), 1)
    return per_tok.mean()


# -- pipeline-parallel composition ------------------------------------------

def loss_fn_pp(
    params, input_ids, attention_mask, labels, config, n_microbatches,
    tp_axis: Optional[str] = None, pipe_axis: str = "pipe",
    stage_layer_counts=None,
):
    """GPipe composition, structured like bloom.loss_fn_pp.
    ``stage_layer_counts``: UNEVEN stages exactly as there (padded
    ``repartition_blocks`` layout, lax.cond slot skip)."""
    from pipegoose_tpu.nn.pipeline_parallel import microbatch as mb
    from pipegoose_tpu.nn.pipeline_parallel.partitioner import (
        masked_stage_scan,
        stage_n_valid,
    )
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import gpipe, last_stage_value

    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), jnp.int32)
    mbs = mb.split(
        {"ids": input_ids, "mask": attention_mask, "labels": labels}, n_microbatches
    )
    h0 = jax.vmap(
        lambda ids: vocab_parallel_embedding(params["embed"], ids, tp_axis).astype(
            config.dtype
        )
    )(mbs["ids"])
    cos, sin = rope_cos_sin(
        s, config.head_dim, config.rope_theta, config.rope_scaling
    )
    side = {"bias": jax.vmap(lambda m: rope_attention_bias(m, config))(mbs["mask"])}

    if stage_layer_counts is not None:
        n_valid = stage_n_valid(stage_layer_counts, config.n_layer, pipe_axis)

        def stage_fn(blocks, h, side):
            return masked_stage_scan(
                lambda blk, hh: _block(blk, hh, cos, sin, side["bias"], config, tp_axis),
                blocks, h, n_valid,
            )
    else:
        def stage_fn(blocks, h, side):
            def scan_fn(carry, blk):
                return _block(blk, carry, cos, sin, side["bias"], config, tp_axis), None

            h, _ = jax.lax.scan(scan_fn, h, blocks)
            return h

    outs = gpipe(
        stage_fn, params["blocks"], h0, side_inputs=side,
        axis_name=pipe_axis, remat=config.remat,
    )

    def head_one(h, mask, labels):
        h = rms_norm(params["ln_f"], h, config.rms_eps)
        if config.fused_ce:
            from pipegoose_tpu.ops.fused_ce import fused_ce_shifted_sums

            weight, layout = _head_weight_layout(params, config)
            return fused_ce_shifted_sums(
                h, weight, labels, mask, tp_axis,
                config.valid_vocab_size, weight_layout=layout,
            )
        logits = logits_fn(params, h, config, tp_axis)
        per_tok = vocab_parallel_cross_entropy(
            logits[:, :-1], labels[:, 1:], tp_axis, valid_size=config.valid_vocab_size
        )
        w = mask[:, 1:].astype(per_tok.dtype)
        return (per_tok * w).sum(), w.sum()

    tot, cnt = jax.vmap(head_one)(outs, mbs["mask"], mbs["labels"])
    return last_stage_value(tot.sum() / jnp.maximum(cnt.sum(), 1), pipe_axis)


def loss_fn_1f1b(
    params, input_ids, attention_mask, labels, config, n_microbatches,
    tp_axis: Optional[str] = None, pipe_axis: str = "pipe",
    stage_layer_counts=None,
):
    """Llama on the 1F1B (PipeDream-flush) runtime: same value/gradients
    as :func:`loss_fn_pp` with O(stages) activation memory — the same
    custom-vjp manual-gradient wrapper as ``bloom.loss_fn_1f1b``.
    Handles both tied and untied heads (tied: the embedding gets input
    AND head gradient contributions)."""
    from pipegoose_tpu.nn.pipeline_parallel import microbatch as mb
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import (
        manual_grads_loss,
        one_f_one_b,
    )

    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), jnp.int32)
    mbs = mb.split(
        {"ids": input_ids, "mask": attention_mask, "labels": labels}, n_microbatches
    )
    cos, sin = rope_cos_sin(
        s, config.head_dim, config.rope_theta, config.rope_scaling
    )
    side = {
        "bias": jax.vmap(lambda m: rope_attention_bias(m, config))(mbs["mask"]),
        "labels": mbs["labels"],
        "mask": mbs["mask"],
    }
    inv_count = 1.0 / jnp.maximum(attention_mask[:, 1:].sum().astype(jnp.float32), 1)

    block = partial(_block, config=config, tp_axis=tp_axis)
    if config.remat:
        block = jax.checkpoint(block)

    if stage_layer_counts is not None:
        from pipegoose_tpu.nn.pipeline_parallel.partitioner import (
            masked_stage_scan,
            stage_n_valid,
        )

        n_valid = stage_n_valid(stage_layer_counts, config.n_layer, pipe_axis)

        def stage_fn(blocks, h, side):
            return masked_stage_scan(
                lambda blk, hh: block(blk, hh, cos, sin, side["bias"]),
                blocks, h, n_valid,
            )
    else:
        def stage_fn(blocks, h, side):
            def scan_fn(carry, blk):
                return block(blk, carry, cos, sin, side["bias"]), None

            h, _ = jax.lax.scan(scan_fn, h, blocks)
            return h

    tied = config.tie_word_embeddings

    def head_fn(hp, h, side):
        h = rms_norm(hp["ln_f"], h, config.rms_eps)
        if config.fused_ce:
            from pipegoose_tpu.ops.fused_ce import fused_ce_shifted_sums

            weight, layout = _head_weight_layout(hp, config)
            tot, _ = fused_ce_shifted_sums(
                h, weight, side["labels"], side["mask"], tp_axis,
                config.valid_vocab_size, weight_layout=layout,
            )
            return (tot * inv_count).astype(jnp.float32)
        logits = logits_fn(hp, h, config, tp_axis)
        per_tok = vocab_parallel_cross_entropy(
            logits[:, :-1], side["labels"][:, 1:], tp_axis,
            valid_size=config.valid_vocab_size,
        )
        w = side["mask"][:, 1:].astype(per_tok.dtype)
        return ((per_tok * w).sum() * inv_count).astype(jnp.float32)

    def run(params):
        h0, embed_vjp = jax.vjp(
            lambda ep: jax.vmap(
                lambda ids: vocab_parallel_embedding(ep, ids, tp_axis).astype(
                    config.dtype
                )
            )(mbs["ids"]),
            params["embed"],
        )
        head_params = {"ln_f": params["ln_f"]}
        if tied:
            head_params["embed"] = params["embed"]
        else:
            head_params["lm_head"] = params["lm_head"]
        loss_local, dh0, d_blocks, d_head = one_f_one_b(
            stage_fn, params["blocks"], head_fn, head_params, h0, side, pipe_axis
        )
        (d_embed,) = embed_vjp(dh0)
        P = jax.lax.axis_size(pipe_axis)
        is_last = jax.lax.axis_index(pipe_axis) == P - 1
        loss = jax.lax.psum(jnp.where(is_last, loss_local, 0.0), pipe_axis)
        if tied:
            d_embed = {
                "weight": d_embed["weight"] + d_head["embed"]["weight"]
            }
        grads = {
            "embed": d_embed,
            "blocks": d_blocks,
            "ln_f": d_head["ln_f"],
        }
        if not tied:
            grads["lm_head"] = d_head["lm_head"]
        return loss, grads

    return manual_grads_loss(run, params)


# -- TP/PP policy -----------------------------------------------------------

def specs(params: dict, tp_axis: str = "tensor") -> dict:
    """PartitionSpecs: q/k/v/gate/up column, o/down row, embedding
    vocab-sharded, lm_head column; stacked n_layer dim free for pipe."""
    from jax.sharding import PartitionSpec as P

    from pipegoose_tpu.nn.parallel import spec_tree

    t = tp_axis

    def spec_fn(path, x):
        if any(k in path for k in ("attn/q", "attn/k", "attn/v", "mlp/gate", "mlp/up")):
            return P(None, None, t)
        if "attn/o" in path or "mlp/down" in path:
            return P(None, t, None)
        if "embed/weight" in path:
            return P(t, None)
        if "lm_head" in path:
            return P(None, t)
        return P()

    return spec_tree(params, spec_fn)


# -- sequence-parallel composition ------------------------------------------

def loss_fn_sp(
    params: dict,
    input_ids: jax.Array,  # (B, S_local) — sequence sharded over sp_axis
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: LlamaConfig,
    tp_axis: Optional[str] = None,
    sp_axis: str = "seq",
    variant: str = "ring",
) -> jax.Array:
    """Sequence-parallel Llama loss: ring (or ``variant="ulysses"``)
    attention over ``sp_axis`` with RoPE at global positions
    (rope_scaling honored). Shares mixtral._attention_sp — the RoPE/GQA
    SP paths are family-agnostic; only the dense SwiGLU block body
    differs from Mixtral's MoE.

    Grad sync for replicated params: ``grad_sync_axes=(("seq","sum"),)``.
    """
    from pipegoose_tpu.distributed.functional import reduce_from_tensor_group
    from pipegoose_tpu.models.mixtral import _attention_sp
    from pipegoose_tpu.nn.sequence_parallel.targets import sp_shifted_targets

    b, s_local = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s_local), jnp.int32)

    x = vocab_parallel_embedding(params["embed"], input_ids, tp_axis).astype(
        config.dtype
    )

    def block(blk, h):
        ln1 = rms_norm(blk["ln_1"], h, config.rms_eps)
        h = h + _attention_sp(
            blk["attn"], ln1, config, tp_axis, sp_axis, attention_mask, variant
        )
        ln2 = rms_norm(blk["ln_2"], h, config.rms_eps)
        return h + _mlp(blk["mlp"], ln2, tp_axis)

    def scan_fn(carry, blk):
        return block(blk, carry), None

    step = jax.checkpoint(scan_fn) if config.remat else scan_fn
    x, _ = jax.lax.scan(step, x, params["blocks"])

    x = rms_norm(params["ln_f"], x, config.rms_eps)
    shifted_labels, shifted_w = sp_shifted_targets(
        labels, attention_mask, sp_axis
    )
    if config.fused_ce:
        from pipegoose_tpu.ops.fused_ce import fused_ce_masked_sums

        weight, layout = _head_weight_layout(params, config)
        tot, cnt = fused_ce_masked_sums(
            x, weight, shifted_labels, shifted_w, tp_axis,
            config.valid_vocab_size, weight_layout=layout,
        )
    else:
        logits = logits_fn(params, x, config, tp_axis)
        per_tok = vocab_parallel_cross_entropy(
            logits, shifted_labels, tp_axis,
            valid_size=config.valid_vocab_size,
        )
        w = shifted_w.astype(per_tok.dtype)
        tot, cnt = (per_tok * w).sum(), w.sum()
    count = jax.lax.psum(cnt, sp_axis)
    return reduce_from_tensor_group(
        tot / jnp.maximum(count, 1), sp_axis
    )


def pp_specs(params: dict, tp_axis: str = "tensor", pipe_axis: str = "pipe") -> dict:
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import pipe_stage_specs

    sp = specs(params, tp_axis)
    sp["blocks"] = pipe_stage_specs(sp["blocks"], pipe_axis)
    return sp


# -- generation (KV cache) ---------------------------------------------------

def init_cache(config: LlamaConfig, batch: int, max_len: int) -> dict:
    L, nkv, hd = config.n_layer, config.n_kv_head, config.head_dim
    shape = (L, batch, max_len, nkv, hd)
    return {"k": jnp.zeros(shape, config.dtype), "v": jnp.zeros(shape, config.dtype)}


def forward_cached(params, ids, cache, start, config):
    """(logits at last position, new cache) — shares Mixtral's grouped-GQA
    cached attention; the per-layer body swaps the MoE for dense SwiGLU."""
    from pipegoose_tpu.models.mixtral import _attn_cached

    x = vocab_parallel_embedding(params["embed"], ids, None).astype(config.dtype)
    max_len = cache["k"].shape[2]
    if config.rope_scaling is not None and config.rope_scaling.rope_type == "dynamic":
        # dynamic NTK makes inv_freq a function of the CURRENT length;
        # precomputing at cache capacity would rescale short prompts HF
        # leaves unscaled — reject rather than silently diverge
        raise NotImplementedError(
            "rope_scaling type 'dynamic' is not supported in the KV-cache "
            "decode path (length-dependent frequencies)"
        )
    cos_full, sin_full = rope_cos_sin(
        max_len, config.head_dim, config.rope_theta, config.rope_scaling
    )

    def scan_fn(carry, blk_and_cache):
        h = carry
        blk, kc, vc = blk_and_cache
        ln1 = rms_norm(blk["ln_1"], h, config.rms_eps)
        attn, kc, vc = _attn_cached(
            blk["attn"], ln1, kc, vc, start, cos_full, sin_full, config
        )
        h = h + attn
        ln2 = rms_norm(blk["ln_2"], h, config.rms_eps)
        return h + _mlp(blk["mlp"], ln2, None), (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(
        scan_fn, x, (params["blocks"], cache["k"], cache["v"])
    )
    x = rms_norm(params["ln_f"], x, config.rms_eps)
    logits = logits_fn(params, x[:, -1:], config, None)[:, 0]
    return logits, {"k": k_new, "v": v_new}


def generate(
    params, input_ids, config, max_new_tokens,
    temperature: float = 0.0, rng=None, eos_token_id=None,
) -> jax.Array:
    from pipegoose_tpu.models._decode import autoregressive_generate, vocab_mask_for

    return autoregressive_generate(
        forward_cached, init_cache, params, input_ids, config,
        max_new_tokens, temperature, rng, eos_token_id,
        logits_mask=vocab_mask_for(config),
    )
