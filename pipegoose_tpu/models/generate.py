"""Autoregressive generation with a KV cache for BLOOM.

The reference relies on HF's ``model.generate`` over the wrapped torch
module (convergence scripts); a standalone framework needs its own
decode path. TPU-native design: a fixed-size (max_len) cache stacked per
layer rides a ``lax.scan`` over blocks, prefill and per-token decode are
two jitted programs with static shapes, and the decode loop is a
``lax.scan`` over time steps — the whole generation is compiled, no
per-token Python.

Ragged batches follow HF generate's LEFT-padding convention: pass
``attention_mask`` and each row's prompt ends at the last column. The
mask is a RUNTIME side input (``_decode`` extras) — the compiled
programs are shared across masks; ALiBi uses the mask-aware positions
(build_alibi semantics) and pad slots stay masked as keys for the whole
generation. Without a mask, prompts are assumed unpadded and plain
global positions apply.

Telemetry: the shared decode driver records ``generate.prefill`` /
``generate.decode`` spans (fenced, so device work is attributed
correctly) when the telemetry registry is enabled — see
pipegoose_tpu/telemetry/ and docs/observability.md. Disabled, the spans
are single-branch no-ops.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pipegoose_tpu.models.bloom import (
    BloomConfig,
    NEG_INF,
    alibi_slopes,
    bloom_gelu,
    layer_norm,
    logits_fn,
)
from pipegoose_tpu.nn.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)


def init_cache(config: BloomConfig, batch: int, max_len: int, tp: int = 1) -> dict:
    """KV cache; under TP the cache holds this shard's nh/tp heads."""
    L, nh, hd = config.n_layer, config.n_head, config.head_dim
    shape = (L, batch, max_len, nh // tp, hd)
    return {
        "k": jnp.zeros(shape, config.dtype),
        "v": jnp.zeros(shape, config.dtype),
    }


def _qkv_proj(blk, x, config, tp_axis=None):
    """Fused qkv projection split into (q, k, v), each (B, S, nh_local,
    hd). Under TP the projection is column-parallel and the head dim is
    the LOCAL subset. Shared by the contiguous-cache path below and the
    serving engine's page-table path (serving/kv_pool.py)."""
    b, s, _ = x.shape
    hd = config.head_dim
    tp = jax.lax.axis_size(tp_axis) if tp_axis else 1
    nh = config.n_head // tp
    fused = column_parallel_linear(blk["qkv"], x, tp_axis)
    fused = fused.reshape(b, s, nh, 3, hd)
    return fused[..., 0, :], fused[..., 1, :], fused[..., 2, :]


def _attn_core(q, keys, values, bias, qmask, out_dtype):
    """Softmax attention of q (B, S, nh, hd) against a key/value view
    (B, K, nh, hd) under an additive bias (B|1, nh, S, K). The view can
    be a contiguous cache OR the per-slot gather through a serving page
    table — invalid key columns must arrive masked (NEG_INF) in ``bias``
    so their softmax weight is exactly zero."""
    hd = q.shape[-1]
    b, s, nh, _ = q.shape
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, keys, preferred_element_type=jnp.float32
    ) * (hd**-0.5)
    probs = jax.nn.softmax(scores + bias, axis=-1).astype(out_dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, values, preferred_element_type=jnp.float32)
    if qmask is not None:
        # pad-query context is ZERO in every attention path (bloom._attention)
        ctx = ctx * qmask[:, :, None, None].astype(ctx.dtype)
    return ctx.astype(out_dtype).reshape(b, s, nh * hd)


def _attn_cached(blk, x, k_cache, v_cache, start, config, tp_axis=None,
                 bias=None, qmask=None):
    """Attend S new tokens against cache[:start] + themselves; returns
    (out, new_k_cache, new_v_cache). ``start`` is the number of tokens
    already cached (traced scalar). Under TP the qkv projection is
    column-parallel, the cache and slopes carry the LOCAL head subset,
    and the out projection's row-parallel psum recombines heads.
    ``bias``/``qmask`` come from :func:`_decode_bias` (hoisted — shared
    by all layers of one forward)."""
    q, k, v = _qkv_proj(blk, x, config, tp_axis)
    k_cache = lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype), (0, start, 0, 0))
    v_cache = lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype), (0, start, 0, 0))
    ctx = _attn_core(q, k_cache, v_cache, bias, qmask, x.dtype)
    return row_parallel_linear(blk["out"], ctx, tp_axis), k_cache, v_cache


def _decode_bias(config, b, s, start, max_len, extras, tp_axis):
    """Attention bias for one cached-forward call, shared by all layers:
    causal-by-slot keep + ALiBi (+ per-row key validity for ragged
    LEFT-padded prompts). Returns (bias (B|1, nh_local, S, max_len),
    qmask (B, S) or None).

    Ragged prompts (``extras={"mask": (B, max_len)}`` — the prompt's
    attention mask extended with ones over the generated tail, HF
    left-padding convention): ALiBi positions become the mask-aware
    global ``(cumsum(mask)-1)*mask`` (exactly ``build_alibi``), pad
    slots are masked as keys for every future step, and pad-query rows
    of the prefill get zero context."""
    tp = jax.lax.axis_size(tp_axis) if tp_axis else 1
    nh = config.n_head // tp
    slopes = jnp.asarray(alibi_slopes(config.n_head))
    if tp_axis:
        slopes = lax.dynamic_slice_in_dim(
            slopes, jax.lax.axis_index(tp_axis) * nh, nh, 0
        )
    key_pos = jnp.arange(max_len)
    q_pos = start + jnp.arange(s)
    keep = key_pos[None, :] <= q_pos[:, None]  # (S, max_len): causal + not-yet-written
    causal = jnp.where(keep[None, None], 0.0, NEG_INF)
    if extras is None:
        bias = slopes[None, :, None, None] * key_pos[None, None, None, :].astype(jnp.float32)
        return bias + causal, None
    m = extras["mask"].astype(jnp.float32)  # (B, max_len)
    apos = (jnp.cumsum(m, axis=-1) - 1.0) * m
    bias = slopes[None, :, None, None] * apos[:, None, None, :]
    bias = bias + jnp.where(m[:, None, None, :] > 0, 0.0, NEG_INF)
    qmask = lax.dynamic_slice_in_dim(m, start, s, axis=1)  # (B, S)
    return bias + causal, qmask


def forward_cached(params, ids, cache, start, config, tp_axis=None,
                   extras=None):
    """Forward S tokens with cache read/write. Returns (logits last
    position, new cache). Under TP the returned logits are the LOCAL
    vocab shard (pair with ``_decode.global_greedy_pick``).
    ``extras={"mask": (B, max_len)}`` enables ragged/left-padded
    prompts (see _decode_bias)."""
    x = vocab_parallel_embedding(params["embed"], ids, tp_axis).astype(config.dtype)
    x = layer_norm(params["embed_ln"], x, config.layer_norm_epsilon)
    b, s = ids.shape
    bias, qmask = _decode_bias(
        config, b, s, start, cache["k"].shape[2], extras, tp_axis
    )

    def scan_fn(carry, blk_and_cache):
        h = carry
        blk, kc, vc = blk_and_cache
        ln1 = layer_norm(blk["ln_1"], h, config.layer_norm_epsilon)
        attn, kc, vc = _attn_cached(
            {"qkv": blk["attn"]["qkv"], "out": blk["attn"]["out"]},
            ln1, kc, vc, start, config, tp_axis, bias=bias, qmask=qmask,
        )
        h = h + attn
        ln2 = layer_norm(blk["ln_2"], h, config.layer_norm_epsilon)
        up = column_parallel_linear(blk["mlp"]["up"], ln2, tp_axis)
        h = h + row_parallel_linear(blk["mlp"]["down"], bloom_gelu(up), tp_axis)
        return h, (kc, vc)

    x, (k_new, v_new) = lax.scan(scan_fn, x, (params["blocks"], cache["k"], cache["v"]))
    x = layer_norm(params["ln_f"], x, config.layer_norm_epsilon)
    logits = logits_fn(params, x[:, -1:], tp_axis)[:, 0]  # (B, V/tp)
    return logits, {"k": k_new, "v": v_new}


def _bloom_init_cache(config, batch, max_len, tp=1):
    return init_cache(config, batch, max_len, tp)




def _ragged_extras(attention_mask, max_new_tokens):
    """Extend a LEFT-padded prompt mask with ones over the generated
    tail: the runtime side input for ragged decode (HF generate's
    left-padding convention — the prompt must END at the last column;
    generated tokens are always valid).

    A RIGHT-padded mask would silently mis-position the generated tail
    (the appended ones land after the pad gap), so fail loudly instead
    (advisor r4). The check runs only for HOST-resident masks (numpy
    arrays, lists — the common entry point, where it is free): fetching
    a column of a device ``jax.Array`` would force a blocking
    device-to-host sync on every generate call, and a tracer or a
    non-fully-addressable multihost mask cannot be fetched at all
    (ADVICE r5) — those skip the guard. The except clause names the
    specific failure modes of exotic array-likes reaching ``np.asarray``
    instead of swallowing real errors with a bare Exception."""
    if isinstance(attention_mask, jax.Array):
        ends_valid = True  # device array / tracer: skip, no forced sync
    else:
        try:
            # keep the materialized array: plain lists have no .shape,
            # so the concatenate below needs this form anyway
            attention_mask = np.asarray(attention_mask)
            ends_valid = bool(attention_mask[:, -1].all())
        except (jax.errors.TracerBoolConversionError,
                jax.errors.ConcretizationTypeError,
                RuntimeError):  # non-addressable shard behind an array-like
            ends_valid = True
    if not ends_valid:
        raise ValueError(
            "ragged generate expects a LEFT-padded attention_mask (HF "
            "generate convention): the last column must be all ones, but "
            "some rows end in padding. Re-tokenize with "
            "padding_side='left'."
        )
    b = attention_mask.shape[0]
    ones = jnp.ones((b, max_new_tokens), attention_mask.dtype)
    return {"mask": jnp.concatenate([attention_mask, ones], axis=1)}


def generate(
    params: dict,
    input_ids: jax.Array,  # (B, S) prompt; ragged rows LEFT-padded
    config: BloomConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_token_id: Optional[int] = None,
    attention_mask: Optional[jax.Array] = None,  # (B, S): ragged prompts
) -> jax.Array:
    """Greedy (temperature=0) or sampled decoding. Returns (B, S+new).
    ``eos_token_id``: finished sequences emit eos from then on (HF
    generate's pad-with-eos behavior). ``attention_mask`` enables
    RAGGED prompts (unequal lengths, LEFT-padded like HF generate):
    ALiBi uses mask-aware positions, pad slots stay masked as keys for
    the whole generation, and the mask is a runtime input — new masks
    don't recompile."""
    from pipegoose_tpu.models._decode import autoregressive_generate, vocab_mask_for

    extras = (
        _ragged_extras(attention_mask, max_new_tokens)
        if attention_mask is not None else None
    )
    return autoregressive_generate(
        forward_cached, _bloom_init_cache, params, input_ids, config,
        max_new_tokens, temperature, rng, eos_token_id,
        logits_mask=vocab_mask_for(config), extras=extras,
    )


def generate_tp(
    params: dict,
    input_ids: jax.Array,
    config: BloomConfig,
    max_new_tokens: int,
    mesh,
    param_specs,
    tp_axis: str = "tensor",
    eos_token_id: Optional[int] = None,
    attention_mask: Optional[jax.Array] = None,  # (B, S): ragged prompts
) -> jax.Array:
    """Tensor-parallel greedy decoding: vocab/head-sharded weights, a
    per-shard KV cache, and a global argmax over the sharded vocab —
    the whole generation compiled as one shard_map program
    (models/_decode.py:autoregressive_generate_sharded).
    ``attention_mask`` enables ragged LEFT-padded prompts, same
    semantics as :func:`generate`."""
    from pipegoose_tpu.models._decode import autoregressive_generate_sharded

    extras = (
        _ragged_extras(attention_mask, max_new_tokens)
        if attention_mask is not None else None
    )
    return autoregressive_generate_sharded(
        forward_cached, _bloom_init_cache, params, input_ids, config,
        max_new_tokens, mesh, param_specs, tp_axis, eos_token_id,
        extras=extras,
    )
