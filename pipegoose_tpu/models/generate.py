"""Autoregressive generation with a KV cache for BLOOM.

The reference relies on HF's ``model.generate`` over the wrapped torch
module (convergence scripts); a standalone framework needs its own
decode path. TPU-native design: a fixed-size (max_len) cache stacked per
layer rides a ``lax.scan`` over blocks, prefill and per-token decode are
two jitted programs with static shapes, and the decode loop is a
``lax.scan`` over time steps — the whole generation is compiled, no
per-token Python.

Prompts are assumed unpadded (equal lengths per batch row) in v1; the
alibi/causal bias uses plain global positions accordingly.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from pipegoose_tpu.models.bloom import (
    BloomConfig,
    NEG_INF,
    alibi_slopes,
    bloom_gelu,
    layer_norm,
    logits_fn,
)
from pipegoose_tpu.nn.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)


def init_cache(config: BloomConfig, batch: int, max_len: int) -> dict:
    L, nh, hd = config.n_layer, config.n_head, config.head_dim
    shape = (L, batch, max_len, nh, hd)
    return {
        "k": jnp.zeros(shape, config.dtype),
        "v": jnp.zeros(shape, config.dtype),
    }


def _attn_cached(blk, x, k_cache, v_cache, start, config):
    """Attend S new tokens against cache[:start] + themselves; returns
    (out, new_k_cache, new_v_cache). ``start`` is the number of tokens
    already cached (traced scalar)."""
    b, s, _ = x.shape
    nh, hd = config.n_head, config.head_dim
    max_len = k_cache.shape[1]

    fused = column_parallel_linear(blk["qkv"], x, None)
    fused = fused.reshape(b, s, nh, 3, hd)
    q, k, v = fused[..., 0, :], fused[..., 1, :], fused[..., 2, :]

    k_cache = lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype), (0, start, 0, 0))
    v_cache = lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype), (0, start, 0, 0))

    key_pos = jnp.arange(max_len)
    q_pos = start + jnp.arange(s)
    slopes = jnp.asarray(alibi_slopes(nh))
    bias = slopes[None, :, None, None] * key_pos[None, None, None, :].astype(jnp.float32)
    keep = key_pos[None, :] <= q_pos[:, None]  # (S, max_len): causal + not-yet-written
    bias = bias + jnp.where(keep[None, None], 0.0, NEG_INF)

    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) * (hd**-0.5)
    probs = jax.nn.softmax(scores + bias, axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v_cache, preferred_element_type=jnp.float32)
    ctx = ctx.astype(x.dtype).reshape(b, s, nh * hd)
    return row_parallel_linear(blk["out"], ctx, None), k_cache, v_cache


def forward_cached(params, ids, cache, start, config):
    """Forward S tokens with cache read/write. Returns (logits last
    position, new cache)."""
    x = vocab_parallel_embedding(params["embed"], ids, None).astype(config.dtype)
    x = layer_norm(params["embed_ln"], x, config.layer_norm_epsilon)

    def scan_fn(carry, blk_and_cache):
        h = carry
        blk, kc, vc = blk_and_cache
        ln1 = layer_norm(blk["ln_1"], h, config.layer_norm_epsilon)
        attn, kc, vc = _attn_cached(
            {"qkv": blk["attn"]["qkv"], "out": blk["attn"]["out"]},
            ln1, kc, vc, start, config,
        )
        h = h + attn
        ln2 = layer_norm(blk["ln_2"], h, config.layer_norm_epsilon)
        up = column_parallel_linear(blk["mlp"]["up"], ln2, None)
        h = h + row_parallel_linear(blk["mlp"]["down"], bloom_gelu(up), None)
        return h, (kc, vc)

    x, (k_new, v_new) = lax.scan(scan_fn, x, (params["blocks"], cache["k"], cache["v"]))
    x = layer_norm(params["ln_f"], x, config.layer_norm_epsilon)
    logits = logits_fn(params, x[:, -1:], None)[:, 0]  # (B, V)
    return logits, {"k": k_new, "v": v_new}


def _bloom_init_cache(config, batch, max_len):
    return init_cache(config, batch, max_len)




def generate(
    params: dict,
    input_ids: jax.Array,  # (B, S) unpadded prompt
    config: BloomConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_token_id: Optional[int] = None,
) -> jax.Array:
    """Greedy (temperature=0) or sampled decoding. Returns (B, S+new).
    ``eos_token_id``: finished sequences emit eos from then on (HF
    generate's pad-with-eos behavior)."""
    from pipegoose_tpu.models._decode import autoregressive_generate, vocab_mask_for

    return autoregressive_generate(
        forward_cached, _bloom_init_cache, params, input_ids, config,
        max_new_tokens, temperature, rng, eos_token_id,
        logits_mask=vocab_mask_for(config),
    )
