"""ALBERT, TPU-native — the encoder (bidirectional) model family.

The reference's TP mapping covers albert alongside bloom
(pipegoose/nn/tensor_parallel/parallel_mapping.py:33-52: query/key/value
and ffn column-parallel, attention.dense and ffn_output row-parallel)
and its DataParallel tests run on an encoder (bert-tiny,
tests/nn/data_parallel/test_data_parallel.py:18) — so an encoder family
with TP + DP coverage is part of the reference's demonstrated surface.
Implemented from scratch in JAX with the same layer functions as the
causal families:

- BIDIRECTIONAL attention: no causal mask — only the key-padding bias
  (every query attends all valid positions);
- factorized embedding (vocab x E, then a dense E->H projection) and
  CROSS-LAYER PARAMETER SHARING: one layer's params applied n_layer
  times — expressed as ``lax.scan`` over a length-n_layer trip with the
  SAME params in the carry closure (no stacked per-layer dim at all);
- post-LN residuals (LayerNorm AFTER the residual add, BERT lineage),
  vs the causal families' pre-LN;
- MLM head: dense H->E + gelu + LN, then the decoder TIED to the word
  embedding (vocab-sharded logits + vocab-parallel CE under TP).

Semantics match HF ``modeling_albert`` (gelu-tanh ``gelu_new``,
separate q/k/v projections, additive key mask, absolute position +
token-type embeddings) so HF checkpoints load exactly; parity is tested
against the torch implementation in tests/models/test_albert.py.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp

from pipegoose_tpu.nn.parallel_mapping import (
    Column,
    ParallelMapping,
    Row,
    Vocab,
)
from pipegoose_tpu.nn.tensor_parallel.layers import (
    column_parallel_linear,
    layer_norm,
    row_parallel_linear,
    vocab_parallel_cross_entropy,
    vocab_parallel_embedding,
)

NEG_INF = -1e9


@dataclasses.dataclass(frozen=True)
class AlbertConfig:
    vocab_size: int = 30000
    embedding_size: int = 128
    hidden_size: int = 768
    n_layer: int = 12
    n_head: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: Any = jnp.float32
    remat: bool = False
    # true vocab size when the embedding was padded for TP divisibility
    valid_vocab_size: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.n_head

    @classmethod
    def albert_base(cls, **kw) -> "AlbertConfig":
        return cls(**kw)  # the defaults ARE albert-base-v2


def gelu_new(x: jax.Array) -> jax.Array:
    """HF ``gelu_new`` (full-precision tanh approximation — ALBERT's
    activation; bloom uses a truncated-constant variant)."""
    return 0.5 * x * (
        1.0 + jnp.tanh(math.sqrt(2.0 / math.pi) * (x + 0.044715 * x**3))
    )


# -- init ------------------------------------------------------------------

def init_params(config: AlbertConfig, key: jax.Array) -> dict:
    """Random init matching HF's scheme. NOTE the layout: ``layer`` holds
    ONE layer's params (cross-layer sharing) — no stacked n_layer dim."""
    c = config
    k = iter(jax.random.split(key, 16))

    def dense(kk, din, dout):
        return {
            "kernel": (jax.random.normal(kk, (din, dout)) * c.initializer_range
                       ).astype(c.dtype),
            "bias": jnp.zeros((dout,), c.dtype),
        }

    def ln(d):
        return {"scale": jnp.ones((d,), c.dtype), "bias": jnp.zeros((d,), c.dtype)}

    emb = lambda kk, n, d: (jax.random.normal(kk, (n, d)) * c.initializer_range
                            ).astype(c.dtype)
    h, e, i = c.hidden_size, c.embedding_size, c.intermediate_size
    return {
        "embed": {
            "word": {"weight": emb(next(k), c.vocab_size, e)},
            "pos": emb(next(k), c.max_position_embeddings, e),
            "type": emb(next(k), c.type_vocab_size, e),
            "ln": ln(e),
        },
        "map_in": dense(next(k), e, h),
        "layer": {
            "attn": {
                "q": dense(next(k), h, h),
                "k": dense(next(k), h, h),
                "v": dense(next(k), h, h),
                "dense": dense(next(k), h, h),
                "ln": ln(h),
            },
            "ffn": {
                "up": dense(next(k), h, i),
                "down": dense(next(k), i, h),
                "ln": ln(h),
            },
        },
        "mlm": {
            "dense": dense(next(k), h, e),
            "ln": ln(e),
            "bias": jnp.zeros((c.vocab_size,), c.dtype),
        },
    }


# -- forward ---------------------------------------------------------------

def _attention(
    blk: dict,
    x: jax.Array,  # (B, S, H)
    key_bias: jax.Array,  # (B, 1, 1, S) additive key-padding bias
    config: AlbertConfig,
    tp_axis: Optional[str],
) -> jax.Array:
    """Bidirectional self-attention, heads sharded over ``tp_axis``
    (q/k/v column-parallel, output dense row-parallel — the reference's
    albert mapping, parallel_mapping.py:33-43). Post-LN residual."""
    b, s, _ = x.shape
    hd = config.head_dim
    tp = jax.lax.axis_size(tp_axis) if tp_axis else 1
    if config.n_head % tp:
        raise ValueError(f"n_head={config.n_head} not divisible by tp={tp}")
    nh = config.n_head // tp

    def heads(p):
        return column_parallel_linear(p, x, tp_axis).reshape(b, s, nh, hd)

    q, k, v = heads(blk["q"]), heads(blk["k"]), heads(blk["v"])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores * (1.0 / math.sqrt(hd)) + key_bias
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                     preferred_element_type=jnp.float32)
    ctx = ctx.astype(x.dtype).reshape(b, s, nh * hd)
    proj = row_parallel_linear(blk["dense"], ctx, tp_axis)
    return layer_norm(blk["ln"], x + proj, config.layer_norm_eps)


def _layer(
    layer: dict,
    x: jax.Array,
    key_bias: jax.Array,
    config: AlbertConfig,
    tp_axis: Optional[str],
) -> jax.Array:
    """One ALBERT layer (HF AlbertLayer): post-LN attention, then
    post-LN FFN (ffn column-parallel, ffn_output row-parallel)."""
    a = _attention(layer["attn"], x, key_bias, config, tp_axis)
    hcol = column_parallel_linear(layer["ffn"]["up"], a, tp_axis)
    down = row_parallel_linear(layer["ffn"]["down"], gelu_new(hcol), tp_axis)
    return layer_norm(layer["ffn"]["ln"], a + down, config.layer_norm_eps)


def embed_tokens(
    params: dict,
    input_ids: jax.Array,
    config: AlbertConfig,
    tp_axis: Optional[str],
    token_type_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """word (vocab-sharded) + position + token-type embeddings -> LN ->
    the E->H projection. Returns (B, S, H)."""
    b, s = input_ids.shape
    x = vocab_parallel_embedding(params["embed"]["word"], input_ids, tp_axis)
    x = x + params["embed"]["pos"][None, :s]
    tt = (
        token_type_ids
        if token_type_ids is not None
        else jnp.zeros((b, s), jnp.int32)
    )
    x = x + jnp.take(params["embed"]["type"], tt, axis=0)
    x = layer_norm(params["embed"]["ln"], x.astype(config.dtype),
                   config.layer_norm_eps)
    h = jnp.einsum("bse,eh->bsh", x, params["map_in"]["kernel"],
                   preferred_element_type=jnp.float32).astype(config.dtype)
    return h + params["map_in"]["bias"]


def forward_hidden(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    config: AlbertConfig,
    tp_axis: Optional[str] = None,
    token_type_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Embeddings -> n_layer applications of the SHARED layer. The scan
    carries only the activations; the one layer's params are closed
    over — the compiled program contains the layer body once, and the
    weights stream from HBM once per application."""
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), dtype=jnp.int32)
    key_bias = (
        (1.0 - attention_mask[:, None, None, :].astype(jnp.float32)) * NEG_INF
    )
    x = embed_tokens(params, input_ids, config, tp_axis, token_type_ids)

    def body(h, _):
        return _layer(params["layer"], h, key_bias, config, tp_axis), None

    step = jax.checkpoint(body) if config.remat else body
    x, _ = jax.lax.scan(step, x, None, length=config.n_layer)
    return x


def logits_fn(
    params: dict,
    hidden: jax.Array,
    tp_axis: Optional[str],
    eps: float = 1e-12,
) -> jax.Array:
    """MLM head: dense H->E + gelu + LN, then the decoder TIED to the
    word embedding (transposed lookup) + vocab bias. Logits come out
    vocab-SHARDED under TP (feed vocab_parallel_cross_entropy)."""
    e = jnp.einsum("bsh,he->bse", hidden, params["mlm"]["dense"]["kernel"],
                   preferred_element_type=jnp.float32)
    e = gelu_new(e + params["mlm"]["dense"]["bias"].astype(jnp.float32))
    e = layer_norm(params["mlm"]["ln"], e.astype(hidden.dtype), eps)
    if tp_axis:
        # f-operator: identity forward, all-reduce backward — each rank's
        # cotangent of ``e`` is only the partial sum over its local vocab
        # shard (same load-bearing collective as bloom.logits_fn)
        from pipegoose_tpu.distributed.functional import copy_to_tensor_group

        e = copy_to_tensor_group(e, tp_axis)
    logits = jnp.einsum("bse,ve->bsv", e, params["embed"]["word"]["weight"],
                        preferred_element_type=jnp.float32)
    # the vocab bias shards with the tied embedding's vocab rows (the
    # mapping marks it Vocab), so under shard_map it arrives as the
    # matching local slice already
    return logits + params["mlm"]["bias"].astype(jnp.float32)


def forward(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    config: AlbertConfig,
    tp_axis: Optional[str] = None,
    token_type_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """(B, S) ids -> (B, S, V[/tp]) MLM logits."""
    hidden = forward_hidden(
        params, input_ids, attention_mask, config, tp_axis, token_type_ids
    )
    return logits_fn(params, hidden, tp_axis, eps=config.layer_norm_eps)


def loss_fn(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    labels: jax.Array,  # (B, S) target ids; positions with label_mask 0 ignored
    config: AlbertConfig,
    tp_axis: Optional[str] = None,
    label_mask: Optional[jax.Array] = None,  # (B, S) 1 = scored position
) -> jax.Array:
    """Masked-LM cross entropy (NO shift — encoder objective): mean CE
    over the scored positions. ``label_mask`` is the analog of HF's
    ``labels != -100``; default scores every valid (attention-masked)
    position."""
    logits = forward(params, input_ids, attention_mask, config, tp_axis)
    per_tok = vocab_parallel_cross_entropy(
        logits, labels, tp_axis, valid_size=config.valid_vocab_size
    )
    if label_mask is None:
        label_mask = (
            attention_mask
            if attention_mask is not None
            else jnp.ones_like(labels)
        )
    w = label_mask.astype(per_tok.dtype)
    return (per_tok * w).sum() / jnp.maximum(w.sum(), 1)


# -- TP policy -------------------------------------------------------------

def tp_mapping(axis: str = "tensor") -> ParallelMapping:
    """The reference's albert TP mapping, as policy rules
    (parallel_mapping.py:33-52): q/k/v and ffn Column, attention dense
    and ffn_output Row, word embedding (and its tied decoder) Vocab."""
    return ParallelMapping(
        [
            ("layer/attn/q", Column(axis)),
            ("layer/attn/k", Column(axis)),
            ("layer/attn/v", Column(axis)),
            ("layer/attn/dense", Row(axis)),
            ("layer/ffn/up", Column(axis)),
            ("layer/ffn/down", Row(axis)),
            ("embed/word", Vocab(axis)),
            ("mlm/bias", Vocab(axis)),
        ]
    )


def tp_specs(params: dict, axis: str = "tensor") -> dict:
    """PartitionSpec pytree (no stacked layer dim — params are shared)."""
    from pipegoose_tpu.nn.parallel import spec_tree

    mapping = tp_mapping(axis)
    return spec_tree(params, lambda path, x: mapping.spec_for(path, x.ndim))
