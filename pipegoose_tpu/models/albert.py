"""ALBERT, TPU-native — the encoder (bidirectional) model family.

The reference's TP mapping covers albert alongside bloom
(pipegoose/nn/tensor_parallel/parallel_mapping.py:33-52: query/key/value
and ffn column-parallel, attention.dense and ffn_output row-parallel)
and its DataParallel tests run on an encoder (bert-tiny,
tests/nn/data_parallel/test_data_parallel.py:18) — so an encoder family
with TP + DP coverage is part of the reference's demonstrated surface.
Implemented from scratch in JAX with the same layer functions as the
causal families:

- BIDIRECTIONAL attention: no causal mask — only the key-padding bias
  (every query attends all valid positions);
- factorized embedding (vocab x E, then a dense E->H projection) and
  CROSS-LAYER PARAMETER SHARING: one layer's params applied n_layer
  times — expressed as ``lax.scan`` over a length-n_layer trip with the
  SAME params in the carry closure (no stacked per-layer dim at all);
- post-LN residuals (LayerNorm AFTER the residual add, BERT lineage),
  vs the causal families' pre-LN;
- MLM head: dense H->E + gelu + LN, then the decoder TIED to the word
  embedding (vocab-sharded logits + vocab-parallel CE under TP).

Semantics match HF ``modeling_albert`` (gelu-tanh ``gelu_new``,
separate q/k/v projections, additive key mask, absolute position +
token-type embeddings) so HF checkpoints load exactly; parity is tested
against the torch implementation in tests/models/test_albert.py.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp

from pipegoose_tpu.nn.parallel_mapping import (
    Column,
    ParallelMapping,
    Row,
    Vocab,
)
from pipegoose_tpu.nn.tensor_parallel.layers import (
    column_parallel_linear,
    layer_norm,
    row_parallel_linear,
    vocab_parallel_cross_entropy,
    vocab_parallel_embedding,
)

NEG_INF = -1e9


@dataclasses.dataclass(frozen=True)
class AlbertConfig:
    vocab_size: int = 30000
    embedding_size: int = 128
    hidden_size: int = 768
    n_layer: int = 12
    n_head: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    dtype: Any = jnp.float32
    remat: bool = False
    # fused Pallas attention (ops/flash_attention.py, causal=False):
    # bidirectional flash — no (S, S) score materialization
    use_flash: bool = False
    # true vocab size when the embedding was padded for TP divisibility
    valid_vocab_size: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.n_head

    @classmethod
    def albert_base(cls, **kw) -> "AlbertConfig":
        return cls(**kw)  # the defaults ARE albert-base-v2


def gelu_new(x: jax.Array) -> jax.Array:
    """HF ``gelu_new`` (full-precision tanh approximation — ALBERT's
    activation; bloom uses a truncated-constant variant)."""
    return 0.5 * x * (
        1.0 + jnp.tanh(math.sqrt(2.0 / math.pi) * (x + 0.044715 * x**3))
    )


# -- init ------------------------------------------------------------------

def init_params(config: AlbertConfig, key: jax.Array) -> dict:
    """Random init matching HF's scheme. NOTE the layout: ``layer`` holds
    ONE layer's params (cross-layer sharing) — no stacked n_layer dim."""
    c = config
    k = iter(jax.random.split(key, 16))

    def dense(kk, din, dout):
        return {
            "kernel": (jax.random.normal(kk, (din, dout)) * c.initializer_range
                       ).astype(c.dtype),
            "bias": jnp.zeros((dout,), c.dtype),
        }

    def ln(d):
        return {"scale": jnp.ones((d,), c.dtype), "bias": jnp.zeros((d,), c.dtype)}

    emb = lambda kk, n, d: (jax.random.normal(kk, (n, d)) * c.initializer_range
                            ).astype(c.dtype)
    h, e, i = c.hidden_size, c.embedding_size, c.intermediate_size
    return {
        "embed": {
            "word": {"weight": emb(next(k), c.vocab_size, e)},
            "pos": emb(next(k), c.max_position_embeddings, e),
            "type": emb(next(k), c.type_vocab_size, e),
            "ln": ln(e),
        },
        "map_in": dense(next(k), e, h),
        "layer": {
            "attn": {
                "q": dense(next(k), h, h),
                "k": dense(next(k), h, h),
                "v": dense(next(k), h, h),
                "dense": dense(next(k), h, h),
                "ln": ln(h),
            },
            "ffn": {
                "up": dense(next(k), h, i),
                "down": dense(next(k), i, h),
                "ln": ln(h),
            },
        },
        "mlm": {
            "dense": dense(next(k), h, e),
            "ln": ln(e),
            "bias": jnp.zeros((c.vocab_size,), c.dtype),
        },
    }


# -- forward ---------------------------------------------------------------

def _attention(
    blk: dict,
    x: jax.Array,  # (B, S, H)
    key_bias: jax.Array,  # (B, 1, 1, S) additive key-padding bias
    config: AlbertConfig,
    tp_axis: Optional[str],
) -> jax.Array:
    """Bidirectional self-attention, heads sharded over ``tp_axis``
    (q/k/v column-parallel, output dense row-parallel — the reference's
    albert mapping, parallel_mapping.py:33-43). Post-LN residual."""
    b, s, _ = x.shape
    hd = config.head_dim
    tp = jax.lax.axis_size(tp_axis) if tp_axis else 1
    if config.n_head % tp:
        raise ValueError(f"n_head={config.n_head} not divisible by tp={tp}")
    nh = config.n_head // tp

    def heads(p):
        return column_parallel_linear(p, x, tp_axis).reshape(b, s, nh, hd)

    q, k, v = heads(blk["q"]), heads(blk["k"]), heads(blk["v"])
    if config.use_flash:
        # the flash kernel's kv_neg input IS the key-padding bias
        # ((B, S) 0 / NEG_INF — key_bias squeezed); causal=False makes
        # it bidirectional, no ALiBi slopes
        from pipegoose_tpu.ops.flash_attention import flash_attention

        ctx = flash_attention(
            q, k, v, causal=False, kv_neg=key_bias[:, 0, 0, :]
        )
    else:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores * (1.0 / math.sqrt(hd)) + key_bias
        probs = jax.nn.softmax(
            scores.astype(jnp.float32), axis=-1
        ).astype(x.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                         preferred_element_type=jnp.float32)
    ctx = ctx.astype(x.dtype).reshape(b, s, nh * hd)
    proj = row_parallel_linear(blk["dense"], ctx, tp_axis)
    return layer_norm(blk["ln"], x + proj, config.layer_norm_eps)


def _layer(
    layer: dict,
    x: jax.Array,
    key_bias: jax.Array,
    config: AlbertConfig,
    tp_axis: Optional[str],
) -> jax.Array:
    """One ALBERT layer (HF AlbertLayer): post-LN attention, then
    post-LN FFN (ffn column-parallel, ffn_output row-parallel)."""
    a = _attention(layer["attn"], x, key_bias, config, tp_axis)
    hcol = column_parallel_linear(layer["ffn"]["up"], a, tp_axis)
    down = row_parallel_linear(layer["ffn"]["down"], gelu_new(hcol), tp_axis)
    return layer_norm(layer["ffn"]["ln"], a + down, config.layer_norm_eps)


def embed_tokens(
    params: dict,
    input_ids: jax.Array,
    config: AlbertConfig,
    tp_axis: Optional[str],
    token_type_ids: Optional[jax.Array] = None,
    pos_offset: Optional[jax.Array] = None,
) -> jax.Array:
    """word (vocab-sharded) + position + token-type embeddings -> LN ->
    the E->H projection. Returns (B, S, H). ``pos_offset`` (traced
    scalar) shifts the absolute-position window — sequence sharding
    passes ``rank * s_local`` so each chunk reads its GLOBAL positions."""
    b, s = input_ids.shape
    x = vocab_parallel_embedding(params["embed"]["word"], input_ids, tp_axis)
    pos = (
        params["embed"]["pos"][:s]
        if pos_offset is None
        else jax.lax.dynamic_slice_in_dim(params["embed"]["pos"], pos_offset, s)
    )
    x = x + pos[None]
    tt = (
        token_type_ids
        if token_type_ids is not None
        else jnp.zeros((b, s), jnp.int32)
    )
    x = x + jnp.take(params["embed"]["type"], tt, axis=0)
    x = layer_norm(params["embed"]["ln"], x.astype(config.dtype),
                   config.layer_norm_eps)
    h = jnp.einsum("bse,eh->bsh", x, params["map_in"]["kernel"],
                   preferred_element_type=jnp.float32).astype(config.dtype)
    return h + params["map_in"]["bias"]


def forward_hidden(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    config: AlbertConfig,
    tp_axis: Optional[str] = None,
    token_type_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Embeddings -> n_layer applications of the SHARED layer. The scan
    carries only the activations; the one layer's params are closed
    over — the compiled program contains the layer body once, and the
    weights stream from HBM once per application."""
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), dtype=jnp.int32)
    key_bias = (
        (1.0 - attention_mask[:, None, None, :].astype(jnp.float32)) * NEG_INF
    )
    x = embed_tokens(params, input_ids, config, tp_axis, token_type_ids)

    def body(h, _):
        return _layer(params["layer"], h, key_bias, config, tp_axis), None

    step = jax.checkpoint(body) if config.remat else body
    x, _ = jax.lax.scan(step, x, None, length=config.n_layer)
    return x


def logits_fn(
    params: dict,
    hidden: jax.Array,
    tp_axis: Optional[str],
    eps: float = 1e-12,
) -> jax.Array:
    """MLM head: dense H->E + gelu + LN, then the decoder TIED to the
    word embedding (transposed lookup) + vocab bias. Logits come out
    vocab-SHARDED under TP (feed vocab_parallel_cross_entropy)."""
    e = jnp.einsum("bsh,he->bse", hidden, params["mlm"]["dense"]["kernel"],
                   preferred_element_type=jnp.float32)
    e = gelu_new(e + params["mlm"]["dense"]["bias"].astype(jnp.float32))
    e = layer_norm(params["mlm"]["ln"], e.astype(hidden.dtype), eps)
    if tp_axis:
        # f-operator: identity forward, all-reduce backward — each rank's
        # cotangent of ``e`` is only the partial sum over its local vocab
        # shard (same load-bearing collective as bloom.logits_fn)
        from pipegoose_tpu.distributed.functional import copy_to_tensor_group

        e = copy_to_tensor_group(e, tp_axis)
    logits = jnp.einsum("bse,ve->bsv", e, params["embed"]["word"]["weight"],
                        preferred_element_type=jnp.float32)
    # the vocab bias shards with the tied embedding's vocab rows (the
    # mapping marks it Vocab), so under shard_map it arrives as the
    # matching local slice already
    return logits + params["mlm"]["bias"].astype(jnp.float32)


def forward(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    config: AlbertConfig,
    tp_axis: Optional[str] = None,
    token_type_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """(B, S) ids -> (B, S, V[/tp]) MLM logits."""
    hidden = forward_hidden(
        params, input_ids, attention_mask, config, tp_axis, token_type_ids
    )
    return logits_fn(params, hidden, tp_axis, eps=config.layer_norm_eps)


def loss_fn(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    labels: jax.Array,  # (B, S) target ids; positions with label_mask 0 ignored
    config: AlbertConfig,
    tp_axis: Optional[str] = None,
    label_mask: Optional[jax.Array] = None,  # (B, S) 1 = scored position
) -> jax.Array:
    """Masked-LM cross entropy (NO shift — encoder objective): mean CE
    over the scored positions. ``label_mask`` is the analog of HF's
    ``labels != -100``; default scores every valid (attention-masked)
    position."""
    logits = forward(params, input_ids, attention_mask, config, tp_axis)
    per_tok = vocab_parallel_cross_entropy(
        logits, labels, tp_axis, valid_size=config.valid_vocab_size
    )
    if label_mask is None:
        label_mask = (
            attention_mask
            if attention_mask is not None
            else jnp.ones_like(labels)
        )
    w = label_mask.astype(per_tok.dtype)
    return (per_tok * w).sum() / jnp.maximum(w.sum(), 1)


# -- TP policy -------------------------------------------------------------

def tp_mapping(axis: str = "tensor") -> ParallelMapping:
    """The reference's albert TP mapping, as policy rules
    (parallel_mapping.py:33-52): q/k/v and ffn Column, attention dense
    and ffn_output Row, word embedding (and its tied decoder) Vocab."""
    return ParallelMapping(
        [
            ("layer/attn/q", Column(axis)),
            ("layer/attn/k", Column(axis)),
            ("layer/attn/v", Column(axis)),
            ("layer/attn/dense", Row(axis)),
            ("layer/ffn/up", Column(axis)),
            ("layer/ffn/down", Row(axis)),
            ("embed/word", Vocab(axis)),
            ("mlm/bias", Vocab(axis)),
        ]
    )


def tp_specs(params: dict, axis: str = "tensor") -> dict:
    """PartitionSpec pytree (no stacked layer dim — params are shared)."""
    from pipegoose_tpu.nn.parallel import spec_tree

    mapping = tp_mapping(axis)
    return spec_tree(params, lambda path, x: mapping.spec_for(path, x.ndim))


# -- pipeline parallel ------------------------------------------------------

def _resolve_stage_counts(config, pipe_axis, stage_layer_counts):
    """(traced this-stage count, static max count) — shared validation
    via stage_n_valid (len/sum check included)."""
    from pipegoose_tpu.nn.pipeline_parallel.partitioner import stage_n_valid

    n_stages = jax.lax.axis_size(pipe_axis)
    counts = (
        tuple(int(c) for c in stage_layer_counts)
        if stage_layer_counts is not None
        else uniform_stage_counts(config.n_layer, n_stages)
    )
    return stage_n_valid(counts, config.n_layer, pipe_axis), max(counts)


def _repeat_stage_fn(n_valid, max_count: int, config, tp_axis,
                     layer_apply=None):
    """Stage body for the SHARED-layer pipeline: apply the (replicated)
    layer params ``n_valid`` times out of ``max_count`` slots — the
    lax.cond genuinely SKIPS pad applications at run time (uneven
    stages), the same mechanism as masked_stage_scan. Shared by the
    GPipe, 1F1B, and PP x SP runtimes; ``layer_apply(layer, h, side)``
    overrides the dense layer body (the SP composition passes the
    sequence-sharded one)."""
    if layer_apply is None:
        def layer_apply(layer, a, side):
            key_bias = side["bias"] if isinstance(side, dict) else side
            return _layer(layer, a, key_bias, config, tp_axis)

    def stage_fn(layer, h, side):
        def body(hh, t):
            out = jax.lax.cond(
                t < n_valid,
                lambda a: layer_apply(layer, a, side),
                lambda a: a,
                hh,
            )
            return out, None

        h, _ = jax.lax.scan(body, h, jnp.arange(max_count))
        return h

    return stage_fn


def _mlm_head_sums(params, h, labels_mb, lmask_mb, config, tp_axis):
    """(weighted CE sum, weight sum) of one microbatch's MLM head —
    shared by the GPipe and PP x SP pipeline losses."""
    logits = logits_fn(params, h, tp_axis, eps=config.layer_norm_eps)
    per_tok = vocab_parallel_cross_entropy(
        logits, labels_mb, tp_axis, valid_size=config.valid_vocab_size
    )
    w = lmask_mb.astype(per_tok.dtype)
    return (per_tok * w).sum(), w.sum()


def uniform_stage_counts(n_layer: int, n_stages: int) -> tuple:
    """Per-stage application counts for the SHARED layer. All albert
    layer applications cost the same (identical params), so the
    interval-DP partitioner's optimum IS the even split — remainder to
    the earliest stages (they also run the cheap embed)."""
    base, rem = divmod(n_layer, n_stages)
    return tuple(base + (1 if i < rem else 0) for i in range(n_stages))


def loss_fn_pp(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: AlbertConfig,
    n_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    stage_layer_counts=None,
    label_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Pipeline-parallel MLM loss for the SHARED-layer encoder.

    Cross-layer parameter sharing inverts the usual PP layout: there is
    no stacked layer stack to shard over the pipe axis — every stage
    holds the SAME layer params (replicated) and applies them
    ``counts[stage]`` times, so the pipeline ships only activations and
    the per-stage "partition" is just a repetition count. Runs on the
    same compiled GPipe runtime as the causal families
    (nn/pipeline_parallel/pipeline.py:gpipe); uneven ``stage_layer_counts``
    use the same lax.cond skip as masked_stage_scan.

    Gradient sync: layer/head/embed params are pipe-replicated but each
    stage produces only its own applications' grads — complete them with
    ``grad_sync_axes=(("pipe", "sum"),)`` exactly as for bloom PP.
    """
    from pipegoose_tpu.nn.pipeline_parallel import microbatch as mb
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import (
        gpipe,
        last_stage_value,
    )

    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), dtype=jnp.int32)
    if label_mask is None:
        label_mask = attention_mask

    n_valid, max_count = _resolve_stage_counts(
        config, pipe_axis, stage_layer_counts
    )

    mbs = mb.split(
        {"ids": input_ids, "mask": attention_mask, "labels": labels,
         "lmask": label_mask},
        n_microbatches,
    )
    h0 = jax.vmap(
        lambda ids: embed_tokens(params, ids, config, tp_axis)
    )(mbs["ids"])
    key_bias = jax.vmap(
        lambda m: (1.0 - m[:, None, None, :].astype(jnp.float32)) * NEG_INF
    )(mbs["mask"])

    stage_fn = _repeat_stage_fn(n_valid, max_count, config, tp_axis)

    outs = gpipe(
        stage_fn,
        params["layer"],
        h0,
        side_inputs=key_bias,
        axis_name=pipe_axis,
        remat=config.remat,
    )  # (M, b/M, S, H), valid on the last stage

    tot, cnt = jax.vmap(
        lambda h, l, m: _mlm_head_sums(params, h, l, m, config, tp_axis)
    )(outs, mbs["labels"], mbs["lmask"])
    loss_local = tot.sum() / jnp.maximum(cnt.sum(), 1)
    return last_stage_value(loss_local, pipe_axis)


def loss_fn_1f1b(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: AlbertConfig,
    n_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    stage_layer_counts=None,
    label_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """1F1B (PipeDream-flush) MLM loss for the SHARED-layer encoder:
    same value and gradients as :func:`loss_fn_pp`, peak activation
    memory bounded by the STAGE count (nn/pipeline_parallel/pipeline.py
    one_f_one_b). The stage body is the same repeat-scan as GPipe's;
    the tied decoder's embedding grads merge from BOTH the embed vjp
    (stage-0 side) and the head (last-stage side), completed — like
    every replicated param here — by grad_sync_axes=(("pipe", "sum"),).
    """
    from pipegoose_tpu.nn.pipeline_parallel import microbatch as mb
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import (
        manual_grads_loss,
        one_f_one_b,
    )

    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), dtype=jnp.int32)
    if label_mask is None:
        label_mask = attention_mask

    n_valid, max_count = _resolve_stage_counts(
        config, pipe_axis, stage_layer_counts
    )
    stage_fn = _repeat_stage_fn(n_valid, max_count, config, tp_axis)

    mbs = mb.split(
        {"ids": input_ids, "mask": attention_mask, "labels": labels,
         "lmask": label_mask},
        n_microbatches,
    )
    key_bias = jax.vmap(
        lambda m: (1.0 - m[:, None, None, :].astype(jnp.float32)) * NEG_INF
    )(mbs["mask"])
    side = {"bias": key_bias, "labels": mbs["labels"], "lmask": mbs["lmask"]}

    # per-microbatch head losses pre-normalized by the GLOBAL scored
    # count so their plain sum equals loss_fn_pp's tot/cnt
    inv_count = 1.0 / jnp.maximum(label_mask.sum().astype(jnp.float32), 1)

    def head_fn(hp, h, side_mb):
        logits = logits_fn(hp, h, tp_axis, eps=config.layer_norm_eps)
        per_tok = vocab_parallel_cross_entropy(
            logits, side_mb["labels"], tp_axis,
            valid_size=config.valid_vocab_size,
        )
        w = side_mb["lmask"].astype(per_tok.dtype)
        return ((per_tok * w).sum() * inv_count).astype(jnp.float32)

    def run(params):
        embed_params = {"embed": params["embed"], "map_in": params["map_in"]}
        h0, embed_vjp = jax.vjp(
            lambda ep: jax.vmap(
                lambda ids: embed_tokens(ep, ids, config, tp_axis)
            )(mbs["ids"]),
            embed_params,
        )
        head_params = {
            "mlm": params["mlm"],
            "embed": {"word": params["embed"]["word"]},
        }
        loss_local, dh0, d_layer, d_head = one_f_one_b(
            stage_fn, params["layer"], head_fn, head_params, h0, side,
            pipe_axis,
        )
        (d_embed,) = embed_vjp(dh0)
        n_stages = jax.lax.axis_size(pipe_axis)
        is_last = jax.lax.axis_index(pipe_axis) == n_stages - 1
        loss = jax.lax.psum(jnp.where(is_last, loss_local, 0.0), pipe_axis)
        emb = dict(d_embed["embed"])
        emb["word"] = {
            "weight": d_embed["embed"]["word"]["weight"]
            + d_head["embed"]["word"]["weight"]
        }
        grads = {
            "embed": emb,
            "map_in": d_embed["map_in"],
            "layer": d_layer,
            "mlm": d_head["mlm"],
        }
        return loss, grads

    return manual_grads_loss(run, params)


def pp_specs(params: dict, tp_axis: str = "tensor", pipe_axis: str = "pipe") -> dict:
    """PartitionSpecs for albert under TP x PP: identical to
    :func:`tp_specs` — the shared layer has no stacked dim to shard
    over ``pipe``, so every param is pipe-REPLICATED and the pipeline
    distributes only repetition counts (see :func:`loss_fn_pp`)."""
    del pipe_axis  # nothing shards over it — documented above
    return tp_specs(params, tp_axis)


# -- sequence parallel ------------------------------------------------------

def _attention_sp(
    blk: dict,
    x: jax.Array,  # (B, S_local, H)
    config: AlbertConfig,
    tp_axis: Optional[str],
    sp_axis: str,
    pad_mask_local: jax.Array,  # (B, S_local)
    variant: str = "ring",
) -> jax.Array:
    """Bidirectional attention with the sequence sharded over
    ``sp_axis``. ``variant="ring"``: K/V (and the padding mask) rotate
    around the ring; the block bias is padding-only
    (make_bidirectional_bias_fn — encoders carry position additively in
    the embeddings, so no causal mask and no position term in the
    bias). ``variant="ulysses"``: all_to_all head/sequence exchange,
    full-sequence attention on nh/sp local heads — with
    ``config.use_flash`` the fused kernel (causal=False) runs inside.
    Heads shard over ``tp_axis`` exactly as in the dense path."""
    b, s_local, _ = x.shape
    hd = config.head_dim
    tp = jax.lax.axis_size(tp_axis) if tp_axis else 1
    nh = config.n_head // tp

    def heads(p):
        return column_parallel_linear(p, x, tp_axis).reshape(b, s_local, nh, hd)

    q, k, v = heads(blk["q"]), heads(blk["k"]), heads(blk["v"])
    if variant == "ulysses":
        from pipegoose_tpu.nn.sequence_parallel.ulysses import (
            ulysses_bidirectional_attention,
        )

        ctx = ulysses_bidirectional_attention(
            q, k, v, sp_axis, pad_mask_local, use_flash=config.use_flash
        )
    elif variant == "ring":
        from pipegoose_tpu.nn.sequence_parallel.ring_attention import (
            make_bidirectional_bias_fn,
            ring_attention,
        )

        ctx = ring_attention(
            q, k, v, sp_axis, make_bidirectional_bias_fn(),
            kv_side=pad_mask_local,
        )
    else:
        raise ValueError(f"unknown SP variant {variant!r} (ring, ulysses)")
    ctx = ctx.astype(x.dtype).reshape(b, s_local, nh * hd)
    proj = row_parallel_linear(blk["dense"], ctx, tp_axis)
    return layer_norm(blk["ln"], x + proj, config.layer_norm_eps)


def _layer_sp(layer, h, config, tp_axis, sp_axis, pad_mask_local,
              variant: str = "ring"):
    """One ALBERT layer on sequence-sharded activations (shared by the
    plain SP loss and the PP x SP composition)."""
    a = _attention_sp(
        layer["attn"], h, config, tp_axis, sp_axis, pad_mask_local, variant
    )
    hcol = column_parallel_linear(layer["ffn"]["up"], a, tp_axis)
    down = row_parallel_linear(layer["ffn"]["down"], gelu_new(hcol), tp_axis)
    return layer_norm(layer["ffn"]["ln"], a + down, config.layer_norm_eps)


def loss_fn_sp(
    params: dict,
    input_ids: jax.Array,  # (B, S_local) — sequence sharded over sp_axis
    attention_mask: Optional[jax.Array],
    labels: jax.Array,  # (B, S_local) local label chunk
    config: AlbertConfig,
    tp_axis: Optional[str] = None,
    sp_axis: str = "seq",
    label_mask: Optional[jax.Array] = None,
    variant: str = "ring",
) -> jax.Array:
    """Sequence-parallel MLM loss: activations live sequence-sharded
    end to end; attention is the bidirectional ring (or Ulysses
    all_to_all with ``variant="ulysses"`` — see _attention_sp; flash
    inside when config.use_flash). Unlike the causal
    families no target shift crosses chunk boundaries (the MLM label
    sits AT its position), so the head is purely local + one psum of
    the (sum, count) pair. Position embeddings read the GLOBAL window
    via ``pos_offset`` (global S must fit max_position_embeddings).

    Grads of seq-replicated params are partial per rank — sum them over
    ``sp_axis`` (grad_sync_axes=(("seq", "sum"),))."""
    from pipegoose_tpu.distributed.functional import reduce_from_tensor_group

    b, s_local = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s_local), dtype=jnp.int32)
    if label_mask is None:
        label_mask = attention_mask

    sp = jax.lax.axis_size(sp_axis)
    if sp * s_local > config.max_position_embeddings:
        # the dense path fails loudly on this (broadcast mismatch); the
        # dynamic position slice would CLAMP silently — wrong absolute
        # positions with no error — so refuse at trace time instead
        raise ValueError(
            f"global sequence {sp}x{s_local}={sp * s_local} exceeds "
            f"max_position_embeddings={config.max_position_embeddings}"
        )
    rank = jax.lax.axis_index(sp_axis)
    x = embed_tokens(
        params, input_ids, config, tp_axis, pos_offset=rank * s_local
    )

    def body(h, _):
        return _layer_sp(
            params["layer"], h, config, tp_axis, sp_axis, attention_mask,
            variant,
        ), None

    step = jax.checkpoint(body) if config.remat else body
    x, _ = jax.lax.scan(step, x, None, length=config.n_layer)

    logits = logits_fn(params, x, tp_axis, eps=config.layer_norm_eps)
    per_tok = vocab_parallel_cross_entropy(
        logits, labels, tp_axis, valid_size=config.valid_vocab_size
    )
    w = label_mask.astype(per_tok.dtype)
    count = jax.lax.psum(w.sum(), sp_axis)
    # identity-backward combine: each rank's grads stay local and are
    # summed over sp by the train step
    return reduce_from_tensor_group(
        (per_tok * w).sum() / jnp.maximum(count, 1), sp_axis
    )


def loss_fn_pp_sp(
    params: dict,
    input_ids: jax.Array,  # (B, S_local) — sequence sharded over sp_axis
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: AlbertConfig,
    n_microbatches: int,
    tp_axis: Optional[str] = None,
    pipe_axis: str = "pipe",
    sp_axis: str = "seq",
    stage_layer_counts=None,
    label_mask: Optional[jax.Array] = None,
    variant: str = "ring",
) -> jax.Array:
    """Pipeline x sequence parallel for the SHARED-layer encoder:
    sequence-sharded activations flow through the compiled GPipe
    schedule while each stage repeats the one replicated layer with the
    bidirectional ring (or Ulysses) inside — long documents AND deep
    stacks, like bloom.loss_fn_pp_sp but with no target shift and the
    repetition-count stages of :func:`loss_fn_pp`.

    Gradient sync: ``grad_sync_axes=(("pipe", "sum"), ("seq", "sum"))``.
    """
    from pipegoose_tpu.distributed.functional import reduce_from_tensor_group
    from pipegoose_tpu.nn.pipeline_parallel import microbatch as mb
    from pipegoose_tpu.nn.pipeline_parallel.pipeline import (
        gpipe,
        last_stage_value,
    )

    b, s_local = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s_local), dtype=jnp.int32)
    if label_mask is None:
        label_mask = attention_mask

    sp = jax.lax.axis_size(sp_axis)
    if sp * s_local > config.max_position_embeddings:
        raise ValueError(
            f"global sequence {sp}x{s_local}={sp * s_local} exceeds "
            f"max_position_embeddings={config.max_position_embeddings}"
        )
    n_valid, max_count = _resolve_stage_counts(
        config, pipe_axis, stage_layer_counts
    )

    mbs = mb.split(
        {"ids": input_ids, "mask": attention_mask, "labels": labels,
         "lmask": label_mask},
        n_microbatches,
    )
    rank = jax.lax.axis_index(sp_axis)
    h0 = jax.vmap(
        lambda ids: embed_tokens(
            params, ids, config, tp_axis, pos_offset=rank * s_local
        )
    )(mbs["ids"])
    side = {"mask": mbs["mask"]}

    stage_fn = _repeat_stage_fn(
        n_valid, max_count, config, tp_axis,
        layer_apply=lambda layer, a, side_mb: _layer_sp(
            layer, a, config, tp_axis, sp_axis, side_mb["mask"], variant
        ),
    )

    outs = gpipe(
        stage_fn, params["layer"], h0, side_inputs=side,
        axis_name=pipe_axis, remat=config.remat,
    )

    tot, cnt = jax.vmap(
        lambda h, l, m: _mlm_head_sums(params, h, l, m, config, tp_axis)
    )(outs, mbs["labels"], mbs["lmask"])
    count = jax.lax.psum(cnt.sum(), sp_axis)
    loss_local = reduce_from_tensor_group(
        tot.sum() / jnp.maximum(count, 1), sp_axis
    )
    return last_stage_value(loss_local, pipe_axis)


# -- MLM-fill inference -----------------------------------------------------

def fill_mask(
    params: dict,
    input_ids: jax.Array,  # (B, S) with mask_token_id at slots to fill
    mask_token_id: int,
    config: AlbertConfig,
    attention_mask: Optional[jax.Array] = None,
    token_type_ids: Optional[jax.Array] = None,
    tp_axis: Optional[str] = None,
) -> jax.Array:
    """The encoder's inference path (HF fill-mask pipeline analog):
    one bidirectional forward, argmax the MLM logits at every
    ``mask_token_id`` slot, leave everything else untouched. Jittable;
    under TP the argmax runs over the vocab-SHARDED logits (local
    argmax + max, then a global winner pick over the gathered pairs —
    the same trick as TP greedy decode, models/_decode.py)."""
    logits = forward(
        params, input_ids, attention_mask, config, tp_axis, token_type_ids
    )
    valid = (
        config.valid_vocab_size
        if config.valid_vocab_size is not None
        else config.vocab_size
    )
    v_local = logits.shape[-1]
    offset = (
        jax.lax.axis_index(tp_axis) * v_local if tp_axis else jnp.asarray(0)
    )
    # mask padded vocab slots (TP divisibility padding) out of the argmax
    cols = offset + jnp.arange(v_local)
    logits = jnp.where(cols[None, None, :] < valid, logits, NEG_INF)
    if tp_axis:
        local_best = jnp.argmax(logits, -1) + offset  # (B, S) global ids
        local_max = jnp.max(logits, -1)
        maxes = jax.lax.all_gather(local_max, tp_axis)  # (tp, B, S)
        bests = jax.lax.all_gather(local_best, tp_axis)
        winner = jnp.argmax(maxes, axis=0)  # (B, S)
        pred = jnp.take_along_axis(bests, winner[None], axis=0)[0]
    else:
        pred = jnp.argmax(logits, -1)
    return jnp.where(input_ids == mask_token_id, pred, input_ids)
