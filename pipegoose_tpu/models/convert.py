"""Policy-table-driven HF checkpoint ingestion.

The reference parallelizes *any* HF model whose modules appear in its
per-model policy registry (``__MAPPING__`` tables,
reference nn/tensor_parallel/parallel_mapping.py:16-52, consumed by
module surgery in tensor_parallel.py:44-69). The TPU-native equivalent
is declarative: each model family ships a RULES table mapping HF state-
dict names to stacked-pytree paths, and this module executes it — one
generic converter instead of a hand-written function per family.

Rule format (one dict per target leaf):
  path:      pytree path, "/"-separated ("blocks/attn/q/kernel")
  hf:        HF state-dict name; "{l}" = layer index, "{e}" = expert
             index (presence of the placeholders decides stacking)
  transpose: torch Linear stores (out, in); JAX kernels are (in, out)
  optional:  skip silently if the HF tensor is absent (e.g. untied
             lm_head on a tied checkpoint)

``register_family`` + ``from_hf`` give the reference's top-level UX —
hand over any supported HF model, get (config, params, module) back.
"""
from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp
import numpy as np


def _t(x) -> np.ndarray:
    x = x.detach().cpu()
    if str(x.dtype) == "torch.bfloat16":  # torch bf16 has no .numpy()
        x = x.float()
    return np.asarray(x.numpy())


def _set_in(tree: dict, path: list, value) -> None:
    for k in path[:-1]:
        tree = tree.setdefault(k, {})
    tree[path[-1]] = value


def params_from_state_dict(
    sd: dict,
    rules: list,
    n_layer: int,
    n_experts: int = 0,
    dtype=jnp.float32,
    prefix: str = "",
) -> dict:
    """Execute a RULES table against an HF state dict -> stacked pytree."""
    out: dict = {}
    for rule in rules:
        hf = prefix + rule["hf"]
        tr = rule.get("transpose", False)

        def get(name):
            m = _t(sd[name])
            return m.T if tr else m

        try:
            if "{e}" in hf:
                arr = np.stack(
                    [
                        np.stack([get(hf.format(l=l, e=e)) for e in range(n_experts)])
                        for l in range(n_layer)
                    ]
                )
            elif "{l}" in hf:
                arr = np.stack([get(hf.format(l=l)) for l in range(n_layer)])
            else:
                arr = get(hf)
        except KeyError:
            if rule.get("optional"):
                continue
            raise
        _set_in(out, rule["path"].split("/"), jnp.asarray(arr, dtype=dtype))
    return out


def state_dict_from_params(params: dict, rules: list, prefix: str = "") -> dict:
    """Inverse conversion: stacked pytree -> HF-named numpy state dict."""

    def get_in(tree, path):
        for k in path:
            if k not in tree:
                return None
            tree = tree[k]
        return tree

    out = {}
    for rule in rules:
        leaf = get_in(params, rule["path"].split("/"))
        if leaf is None:
            if rule.get("optional"):
                continue
            raise KeyError(rule["path"])
        arr = np.asarray(leaf)
        tr = rule.get("transpose", False)
        hf = prefix + rule["hf"]
        if "{e}" in hf:
            for l in range(arr.shape[0]):
                for e in range(arr.shape[1]):
                    m = arr[l, e]
                    out[hf.format(l=l, e=e)] = m.T if tr else m
        elif "{l}" in hf:
            for l in range(arr.shape[0]):
                m = arr[l]
                out[hf.format(l=l)] = m.T if tr else m
        else:
            out[hf] = arr.T if tr else arr
    return out


# -- family registry ---------------------------------------------------------

_FAMILIES: dict = {}


def register_family(model_type: str, loader: Callable) -> None:
    """loader(hf_model, dtype) -> (config, params, module)."""
    _FAMILIES[model_type] = loader


def from_hf(model: Any, dtype=jnp.float32):
    """Convert any registered HF model: returns (config, params, module)
    where ``module`` is the framework model module (forward/loss_fn/
    specs/generate live there). The reference's equivalent is
    ``TensorParallel(model, ...).parallelize()`` over its mapping
    registry — here conversion is explicit and happens once."""
    # import for registration side effects
    from pipegoose_tpu.models import hf as _hf  # noqa: F401

    mt = getattr(model.config, "model_type", None)
    if mt not in _FAMILIES:
        raise NotImplementedError(
            f"model_type={mt!r} has no registered family "
            f"(supported: {sorted(_FAMILIES)})"
        )
    return _FAMILIES[mt](model, dtype)
