"""BLOOM-MoE: BLOOM with Switch/Mixtral-style MoE MLPs.

The reference's MoE path wraps BLOOM and swaps chosen ``mlp`` modules
for ExpertLayers (expert_parallel.py:53-80, convergence test
tests/convergence/run_ep.py). Here the MoE variant is a first-class
model sharing BLOOM's attention/embedding/LN code: every block's MLP is
a routed expert layer (the Switch-Transformer layout; a Mixtral-style
config is this model with top_k=2), dispatched with static shapes over
the ``expert`` mesh axis and optionally Megatron-sharded over ``tensor``
inside each expert.

Router aux/z losses are returned functionally (summed over layers by the
scan), not via a process singleton (vs expert_context.py:7-32).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from pipegoose_tpu.models import bloom as _bloom
from pipegoose_tpu.models.bloom import (
    BloomConfig,
    attention_bias,
    embed_tokens,
    layer_norm,
    logits_fn,
)
from pipegoose_tpu.nn.expert_parallel.experts import moe_layer
from pipegoose_tpu.nn.expert_parallel.loss import ExpertLoss
from pipegoose_tpu.nn.expert_parallel.routers import TopKRouter
from pipegoose_tpu.nn.tensor_parallel.layers import vocab_parallel_cross_entropy


@dataclasses.dataclass(frozen=True)
class BloomMoEConfig(BloomConfig):
    num_experts: int = 8
    top_k: int = 1
    capacity_factor: float = 1.25
    router_noise_eps: float = 0.1
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 0.001
    ffn_mult: int = 4

    def router(self) -> TopKRouter:
        from pipegoose_tpu.nn.expert_parallel.routers import SwitchNoisePolicy

        noise = SwitchNoisePolicy(self.router_noise_eps) if self.router_noise_eps else None
        return TopKRouter(
            num_experts=self.num_experts,
            top_k=self.top_k,
            capacity_factor=self.capacity_factor,
            noise=noise,
        )


def init_params(config: BloomMoEConfig, key: jax.Array) -> dict:
    """Fresh MoE init: BLOOM trunk + independently-drawn expert stacks +
    router gate. (To *upcycle* an existing dense model into MoE with the
    dense MLP as every expert's template — the reference's semantics —
    use ExpertParallel.from_dense.)"""
    kd, ke, kr = jax.random.split(key, 3)
    params = _bloom.init_params(config, kd)
    h, L, E, F = (
        config.hidden_size,
        config.n_layer,
        config.num_experts,
        config.ffn_mult * config.hidden_size,
    )
    std, dt = config.initializer_range, config.dtype
    k1, k2 = jax.random.split(ke)
    params["blocks"]["moe"] = {
        "up": {
            "kernel": (jax.random.normal(k1, (L, E, h, F)) * std).astype(dt),
            "bias": jnp.zeros((L, E, F), dt),
        },
        "down": {
            "kernel": (jax.random.normal(k2, (L, E, F, h)) * std).astype(dt),
            "bias": jnp.zeros((L, E, h), dt),
        },
    }
    params["blocks"]["router"] = {
        "gate": {"kernel": (jax.random.normal(kr, (L, h, E)) * std).astype(dt)}
    }
    del params["blocks"]["mlp"]
    return params


def _moe_block(
    blk: dict,
    x: jax.Array,
    bias: dict,
    key: Optional[jax.Array],
    config: BloomMoEConfig,
    tp_axis: Optional[str],
    ep_axis: Optional[str],
    train: bool,
):
    eps = config.layer_norm_epsilon
    ln1 = layer_norm(blk["ln_1"], x, eps)
    x = x + _bloom._attention(blk["attn"], ln1, bias, config, tp_axis)
    ln2 = layer_norm(blk["ln_2"], x, eps)

    router = config.router()
    flat = ln2.reshape(-1, ln2.shape[-1])
    routing = router(blk["router"], flat, key=key, train=train)
    y = moe_layer(
        blk["moe"],
        ln2,
        routing,
        axis_name=ep_axis,
        act=_bloom.bloom_gelu,
        tp_axis=tp_axis,
    )
    return x + y, routing.aux_loss, routing.z_loss


def forward_hidden(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    config: BloomMoEConfig,
    tp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
    rng: Optional[jax.Array] = None,
    train: bool = False,
):
    """Returns (hidden (B,S,H), aux_losses (L,), z_losses (L,))."""
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), dtype=jnp.int32)
    x = embed_tokens(params, input_ids, config, tp_axis)
    bias = attention_bias(attention_mask, config)

    if rng is None:
        if train and config.router_noise_eps:
            raise ValueError(
                "train=True with router noise needs an explicit rng (fold in "
                "the step count and data/expert axis indices); a fixed "
                "default key would apply the SAME perturbation every step"
            )
        rng = jax.random.PRNGKey(0)  # inert: noise disabled on this path
    layer_keys = jax.random.split(rng, config.n_layer)

    def scan_fn(carry, blk_and_key):
        blk, key = blk_and_key
        out, aux, z = _moe_block(
            blk, carry, bias, key, config, tp_axis, ep_axis, train,
        )
        return out, (aux, z)

    step = jax.checkpoint(scan_fn) if config.remat else scan_fn
    x, (aux, z) = jax.lax.scan(step, x, (params["blocks"], layer_keys))
    return layer_norm(params["ln_f"], x, config.layer_norm_epsilon), aux, z


def loss_fn(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array],
    labels: jax.Array,
    config: BloomMoEConfig,
    tp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
    rng: Optional[jax.Array] = None,
    train: bool = True,
) -> jax.Array:
    hidden, aux, z = forward_hidden(
        params, input_ids, attention_mask, config, tp_axis, ep_axis, rng, train
    )
    logits = logits_fn(params, hidden, tp_axis)
    per_tok = vocab_parallel_cross_entropy(
        logits[:, :-1], labels[:, 1:], tp_axis, valid_size=config.valid_vocab_size
    )
    if attention_mask is not None:
        w = attention_mask[:, 1:].astype(per_tok.dtype)
        task = (per_tok * w).sum() / jnp.maximum(w.sum(), 1)
    else:
        task = per_tok.mean()
    return ExpertLoss(config.aux_loss_weight, config.z_loss_weight)(task, aux, z)


def moe_specs(
    params: dict, tp_axis: str = "tensor", ep_axis: str = "expert"
) -> dict:
    """tp_specs for the shared trunk + expert/router specs: experts over
    the expert axis, expert FFN over tensor, router gate replicated."""
    from jax.sharding import PartitionSpec as P

    from pipegoose_tpu.nn.expert_parallel.experts import expert_mlp_specs
    from pipegoose_tpu.nn.parallel import spec_tree

    base_mapping = _bloom.tp_mapping(tp_axis)
    especs = expert_mlp_specs(ep_axis, tp_axis)

    def spec_fn(path, x):
        if "blocks/moe" in path:
            proj = "up" if "/up/" in path else "down"
            kind = "kernel" if path.endswith("kernel") else "bias"
            return especs[proj][kind]
        if "blocks/router" in path:
            return P()
        if "blocks" in path:
            base = base_mapping.spec_for(path, x.ndim - 1)
            return P(None, *base)
        return base_mapping.spec_for(path, x.ndim)

    return spec_tree(params, spec_fn)
