"""Page-granular cross-mesh KV transfer at wire precision.

The primitive under disaggregated prefill/decode serving (ROADMAP
item 2, arXiv 2211.05322's cross-mesh resharding as a first-class op):
move finished prefix pages between two differently sharded
``PagePool``s — the prefill pool's mesh (compute-dense, e.g. tp=2) and
the decode pool's (bandwidth-dense, e.g. tp=1) need not match; only
the page GEOMETRY (layer count, page size, heads, head dim) must, the
page COUNTS may differ.

The transfer is host-mediated, which is exactly where the resharding
happens for free:

- **export** — one jitted gather (:func:`~pipegoose_tpu.serving.
  kv_pool.export_page_slab`) pulls the selected pages into a
  contiguous ``(L, W, ps, nh, hd)`` slab ON THE SOURCE MESH (each
  shard contributes its heads), and the host fetch materializes the
  GLOBAL slab — tp_prefill's sharding is gone.
- **wire format** — the slab ships at WIRE precision: an int8 pool's
  ``{"q", "scale"}`` planes go verbatim (quantized pages are NEVER
  dequantized in flight — that would 4x the bytes and re-quantization
  would break the token-exactness contract); fp pools optionally take
  a bf16 wire (``wire_dtype="bf16"``, the distributed/compressed.py
  convention — exact for bf16 pools, lossy for fp32 ones, so the
  default wire is the pool dtype and the token-identity pins run on
  it).
- **import** — one jitted scatter (:func:`~pipegoose_tpu.serving.
  kv_pool.import_page_slab`) writes the slab into the DESTINATION
  pool's pages under its own sharding; pad entries route to the NULL
  page like every other pad write.

Both programs are compiled ONCE per pool pair at a fixed width ``W``
(the prefill chunk's page count — the streaming boundary), with
shorter shipments padded, so a serving run never compiles a new
transfer shape.

``TransferQueue`` is the bounded in-flight buffer between the pools:
the orchestrator stops ticking the prefill engine while it is full
(backpressure — a decode pool that cannot stage reservations must
slow prefill down, not buffer unboundedly). ``set_transfer_fault`` is
the failure seam (checkpoint.py's ``set_io_fault_hook`` convention):
a hook raising :class:`TransferError` during import exercises the
fall-back-to-local-re-prefill path end to end.

Host-side by design (jit-safety allowlisted): the jitted gather/
scatter are the only device programs; everything else is numpy + host
bookkeeping.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pipegoose_tpu.serving.kv_pool import (
    NULL_PAGE,
    export_page_slab,
    import_page_slab,
)


class TransferError(RuntimeError):
    """A cross-pool page shipment failed (link fault, checksum, test
    injection). The orchestrator's contract: abort the staged transfer
    and fall back to a local re-prefill on the decode pool."""


_fault_hook: Optional[Callable[..., None]] = None


def set_transfer_fault(hook: Optional[Callable[..., None]]):
    """Install a fault-injection hook ``hook(kind, uid, n_pages)``
    called before every import; raise :class:`TransferError` from it to
    fail that shipment. Returns the previous hook (restore it — the
    chaos-harness convention)."""
    global _fault_hook
    prev = _fault_hook
    _fault_hook = hook
    return prev


@dataclass(eq=False)
class PageHandoff:
    """One shipment: ``n_pages`` consecutive logical pages of ``req``'s
    prompt starting at ``page_index``, as host wire slabs. ``final``
    marks the prefill-completion handoff — it carries the first token
    and may legitimately hold zero pages (prompt length a page
    multiple, everything already streamed). Identity equality
    (``eq=False``): records hold numpy slabs and the queue's
    ``remove`` must match THIS record, not a value-equal twin."""

    req: Any
    page_index: int
    n_pages: int
    tokens_end: int                    # materialized positions after import
    k: Any                             # host slab (or None when n_pages=0)
    v: Any
    wire_bytes: int
    final: bool
    first_token: Optional[int]
    t_created: float


class TransferQueue:
    """Bounded FIFO of in-flight :class:`PageHandoff` records. The
    bound is the backpressure valve: ``has_room()`` gates both the
    prefill engine's tick and the streaming exports, so a slow decode
    pool stalls prefill instead of growing host memory. (The final
    handoff of a chunk already mid-tick may overshoot by one record
    per prefill slot — a soft bound, pinned by test.)

    ``max_age_s`` is the stuck-shipment timeout: a record older than
    this when the decode worker services it (a staging-blocked head
    the decode ledger can NEVER cover, a hung link) raises
    :class:`TransferError` into the existing per-shipment fallback —
    the request re-prefills locally — instead of blocking the queue
    until the whole-run stall watchdog gives up. ``None`` (default)
    disables the timeout; backpressure alone bounds the wait."""

    def __init__(self, max_inflight: int = 8,
                 max_age_s: Optional[float] = None):
        if max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1, got {max_inflight}"
            )
        if max_age_s is not None and max_age_s <= 0:
            raise ValueError(
                f"max_age_s must be > 0 (or None), got {max_age_s}"
            )
        self.max_inflight = int(max_inflight)
        self.max_age_s = max_age_s
        self._q: Deque[PageHandoff] = deque()
        self.max_depth = 0             # high-water mark (test + bench)

    def __len__(self) -> int:
        return len(self._q)

    def has_room(self) -> bool:
        return len(self._q) < self.max_inflight

    def push(self, rec: PageHandoff) -> None:
        self._q.append(rec)
        self.max_depth = max(self.max_depth, len(self._q))

    def peek(self) -> PageHandoff:
        return self._q[0]

    def pop(self) -> PageHandoff:
        return self._q.popleft()

    def remove(self, rec: PageHandoff) -> None:
        """Drop one record mid-queue (the decode worker imports
        already-staged requests' records past a staging-blocked head;
        relative order of the rest is untouched)."""
        self._q.remove(rec)

    def reset_depth_mark(self) -> None:
        """Start a fresh high-water measurement (per-run reporting)."""
        self.max_depth = len(self._q)

    def oldest_age(self, now: float) -> float:
        """Age of the oldest queued shipment (0.0 when empty) — the
        ``serving.transfer.queue_age_seconds`` gauge's source."""
        if not self._q:
            return 0.0
        return max(now - self._q[0].t_created, 0.0)

    def expired(self, rec: PageHandoff, now: float) -> bool:
        """Has ``rec`` outlived the stuck-shipment timeout?"""
        return (self.max_age_s is not None
                and now - rec.t_created > self.max_age_s)

    def clear(self) -> List[PageHandoff]:
        """Drop every queued shipment and return the dropped records —
        the POOL-LEVEL failure path (a dead prefill pool's in-flight
        shipments can never complete coherently; the affected requests
        re-prefill locally on the decode pool instead)."""
        dropped = list(self._q)
        self._q.clear()
        return dropped


def _host(slab):
    """Device slab -> host numpy pytree (the wire buffer)."""
    return jax.tree_util.tree_map(np.asarray, slab)


def _slice_pages(slab, n: int):
    return jax.tree_util.tree_map(lambda a: a[:, :n], slab)


def _pad_pages(slab, width: int):
    def pad(a):
        n = a.shape[1]
        if n == width:
            return a
        fill = np.zeros((a.shape[0], width - n) + a.shape[2:], a.dtype)
        return np.concatenate([a, fill], axis=1)

    return jax.tree_util.tree_map(pad, slab)


def slab_nbytes(slab) -> int:
    """Exact wire byte census of a host slab (values + scale planes at
    their wire dtypes — the test that int8 ships q+scale, never fp)."""
    if slab is None:
        return 0
    return int(sum(a.size * a.dtype.itemsize
                   for a in jax.tree_util.tree_leaves(slab)))


class PoolTransfer:
    """The compiled export/import pair between one (prefill, decode)
    engine pair. Validates geometry compatibility once; page counts
    and meshes may differ (that difference IS the feature)."""

    def __init__(self, src_engine, dst_engine, *,
                 wire_dtype: Optional[str] = None,
                 width: Optional[int] = None):
        scfg, dcfg = src_engine.config, dst_engine.config
        for attr in ("n_layer", "n_head", "head_dim"):
            if getattr(scfg, attr) != getattr(dcfg, attr):
                raise ValueError(
                    f"pool geometry mismatch: {attr} "
                    f"{getattr(scfg, attr)} != {getattr(dcfg, attr)}"
                )
        if src_engine.page_size != dst_engine.page_size:
            raise ValueError(
                f"page_size mismatch: {src_engine.page_size} != "
                f"{dst_engine.page_size} (page geometry may differ only "
                f"in COUNT)"
            )
        if src_engine.kv_dtype != dst_engine.kv_dtype:
            raise ValueError(
                f"kv_dtype mismatch: {src_engine.kv_dtype!r} != "
                f"{dst_engine.kv_dtype!r} — the wire format is the "
                f"pools' shared storage format"
            )
        if wire_dtype is not None and src_engine.kv_dtype == "int8":
            raise ValueError(
                "int8 pools define their own wire format (q + scale "
                "planes); wire_dtype applies to fp pools only"
            )
        if width is None and src_engine.prefill_chunk is None:
            raise ValueError(
                "the source engine needs prefill_chunk: the chunk is "
                "the streaming boundary that fixes the transfer width "
                "(or pass width= explicitly — the kv_tier spill/restore "
                "path does, its shipments are page-granular)"
            )
        if width is not None and width < 1:
            raise ValueError(f"width must be >= 1 pages, got {width}")
        self.src = src_engine
        self.dst = dst_engine
        self.wire_dtype = wire_dtype
        self.page_size = src_engine.page_size
        self.width = (int(width) if width is not None
                      else max(1, src_engine.prefill_chunk // self.page_size))

        def _exp(kp, vp, ids):
            return (export_page_slab(kp, ids, wire_dtype),
                    export_page_slab(vp, ids, wire_dtype))

        def _imp(kp, vp, ks, vs, dst_ids):
            return (import_page_slab(kp, ks, dst_ids),
                    import_page_slab(vp, vs, dst_ids))

        self._export_fn = jax.jit(_exp)
        if dst_engine.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            shard = jax.tree_util.tree_map(
                lambda s: NamedSharding(dst_engine.mesh, s),
                dst_engine._pspec,
                is_leaf=lambda x: isinstance(x, P),
            )
            self._import_fn = jax.jit(
                _imp, donate_argnums=(0, 1),
                out_shardings=(shard, shard),
            )
        else:
            self._import_fn = jax.jit(_imp, donate_argnums=(0, 1))
        # the fp-equivalent per-page wire size: what a no-quantization
        # transfer of the same pages would move — the "GB saved" meter
        itemsize = int(np.dtype(scfg.dtype).itemsize)
        self.fp_page_bytes = (2 * scfg.n_layer * self.page_size
                              * scfg.n_head * scfg.head_dim * itemsize)

    def export(self, page_ids: List[int]) -> Tuple[Any, Any, int]:
        """Gather ``page_ids`` from the source pool into host wire
        slabs (sliced to the REAL page count — padding never rides the
        wire census). Returns ``(k_slab, v_slab, wire_bytes)``."""
        n = len(page_ids)
        if n == 0:
            return None, None, 0
        if n > self.width:
            raise ValueError(
                f"shipment of {n} pages exceeds the transfer width "
                f"{self.width} (split at the streaming boundary)"
            )
        ids = np.zeros((self.width,), np.int32)
        ids[:n] = page_ids
        ks, vs = self._export_fn(
            self.src.k_pages, self.src.v_pages, jnp.asarray(ids)
        )
        ks, vs = _slice_pages(_host(ks), n), _slice_pages(_host(vs), n)
        return ks, vs, slab_nbytes(ks) + slab_nbytes(vs)

    def import_(self, rec: PageHandoff, dst_pages: List[int]) -> None:
        """Scatter a shipment into the destination pool's pages
        (``dst_pages``, one per shipped page). The fault seam fires
        FIRST: a failed shipment must not half-write the pool."""
        if _fault_hook is not None:
            _fault_hook("import", rec.req.uid, rec.n_pages)
        if rec.n_pages == 0:
            return
        if len(dst_pages) != rec.n_pages:
            raise ValueError(
                f"shipment has {rec.n_pages} pages but {len(dst_pages)} "
                f"destination pages were provided"
            )
        dst = np.full((self.width,), NULL_PAGE, np.int32)
        dst[:rec.n_pages] = dst_pages
        ks = _pad_pages(rec.k, self.width)
        vs = _pad_pages(rec.v, self.width)
        to_dev = jax.tree_util.tree_map(jnp.asarray, (ks, vs))
        self.dst.k_pages, self.dst.v_pages = self._import_fn(
            self.dst.k_pages, self.dst.v_pages,
            to_dev[0], to_dev[1], jnp.asarray(dst),
        )
