"""The two pool-side halves of disaggregated serving.

``PrefillWorker`` rides a ``prefill_only`` engine (compute-dense pool):
it streams each request's COMPLETED full pages into the transfer queue
as soon as a chunk finishes — not after the whole prefill — and, via
the engine's handoff hook, ships the final partial page together with
the first token the last chunk's logits produced, at which point the
request leaves the prefill scheduler entirely (slot, pages and
reservation freed; cache-shared pages survive in the prefill pool's
prefix cache for the next request with the same prefix).

``DecodeWorker`` rides a normal paged engine (bandwidth-dense pool):
it stages inbound requests against the decode scheduler's transfer
ledger (``begin_transfer`` reserves the FULL decode worst case before
the first page lands — the never-strand contract), imports shipments
in order, and admits a request into a decode slot the moment its page
table is fully materialized (``admit_with_pages`` — no prefill ever
runs for it here). A shipment that fails (:class:`~pipegoose_tpu.
serving.disagg.transfer.TransferError`) aborts the staging and, once
the request has fully left the prefill pool (its final record drained),
falls back to a LOCAL re-prefill on the decode engine — greedy
determinism makes the fallback's tokens identical to the transfer
path's.

Both workers are host-side orchestration; the only device programs are
the engines' own compiled steps plus the pool pair's export/import
gather/scatter (transfer.py).
"""
from __future__ import annotations

from typing import Dict, Optional

from pipegoose_tpu.serving.disagg.transfer import (
    PageHandoff,
    PoolTransfer,
    TransferError,
    TransferQueue,
)
from pipegoose_tpu.serving.scheduler import Request, Status


class PrefillWorker:
    """Streams finished prefix pages off a ``prefill_only`` engine.

    ``stream_ready`` runs after each engine tick: any PREFILL request
    whose ``prefilled_len`` crossed new full-page boundaries has those
    pages' content FINAL (chunked prefill writes strictly forward), so
    they export immediately — the decode pool starts materializing the
    page table while later chunks still compute. The handoff hook (the
    engine calls it at prefill completion, before the scheduler
    releases anything) ships the tail.

    Preemption-safe: a preempted prefill re-prefills BYTE-identical
    page content (token values alone determine it, quantized or not),
    so pages streamed before the preemption stay valid and are never
    re-shipped."""

    def __init__(self, engine, queue: TransferQueue,
                 transfer: PoolTransfer):
        if not getattr(engine, "prefill_only", False):
            raise ValueError(
                "PrefillWorker needs a prefill_only engine — a normal "
                "engine would reserve decode pages this pool never "
                "writes and try to decode instead of handing off"
            )
        self.engine = engine
        self.queue = queue
        self.transfer = transfer
        self._streamed: Dict[int, int] = {}    # uid -> pages shipped
        engine.set_handoff_hook(self._handoff)

    def stream_ready(self, now) -> int:
        """Export every newly completed full page of every in-flight
        prefill, bounded by the queue's room. Returns shipments made."""
        n_recs = 0
        ps = self.engine.page_size
        for req in self.engine.sched.active():
            if req.status is not Status.PREFILL:
                continue
            stable = min(req.prefilled_len, req.prompt_len) // ps
            start = self._streamed.get(req.uid, 0)
            while start < stable and self.queue.has_room():
                end = min(start + self.transfer.width, stable)
                self._push(req, start, end, final=False,
                           first_token=None, t=now())
                start = end
                n_recs += 1
            if start:
                self._streamed[req.uid] = start
        return n_recs

    def _handoff(self, engine, req: Request, first_token: int,
                 t: float) -> None:
        """Engine handoff hook: ship whatever was not streamed yet —
        including the final partial page — with the first token. Runs
        BEFORE finish_handoff releases the pages."""
        total = engine.pool.pages_for(req.prompt_len)
        start = self._streamed.pop(req.uid, 0)
        # the final shipment may span several widths when streaming was
        # backpressured; all but the last ride as plain chunks
        while total - start > self.transfer.width:
            end = start + self.transfer.width
            self._push(req, start, end, final=False, first_token=None, t=t)
            start = end
        self._push(req, start, total, final=True,
                   first_token=first_token, t=t)

    def reset_streams(self) -> None:
        """Forget per-request streaming progress — the prefill-pool
        failure path (every affected request restarts from its prompt
        on the decode pool; nothing already shipped stays valid)."""
        self._streamed.clear()

    def _push(self, req: Request, p0: int, p1: int, *, final: bool,
              first_token: Optional[int], t: float) -> None:
        ids = req.pages[p0:p1]
        k, v, nbytes = self.transfer.export(ids)
        end_tokens = min(p1 * self.engine.page_size, req.prompt_len)
        self.queue.push(PageHandoff(
            req=req, page_index=p0, n_pages=len(ids),
            tokens_end=end_tokens, k=k, v=v, wire_bytes=nbytes,
            final=final, first_token=first_token, t_created=t,
        ))


class DecodeWorker:
    """Stages, imports, and admits inbound transfers on the decode
    pool; owns the transfer-failure fallback."""

    def __init__(self, engine, transfer: PoolTransfer, owner=None):
        if not getattr(engine, "_paged_prefill", False):
            raise ValueError(
                "DecodeWorker needs the paged prefill path on the "
                "decode engine (prefix_cache=True and/or "
                "prefill_chunk=) — the transfer-failure fallback "
                "re-prefills locally"
            )
        if getattr(engine, "prefill_only", False):
            raise ValueError("the decode engine cannot be prefill_only")
        self.engine = engine
        self.transfer = transfer
        self.owner = owner                     # DisaggEngine (metrics)
        self._staged: Dict[int, dict] = {}     # uid -> {req, first_token,
        #                                        complete}
        # uid -> req awaiting fallback (the request rides along so a
        # POOL-level failure — the final record never arriving — can
        # still fall back without a queue record in hand)
        self._failed: Dict[int, Request] = {}
        self.fallbacks = 0
        self.failures = 0

    # -- the per-tick drains ----------------------------------------------

    def service(self, queue: TransferQueue, now) -> int:
        """Drain the transfer queue: stage (reserve) on first contact,
        import each shipment in order, mark complete at the final
        record. STAGING is head-of-line: when the decode ledger cannot
        cover a new request's worst case, no request behind it stages
        either (FIFO-deterministic, no starvation) — but records of
        ALREADY-STAGED requests behind the blocked one still import
        (their reservations were made; finishing them is exactly what
        frees the ledger for the blocked head — skipping them would
        deadlock the very backpressure this implements). Per-request
        record order is preserved (the scan keeps relative order).
        Returns shipments imported."""
        n = 0
        sched = self.engine.sched
        staging_blocked = False
        for rec in list(queue._q):
            req = rec.req
            if req.uid in self._failed:
                # a failed request's stragglers drain without import;
                # the FINAL record marks the prefill pool done with it
                # — only then may the fallback re-own the request
                queue.remove(rec)
                if rec.final:
                    del self._failed[req.uid]
                    self._fallback(req)
                continue
            if queue.expired(rec, now()):
                # stuck-shipment timeout (TransferQueue.max_age_s): a
                # record nobody could service in time — typically a
                # staging-blocked head whose reservation the decode
                # ledger can never cover — fails into the SAME
                # per-shipment fallback instead of blocking the queue
                # until the run-level stall watchdog gives up
                queue.remove(rec)
                self._fail(
                    req,
                    TransferError(
                        f"shipment for uid={req.uid} aged out "
                        f"(> {queue.max_age_s}s in the transfer queue)"
                    ),
                    final_seen=rec.final,
                )
                continue
            if req.uid not in self._staged:
                if staging_blocked or not sched.begin_transfer(req, now()):
                    # ledger full: this uid (and, for fairness, every
                    # unstaged uid behind it) retries next tick
                    staging_blocked = True
                    continue
                self._staged[req.uid] = {
                    "req": req, "first_token": None, "complete": False,
                }
            t0 = now()
            try:
                if rec.n_pages:
                    pages = sched.transfer_pages(req, rec.tokens_end)
                    dst = pages[
                        rec.page_index:rec.page_index + rec.n_pages
                    ]
                    self.transfer.import_(rec, dst)
                elif rec.final:
                    # zero-page final: still route through the fault
                    # seam so an injected failure on it exercises the
                    # fallback too
                    self.transfer.import_(rec, [])
            except TransferError as e:
                queue.remove(rec)
                self._fail(req, e, final_seen=rec.final)
                continue
            queue.remove(rec)
            n += 1
            t1 = now()
            st = self._staged[req.uid]
            if rec.final:
                st["first_token"] = rec.first_token
                st["complete"] = True
            self._observe(rec, req, t0, t1)
        return n

    def admit_ready(self, now) -> int:
        """Admit every fully materialized staged request into a free
        decode slot (insertion order — deterministic). Returns
        admissions made."""
        n = 0
        for uid in list(self._staged):
            st = self._staged[uid]
            if not st["complete"]:
                continue
            if not self.engine.admit_transferred(st["req"],
                                                 st["first_token"]):
                break                  # no free slot: retry next tick
            del self._staged[uid]
            n += 1
        return n

    @property
    def pending(self) -> int:
        return len(self._staged) + len(self._failed)

    # -- failure path ------------------------------------------------------

    def _fail(self, req: Request, err: TransferError,
              final_seen: bool) -> None:
        self.failures += 1
        if self.owner is not None:
            self.owner._m_failures.inc()
        st = self._staged.pop(req.uid, None)
        if st is not None:
            self.engine.sched.abort_transfer(req)
        if final_seen:
            self._fallback(req)        # prefill pool already released it
        else:
            self._failed[req.uid] = req  # wait for the final record

    def _fallback(self, req: Request) -> None:
        """Local re-prefill: the decode engine's own paged prefill
        serves the request from scratch (hitting its prefix cache
        where transferred-in neighbors already published the prefix).
        Greedy determinism keeps the tokens identical to the transfer
        path's — the contract the fallback test pins."""
        self.fallbacks += 1
        if self.owner is not None:
            self.owner._m_fallbacks.inc()
        tr = self.engine.tracer
        if tr is not None:
            # stitched fleet traces surface WHY this leg re-prefilled
            # locally instead of admitting the transferred pages
            tr.annotate(req, "disagg_fallback")
        self.engine.submit_request(req, reuse_uid=True)

    def _observe(self, rec: PageHandoff, req: Request, t0: float,
                 t1: float) -> None:
        tr = self.engine.tracer
        if tr is not None:
            tr.on_transfer_chunk(
                req, t1, dur_s=t1 - t0,
                tokens=rec.tokens_end - rec.page_index
                * self.engine.page_size,
                pages=rec.n_pages, nbytes=rec.wire_bytes,
            )
        if self.owner is not None:
            self.owner._observe_shipment(rec, t1)
