"""DisaggEngine: prefill on one mesh, decode on another, KV in flight.

The orchestrator over one prefill pool and one decode pool (ROADMAP
item 2): prefill is compute-bound, decode is HBM-bandwidth-bound, and
one engine on one mesh sizes both wrong. Here each pool is an ordinary
``ServingEngine`` on ITS OWN mesh (geometry may differ — tp_prefill=2
feeding tp_decode=1 is the canonical reshard), driven tick-by-tick in
one host thread through the steppable-run API, with the page-granular
transfer primitive (transfer.py) between them:

    while work remains:
        tick the prefill engine        (unless the transfer queue is
                                        full — backpressure)
        stream completed pages         (chunk boundary = streaming
                                        boundary; pages ship as soon
                                        as their content is final)
        service the transfer queue     (stage -> import -> mark
                                        complete on the decode pool)
        admit materialized requests    (admit_with_pages: decode
                                        starts, no prefill ever runs
                                        on the decode pool)
        tick the decode engine
        collect finished requests

Invariants the tests pin:

- **Token identity.** Greedy output is token-identical to a single
  engine serving the same requests — across fp and int8-KV pools and
  across the tp 2 -> 1 reshard. The wire never changes a value the
  attention core reads: int8 pages ship q + scale verbatim, fp pages
  ship at pool precision by default.
- **Exact attribution.** With a shared ``RequestTracer``, every
  request's queue + prefill + transfer + decode + stall components sum
  to its e2e exactly — ``transfer`` is a first-class phase, not decode
  noise.
- **Bounded in-flight.** The transfer queue is the only buffer; its
  bound pauses prefill rather than queueing host slabs unboundedly.
- **Fallback.** A failed shipment aborts the staging and re-prefills
  on the decode pool (same tokens, by determinism); the disagg run
  finishes every request either way.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional, Sequence

from pipegoose_tpu.serving.disagg.transfer import (
    PageHandoff,
    PoolTransfer,
    TransferQueue,
)
from pipegoose_tpu.serving.disagg.workers import DecodeWorker, PrefillWorker
from pipegoose_tpu.serving.scheduler import Request, Status
from pipegoose_tpu.telemetry.registry import get_registry


class DisaggEngine:
    """Two-pool disaggregated serving orchestrator.

    ``prefill_engine`` must be ``prefill_only`` (with ``prefill_chunk``
    — the streaming boundary); ``decode_engine`` must have the paged
    prefill path enabled (the fallback re-prefills there). Pools must
    share page geometry and ``kv_dtype``; page counts and meshes may
    differ. ``max_inflight`` bounds queued shipments (backpressure);
    ``wire_dtype="bf16"`` opts fp pools into a half-width wire
    (transfer.py's precision caveats apply). ``tracer`` attaches ONE
    shared ``RequestTracer`` to both engines so the attribution
    contract spans the whole pipeline."""

    def __init__(self, prefill_engine, decode_engine, *,
                 max_inflight: int = 8,
                 wire_dtype: Optional[str] = None,
                 registry=None, tracer=None,
                 stall_patience: int = 1000,
                 recorder=None,
                 max_shipment_age_s: Optional[float] = None,
                 prefill_fail_patience: int = 50):
        """``recorder``: optional ``telemetry.FlightRecorder`` — a
        prefill-pool death dumps one ``replica_failure`` black box
        naming the pool and every resubmitted uid (the fleet failure
        contract, docs/robustness.md). ``max_shipment_age_s``: the
        transfer queue's stuck-shipment timeout (``TransferQueue
        (max_age_s=)``) — an aged-out shipment fails into the
        per-shipment fallback instead of blocking the queue.
        ``prefill_fail_patience``: consecutive no-progress prefill
        ticks (with work pending and queue room) before the prefill
        pool is declared WEDGED and the pool-level fallback fires —
        must stay well under ``stall_patience`` so a dead prefill pool
        degrades to local re-prefill instead of stalling the run."""
        if stall_patience < 1:
            raise ValueError(
                f"stall_patience must be >= 1, got {stall_patience}"
            )
        if not 1 <= prefill_fail_patience < stall_patience:
            raise ValueError(
                f"need 1 <= prefill_fail_patience "
                f"({prefill_fail_patience}) < stall_patience "
                f"({stall_patience})"
            )
        self.registry = registry if registry is not None else get_registry()
        reg = self.registry
        self._m_handoffs = reg.counter("serving.transfer.handoffs_total")
        self._m_pages = reg.counter("serving.transfer.pages_total")
        self._m_bytes = reg.counter("serving.transfer.bytes_total")
        self._m_failures = reg.counter("serving.transfer.failures_total")
        self._m_fallbacks = reg.counter("serving.transfer.fallbacks_total")
        self._h_bytes = reg.histogram("serving.transfer.bytes")
        self._h_lat = reg.histogram("serving.transfer.seconds")
        self._m_qdepth = reg.gauge("serving.transfer.queue_depth")
        self._m_qage = reg.gauge("serving.transfer.queue_age_seconds")
        self._m_pool_failures = reg.counter("serving.fleet.failures_total")
        self._m_resubmitted = reg.counter("serving.fleet.resubmitted_total")
        self.stall_patience = stall_patience
        self.prefill_fail_patience = prefill_fail_patience
        self.recorder = recorder
        self.prefill_pool_failed: Optional[str] = None  # failure reason
        # plain host tallies next to the registry instruments, so the
        # run metrics stay truthful even under a disabled registry
        self.total_handoffs = self.total_pages = self.total_bytes = 0
        self.transfer = PoolTransfer(prefill_engine, decode_engine,
                                     wire_dtype=wire_dtype)
        self.queue = TransferQueue(max_inflight, max_age_s=max_shipment_age_s)
        self.prefill = PrefillWorker(prefill_engine, self.queue,
                                     self.transfer)
        self.decode = DecodeWorker(decode_engine, self.transfer,
                                   owner=self)
        if tracer is not None:
            prefill_engine.attach_tracer(tracer)
            decode_engine.attach_tracer(tracer)
        self.tracer = tracer

    # -- shipment telemetry (DecodeWorker calls back) ----------------------

    def _observe_shipment(self, rec: PageHandoff, t: float) -> None:
        self.total_pages += rec.n_pages
        self.total_bytes += rec.wire_bytes
        self._m_pages.inc(rec.n_pages)
        self._m_bytes.inc(rec.wire_bytes)
        self._h_bytes.observe(float(rec.wire_bytes))
        self._h_lat.observe(max(t - rec.t_created, 0.0))
        if rec.final:
            self.total_handoffs += 1
            self._m_handoffs.inc()

    # -- prefill-pool failure: the pool-level fallback ---------------------

    def _fail_prefill_pool(self, reason: str, tick: int) -> list:
        """The per-shipment fallback, promoted to POOL level: the
        prefill pool died (tick raised, or wedged past
        ``prefill_fail_patience``), so every request it still owed —
        queued shipments, staged-but-incomplete transfers, failures
        awaiting a final record that will never come, and requests
        still queued/mid-prefill on its scheduler — re-prefills LOCALLY
        on the decode pool (``reuse_uid`` keeps each tracer timeline;
        greedy determinism keeps every token identical, test-pinned).
        Requests already staged COMPLETE keep their materialized pages
        and admit normally; requests already decoding are untouched.
        One ``replica_failure`` black box names the pool and every
        resubmitted uid. Returns the prefill side's finished-but-
        untaken (request, output) pairs — deadline sheds buffered in
        the run state that abort_run would otherwise silently drop."""
        pe = self.prefill.engine
        de = self.decode.engine
        self.prefill_pool_failed = reason
        self._m_pool_failures.inc()
        finished: list = []
        try:
            if pe.run_in_progress:
                finished = pe.take_finished()
        except Exception:  # noqa: BLE001 - best effort on a dead engine
            finished = []
        try:
            pe.abort_run()
        except Exception:  # noqa: BLE001 - best effort on a dead engine
            pass
        self.prefill.reset_streams()
        affected: Dict[int, Request] = {}
        # in-flight shipments can never complete coherently — drop them,
        # remembering their owners
        for rec in self.queue.clear():
            affected[rec.req.uid] = rec.req
        # staged-but-incomplete transfers: the final record will never
        # come — release the staged pages + reservation now
        for uid, st in list(self.decode._staged.items()):
            if not st["complete"]:
                req = st["req"]
                del self.decode._staged[uid]
                try:
                    de.sched.abort_transfer(req)
                except Exception:  # noqa: BLE001 - ledger best effort
                    pass
                affected[uid] = req
        # per-shipment failures already waiting for their final record
        for uid, req in list(self.decode._failed.items()):
            del self.decode._failed[uid]
            affected[uid] = req
        # requests still living on the prefill scheduler (queued or
        # mid-prefill) — harvest them off it, best effort per request
        sched = pe.sched
        for req in list(sched.active()) + list(sched.queue):
            try:
                if req.status in (Status.PREFILL, Status.DECODE):
                    sched.preempt(req)
                if req.status is Status.QUEUED:
                    sched.withdraw(req)
            except Exception:  # noqa: BLE001 - unreachable prefill-side
                # state: scrub the fields the decode-pool re-prefill
                # must not inherit (a prefill-only request holds no
                # generated tokens, so nothing is lost)
                req.clear_residency()
            affected[req.uid] = req
        for uid in sorted(affected):
            self.decode._fallback(affected[uid])
        self._m_resubmitted.inc(len(affected))
        if self.recorder is not None:
            # an earlier unconsumed trigger must survive this dump (the
            # control-plane convention): remember it, fire, consume only
            # OUR trigger, restore the earlier one
            pending = self.recorder.last_trigger
            trig = self.recorder.fire_trigger(
                "replica_failure",
                f"prefill pool failed at tick {tick}: {reason} — "
                f"{len(affected)} request(s) re-prefill locally on the "
                f"decode pool",
                tick,
                details={
                    "pool": "prefill",
                    "reason": reason,
                    "resubmitted_uids": sorted(affected),
                    "lost_uids": [],
                    "router": {
                        "verdict": "per-shipment fallback promoted to "
                                   "pool level: decode pool serves "
                                   "everything locally",
                    },
                },
            )
            if self.recorder.last_trigger is trig:
                # nothing lost and the decode pool carries on: degraded,
                # not down — the black box stays on disk, only the
                # pending /healthz flag clears (the fleet convention)
                self.recorder.take_trigger()
                if pending is not None:
                    self.recorder.last_trigger = pending
        return finished

    # -- the loop ----------------------------------------------------------

    def _busy(self) -> bool:
        pe, de = self.prefill.engine, self.decode.engine
        return ((self.prefill_pool_failed is None
                 and not pe.sched.all_done())
                or len(self.queue) > 0
                or self.decode.pending > 0 or not de.sched.all_done())

    def run(self, requests: Sequence[Request], now=time.perf_counter,
            tick_hook=None):
        """Serve ``requests`` through the two pools to completion;
        returns (outputs in uid order, metrics dict — pool metrics
        plus the ``transfer`` block). ``tick_hook(engine, tick)`` is
        the orchestration/test seam."""
        pe, de = self.prefill.engine, self.decode.engine
        if pe.run_in_progress or de.run_in_progress:
            # guard BEFORE start_run: the exception path below aborts
            # both engines, which must never tear down a live outer run
            raise RuntimeError("a disagg run is already in progress")
        pe.start_run((), now=now)
        de.start_run((), now=now)
        outputs: Dict[int, Any] = {}
        # per-RUN deltas: the tallies are lifetime (warmup runs would
        # otherwise pollute a measured run's transfer block)
        h0, p0, b0 = (self.total_handoffs, self.total_pages,
                      self.total_bytes)
        f0, fb0 = self.decode.failures, self.decode.fallbacks
        self.queue.reset_depth_mark()   # per-run high-water, like the rest
        self.prefill_pool_failed = None
        t0 = now()
        tick = stalled = pe_idle = 0
        try:
            for req in requests:
                pe.submit_request(req)
            while self._busy():
                tick += 1
                if tick_hook is not None:
                    tick_hook(self, tick)
                progressed = False
                pe_alive = self.prefill_pool_failed is None
                if (pe_alive and not pe.sched.all_done()
                        and self.queue.has_room()):
                    # queue full = backpressure: the prefill pool
                    # pauses instead of racing ahead of a decode pool
                    # that cannot stage reservations yet
                    try:
                        ticked = pe.tick_once()
                    except Exception as e:  # noqa: BLE001 - pool crash
                        for _, out in self._fail_prefill_pool(
                            f"tick_once raised {type(e).__name__}: {e}",
                            tick,
                        ):
                            outputs[out.uid] = out
                        pe_alive = False
                        progressed = True  # failure handling IS progress
                    else:
                        progressed = ticked or progressed
                        if ticked:
                            pe_idle = 0
                        else:
                            # heartbeat miss with work pending and queue
                            # room: the prefill-pool wedge ladder
                            pe_idle += 1
                            if pe_idle >= self.prefill_fail_patience:
                                for _, out in self._fail_prefill_pool(
                                    f"wedged: no prefill progress for "
                                    f"{pe_idle} ticks with work pending",
                                    tick,
                                ):
                                    outputs[out.uid] = out
                                pe_alive = False
                                progressed = True
                if pe_alive:
                    progressed = (self.prefill.stream_ready(now) > 0
                                  or progressed)
                progressed = self.decode.service(self.queue, now) > 0 \
                    or progressed
                progressed = self.decode.admit_ready(now) > 0 or progressed
                if not de.sched.all_done():
                    progressed = de.tick_once() or progressed
                for req, out in de.take_finished():
                    outputs[out.uid] = out
                    progressed = True
                if pe_alive:
                    for req, out in pe.take_finished():
                        outputs[out.uid] = out   # prefill-side sheds only
                        progressed = True
                self._m_qdepth.set(float(len(self.queue)))
                self._m_qage.set(self.queue.oldest_age(now()))
                if progressed:
                    stalled = 0
                else:
                    stalled += 1
                    if stalled >= self.stall_patience:
                        raise RuntimeError(
                            f"disagg stall: no progress for "
                            f"{self.stall_patience} ticks — "
                            f"{len(self.queue)} queued shipments, "
                            f"{self.decode.pending} staged, prefill "
                            f"done={pe.sched.all_done()}, decode "
                            f"done={de.sched.all_done()}"
                        )
            if pe.run_in_progress:
                _, pmetrics = pe.finish_run()
            else:  # pool death aborted it
                pmetrics = {"failed": self.prefill_pool_failed}
            _, dmetrics = de.finish_run()
        except BaseException:
            pe.abort_run()
            de.abort_run()
            raise
        wall = max(now() - t0, 1e-9)
        outs = [outputs[uid] for uid in sorted(outputs)]
        generated = sum(len(o.generated) for o in outs)
        step_time = dmetrics.get("decode_step_time_s", 0.0)
        metrics = {
            "wall_time_s": round(wall, 6),
            "requests": len(outs),
            "generated_tokens": generated,
            "decode_tokens_per_s": round(generated / wall, 2),
            # the decode POOL's intrinsic rate (prefill + transfer off
            # its critical path): generated / summed decode-step time
            "decode_pool_tokens_per_s": round(
                generated / max(step_time, 1e-9), 2
            ) if step_time else 0.0,
            "shed_requests": sum(
                1 for o in outs if o.finish_reason == "shed"
            ),
            # None on a healthy run; the failure reason after the
            # pool-level fallback served everything locally
            "prefill_pool_failed": self.prefill_pool_failed,
            "transfer": {
                "handoffs": self.total_handoffs - h0,
                "pages": self.total_pages - p0,
                "wire_bytes": self.total_bytes - b0,
                "fp_equiv_bytes": ((self.total_pages - p0)
                                   * self.transfer.fp_page_bytes),
                "failures": self.decode.failures - f0,
                "fallbacks": self.decode.fallbacks - fb0,
                "max_queue_depth": self.queue.max_depth,
            },
            "prefill_pool": pmetrics,
            "decode_pool": dmetrics,
        }
        fp_eq = metrics["transfer"]["fp_equiv_bytes"]
        metrics["transfer"]["wire_savings_ratio"] = round(
            1.0 - metrics["transfer"]["wire_bytes"] / fp_eq, 4
        ) if fp_eq else 0.0
        return outs, metrics
