"""Disagg-vs-monolithic replay: the tentpole's acceptance meter.

``disagg_serving_benchmark`` replays the same Zipf-skewed shared-prefix
trace through (a) one monolithic ``ServingEngine`` (prefix cache +
chunked prefill — the PR 6 production shape) and (b) a ``DisaggEngine``
whose prefill and decode pools split the same work, reporting per arm:

- tokens/s and TTFT p50/p99 (the user-visible columns);
- the **decode-pool rate** ``generated / summed decode-step time`` —
  for the monolithic arm that is what a decode-only engine would do
  (its decode steps, measured, minus the prefill stalls between them);
  for the disagg arm it is the decode pool's actual rate. The ratio is
  the "prefill off the critical path" acceptance meter (within 10% on
  hardware; reported, not asserted, here — CI asserts token identity);
- the **transfer block**: wire bytes moved, their fp-equivalent, and
  the savings ratio (int8 pools ship q + scale at ~1/(itemsize)x the
  fp bytes — the GB-equivalent saved per the ISSUE), plus the queue
  high-water mark;
- the **token-identity verdict**: both arms' measured runs must emit
  identical streams (the invariant every disagg test pins).

Tiny-config friendly: bench.py's serving block runs it on CPU smoke
and TPU geometries, and ``scripts/sweep_tpu_perf.py disagg`` sweeps it
on hardware.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from pipegoose_tpu.serving.disagg.engine import DisaggEngine
from pipegoose_tpu.serving.engine import ServingEngine, make_skewed_replay
from pipegoose_tpu.serving.scheduler import Request
from pipegoose_tpu.telemetry.registry import Histogram, MetricsRegistry


def _requests(replay):
    return [Request(prompt=p, max_new_tokens=n) for p, n in replay]


def _row(outs, wall_metrics) -> Dict:
    h_ttft = Histogram("disagg.arm.ttft_seconds")   # standalone reservoir
    for o in outs:
        if o.ttft_s is not None:
            h_ttft.observe(o.ttft_s)
    return {
        "decode_tokens_per_s": wall_metrics["decode_tokens_per_s"],
        "ttft_p50_s": round(h_ttft.quantile(0.5), 6),
        "ttft_p99_s": round(h_ttft.quantile(0.99), 6),
        "wall_time_s": wall_metrics["wall_time_s"],
    }


def disagg_serving_benchmark(
        params, config, *, n_requests: int = 12, n_prefixes: int = 3,
        prefix_len: int = 48, suffix_lens=(2, 4, 6), max_new: int = 4,
        seed: int = 0, zipf_a: float = 1.2, num_slots: int = 2,
        prefill_pages: int = 33, decode_pages: int = 33,
        page_size: int = 8, max_context: int = 96,
        prefill_chunk: int = 16, max_inflight: int = 8,
        kv_dtype: Optional[str] = None,
        prefill_mesh=None, prefill_param_specs=None,
        decode_mesh=None, decode_param_specs=None,
        tp_axis: str = "tensor",
        attn_kernel: str = "gather"):
    """Measure disagg vs monolithic on one trace (module docstring);
    returns a JSON-able dict with both arms, the transfer block, and
    the token-identity verdict. Pass ``prefill_mesh``/``decode_mesh``
    (+ matching param-spec trees) to put the pools on different
    meshes — tp 2 -> 1 is the reshard the tests pin.
    ``attn_kernel="paged"`` routes BOTH arms (monolithic reference,
    disagg prefill + decode pools) through the fused Pallas
    paged-attention kernel — the disagg decode worker is the pool the
    kernel is sized for."""
    vocab = getattr(config, "valid_vocab_size", None) or config.vocab_size
    replay = make_skewed_replay(
        n_requests=n_requests, n_prefixes=n_prefixes, prefix_len=prefix_len,
        suffix_lens=suffix_lens, max_new=max_new, vocab=vocab, seed=seed,
        zipf_a=zipf_a,
    )
    results: Dict = {}

    # -- monolithic reference arm -----------------------------------------
    single = ServingEngine(
        params, config, num_slots=num_slots, num_pages=decode_pages,
        page_size=page_size, max_context=max_context,
        prefix_cache=True, prefill_chunk=prefill_chunk,
        kv_dtype=kv_dtype, mesh=decode_mesh,
        param_specs=decode_param_specs, tp_axis=tp_axis,
        attn_kernel=attn_kernel,
    )
    single.run(_requests(replay))           # cold warmup: compiles
    single.run(_requests(replay))           # warm warmup: hit paths
    ref_outs, ref_metrics = single.run(_requests(replay))
    row = _row(ref_outs, ref_metrics)
    step_t = ref_metrics.get("decode_step_time_s", 0.0)
    row["decode_only_tokens_per_s"] = round(
        ref_metrics["generated_tokens"] / max(step_t, 1e-9), 2
    ) if step_t else 0.0
    row["prefill_tokens"] = ref_metrics["prefill_tokens"]
    results["single"] = row

    # -- disagg arm --------------------------------------------------------
    def build():
        pe = ServingEngine(
            params, config, num_slots=num_slots, num_pages=prefill_pages,
            page_size=page_size, max_context=max_context,
            prefix_cache=True, prefill_chunk=prefill_chunk,
            prefill_only=True, kv_dtype=kv_dtype, mesh=prefill_mesh,
            param_specs=prefill_param_specs, tp_axis=tp_axis,
            registry=MetricsRegistry(), attn_kernel=attn_kernel,
        )
        de = ServingEngine(
            params, config, num_slots=num_slots, num_pages=decode_pages,
            page_size=page_size, max_context=max_context,
            prefix_cache=True, prefill_chunk=prefill_chunk,
            kv_dtype=kv_dtype, mesh=decode_mesh,
            param_specs=decode_param_specs, tp_axis=tp_axis,
            registry=MetricsRegistry(), stall_patience=10_000,
            attn_kernel=attn_kernel,
        )
        return DisaggEngine(pe, de, max_inflight=max_inflight,
                            registry=MetricsRegistry())

    disagg = build()
    disagg.run(_requests(replay))           # cold warmup
    disagg.run(_requests(replay))           # warm warmup
    outs, metrics = disagg.run(_requests(replay))
    row = _row(outs, metrics)
    row["decode_pool_tokens_per_s"] = metrics["decode_pool_tokens_per_s"]
    row["prefill_tokens"] = metrics["prefill_pool"]["prefill_tokens"]
    row["transfer"] = metrics["transfer"]
    results["disagg"] = row

    identical = len(ref_outs) == len(outs) and all(
        np.array_equal(a.generated, b.generated)
        for a, b in zip(ref_outs, outs)
    )
    xfer = metrics["transfer"]
    results["summary"] = {
        "requests": n_requests,
        "kv_dtype": kv_dtype or "fp",
        "attn_kernel": attn_kernel,
        "outputs_token_identical": bool(identical),
        # prefill off the decode pool's critical path: its measured
        # rate vs the monolithic arm's decode-only rate
        "decode_pool_vs_decode_only": round(
            row["decode_pool_tokens_per_s"]
            / max(results["single"]["decode_only_tokens_per_s"], 1e-9), 3,
        ),
        "transfer_wire_mb": round(xfer["wire_bytes"] / 1e6, 3),
        "transfer_fp_equiv_mb": round(xfer["fp_equiv_bytes"] / 1e6, 3),
        "wire_savings_ratio": xfer["wire_savings_ratio"],
        "max_queue_depth": xfer["max_queue_depth"],
        "fallbacks": xfer["fallbacks"],
    }
    return results
