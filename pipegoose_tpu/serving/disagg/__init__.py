"""Disaggregated prefill/decode serving (ROADMAP item 2).

Prefill is compute-bound, decode is HBM-bandwidth-bound; serving one
model on one mesh sizes both pools wrong. This package splits them:

- **transfer.py** — the page-granular KV transfer primitive between
  two differently sharded pools, at WIRE precision (int8 pages ship
  q + scale planes, never fp; fp pools get an opt-in bf16 wire), with
  a bounded in-flight queue and a fault seam.
- **workers.py** — ``PrefillWorker`` (streams completed pages chunk by
  chunk off a ``prefill_only`` engine, hands off the first token) and
  ``DecodeWorker`` (stages against the transfer ledger, imports,
  admits via ``admit_with_pages``, owns the re-prefill fallback).
- **engine.py** — ``DisaggEngine``, the one-host-thread orchestrator
  over both pools' steppable-run APIs.
- **benchmark.py** — the disagg-vs-monolithic replay bench.

Greedy output is token-identical to a single-engine run (pinned across
fp/int8 KV and the tp 2 -> 1 reshard), and the request tracer's new
``transfer`` phase keeps queue + prefill + transfer + decode + stall
== e2e exact. See docs/serving.md "Disaggregated prefill/decode".
"""
from pipegoose_tpu.serving.disagg.benchmark import disagg_serving_benchmark
from pipegoose_tpu.serving.disagg.engine import DisaggEngine
from pipegoose_tpu.serving.disagg.transfer import (
    PageHandoff,
    PoolTransfer,
    TransferError,
    TransferQueue,
    set_transfer_fault,
)
from pipegoose_tpu.serving.disagg.workers import DecodeWorker, PrefillWorker

__all__ = [
    "DecodeWorker",
    "DisaggEngine",
    "PageHandoff",
    "PoolTransfer",
    "PrefillWorker",
    "TransferError",
    "TransferQueue",
    "disagg_serving_benchmark",
    "set_transfer_fault",
]
