"""Replica lifecycle: one ServingEngine behind the router.

A replica is an independently meshed engine — its own scheduler, page
pool, and prefix cache — wrapped with the state machine the router,
autoscaler, and crash-recovery path act on:

    SERVING ──start_drain──> DRAINING ──(emptied)──> STOPPED
       │ ▲                      │
       │ └──(progress)──┐       │ (tick raised / wedged while draining)
       ▼                │       ▼
    SUSPECT ──(no progress x failed_after / tick raised)──> FAILED
                                                              │
                                  (rejoin: fault cleared) ────┘
                                   -> SERVING on probation

- **SERVING** accepts routed requests and ticks every control-plane
  iteration.
- **SUSPECT** is a replica that stopped making progress while it still
  had work (heartbeat miss). It keeps ticking — it may recover — but
  fresh dispatch to it is PROBED with exponential backoff instead of
  flowing freely; one progressing tick restores SERVING and resets the
  backoff.
- **FAILED** is the quarantine: a tick raised (crash) or the heartbeat
  stayed flat past ``failed_after`` (wedge). The control plane
  best-effort-aborts the engine's run, drops the router's shadow index
  for it, and SALVAGES its admitted requests onto the survivors
  (plane.py). A failed replica can :meth:`ControlPlane.rejoin` after
  the operator clears the fault — it re-enters SERVING **on
  probation**: ticked, but not routed fresh ingress until the
  probation cooldown elapses.
- **DRAINING** stops accepting. The control plane immediately preempts
  its in-flight requests (pages released, shared prefix pages survive
  in the cache) and withdraws its queue; the migrated requests re-admit
  elsewhere through the normal re-prefill path — which HITS the target
  replica's cache for any shared prefix — so scale-down drops zero
  admitted work. The replica still ticks until its scheduler empties.
- **STOPPED** is terminal: the engine's run is finished and its
  aggregate metrics captured in ``final_metrics``.

This module is the structural seam ROADMAP item 2 (disaggregated
prefill/decode pools) hangs from: a pool is a set of replicas with
a role tag, and cross-mesh KV streaming replaces the re-prefill
migration path.
"""
from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional


class ReplicaState(enum.Enum):
    SERVING = "serving"
    SUSPECT = "suspect"
    FAILED = "failed"
    DRAINING = "draining"
    STOPPED = "stopped"


#: probe-backoff cap (ticks): a SUSPECT replica is probed at 1, 2, 4,
#: ... up to this many ticks apart — bounded so a recovered replica is
#: rediscovered within one cap interval, not "eventually"
MAX_PROBE_BACKOFF = 64

#: state-transition history bound (newest kept): enough to read a
#: whole crash->suspect->failed->rejoin->probation arc off a
#: ``/debug/fleet`` row without growing per-replica state unboundedly
MAX_STATE_HISTORY = 64


class Replica:
    """One engine + lifecycle state + per-replica bookkeeping. The
    ``registry`` is the replica's OWN metrics registry (fleet-level
    views merge them — telemetry/fleet.py); ``index`` is the stable
    routing tie-break."""

    def __init__(self, name: str, engine: Any, *, registry: Any = None,
                 index: int = 0):
        self.name = name
        self.engine = engine
        self.registry = registry
        self.index = index
        self.state = ReplicaState.SERVING
        self.dispatched = 0            # requests routed here, lifetime
        self.migrated_out = 0          # requests drained away
        self.salvaged_out = 0          # requests salvaged off a failure
        self.final_metrics: Optional[dict] = None
        # health bookkeeping (the control plane's heartbeat writes it):
        # consecutive ticks with work but no progress, the SUSPECT probe
        # backoff, the post-rejoin probation countdown, and the plane-
        # side ledger of every request currently owned by this replica
        # (id(req) -> req, insertion-ordered) — salvage harvests from
        # HERE, so a crashed scheduler cannot hide its admitted work.
        self.no_progress_ticks = 0
        self.probe_backoff = 1
        self.next_probe_tick = 0
        self.probation_ticks_left = 0
        self.failure_reason: Optional[str] = None
        # set when salvage had to take the resubmit-from-prompt
        # degradation (the scheduler raised mid-harvest): the engine's
        # internal state can no longer be trusted, so rejoin refuses —
        # replace the replica with scale_up instead
        self.salvage_degraded = False
        self.inflight: Dict[int, Any] = {}
        # state-transition audit: (state, since_tick) per transition,
        # newest-bounded — the /debug/fleet dwell trail (None tick =
        # the transition happened outside a run's tick loop)
        self.state_history: List[Any] = [("serving", 0)]

    def _note_state(self, tick: Optional[int]) -> None:
        self.state_history.append((self.state.value, tick))
        if len(self.state_history) > MAX_STATE_HISTORY:
            del self.state_history[0]

    @property
    def accepting(self) -> bool:
        """Router-facing: may fresh work be placed here at all? SUSPECT
        stays True — the PLANE's backoff filter decides WHEN a suspect
        is probed (it has the tick clock; this property does not)."""
        return self.state in (ReplicaState.SERVING, ReplicaState.SUSPECT)

    @property
    def busy(self) -> bool:
        return (self.state not in (ReplicaState.STOPPED,
                                   ReplicaState.FAILED)
                and self.engine.run_in_progress
                and not self.engine.sched.all_done())

    # -- health transitions (driven by ControlPlane's heartbeat) -----------

    def note_progress(self, tick: Optional[int] = None) -> bool:
        """A tick made progress: reset the heartbeat, and recover a
        SUSPECT back to SERVING (backoff reset). Returns True on the
        SUSPECT->SERVING recovery transition."""
        self.no_progress_ticks = 0
        if self.state is ReplicaState.SUSPECT:
            self.state = ReplicaState.SERVING
            self.probe_backoff = 1
            self.next_probe_tick = 0
            self._note_state(tick)
            return True
        return False

    def note_no_progress(self) -> int:
        self.no_progress_ticks += 1
        return self.no_progress_ticks

    def mark_suspect(self, tick: int) -> None:
        if self.state is ReplicaState.SERVING:
            self.state = ReplicaState.SUSPECT
            self.probe_backoff = 1
            self.next_probe_tick = tick  # first probe allowed right away
            self._note_state(tick)

    def mark_failed(self, reason: str,
                    tick: Optional[int] = None) -> None:
        self.state = ReplicaState.FAILED
        self.failure_reason = reason
        self._note_state(tick)

    def probe_allowed(self, tick: int) -> bool:
        """SUSPECT dispatch gate, side-effect-free: is a probe window
        open at ``tick``? The backoff advances only when a probe
        request is actually PLACED (:meth:`note_probe`) — an idle fleet
        must not burn through the backoff ladder without ever sending a
        probe."""
        return tick >= self.next_probe_tick

    def note_probe(self, tick: int) -> None:
        """One probe request was routed here: close the window and
        double the interval to the next one (capped); recovery
        (:meth:`note_progress`) resets it."""
        self.next_probe_tick = tick + self.probe_backoff
        self.probe_backoff = min(self.probe_backoff * 2, MAX_PROBE_BACKOFF)

    def rejoin(self, probation_ticks: int,
               tick: Optional[int] = None) -> None:
        """FAILED -> SERVING on probation (the control plane clears the
        engine fault and restarts the run; this just flips the state)."""
        if self.state is not ReplicaState.FAILED:
            raise ValueError(
                f"replica {self.name!r} is {self.state.value}, not failed"
            )
        self.state = ReplicaState.SERVING
        self.failure_reason = None
        self.no_progress_ticks = 0
        self.probe_backoff = 1
        self.next_probe_tick = 0
        self.probation_ticks_left = int(probation_ticks)
        self._note_state(tick)

    # -- planned lifecycle -------------------------------------------------

    def start_drain(self, tick: Optional[int] = None) -> List[Any]:
        """Flip to DRAINING and give up every request: active ones are
        preempted (the scheduler requeues them with pages released),
        then the whole queue is withdrawn. Returns the migrated
        requests — each still carries its generated tokens and its
        original submit/admit timestamps, so re-admission elsewhere
        resumes the exact greedy stream (token-identity pinned)."""
        if self.state not in (ReplicaState.SERVING, ReplicaState.SUSPECT):
            raise ValueError(
                f"replica {self.name!r} is {self.state.value}, not serving"
            )
        self.state = ReplicaState.DRAINING
        self._note_state(tick)
        sched = self.engine.sched
        for req in list(sched.active()):
            sched.preempt(req)
        migrated = [sched.withdraw(req) for req in list(sched.queue)]
        self.migrated_out += len(migrated)
        for req in migrated:
            self.inflight.pop(id(req), None)
        return migrated

    def maybe_stop(self, tick: Optional[int] = None) -> bool:
        """DRAINING -> STOPPED once the scheduler is empty; closes the
        engine's run and captures its aggregate metrics."""
        if self.state is not ReplicaState.DRAINING:
            return False
        if not self.engine.sched.all_done():
            return False
        if self.engine.run_in_progress:
            _, self.final_metrics = self.engine.finish_run()
        self.state = ReplicaState.STOPPED
        self._note_state(tick)
        return True

    def status(self) -> Dict[str, Any]:
        """JSON-able row for ``/debug/fleet``."""
        cache = self.engine.prefix_cache
        out: Dict[str, Any] = {
            "name": self.name,
            "state": self.state.value,
            "dispatched": self.dispatched,
            "migrated_out": self.migrated_out,
            "salvaged_out": self.salvaged_out,
            "no_progress_ticks": self.no_progress_ticks,
            # the dwell trail: every transition as (state, since_tick)
            # — quarantine/probation dwell readable without the full
            # goodput report (the plane adds state_seconds when a
            # goodput ledger is attached)
            "state_history": [list(h) for h in self.state_history],
        }
        if self.failure_reason is not None:
            out["failure_reason"] = self.failure_reason
        if self.probation_ticks_left:
            out["probation_ticks_left"] = self.probation_ticks_left
        if self.state is ReplicaState.SUSPECT:
            out["probe_backoff"] = self.probe_backoff
        if self.inflight:
            # (trace_id, uid) per in-flight request, so /debug/fleet
            # rows join straight onto /debug/trace without a search
            out["inflight"] = sorted(
                ((getattr(r, "trace_id", None), r.uid)
                 for r in self.inflight.values()),
                key=lambda p: (p[0] is None, p[0] or 0, p[1] or 0),
            )
        if self.state not in (ReplicaState.STOPPED, ReplicaState.FAILED):
            out["load"] = self.engine.sched.capacity_snapshot()
            if cache is not None:
                out["cache"] = {
                    "cached_pages": cache.cached_pages,
                    "evictable_pages": cache.evictable_count(),
                }
        return out
