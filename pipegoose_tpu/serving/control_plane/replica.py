"""Replica lifecycle: one ServingEngine behind the router.

A replica is an independently meshed engine — its own scheduler, page
pool, and prefix cache — wrapped with the state machine the router and
autoscaler act on:

    SERVING ──start_drain──> DRAINING ──(emptied)──> STOPPED

- **SERVING** accepts routed requests and ticks every control-plane
  iteration.
- **DRAINING** stops accepting. The control plane immediately preempts
  its in-flight requests (pages released, shared prefix pages survive
  in the cache) and withdraws its queue; the migrated requests re-admit
  elsewhere through the normal re-prefill path — which HITS the target
  replica's cache for any shared prefix — so scale-down drops zero
  admitted work. The replica still ticks until its scheduler empties.
- **STOPPED** is terminal: the engine's run is finished and its
  aggregate metrics captured in ``final_metrics``.

This module is the structural seam ROADMAP item 2 (disaggregated
prefill/decode pools) will hang from: a pool is a set of replicas with
a role tag, and cross-mesh KV streaming replaces the re-prefill
migration path.
"""
from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional


class ReplicaState(enum.Enum):
    SERVING = "serving"
    DRAINING = "draining"
    STOPPED = "stopped"


class Replica:
    """One engine + lifecycle state + per-replica bookkeeping. The
    ``registry`` is the replica's OWN metrics registry (fleet-level
    views merge them — telemetry/fleet.py); ``index`` is the stable
    routing tie-break."""

    def __init__(self, name: str, engine: Any, *, registry: Any = None,
                 index: int = 0):
        self.name = name
        self.engine = engine
        self.registry = registry
        self.index = index
        self.state = ReplicaState.SERVING
        self.dispatched = 0            # requests routed here, lifetime
        self.migrated_out = 0          # requests drained away
        self.final_metrics: Optional[dict] = None

    @property
    def accepting(self) -> bool:
        return self.state is ReplicaState.SERVING

    @property
    def busy(self) -> bool:
        return (self.state is not ReplicaState.STOPPED
                and self.engine.run_in_progress
                and not self.engine.sched.all_done())

    def start_drain(self) -> List[Any]:
        """Flip to DRAINING and give up every request: active ones are
        preempted (the scheduler requeues them with pages released),
        then the whole queue is withdrawn. Returns the migrated
        requests — each still carries its generated tokens and its
        original submit/admit timestamps, so re-admission elsewhere
        resumes the exact greedy stream (token-identity pinned)."""
        if self.state is not ReplicaState.SERVING:
            raise ValueError(
                f"replica {self.name!r} is {self.state.value}, not serving"
            )
        self.state = ReplicaState.DRAINING
        sched = self.engine.sched
        for req in list(sched.active()):
            sched.preempt(req)
        migrated = [sched.withdraw(req) for req in list(sched.queue)]
        self.migrated_out += len(migrated)
        return migrated

    def maybe_stop(self) -> bool:
        """DRAINING -> STOPPED once the scheduler is empty; closes the
        engine's run and captures its aggregate metrics."""
        if self.state is not ReplicaState.DRAINING:
            return False
        if not self.engine.sched.all_done():
            return False
        if self.engine.run_in_progress:
            _, self.final_metrics = self.engine.finish_run()
        self.state = ReplicaState.STOPPED
        return True

    def status(self) -> Dict[str, Any]:
        """JSON-able row for ``/debug/fleet``."""
        cache = self.engine.prefix_cache
        out: Dict[str, Any] = {
            "name": self.name,
            "state": self.state.value,
            "dispatched": self.dispatched,
            "migrated_out": self.migrated_out,
        }
        if self.state is not ReplicaState.STOPPED:
            out["load"] = self.engine.sched.capacity_snapshot()
            if cache is not None:
                out["cache"] = {
                    "cached_pages": cache.cached_pages,
                    "evictable_pages": cache.evictable_count(),
                }
        return out
