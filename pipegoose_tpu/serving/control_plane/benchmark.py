"""Multi-tenant routing-arm replay: the control plane's acceptance meter.

``control_plane_replay_benchmark`` replays the SAME multi-tenant
Zipf-skewed trace (Zipf over tenants x Zipf over shared prefixes —
``make_skewed_replay(n_tenants=...)``) through a fleet of N replicas
under each routing arm:

- ``round_robin``: placement ignores the caches — a request whose
  prefix is hot on replica A lands wherever the rotation points.
- ``cache_aware``: the router probes every replica's prefix cache and
  places each request on the replica holding its longest cached
  prefix.

Both arms serve identical tokens (greedy parity per engine); the meter
is ``prefill_tokens`` — prompt tokens actually forwarded fleet-wide —
plus TTFT p50/p99 over the same trace. Cache-aware routing forwards
fewer tokens because hits stop being placement luck; the prefill-side
win is what moves p99 TTFT on prefill-bound (long shared prefix)
workloads.

``drain_check=True`` additionally re-runs the cache-aware arm with a
forced scale-down drain mid-run and asserts the ZERO-DROP contract:
every request finishes and the per-request token streams are identical
to the no-drain run (the drained requests re-prefilled elsewhere and
resumed their exact greedy streams).

All engines are tiny-config friendly: the bench is part of bench.py's
serving block (CPU smoke + TPU) and the ci_fast.sh router smoke.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from pipegoose_tpu.serving.control_plane.plane import ControlPlane
from pipegoose_tpu.serving.engine import make_skewed_replay
from pipegoose_tpu.serving.scheduler import Request
from pipegoose_tpu.telemetry.registry import Histogram


def _requests(replay):
    return [Request(prompt=p, max_new_tokens=n, tenant=t)
            for p, n, t in replay]


_ROUTER_COUNTERS = ("decisions_total", "cache_routed_total",
                    "matched_tokens_total", "unplaceable_total")
_TENANT_COUNTERS = ("submitted", "dispatched", "dispatched_tokens",
                    "shed", "done", "generated_tokens")


def _fleet_counters(plane) -> Dict:
    """Snapshot of the plane-lifetime router/ledger counters — taken
    before and after the measured run so the per-arm rows report the
    MEASURED replay's deltas, not warmup-polluted lifetime totals."""
    stats = plane.ledger.stats()
    return {
        "router": {k: plane.router.stats()[k] for k in _ROUTER_COUNTERS},
        "tenants": {t: {k: s[k] for k in _TENANT_COUNTERS}
                    for t, s in stats.items()},
    }


def _arm_row(outputs, metrics, before, after) -> Dict:
    h_ttft = Histogram("control_plane.arm.ttft_seconds")  # standalone
    for o in outputs:
        if o.ttft_s is not None:
            h_ttft.observe(o.ttft_s)
    router = {"policy": metrics["router"]["policy"]}
    for k in _ROUTER_COUNTERS:
        router[k] = after["router"][k] - before["router"][k]
    tenants: Dict = {}
    total_tokens = 0
    for t, a in after["tenants"].items():
        b = before["tenants"].get(t, {})
        tenants[t] = {k: a[k] - b.get(k, 0) for k in _TENANT_COUNTERS}
        total_tokens += tenants[t]["dispatched_tokens"]
    for t, row_t in tenants.items():
        row_t["dispatched_token_share"] = (
            round(row_t["dispatched_tokens"] / total_tokens, 4)
            if total_tokens else 0.0
        )
        row_t["fair_floor"] = metrics["tenants"][t]["fair_floor"]
    return {
        "decode_tokens_per_s": metrics["decode_tokens_per_s"],
        "ttft_p50_s": round(h_ttft.quantile(0.5), 6),
        "ttft_p99_s": round(h_ttft.quantile(0.99), 6),
        "prefill_tokens": metrics["prefill_tokens"],
        "generated_tokens": metrics["generated_tokens"],
        "shed_requests": metrics["shed_requests"],
        "wall_time_s": metrics["wall_time_s"],
        "router": router,
        "tenants": tenants,
    }


def control_plane_replay_benchmark(
        params, config, *, n_requests: int = 16, n_prefixes: int = 4,
        prefix_len: int = 64, suffix_lens=(2, 4), max_new: int = 2,
        n_tenants: int = 3, seed: int = 0, zipf_a: float = 1.2,
        n_replicas: int = 2, num_slots: int = 1, num_pages: int = 41,
        page_size: int = 8, max_context: int = 96,
        prefill_chunk: Optional[int] = None, drain_check: bool = True,
        drain_at_tick: int = 3, affinity_slack_tokens: int = 192,
        fleet_trace: bool = False, goodput: bool = True):
    """Measure the routing arms on one multi-tenant trace (module
    docstring); returns a JSON-able dict with per-arm rows, a summary
    (prefill-token reduction + TTFT p99 speedup of cache-aware over
    round-robin), and the drain zero-drop verdict.

    ``fleet_trace=True`` runs one EXTRA cache-aware replay AFTER the
    measurement on a :class:`~pipegoose_tpu.telemetry.fleettrace.
    FleetTracer`-equipped plane (tracing overhead never pollutes the
    measured rows) and attaches its stitched attribution — per-hop
    p50/p99 over ingress/ledger/route/dispatch/replica plus the top-3
    slowest tail exemplars per objective — as ``results["fleet_
    trace"]`` (bench.py writes it to ``bench_fleet_trace.json``).

    ``goodput=True`` (default) runs the arms on goodput-ledgered
    planes and attaches the cache-aware arm's wall attribution —
    goodput fraction, per-class badput split, incident count — as
    ``results["goodput"]``, so BENCH_HISTORY rows carry an
    availability signal ``PerfSentinel`` can watch."""
    vocab = getattr(config, "valid_vocab_size", None) or config.vocab_size
    replay = make_skewed_replay(
        n_requests=n_requests, n_prefixes=n_prefixes, prefix_len=prefix_len,
        suffix_lens=suffix_lens, max_new=max_new, vocab=vocab, seed=seed,
        zipf_a=zipf_a, n_tenants=n_tenants,
    )

    def factory(params=params, config=config):
        def make(name, registry):
            from pipegoose_tpu.serving.engine import ServingEngine

            return ServingEngine(
                params, config, num_slots=num_slots, num_pages=num_pages,
                page_size=page_size, max_context=max_context,
                prefix_cache=True, prefill_chunk=prefill_chunk,
                registry=registry,
            )
        return make

    results: Dict = {}
    planes: Dict[str, ControlPlane] = {}
    for policy in ("round_robin", "cache_aware"):
        # pull_hints OFF: this benchmark isolates ROUTING — with fleet
        # prefix sharing on, a round-robin miss pulls the peer's pages
        # instead of recomputing and both arms forward the same token
        # count (the sharing win is prefix_replay_benchmark's
        # fleet_pull arm, measured separately)
        plane = ControlPlane(factory(), n_replicas=n_replicas,
                             policy=policy, pull_hints=False,
                             affinity_slack_tokens=affinity_slack_tokens,
                             goodput=goodput)
        planes[policy] = plane
        # two warmups, same convention as prefix_replay_benchmark: the
        # first compiles the miss paths and seeds every replica cache,
        # the second exercises the warm hit paths — nothing compiles
        # inside the measured replay. The caches are then CLEARED: a
        # fleet fully warmed by the warmups hits everywhere under ANY
        # policy, so the measured trace runs cold-cache/warm-compile —
        # the regime where placement decides whether the n-th
        # occurrence of a prefix hits (round-robin pays ~n_replicas
        # cold prefills per prefix, cache-aware pays one)
        plane.run(_requests(replay))
        plane.run(_requests(replay))
        plane.clear_prefix_caches()
        before = _fleet_counters(plane)
        outputs, metrics = plane.run(_requests(replay))
        results[policy] = _arm_row(outputs, metrics, before,
                                   _fleet_counters(plane))
    rr, ca = results["round_robin"], results["cache_aware"]
    results["summary"] = {
        "requests": n_requests,
        "tenants": n_tenants,
        "replicas": n_replicas,
        "prefill_token_reduction": round(
            1.0 - ca["prefill_tokens"] / max(rr["prefill_tokens"], 1), 4
        ),
        "ttft_p99_speedup": round(
            rr["ttft_p99_s"] / max(ca["ttft_p99_s"], 1e-9), 3
        ),
        "tokens_per_s_speedup": round(
            ca["decode_tokens_per_s"]
            / max(rr["decode_tokens_per_s"], 1e-9), 3,
        ),
    }
    if drain_check:
        # the zero-drop contract, measured: same warm cache-aware
        # plane, one run clean and one with a forced scale-down drain
        # mid-run — every request must finish with the identical token
        # stream (the drained ones re-prefill elsewhere and resume)
        plane = planes["cache_aware"]
        clean_outs, _ = plane.run(_requests(replay))

        def force_drain(p, tick):
            # drain the BUSIEST replica: the point is to demonstrate
            # in-flight migration, not to retire an idle one
            if tick == drain_at_tick and len(p.serving_replicas()) > 1:
                def owed(rep):
                    s = rep.engine.sched.capacity_snapshot()
                    return (s["queued_tokens"]
                            + s["active_tokens_remaining"])
                p.start_drain(max(p.serving_replicas(), key=owed).name)

        drain_outs, _ = plane.run(
            _requests(replay), tick_hook=force_drain,
        )
        identical = len(clean_outs) == len(drain_outs) and all(
            np.array_equal(a.generated, b.generated)
            for a, b in zip(clean_outs, drain_outs)
        )
        results["drain"] = {
            "performed": any(
                r.state.value != "serving" for r in plane.replicas
            ),
            "migrated": int(plane._m_migrated.value),
            "finished": len(drain_outs),
            "dropped": n_requests - len(drain_outs),
            "outputs_token_identical": bool(identical),
        }
    if goodput:
        # the cache-aware arm's full-lifetime wall attribution (warmups
        # + measured replay + drain when enabled): the availability row
        # BENCH_HISTORY carries for PerfSentinel
        results["goodput"] = planes["cache_aware"].goodput.summary()
    if fleet_trace:
        # one traced replay on a fresh cache-aware plane: the stitched
        # per-hop attribution (conservation-exact: plane hops + replica
        # phases == fleet e2e) plus the slowest tail exemplars, each
        # naming its dominant hop — the "where does fleet p99 go" row
        from pipegoose_tpu.telemetry.fleettrace import FleetTracer
        from pipegoose_tpu.telemetry.registry import MetricsRegistry

        tracer = FleetTracer(registry=MetricsRegistry(enabled=True))
        plane = ControlPlane(factory(), n_replicas=n_replicas,
                             policy="cache_aware", pull_hints=False,
                             affinity_slack_tokens=affinity_slack_tokens,
                             fleet_tracer=tracer)
        plane.run(_requests(replay))       # compile warmup
        tracer.reset()                     # warmup traces don't report
        plane.run(_requests(replay))       # the traced replay
        results["fleet_trace"] = tracer.summary_payload(top_n=3)
    return results
