"""SLO-driven elasticity: burn rate in, capacity decisions out.

The per-replica ``SLOMonitor`` (telemetry/slo.py) answers "is THIS
engine burning its error budget"; the autoscaler consumes the
FLEET-level verdict — the same targets evaluated over the merged
per-replica metrics (telemetry/fleet.py), so one overloaded replica
among idle peers reads as a routing problem, not a capacity one —
and turns sustained burn into ``scale up`` and sustained calm into
``scale down``:

- **up** when any target's fast-window burn >= ``scale_up_burn``
  (breach-grade pressure) and the fleet is below ``max_replicas``.
- **down** when every target's fast-window burn <= ``scale_down_burn``,
  there is no ingress backlog, and the fleet is above ``min_replicas``.
  The control plane then DRAINS one replica (replica.py): routing
  stops, in-flight work migrates, zero admitted requests drop.
- ``cooldown_ticks`` of hysteresis between actions, because a scale-up
  that immediately re-triggers on its own compile warm-up (or a drain
  that flaps back) is worse than no autoscaler at all.

Pull-driven like the monitor itself: the control plane calls
:meth:`decide` once per tick; nothing here owns a thread.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, Optional


@dataclass(frozen=True)
class AutoscalerConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_burn: float = 2.0     # fast burn >= this on ANY target -> up
    scale_down_burn: float = 0.5   # fast burn <= this on ALL targets -> down
    cooldown_ticks: int = 50
    # memory-ledger capacity signal (telemetry/memledger.py): scale up
    # when any replica's steps-to-exhaustion forecast falls to this or
    # below — BEFORE the first admission deferral, which is the whole
    # point of forecasting. 0 disables (the default: fleets without a
    # ledger attached never see the signal).
    scale_up_memory_steps: float = 0.0

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})"
            )
        if self.scale_down_burn >= self.scale_up_burn:
            raise ValueError(
                f"scale_down_burn ({self.scale_down_burn}) must be < "
                f"scale_up_burn ({self.scale_up_burn}) — equal thresholds "
                f"flap"
            )
        if self.cooldown_ticks < 0:
            raise ValueError("cooldown_ticks must be >= 0")
        if self.scale_up_memory_steps < 0:
            raise ValueError("scale_up_memory_steps must be >= 0")


class Autoscaler:
    """Evaluate the fleet SLO monitor and emit up/down/None decisions
    (module docstring). ``monitor`` is an ``SLOMonitor`` over the
    fleet-merged registry; the decision log is the ``/debug/fleet``
    audit trail."""

    def __init__(self, monitor: Any,
                 config: Optional[AutoscalerConfig] = None,
                 max_log: int = 256):
        self.monitor = monitor
        self.config = config or AutoscalerConfig()
        # bounded like Router.decisions: a long-lived plane must not
        # grow its audit trail without limit — newest kept, drops
        # counted so a truncated trail is detectable
        self.log: Deque[Dict[str, Any]] = deque(maxlen=max_log)
        self.log_dropped = 0
        self._last_action_tick: Optional[int] = None

    def decide(self, tick: int, n_serving: int, backlog: int,
               now: Optional[float] = None,
               n_failed: int = 0,
               memory_steps: Optional[float] = None) -> Optional[str]:
        """One evaluation: returns "up", "down", or None. ``n_serving``
        counts SERVING replicas (draining ones are already leaving),
        ``backlog`` the control plane's undispatched ingress — scaling
        down while requests queue would immediately re-breach.
        ``n_failed`` is the UNCOMPENSATED unplanned capacity loss (the
        control plane's ``_capacity_gap``: failures minus scale-ups/
        rejoins since): any loss is an immediate scale-up signal — the
        burn rate would discover it eventually, but only after users
        paid the latency — and a fleet carrying a failure never scales
        DOWN (the backlog guard's crash sibling). ``memory_steps`` is
        the FLEET MINIMUM of the memory ledger's steps-to-exhaustion
        forecast (None = no ledger attached anywhere): at or below
        ``scale_up_memory_steps`` it scales up ahead of the first
        admission deferral, and a fleet under memory pressure never
        scales down — shedding capacity while KV headroom runs out is
        the one move guaranteed to convert a forecast into a breach."""
        cfg = self.config
        if (self._last_action_tick is not None
                and tick < self._last_action_tick):
            # the tick counter restarted (a new plane.run): a stale
            # marker from the previous run would make the delta
            # negative and suppress decisions far past the configured
            # hysteresis
            self._last_action_tick = None
        if (self._last_action_tick is not None
                and tick - self._last_action_tick < cfg.cooldown_ticks):
            return None
        status = self.monitor.evaluate(now)
        burns = {name: t.get("burn_fast", 0.0)
                 for name, t in status.get("targets", {}).items()}
        mem_pressure = (
            cfg.scale_up_memory_steps > 0
            and memory_steps is not None
            and memory_steps <= cfg.scale_up_memory_steps)
        decision = None
        reason = ""
        if n_failed > 0 and n_serving < cfg.max_replicas:
            decision = "up"
            reason = (f"{n_failed} failed replica(s): unplanned "
                      f"capacity loss")
        elif mem_pressure and n_serving < cfg.max_replicas:
            decision = "up"
            reason = (f"memory ledger forecasts {memory_steps:.0f} "
                      f"step(s) to KV exhaustion <= "
                      f"{cfg.scale_up_memory_steps:.0f}")
        elif burns and max(burns.values()) >= cfg.scale_up_burn:
            if n_serving < cfg.max_replicas:
                hot = max(burns, key=burns.get)
                decision = "up"
                reason = (f"target {hot!r} burning {burns[hot]:.2f}x >= "
                          f"{cfg.scale_up_burn}x")
            # at max: nothing to add — shedding stays the pressure valve
        elif (burns and backlog == 0 and n_failed == 0
                and not mem_pressure
                and n_serving > cfg.min_replicas
                and max(burns.values()) <= cfg.scale_down_burn):
            decision = "down"
            reason = (f"all burns <= {cfg.scale_down_burn}x and no "
                      f"backlog")
        if decision is not None:
            self._last_action_tick = tick
            if (self.log.maxlen is not None
                    and len(self.log) == self.log.maxlen):
                self.log_dropped += 1
            self.log.append({
                "tick": tick,
                "decision": decision,
                "reason": reason,
                "burns": burns,
                "n_serving": n_serving,
                "backlog": backlog,
                "n_failed": n_failed,
                "memory_steps": memory_steps,
            })
        return decision
