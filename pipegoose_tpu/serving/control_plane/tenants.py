"""Per-tenant fair-share dispatch: weighted deficit round robin.

The single-engine scheduler is deliberately FIFO (deterministic
admission); fairness belongs one layer up, where traffic from many
tenants meets finite fleet capacity. The ledger holds one ingress FIFO
per tenant and releases requests to the router in **deficit round
robin** order (Shreedhar & Varghese): each dispatch round, every
backlogged tenant's deficit grows by ``quantum_tokens x weight``; a
tenant may release queued requests while its deficit covers their
token cost (``prompt_len + max_new_tokens`` — the work the fleet will
actually spend). A hot tenant flooding the queue therefore gets
exactly its weighted share of dispatched tokens, never the whole
fleet, while an idle tenant's deficit resets (no hoarding credit to
burst later past everyone).

**Priority classes** are strict between classes: all class-0 backlogs
dispatch before any class-1 request is considered, DRR applies within
a class. Use sparingly — a saturating class 0 starves the rest by
design (that is what priority means); the starvation-freedom pin
applies to tenants of equal class.

**Deadline shedding is the pressure valve** (PR 9): requests carry
``deadline_s`` from submit, the ledger sheds expired never-dispatched
requests at each dispatch round exactly like the scheduler sheds
never-admitted ones at admission — under sustained overload a tenant's
excess traffic dies in ITS OWN queue instead of crowding the fleet.

Host-side only; no jax, no device state.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

DEFAULT_TENANT = "default"


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's dispatch contract: ``weight`` scales its DRR
    quantum (2.0 = twice the fair share of dispatched tokens under
    contention), ``priority`` its strict class (lower dispatches
    first)."""

    name: str
    weight: float = 1.0
    priority: int = 0

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, "
                f"got {self.weight}"
            )
        if self.priority < 0:
            raise ValueError(
                f"tenant {self.name!r}: priority must be >= 0, "
                f"got {self.priority}"
            )


class _TenantState:
    __slots__ = ("spec", "queue", "deficit", "submitted", "dispatched",
                 "dispatched_tokens", "shed", "done", "done_tokens")

    def __init__(self, spec: TenantSpec):
        self.spec = spec
        self.queue: deque = deque()
        self.deficit = 0.0
        self.submitted = 0
        self.dispatched = 0
        self.dispatched_tokens = 0
        self.shed = 0
        self.done = 0
        self.done_tokens = 0


class TenantLedger:
    """Weighted fair-share ingress queue over tenants (module
    docstring). Unknown tenants auto-register at ``default_weight`` /
    ``default_priority`` — production fleets pre-register contracts,
    tests and benches just submit."""

    def __init__(self, specs: Optional[List[TenantSpec]] = None, *,
                 quantum_tokens: int = 64, default_weight: float = 1.0,
                 default_priority: int = 0):
        if quantum_tokens < 1:
            raise ValueError(
                f"quantum_tokens must be >= 1, got {quantum_tokens}"
            )
        self.quantum_tokens = int(quantum_tokens)
        self.default_weight = float(default_weight)
        self.default_priority = int(default_priority)
        self._tenants: Dict[str, _TenantState] = {}
        self._order: List[str] = []       # registration order (stable RR)
        self._rr_start = 0                # rotating DRR start pointer
        for spec in specs or []:
            self.register(spec)

    # -- registration ------------------------------------------------------

    def register(self, spec: TenantSpec) -> None:
        if spec.name in self._tenants:
            raise ValueError(f"tenant {spec.name!r} already registered")
        self._tenants[spec.name] = _TenantState(spec)
        self._order.append(spec.name)

    def _state(self, tenant: Optional[str]) -> _TenantState:
        name = tenant if tenant is not None else DEFAULT_TENANT
        st = self._tenants.get(name)
        if st is None:
            st = _TenantState(TenantSpec(
                name, weight=self.default_weight,
                priority=self.default_priority,
            ))
            self._tenants[name] = st
            self._order.append(name)
        return st

    # -- ingress -----------------------------------------------------------

    @staticmethod
    def cost(req: Any) -> int:
        """DRR token cost: the work the fleet will spend on the request
        (whole prompt through prefill + the new-token budget)."""
        return int(req.prompt_len) + int(req.max_new_tokens)

    def submit(self, req: Any) -> None:
        st = self._state(req.tenant)
        st.submitted += 1
        st.queue.append(req)

    def pending(self) -> int:
        return sum(len(st.queue) for st in self._tenants.values())

    def shed_expired(self, now: float) -> List[Any]:
        """Drop queued requests already past their deadline (same
        contract as ``Scheduler._shed_expired``: never-admitted only —
        ``t_admit`` marks paid prefill and exempts a migrated
        request). Returns the shed requests, terminal with
        ``finish_reason="shed"``, for the control plane to report."""
        from pipegoose_tpu.serving.scheduler import Status

        shed: List[Any] = []
        for st in self._tenants.values():
            if not any(r.deadline_s is not None for r in st.queue):
                continue
            kept: deque = deque()
            for req in st.queue:
                if (req.deadline_s is not None
                        and req.t_admit is None
                        and req.t_submit is not None
                        and now - req.t_submit > req.deadline_s):
                    req.status = Status.DONE
                    req.finish_reason = "shed"
                    req.t_done = now
                    st.shed += 1
                    shed.append(req)
                else:
                    kept.append(req)
            st.queue = kept
        return shed

    # -- dispatch ----------------------------------------------------------

    def next_batch(self, budget_requests: int) -> List[Any]:
        """One DRR round: release up to ``budget_requests`` requests in
        weighted fair order. Strict priority between classes; within a
        class, each backlogged tenant earns ``quantum x weight`` deficit
        and releases FIFO while the deficit covers the head's cost. A
        tenant whose queue drains loses its leftover deficit (classic
        DRR: idleness is not bankable credit). The rotating start
        pointer keeps same-round ordering fair across rounds."""
        out: List[Any] = []
        if budget_requests < 1 or not self._order:
            return out
        backlogged = [n for n in self._order if self._tenants[n].queue]
        if not backlogged:
            return out
        classes = sorted({self._tenants[n].spec.priority
                          for n in backlogged})
        self._rr_start += 1
        for prio in classes:
            names = [n for n in backlogged
                     if self._tenants[n].spec.priority == prio]
            k = self._rr_start % max(len(names), 1)
            names = names[k:] + names[:k]
            # keep granting quanta until the budget fills or the class
            # drains — a single quantum smaller than one request's cost
            # must not deadlock dispatch (the deficit accumulates)
            while len(out) < budget_requests:
                progressed = False
                for name in names:
                    st = self._tenants[name]
                    if not st.queue:
                        st.deficit = 0.0
                        continue
                    st.deficit += self.quantum_tokens * st.spec.weight
                    while (st.queue and len(out) < budget_requests
                           and st.deficit >= self.cost(st.queue[0])):
                        req = st.queue.popleft()
                        c = self.cost(req)
                        st.deficit -= c
                        st.dispatched += 1
                        st.dispatched_tokens += c
                        out.append(req)
                        progressed = True
                    if not st.queue:
                        st.deficit = 0.0
                if not progressed and not any(
                        self._tenants[n].queue for n in names):
                    break
                if not progressed:
                    # budget not filled but quanta keep accruing toward
                    # the cheapest head; loop again (bounded: deficit
                    # grows monotonically toward the head's cost)
                    continue
        return out

    def requeue_front(self, req: Any) -> None:
        """Put an un-placeable request back at the FRONT of its tenant
        queue WITHOUT re-charging its dispatch (the deficit already
        paid; re-charging would bill a full cost per failed placement
        attempt)."""
        st = self._state(req.tenant)
        st.dispatched -= 1
        st.dispatched_tokens -= self.cost(req)
        st.queue.appendleft(req)

    def record_done(self, req: Any) -> None:
        st = self._state(req.tenant)
        st.done += 1
        st.done_tokens += len(req.generated)

    # -- views -------------------------------------------------------------

    def fair_floor(self, tenant: str) -> float:
        """The tenant's guaranteed dispatched-token share among SAME-
        priority tenants that have dispatched anything: weight over the
        class's total weight. The starvation-freedom pin asserts every
        continuously backlogged tenant's measured share stays >= this
        floor (less DRR's one-quantum granularity slack)."""
        st = self._tenants[tenant]
        peers = [s for s in self._tenants.values()
                 if s.spec.priority == st.spec.priority
                 and (s.dispatched or s.queue)]
        total = sum(s.spec.weight for s in peers)
        return st.spec.weight / total if total else 1.0

    def stats(self) -> Dict[str, Any]:
        total_tokens = sum(s.dispatched_tokens
                           for s in self._tenants.values())
        out: Dict[str, Any] = {}
        for name in self._order:
            st = self._tenants[name]
            out[name] = {
                "weight": st.spec.weight,
                "priority": st.spec.priority,
                "submitted": st.submitted,
                "queued": len(st.queue),
                "dispatched": st.dispatched,
                "dispatched_tokens": st.dispatched_tokens,
                "dispatched_token_share": (
                    round(st.dispatched_tokens / total_tokens, 4)
                    if total_tokens else 0.0
                ),
                "fair_floor": round(self.fair_floor(name), 4),
                "shed": st.shed,
                "done": st.done,
                "generated_tokens": st.done_tokens,
            }
        return out
