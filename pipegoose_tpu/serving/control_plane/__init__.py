"""Multi-replica serving control plane (ROADMAP item 3).

One ``ServingEngine`` + one ``Scheduler`` serves one slice; "millions
of users" means N independently meshed engine replicas behind one
front door. This package is that front door:

- **Cache-aware routing** (:mod:`router`): every replica owns its own
  page pool and radix prefix cache; the router probes each replica's
  cache with the read-only ``longest_prefix_len`` and routes a request
  to the replica already holding its longest cached prefix, tie-broken
  by load (queued tokens + free/evictable pages via the scheduler's
  non-mutating ``can_admit``/``capacity_snapshot`` probes). Hit rate
  becomes a placement decision, not luck.
- **Per-tenant fairness** (:mod:`tenants`): weighted fair-share
  dispatch with priority classes and deficit accounting across
  replicas; deadline shedding (PR 9) is the pressure valve. One hot
  tenant cannot starve the rest (pinned by test).
- **SLO-driven elasticity** (:mod:`autoscaler`, :mod:`replica`): the
  fleet-merged burn-rate signal (telemetry/fleet.py aggregates every
  replica's registry) adds a replica or drains one; drain = stop
  routing, preempt in-flight requests, re-admit them elsewhere through
  the existing re-prefill-hits-the-cache path — scale-down drops ZERO
  admitted work (outputs token-identical to a no-drain run, pinned).

:class:`~pipegoose_tpu.serving.control_plane.plane.ControlPlane` is
the orchestrator driving the replicas' steppable-run API tick by tick
in one host thread; ``/debug/fleet`` (telemetry/opsserver.py) serves
its live :meth:`fleet_status`. See docs/serving.md "Control plane".
"""
from pipegoose_tpu.serving.control_plane.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
)
from pipegoose_tpu.serving.control_plane.benchmark import (
    control_plane_replay_benchmark,
)
from pipegoose_tpu.serving.control_plane.plane import ControlPlane
from pipegoose_tpu.serving.control_plane.replica import Replica, ReplicaState
from pipegoose_tpu.serving.control_plane.router import Router
from pipegoose_tpu.serving.control_plane.tenants import (
    TenantLedger,
    TenantSpec,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "ControlPlane",
    "Replica",
    "ReplicaState",
    "Router",
    "TenantLedger",
    "TenantSpec",
    "control_plane_replay_benchmark",
]
